"""Async PS training with REAL jitted compute in every process.

The full AsySG-InCon stack the reference ran — every rank doing actual
backprop, gradients shipped through the wire, a PS applying them in
arrival order (reference ``README.md:61-81`` pseudo-code; hook/pool
overlap ``ps.py:65-66,98-101``) — realized end-to-end across OS
processes:

  worker process:  read latest params (inconsistent read, seqlock)
                   → jitted ``value_and_grad`` of a flax model on device
                   → codec ``encode`` (jitted, CodecWire)
                   → payload BYTES into the shm mailbox
  server process:  poll mailboxes in arrival order
                   → codec ``decode`` (jitted)
                   → jitted fused ``sgd_update``/``adam_update``
                   → publish new snapshot (version += 1)

No gradient anywhere is computed outside ``jax.jit``. Staleness is
measured against publish versions and bounded by the server
(``max_staleness`` drops, ``stale_drops`` counter); a deliberately slow
worker exercises both the nontrivial staleness histogram and the drops.

Two serve disciplines, for the async-vs-sync wall-clock comparison the
algorithm exists for (Lian et al. 2015, arXiv:1506.08272):

- ``serve(..., sync_barrier=False)`` — AsySG: apply each gradient the
  moment it arrives. Throughput tracks the FAST workers.
- ``serve(..., sync_barrier=True)``  — synchronous PS oracle: collect one
  gradient from EVERY worker per round, apply the batch, publish once.
  Throughput collapses to the slowest worker (the straggler effect the
  reference's two-phase protocol fought, ``mpi_comms.py:190-191``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from pytorch_ps_mpi_tpu import telemetry

PyTree = Any

# update/wait latency buckets (seconds): sub-ms jitted updates through
# multi-second straggler waits
_LATENCY_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                    5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _telemetry_from_cfg(cfg: Dict[str, Any], worker: Any):
    """The zero-cost-when-disabled switch: ``cfg["telemetry_dir"]``
    enables the process-global FlightRecorder (server process AND every
    spawned worker — cfg rides the spawn's JSON argv, so one flag arms
    the whole fleet). Returns the active recorder or None."""
    rec = telemetry.get_recorder()
    if rec is None and cfg.get("telemetry_dir"):
        rec = telemetry.configure(
            capacity=int(cfg.get("telemetry_capacity", 65536)), worker=worker
        )
    return rec


def _dump_recorder(cfg: Dict[str, Any], rec, filename: str) -> Optional[str]:
    tdir = cfg.get("telemetry_dir")
    if rec is None or not tdir:
        return None
    os.makedirs(tdir, exist_ok=True)
    return rec.dump_jsonl(os.path.join(tdir, filename))


def _model_by_name(name: str, **kw):
    if name == "mlp":
        from pytorch_ps_mpi_tpu.models import MLP

        return MLP(features=tuple(kw.get("features", (32, 8))))
    if name == "resnet18":
        from pytorch_ps_mpi_tpu.models import ResNet18

        return ResNet18(num_classes=kw.get("num_classes", 10),
                        small_inputs=True)
    if name == "resnet50":
        from pytorch_ps_mpi_tpu.models import ResNet50

        return ResNet50(num_classes=kw.get("num_classes", 10),
                        small_inputs=True)
    if name == "gpt":
        from pytorch_ps_mpi_tpu.models import GPTLM, gpt_tiny

        # forward EVERY config knob (remat, attention, dtype, ...);
        # only the sizing defaults are overridden for fleet-test scale
        return GPTLM(gpt_tiny(**{
            "vocab_size": 256, "hidden_size": 64, "num_layers": 2,
            "num_heads": 4, "intermediate_size": 128, "max_position": 64,
            **kw,
        }))
    raise ValueError(f"unknown model {name!r}")


def make_problem(cfg: Dict[str, Any]):
    """(model, params0, batch_fn, loss_fn) deterministically from ``cfg``
    — every process (server and workers) rebuilds the same problem from
    the same dict, the rank-parameterized-oracle pattern of the
    reference's tests (SURVEY §4) applied to a train job."""
    import jax
    import jax.numpy as jnp

    model = _model_by_name(cfg["model"], **cfg.get("model_kw", {}))
    in_shape = tuple(cfg.get("in_shape", (8,)))
    batch = int(cfg.get("batch", 32))
    k = jax.random.key(int(cfg.get("seed", 0)))
    kp, kx, kw = jax.random.split(k, 3)
    if cfg["model"] != "gpt":  # token models init on int inputs below
        x0 = jnp.zeros((1,) + in_shape, jnp.float32)
        params0 = model.init(kp, x0)

    n_out = int(cfg.get("model_kw", {}).get("num_classes", 0)) or (
        tuple(cfg.get("model_kw", {}).get("features", (32, 8)))[-1]
        if cfg["model"] == "mlp" else 10
    )

    if cfg["model"] == "gpt":
        # causal LM on a fixed bigram Markov chain: the TABLE is built
        # once from cfg['seed'] (every process sees the same language);
        # sampling streams derive per (worker, step) through a
        # SeedSequence, which cannot collide the way linear seed
        # arithmetic (1000*worker + step) did at step >= 1000
        from pytorch_ps_mpi_tpu.data import markov_table, sample_markov
        from pytorch_ps_mpi_tpu.models import causal_lm_loss

        vocab = model.cfg.vocab_size
        seq = int(cfg.get("seq_len", 32))
        if seq > model.cfg.max_position:
            raise ValueError(
                f"seq_len={seq} exceeds the model's max_position="
                f"{model.cfg.max_position}: positions past it would be "
                "silently clamped to one embedding"
            )
        base_seed = int(cfg.get("seed", 0))
        cum = markov_table(vocab, base_seed)
        params0 = model.init(kp, jnp.zeros((1, seq), jnp.int32))

        def batch_fn(step: int, worker: int):
            ss = np.random.SeedSequence([base_seed, worker, step])
            rng = np.random.RandomState(ss.generate_state(1)[0])
            return jnp.asarray(sample_markov(cum, batch, seq, rng))

        def loss_fn(params, tokens):
            return causal_lm_loss(model.apply(params, tokens), tokens)

        return model, params0, batch_fn, loss_fn

    if cfg["model"] == "mlp":
        # regression against a fixed random linear teacher: smooth convex-
        # ish loss whose value cleanly separates trained from untrained
        d_in = int(np.prod(in_shape))
        w_true = jax.random.normal(kw, (d_in, n_out)) / d_in ** 0.5

        def batch_fn(step: int, worker: int):
            kk = jax.random.fold_in(jax.random.fold_in(kx, worker), step)
            x = jax.random.normal(kk, (batch,) + in_shape)
            y = x.reshape(batch, -1) @ w_true
            return x, y

        def loss_fn(params, b):
            x, y = b
            pred = model.apply(params, x)
            return jnp.mean((pred - y) ** 2)
    else:
        def batch_fn(step: int, worker: int):
            kk = jax.random.fold_in(jax.random.fold_in(kx, worker), step)
            x = jax.random.normal(kk, (batch,) + in_shape)
            y = jax.random.randint(jax.random.fold_in(kk, 1), (batch,), 0, n_out)
            return x, y

        def loss_fn(params, b):
            x, y = b
            logits = model.apply(params, x)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    return model, params0, batch_fn, loss_fn


def worker_cfg(cfg: Dict[str, Any], worker_id: int) -> Tuple[float, int]:
    """Per-worker (slow_ms, steps) from the shared job config — one
    parser for every worker body (shm, tcp, sharded)."""
    slow_ms = float(cfg.get("slow_ms", {}).get(str(worker_id), 0.0)) if isinstance(
        cfg.get("slow_ms"), dict) else 0.0
    steps = int(cfg.get("worker_steps", {}).get(str(worker_id),
                cfg.get("steps", 10))) if isinstance(
        cfg.get("worker_steps"), dict) else int(cfg.get("steps", 10))
    return slow_ms, steps


def worker_main(name: str, worker_id: int, cfg: Dict[str, Any]) -> int:
    """Worker process body: jitted fwd/bwd → encode → push bytes.
    Returns the number of gradients pushed.

    ``cfg["transport"]`` selects the wire: ``"shm"`` (default, co-hosted
    processes, ``dcn.py``) or ``"tcp"`` (cross-host DCN role, ``tcp.py``
    — ``name`` then carries ``"host:port"``). The compute path is
    identical either way: no gradient is ever produced outside jit.

    Resilience knobs (all off by default — the legacy fail-fast worker):

    - ``cfg["frame_check"]``: seal every push in a self-verifying frame
      (CRC + config fingerprint, ``resilience.frames``) — must match the
      server's setting, like the codec config it fingerprints.
    - ``cfg["resilient"]``: wrap the transport in
      :class:`~pytorch_ps_mpi_tpu.resilience.worker.ResilientWorker` —
      backoff+retry on timeouts, full reconnect on EOF — so a server
      restart-from-checkpoint is survived instead of raised on
      (``cfg["resilience_kw"]`` forwards tuning knobs).
    - ``cfg["fault_plan"]``: consult a deterministic
      :class:`~pytorch_ps_mpi_tpu.resilience.faults.FaultInjector` for
      this worker id at every step (drop/delay/duplicate/corrupt/
      crash_worker kinds).
    """
    import jax

    code = None
    if cfg.get("codec"):
        from pytorch_ps_mpi_tpu.codecs import get_codec

        code = get_codec(cfg["codec"], **cfg.get("codec_kw", {}))

    _, params0, batch_fn, loss_fn = make_problem(cfg)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))  # ONLY grad source

    slow_ms, steps = worker_cfg(cfg, worker_id)
    frame = bool(cfg.get("frame_check"))

    def make_transport():
        if cfg.get("transport", "shm") == "tcp":
            from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSWorker

            host, port = name.rsplit(":", 1)
            return TcpPSWorker(host, int(port), worker_id, params0,
                               code=code,
                               timeout=float(cfg.get("open_timeout", 60.0)),
                               bucket_mb=float(cfg.get("bucket_mb", 0.0)),
                               frame=frame)
        from pytorch_ps_mpi_tpu.parallel.dcn import ShmPSWorker

        return ShmPSWorker(name, worker_id, params0, code=code,
                           timeout=float(cfg.get("open_timeout", 60.0)),
                           bucket_mb=float(cfg.get("bucket_mb", 0.0)),
                           frame=frame)

    rec = _telemetry_from_cfg(cfg, worker=worker_id)
    if cfg.get("tree_leader"):
        # aggregation-tree leaf: push to the group leader, fall back to
        # the root when the leader dies, rejoin on its respawn — the
        # tree's own failover IS the resilience layer here
        from pytorch_ps_mpi_tpu.parallel.tree import TreeWorkerConn

        w = TreeWorkerConn(worker_id, params0, cfg)
    elif cfg.get("resilient"):
        from pytorch_ps_mpi_tpu.resilience.worker import ResilientWorker

        w = ResilientWorker(make_transport, worker_id=worker_id,
                            seed=int(cfg.get("fault_seed",
                                             cfg.get("seed", 0))),
                            **cfg.get("resilience_kw", {}))
    else:
        w = make_transport()

    from pytorch_ps_mpi_tpu.resilience.faults import (
        CRASH_EXIT_CODE,
        FaultInjector,
    )

    inj = FaultInjector.from_cfg(cfg, role=worker_id)
    push_timeout = float(cfg.get("push_timeout", 60.0))
    # self-driving control plane, worker half: when the controller is
    # armed, the server publishes codec renegotiations (wire-epoch
    # bumps) as an atomically-replaced control-epoch.json; the worker
    # polls it between steps (one os.stat per step) and rebuilds its
    # wire onto the new epoch. No other worker-side change exists — LR
    # scaling and evict/readmit are applied entirely server-side.
    control_dir = cfg.get("control_dir") or (
        cfg.get("telemetry_dir")
        if (cfg.get("control") or cfg.get("control_kw")
            or cfg.get("topo_actions")) else None)
    epoch_state: Dict[str, Any] = {"epoch": 0, "mtime": 0}
    # structural-control half: control-topo.json carries the leader
    # re-assignment map (group split/merge); a tree leaf repoints its
    # leader connection when the map names it
    topo_state: Dict[str, Any] = {"seq": 0, "mtime": 0}
    # monotonic push seq — the third leg of the (worker, step, seq)
    # trace ID stamped into every framed push at THIS encode site;
    # duplicates get their own seq (both frames really travel)
    push_seq = 0
    prober = None
    probe_every = 0
    if cfg.get("numerics_dir") and getattr(w, "wire", None) is not None:
        # the codec-fidelity half of the numerics layer: decode-after-
        # encode probes must run HERE, on the pre-encode gradient — the
        # server only ever sees decoded values, and re-encoding those
        # measures ~0 error for sign-like codecs. Rows are tailed live
        # by the server-side NumericsMonitor.
        from pytorch_ps_mpi_tpu.telemetry.numerics import (
            NUMERICS_KNOBS,
            ProbeWriter,
        )

        probe_every = max(1, int((cfg.get("numerics_kw") or {}).get(
            "probe_every", NUMERICS_KNOBS["probe_every"])))
        prober = ProbeWriter(cfg["numerics_dir"], worker_id)
    wprof = None
    if cfg.get("profile") or cfg.get("profile_dir"):
        prof_dir = cfg.get("profile_dir") or cfg.get("telemetry_dir")
        if prof_dir:
            # continuous profiling, worker half: the same collapsed-stack
            # sampler the serve loop runs, one profile-worker-N.txt per
            # process, merged by tools/telemetry_report.py
            from pytorch_ps_mpi_tpu.telemetry.profiler import (
                SamplingProfiler,
            )

            wprof = SamplingProfiler(
                name=f"worker-{worker_id}", dir=prof_dir,
                **(cfg.get("profile_kw") or {})).start()
    beacon = None
    if cfg.get("health_dir"):
        # the online-diagnosis side channel: one appended JSONL row per
        # step with the SAME durations the recorder spans measure, so
        # the server-side HealthMonitor can attribute a straggle to
        # compute vs wire while the run is still going (the recorder
        # dump only lands at exit)
        from pytorch_ps_mpi_tpu.telemetry.diagnosis import BeaconWriter

        beacon = BeaconWriter(cfg["health_dir"], worker_id)
    pushed = 0
    try:
        for step in range(steps):
            t_step0 = time.monotonic()
            if control_dir is not None:
                from pytorch_ps_mpi_tpu import control as _control

                doc = _control.poll_epoch(control_dir, epoch_state)
                if doc is not None:
                    try:
                        _control.apply_epoch(w, doc)
                    except Exception:
                        pass  # a bad epoch doc must never kill a worker
                if hasattr(w, "repoint"):
                    from pytorch_ps_mpi_tpu.control.topo import poll_topo

                    tdoc = poll_topo(control_dir, topo_state)
                    if tdoc is not None:
                        addr = (tdoc.get("assign") or {}).get(
                            str(worker_id))
                        if addr:
                            try:
                                w.repoint(addr)
                            except Exception:
                                pass  # failover owns recovery; a bad
                                # repoint must never kill a worker
            drop = duplicate = poison = False
            if inj is not None:
                for f in inj.faults_at(step):
                    kind = f["kind"]
                    if kind == "crash_worker":
                        # fired (and fault-logged) BEFORE dying; os._exit
                        # skips every finally — the closest an injector
                        # gets to SIGKILL from inside the process
                        inj.fire(f)
                        _dump_recorder(cfg, rec, f"worker-{worker_id}.jsonl")
                        os._exit(CRASH_EXIT_CODE)
                    elif kind == "delay":
                        inj.fire(f)
                        time.sleep(float(f.get("delay_ms", 100.0)) / 1e3)
                    elif kind == "wire_delay":
                        # emulated wire latency: the transport sleeps
                        # AFTER sealing the frame (send_wall stamped),
                        # so the delay lands in the lineage wire stage
                        # — unlike "delay", which inflates produce
                        inj.fire(f)
                        wd = float(f.get("delay_ms", 100.0)) / 1e3
                        if hasattr(w, "set_wire_delay"):
                            w.set_wire_delay(wd)
                        else:
                            w._wire_delay_s = wd
                    elif kind == "drop":
                        inj.fire(f)
                        drop = True
                    elif kind == "duplicate":
                        inj.fire(f)
                        duplicate = True
                    elif kind == "nan":
                        # numerics chaos: poison this step's gradient
                        # with NaNs BEFORE encode — the quarantine leg's
                        # deterministic test vector
                        inj.fire(f)
                        poison = True
                    elif kind == "corrupt":
                        # fires when the tampered push actually happens
                        tamper = inj.make_tamper(f)
                        if hasattr(w, "set_tamper"):
                            w.set_tamper(tamper)
                        else:
                            w._tamper = tamper
            if drop:
                # a dropped push cannot also be corrupted or
                # wire-delayed: disarm any one-shot hooks armed this
                # step, or they would leak onto the NEXT step's push
                # (logged under the wrong step) — the faults
                # deterministically never fire instead
                if hasattr(w, "set_tamper"):
                    w.set_tamper(None)
                else:
                    w._tamper = None
                if hasattr(w, "set_wire_delay"):
                    w.set_wire_delay(0.0)
                else:
                    w._wire_delay_s = 0.0
            # one measured path for recorder spans AND health beacons:
            # durations are taken once and shared (explicit ts/dur events
            # are exactly what rec.span records)
            t0 = time.monotonic()
            params, version = w.read_params()
            if rec is not None:
                rec.event("worker.read_params", kind="span", ts=t0,
                          dur=time.monotonic() - t0, step=step)
            t0 = time.monotonic()
            loss, grads = grad_fn(params, batch_fn(step, worker_id))
            jax.block_until_ready(grads)
            compute_s = time.monotonic() - t0
            if rec is not None:
                rec.event("worker.grad", kind="span", ts=t0, dur=compute_s,
                          step=step, version=version)
            if poison:
                import jax.numpy as jnp

                grads = jax.tree.map(
                    lambda g: jnp.full_like(g, jnp.nan), grads
                )
            if prober is not None and step % probe_every == 0:
                try:
                    prober.write(step, w.wire.probe_fidelity(grads))
                except Exception:
                    pass  # a probe must never take a worker down
            straggle_s = 0.0
            if slow_ms:
                t0 = time.monotonic()
                time.sleep(slow_ms / 1e3)  # deliberate straggler
                straggle_s = time.monotonic() - t0
                if rec is not None:
                    rec.event("worker.straggle", kind="span", ts=t0,
                              dur=straggle_s, step=step)
            if not drop:
                t0 = time.monotonic()
                seq0 = push_seq
                w.push_grad(grads, version, timeout=push_timeout,
                            lineage=(step, push_seq))
                push_seq += 1
                if duplicate:
                    w.push_grad(grads, version, timeout=push_timeout,
                                lineage=(step, push_seq))
                    push_seq += 1
                if rec is not None:
                    # seq joins the span so trace export can tie this
                    # push span to the server's consume span (flow arrow)
                    rec.event("worker.push_grad", kind="span", ts=t0,
                              dur=time.monotonic() - t0, step=step,
                              version=version, seq=seq0)
            pushed += 1
            if beacon is not None:
                # step accounting for straggler ATTRIBUTION: the
                # deliberate slow_ms sleep emulates slow compute, so it
                # rides the compute bucket; everything else that isn't
                # the jitted grad — reads, pushes, retry backoff, and
                # injected delay faults — is wire-side
                wire_s = max(
                    0.0, (time.monotonic() - t_step0) - compute_s
                    - straggle_s)
                beacon.step(step, compute_s + straggle_s, wire_s,
                            straggle_s,
                            retries=getattr(w, "retries", 0),
                            reconnects=getattr(w, "reconnects", 0))
        if rec is not None and hasattr(w, "reconnects"):
            rec.event("resilience.summary", worker=worker_id,
                      retries=w.retries, reconnects=w.reconnects)
    finally:
        w.close()
        _dump_recorder(cfg, rec, f"worker-{worker_id}.jsonl")
        if prober is not None:
            prober.close()
        if beacon is not None:
            beacon.close(retries=getattr(w, "retries", 0),
                         reconnects=getattr(w, "reconnects", 0))
        if wprof is not None:
            wprof.stop()
            wprof.write()
    return pushed


def _restore_ps_checkpoint(ckpt, params, state, checkpoint_every: int):
    """Restore the latest PS snapshot; returns (params, opt_state,
    applied_total, resumed_version). The resumed version is jumped past
    anything a surviving worker could have read in the crash window (the
    SAVED run's cadence bounds it — see serve's docstring); the restored
    step is marked already-saved so it is never re-saved (Orbax raises
    StepAlreadyExistsError). Shared by the single-server serve loop and
    the sharded shard-server loop."""
    template = {"params": params, "opt_state": state,
                "version": 0, "applied_total": 0, "checkpoint_every": 0}
    restored = ckpt.restore(template)
    applied_before = int(restored["applied_total"])
    ckpt._last_ps_step = applied_before
    jump = max(int(restored["checkpoint_every"]), int(checkpoint_every), 0)
    version = int(restored["version"]) + jump + 1
    return restored["params"], restored["opt_state"], applied_before, version


class _PSCheckpointCadence:
    """The save half of PS checkpointing, shared by the single-server
    serve loop and the sharded shard-server loop so the crash-window
    guarantees can never diverge between them: save when the APPLIED
    COUNT has advanced by ``checkpoint_every`` since the last save (not
    on divisibility — sync_barrier mode advances ``applied`` by
    n_workers per round and would hit an exact multiple only every lcm),
    plus one unconditional final save at loop exit."""

    def __init__(self, ckpt, checkpoint_every: int, applied_before: int):
        self.ckpt = ckpt
        self.every = int(checkpoint_every)
        self.last_saved = int(applied_before)

    def _save(self, params, state, server, applied_total: int) -> None:
        if getattr(self.ckpt, "_last_ps_step", None) == applied_total:
            return  # final save coinciding with a periodic one
        import jax

        self.ckpt.save(applied_total, {
            "params": jax.tree.map(np.asarray, params),
            "opt_state": jax.tree.map(np.asarray, state),
            "version": server.version,
            "applied_total": applied_total,
            # the SAVING run's cadence bounds how far past this snapshot
            # the server can have published before a crash — the resume
            # jump must use it, not the restarting run's (possibly
            # smaller) one
            "checkpoint_every": self.every,
        })
        self.ckpt._last_ps_step = applied_total

    def maybe_save(self, params, state, server, applied_total: int) -> None:
        if self.every and applied_total - self.last_saved >= self.every:
            self._save(params, state, server, applied_total)
            self.last_saved = applied_total

    def final_save(self, params, state, server, applied_total: int) -> None:
        self._save(params, state, server, applied_total)


def serve(
    server,
    cfg: Dict[str, Any],
    total_grads: int,
    *,
    sync_barrier: bool = False,
    total_received: Optional[int] = None,
    timeout: float = 300.0,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    on_tick=None,
    stop_when=None,
) -> Tuple[PyTree, Dict[str, float]]:
    """Server body: poll → (decode) → jitted optimizer update → publish.

    ``total_grads`` counts APPLIED gradients (stale drops don't count).
    When ``total_received`` is given, the loop instead runs until that
    many gradients were CONSUMED (applied + stale-dropped) — the right
    stop condition when workers push a fixed count and some pushes are
    expected to be dropped (otherwise their final blocked pushes would
    time out). Returns (final params, metrics incl. steps/sec and final
    loss on a held-out evaluation batch).

    Checkpointing closes the SERVER side of the failure story (workers
    are already elastic): with ``checkpoint_dir`` set, the full PS state
    (params, optimizer state, publish version, applied count) is saved
    every ``checkpoint_every`` applied gradients; a replacement server
    started with ``resume=True`` restores the latest snapshot and keeps
    the version counter monotonic, so training continues where the dead
    server left off — workers just reconnect and read the next snapshot
    (the reference's MPI job had no analog: a rank-0 death ended the
    job, SURVEY §5.4/§5.3). ``applied``/counters restart per serve call;
    the restored ``applied_total`` rides in the metrics.

    Telemetry (``cfg`` keys, so one dict arms server and workers):

    - ``telemetry_dir``: enables the FlightRecorder here AND in every
      spawned worker (cfg rides the spawn argv); each process dumps its
      JSONL into the directory at exit (``server.jsonl``,
      ``worker-N.jsonl``) and the path rides the returned metrics as
      ``telemetry_jsonl``. Disabled (the default), the loop pays one
      None-check per gradient.
    - ``metrics_port``: start the Prometheus ``/metrics`` (+ ``/health``)
      HTTP endpoint (both transports — the endpoint renders live Python
      state on a daemon thread; 0 = auto-assign). The bound port is
      returned as ``metrics_port`` in the metrics and the endpoint stays
      up until ``server.close()``. Either way the serve loop feeds
      step-latency and straggler-wait histograms into
      ``server.scrape_registry()`` — also scrapable in-process via
      ``server.prometheus_text()``.

    Online diagnosis (``telemetry.diagnosis``): ``health_dir`` (worker
    beacon files + the HealthMonitor), ``health_port`` (serve ``/health``
    + ``/metrics`` over HTTP when ``metrics_port`` isn't set; same
    endpoint), or ``health: true`` (monitor only — verdicts ride the
    returned metrics as ``health``) arm a :class:`HealthMonitor` fed
    from INSIDE this loop: per-gradient EWMA/MAD anomaly flags, beacon
    tailing at tick cadence, and sync-round critical-path gating. Armed,
    the scrape registry additionally carries ``ps_worker_anomaly_total``,
    ``ps_round_gating_seconds`` and ``ps_worker_health`` per worker.

    Numerics observability (``telemetry.numerics``): ``numerics: true``
    (or ``numerics_dir`` / ``numerics_kw``) arms a
    :class:`NumericsMonitor` — every consumed push is validated BEFORE
    it can touch the optimizer (non-finite pushes counted per worker
    through ``_reject_frame``, the worker quarantined, the push skipped
    / sanitized / run-aborting per ``numerics_kw["policy"]``), grad-norm
    and update-to-weight-ratio statistics flow into the canonical
    metrics and ``/health``'s ``numerics`` section, workers append
    codec-fidelity probe rows into ``numerics_dir`` (tailed at tick
    cadence), and a NaN or norm spike writes a ``postmortem-*.json``
    divergence capture. An abort lands in the returned metrics as
    ``numerics_abort``.

    Gradient lineage (``telemetry.lineage``): ``lineage: true`` (or
    ``lineage_dir``) arms a :class:`LineageTracker` — every framed push
    carries a causal trace ID (worker, step, seq) + encode-site
    timestamp from the v2 frame header, ``framed_poll`` feeds the
    tracker per consumed push, and every published version gets a
    recorded lineage row (the exact composing pushes with staleness,
    bytes and per-stage wall times) in ``lineage-server.jsonl``. Exact
    per-push e2e latency/staleness join the canonical metrics
    (``push_e2e_p50_ms`` etc) and the scrape registry
    (``ps_push_e2e_seconds`` histogram), sync rounds get stage-level
    critical-path rows, and the snapshot rides the returned metrics as
    ``lineage``. Requires ``frame_check`` (the trace ID rides the frame
    header); skipped with a printed notice otherwise.

    Round anatomy (``telemetry.anatomy``): armed automatically with
    lineage (``cfg["anatomy"]`` defaults to ``"auto"``; ``False`` opts
    out) — every published version is decomposed into its exact
    stage-level critical path (produce / encode / wire / leader-fold /
    root-fold / optimizer-publish, clock-skew-corrected, composed
    trailers expanding tree hops) with Coz-style what-if projections,
    written as ``anatomy-server.jsonl`` rounds. The ``anatomy_*``
    canonical keys join the metrics/scrape/TSDB surfaces, ``/health``
    gains an ``anatomy`` section, the controller's wire-vs-compute
    regime inputs switch to the lineage-derived estimator, and the
    final snapshot (incl. the ranked advisor) rides the returned
    metrics as ``anatomy``.

    Parameter serving (:mod:`pytorch_ps_mpi_tpu.serving`): the loop now
    sits on a :class:`~pytorch_ps_mpi_tpu.serving.ServingCore` that owns
    the monitor plumbing above plus — when ``cfg["serving"]`` or
    ``cfg["read_port"]`` (0 = auto) arms it — the read tier: every
    publish lands an immutable refcounted snapshot in a ring of the last
    K versions; readers issue version-conditional reads answered as
    not-modified / codec-encoded delta / full snapshot, identical
    requests coalesce onto one encode, and a bounded admission queue
    sheds overload with explicit retry-after replies
    (``cfg["serving_kw"]`` tunes ring/admission/delta knobs). Read-tier
    counters join the canonical metrics (``reads_total``,
    ``read_p50_ms/p95_ms``, ``delta_bytes_saved``, ``reads_shed``,
    ``coalesce_hits``, ``reads_not_modified``) and ``/health`` gains a
    ``serving`` section; the bound port rides the returned metrics as
    ``read_port`` and the listener lives until ``server.close()``,
    exactly like the metrics endpoint. Unarmed, publishes degrade to the
    transport's own publish — the legacy path pays nothing.

    Homomorphic aggregation (``cfg["agg"]``: ``"auto"`` default /
    ``"on"`` / ``"off"``): in sync-barrier mode over a codec wire whose
    algebra supports it (``Codec.supports_aggregate`` — int8/qsgd in the
    integer domain, top-k/random-k/threshold by sparse index-merge,
    terngrad in the ternary-count domain, PowerSGD by factor concat,
    sign by per-element vote counts), the loop stops decoding per push:
    payloads queue in compressed form, each round folds one payload per
    active worker into a :class:`~pytorch_ps_mpi_tpu.parallel.dcn.
    WireAggregator`, and exactly ONE decode runs per published version
    (``decodes_per_publish == 1`` in the canonical metrics; ``agg_mode``
    1.0). Per-push server cost becomes a function of PAYLOAD size, and
    the ``[world, ...]`` decoded stack never exists. Falls back to
    decode-sum automatically — async mode, no codec, a codec without
    the algebra, or an armed numerics monitor (its per-push validation
    needs decoded trees) — counting ``agg_fallbacks`` when ``"on"``
    asked explicitly. The sign vote algebra is APPROXIMATE (exact when
    per-push scales agree; measured rel-error in
    ``benchmarks/fidelity_bench.py --aggregate``), so ``"auto"`` never
    arms it — approximate algebras require an explicit ``"on"``, the
    opt-in to that fidelity contract.

    Fleet observability plane (``telemetry.timeseries`` / ``.slo`` /
    ``.profiler`` / ``.fleet``): ``cfg["timeseries"]`` retains every
    canonical metric key as ring-buffered history (raw + 1 s/10 s/60 s
    tiers), sampled at this loop's tick cadence on this thread,
    persisted as ``timeseries-server.jsonl`` and served at
    ``/history?key=...&window=...``; ``cfg["slo"]`` arms the burn-rate
    watchdog over that history (verdicts into ``slo-server.jsonl``, the
    flight recorder, ``/health``'s ``slo`` section and the
    ``ps_slo_*`` instruments); ``cfg["profile"]`` runs the continuous
    sampling profiler (``profile-server.txt`` collapsed stacks, and in
    every spawned worker too); ``cfg["fleet_dir"]`` registers this
    server's endpoint for the fleet pane and serves the merged
    ``/fleet`` snapshot. Final sections ride the returned metrics as
    ``history`` / ``slo`` / ``profile``; the routes stay scrapable
    until ``server.close()``.

    Self-driving control plane (:mod:`pytorch_ps_mpi_tpu.control`):
    ``cfg["control"]`` (or ``control_kw`` / ``control_dir``) arms a
    :class:`Controller` fed at this loop's tick + consume sites. It
    renegotiates the wire codec/``bucket_mb``/agg-mode online from the
    measured wire-vs-compute balance (an epoch bump through the frame
    fingerprint handshake — workers poll ``control-epoch.json`` in
    ``control_dir`` and in-flight old-epoch frames are consumed, never
    rejected), applies staleness-aware per-push LR weights (AsySG-InCon
    bound; decode paths only — a compressed payload cannot be scaled),
    backoff-evicts churn-verdict workers from the sync barrier and
    readmits quarantined workers after a clean probation, and tunes the
    read tier's admission depth + snapshot ring from shed/ageout rates.
    Every action lands in ``control-server.jsonl`` with its triggering
    verdict; the input rows persist through the TSDB
    (``timeseries-control-server.jsonl``) so ``Controller.replay``
    re-derives the identical sequence. The final snapshot rides the
    returned metrics as ``control``.

    Resilience hooks:

    - ``on_tick``: called from INSIDE the loop (same thread as every
      native-transport call — a supervisor's watchdog never races a
      pump) at most every ``cfg["tick_interval"]`` seconds (default
      0.2); used to respawn dead workers.
    - ``stop_when``: extra stop predicate, checked at tick cadence; once
      true the loop drains the already-queued gradients and returns.
      The supervisor's "every worker exited cleanly" condition — exact
      push counts are unknowable under drop/duplicate/corrupt faults.
    - ``cfg["fault_plan"]``: server-targeted faults
      (``worker: "server"``) fire when the APPLIED count crosses their
      ``at_step`` — ``crash_server`` raises
      :class:`~pytorch_ps_mpi_tpu.resilience.faults.InjectedServerCrash`
      out of the loop WITHOUT the final checkpoint save (a crash doesn't
      get one; the periodic cadence is the resume point).
    - ``sync_barrier`` degraded rounds: when a round has been waiting
      longer than ``cfg["degraded_round_after"]`` seconds (default 5),
      workers that are transport-dead (no socket / flagged straggler)
      and have nothing queued are excluded and the round completes over
      the surviving workers — counted in ``degraded_rounds`` and
      ``ps_degraded_rounds_total`` instead of hanging forever. A dead
      worker that comes back (elastic replacement) rejoins the barrier
      the moment its next gradient arrives. Caveat for the shm
      transport: silence is its only death signal, so a LIVE worker
      whose healthy round legitimately exceeds the window is
      indistinguishable from a dead one and gets temporarily excluded
      (its late gradients still apply — it rejoins on arrival, nothing
      is lost) — size ``degraded_round_after`` above the slowest
      expected round. TCP uses the open socket as a positive liveness
      signal and does not have this ambiguity.
    """
    import jax

    from pytorch_ps_mpi_tpu.optim import OPTIMIZERS

    _, params, batch_fn, loss_fn = make_problem(cfg)
    hyper_cls, init_state, update_fn = OPTIMIZERS[cfg.get("optim", "sgd")]
    h = hyper_cls(**cfg.get("hyper", {"lr": 0.05}))
    state = init_state(params)
    update = jax.jit(lambda p, g, s: update_fn(p, g, s, h))
    eval_loss = jax.jit(loss_fn)
    eval_batch = batch_fn(10**6, 10**6)  # never used by any worker

    ckpt = None
    applied_before = 0
    if resume and not checkpoint_dir:
        raise ValueError("resume=True requires checkpoint_dir")
    if checkpoint_dir:
        from pytorch_ps_mpi_tpu.utils.checkpoint import CheckpointManager

        ckpt = CheckpointManager(checkpoint_dir)
        if resume:
            params, state, applied_before, server.version = (
                _restore_ps_checkpoint(ckpt, params, state, checkpoint_every)
            )

    rec = _telemetry_from_cfg(cfg, worker="server")
    reg = server.scrape_registry()
    h_update = reg.histogram(
        "ps_update_seconds", _LATENCY_BUCKETS,
        "optimizer update + publish wall per applied round",
    )
    h_wait = reg.histogram(
        "ps_poll_wait_seconds", _LATENCY_BUCKETS,
        "idle poll time preceding each consumed gradient (straggler wait)",
    )
    g_applied = reg.gauge(
        "ps_applied_total", "gradients applied this serve call"
    )
    # the reusable serving core owns everything that is NOT the trainer
    # loop: monitor plumbing (health / numerics / lineage — construction
    # unchanged, just extracted), the /metrics + /health endpoint, and —
    # when cfg["serving"] / cfg["read_port"] arm it — the snapshot ring
    # + delta/coalescing/admission read tier that serves readers without
    # this loop's involvement (see pytorch_ps_mpi_tpu.serving)
    from pytorch_ps_mpi_tpu.serving import ServingCore

    core = ServingCore(server, cfg)
    monitor = core.health
    numon = core.numerics
    lint = core.lineage
    metrics_http_port = core.metrics_http_port
    numerics_probe_every = int(numon.knobs["probe_every"]) if numon else 0

    from pytorch_ps_mpi_tpu.resilience.faults import (
        FaultInjector,
        InjectedServerCrash,
    )

    inj = FaultInjector.from_cfg(cfg, role="server")

    # -- self-driving control plane (cfg["control"] / "control_kw") -------
    # The Controller closes the verdict→action loop: fed at the SAME
    # on_tick/consume sites as the monitors above (no thread ever
    # touches a native handle), it renegotiates the wire codec from the
    # measured wire-vs-compute balance (epoch bump through the frame
    # fingerprint handshake — in-flight old-epoch frames are consumed,
    # not rejected), de-weights stale workers' pushes per the
    # AsySG-InCon bound (applied below as a per-push weight — no
    # worker-side change), backoff-evicts churning workers from the
    # sync barrier and readmits quarantined ones after a clean
    # probation, and tunes the read tier's admission depth + snapshot
    # ring. Every action is a recorded, replayable, reversible event
    # row (control-server.jsonl); Controller.replay() re-derives the
    # identical sequence from the persisted TSDB input rows.
    # Constructed BEFORE the aggregation arming below: a restarted
    # generation may restore the fleet's current wire epoch here, and
    # the agg decision must see the RESTORED wire (and the restore must
    # never race an already-set agg_mode).
    ctl = None
    if cfg.get("control") or cfg.get("control_kw") or cfg.get("control_dir"):
        from pytorch_ps_mpi_tpu.control import Controller

        ctl = Controller(server, cfg, core=core)

    # -- homomorphic aggregation (cfg["agg"]: "auto" | "on" | "off") ------
    # Armed, the sync-barrier loop stops decoding per push: each arriving
    # payload is kept in its COMPRESSED form, a round folds one payload
    # per active worker into a CodecWire aggregator, and exactly one
    # decode happens per published version (decodes_per_publish == 1).
    # Requirements — any miss falls back to the decode-sum path, loudly
    # when "on" asked for it: a sync barrier (async mode publishes per
    # push, one decode per publish already), a codec wire whose algebra
    # supports aggregation (Codec.supports_aggregate + per-unit
    # can_aggregate; approximate algebras additionally need the explicit
    # "on"), and no numerics monitor (its per-push decoded-tree
    # validation needs the decode; the payload-level non-finite screen
    # below rides the aggregator instead).
    agg_req = str(cfg.get("agg", "auto")).lower()
    if agg_req not in ("auto", "on", "off"):
        raise ValueError(f"cfg['agg'] must be auto/on/off, got {agg_req!r}")
    wire = getattr(server, "wire", None)
    agg_armed = (
        agg_req != "off" and sync_barrier and wire is not None
        and getattr(wire, "agg_supported", False) and numon is None
        # an APPROXIMATE algebra (sign's vote counts, agg_exact=False)
        # changes training numerics, so "auto" never arms it — only an
        # explicit cfg["agg"] = "on" opts into the measured fidelity
        # contract; exact algebras arm under "auto" (bit-identical)
        and (agg_req == "on"
             or getattr(wire.code, "agg_exact", True))
    )
    if agg_req == "on" and not agg_armed:
        why = ("no sync barrier" if not sync_barrier
               else "no codec wire" if wire is None
               else "codec lacks an aggregation algebra"
               if not getattr(wire, "agg_supported", False)
               else "numerics monitor armed")
        print(f"compressed-domain aggregation requested but not armed "
              f"({why}); falling back to decode-sum", flush=True)
    server.agg_mode = 1.0 if agg_armed else 0.0
    if ctl is not None:
        ctl.set_agg(agg_armed)

    def _agg_now() -> bool:
        """Compressed-domain folding is live only while no controller
        transition needs the decode path: a codec renegotiation first
        suspends aggregation (mixed-epoch payloads cannot share one
        accumulator), then bumps the epoch, then re-arms — and only
        while the CURRENT wire (a renegotiation may have replaced the
        boot one) actually supports the algebra under the same
        exactness policy the boot check enforced."""
        if not agg_armed:
            return False
        if ctl is not None and ctl.agg_suspended:
            return False
        if getattr(server, "_epoch_table", None):
            return False  # old-epoch frames may still be in flight
        w = server.wire
        if w is not wire:
            # renegotiated wire: re-validate the algebra (cached per
            # wire object — agg_supported walks every unit)
            ok = w.__dict__.get("_agg_ok_cached")
            if ok is None:
                ok = w.agg_supported and (
                    agg_req == "on"
                    or getattr(w.code, "agg_exact", True))
                w.__dict__["_agg_ok_cached"] = ok
            if not ok:
                return False
        return True

    loss0 = float(eval_loss(params, eval_batch))
    core.publish(params)
    applied = 0
    degraded_rounds = 0
    last_applied_total = applied_before
    cadence = (_PSCheckpointCadence(ckpt, checkpoint_every, applied_before)
               if ckpt else None)
    n_workers = server.num_workers
    # -- hierarchical-tree root mode (cfg["tree"], parallel.tree) ---------
    # The expected pusher set is no longer range(n_workers): leaders
    # (ids cfg["tree_members"]) push composed group aggregates, and leaf
    # workers appear dynamically only when their leader died and they
    # fell back to pushing directly. The sync barrier therefore runs
    # over a MEMBERSHIP-DYNAMIC active set, and every round is averaged
    # by the TOTAL composed worker-push count carried in the frames'
    # lineage trailers (one per direct push), which keeps the weighting
    # exact across degraded groups, ragged group sizes and fallback
    # pushes without any coordination.
    tree_mode = bool(cfg.get("tree"))
    tree_members: set = set(int(w) for w in (cfg.get("tree_members") or ()))
    tree_joined: set = set()
    # sync_barrier holds a FIFO per worker: the server pops mailboxes
    # eagerly (the single-slot mailbox never back-pressures a fast
    # worker), so a worker may deliver several gradients before a
    # straggler's first — queueing them, not overwriting, keeps the
    # oracle a true synchronous PS in which EVERY gradient enters exactly
    # one averaged round.
    import collections

    pending: Dict[int, Any] = collections.defaultdict(collections.deque)
    # critical-path bookkeeping for the monitor: when each worker FIRST
    # became ready (had something queued) in the current sync round
    round_ready: Dict[int, float] = {}
    dead_workers: set = set()
    c_degraded = reg.counter(
        "ps_degraded_rounds_total",
        "sync-barrier rounds completed over a partial fleet "
        "(transport-dead workers excluded)",
    )
    degrade_after = float(cfg.get("degraded_round_after", 5.0))
    tick_interval = float(cfg.get("tick_interval", 0.2))
    t0 = time.perf_counter()
    deadline = t0 + timeout

    def keep_going():
        if total_received is not None:
            return server.grads_received < total_received
        return applied < total_grads

    wait_t0 = time.perf_counter()
    round_t0 = time.perf_counter()
    next_tick = 0.0
    draining = False
    numerics_stop = False
    next_numerics_probe = 0  # applied count of the next update-ratio probe
    # native batched ingest (TCP + frames + native fast path): one C++
    # pump-and-pop drains every queued push, validated, per call; the
    # inbox serves them to the identical per-item bookkeeping below. In
    # raw (aggregation) mode the items are VIEWS into the transport's
    # batch buffer — consumed (copied into their round queue) before the
    # next batched pop, which only happens once the inbox is empty.
    batch_poll = getattr(server, "poll_grad_batch", None)
    inbox: collections.deque = collections.deque()

    def _next_item():
        # items ride the inbox tagged with the WIRE they were validated
        # against at POLL time (None = decoded): a controller agg
        # suspension or epoch bump mid-inbox must neither reinterpret
        # already-polled payload views as decoded trees nor mis-decode
        # them with a renegotiated wire installed after the poll
        if inbox:
            return inbox.popleft()
        raw = _agg_now()
        enc = server.wire if raw else None
        if batch_poll is not None:
            batch = batch_poll(raw=raw)
            if batch is not None:
                inbox.extend((it, enc) for it in batch)
                return inbox.popleft() if inbox else None
        item = server.poll_grad(raw=raw)
        return None if item is None else (item, enc)

    def _fire_server_faults() -> None:
        """Server-targeted faults fire when the global applied count
        crosses their at_step (a sync round advances it by several at
        once). crash_server propagates AFTER the batch's faults fired
        and were logged."""
        nonlocal last_applied_total
        hi = applied_before + applied
        if inj is None or hi == last_applied_total:
            return
        crash = None
        for f in inj.faults_between(last_applied_total, hi):
            inj.fire(f)
            if f["kind"] == "crash_server":
                crash = f
            elif f["kind"] == "delay":
                time.sleep(float(f.get("delay_ms", 100.0)) / 1e3)
        last_applied_total = hi
        if crash is not None:
            raise InjectedServerCrash(crash)

    def _post_update(up_t0: float, lineage_workers=None) -> None:
        # through the serving core: the transport publish plus — when the
        # read tier is armed — one snapshot into the refcounted ring
        # (same single flatten either way)
        server.grad_publishes += 1  # decodes_per_publish denominator
        core.publish(jax.tree.map(np.asarray, params))
        up_dur = time.perf_counter() - up_t0
        h_update.observe(up_dur)
        g_applied.set(float(applied))
        if rec is not None:
            rec.event("serve.update", kind="span", ts=up_t0, dur=up_dur,
                      step=applied, version=server.version)
        if lint is not None:
            # bill the just-published version with its composing pushes
            # (one per active worker in sync-barrier mode — mirroring
            # the pending[w].popleft() above — everything pending in
            # async mode, i.e. exactly the push just applied)
            lint.observe_publish(server.version, up_dur,
                                 workers=lineage_workers)
        if cadence:
            cadence.maybe_save(params, state, server, applied_before + applied)
        _fire_server_faults()

    def _mark_dead_workers() -> None:
        """Transport-level liveness sweep, consulted only once a sync
        round has waited ``degrade_after`` seconds: TCP's ``connected``
        is a positive dead-socket signal; shm falls back to the
        stragglers silence window. A worker with a queued gradient is
        never marked — its round contribution is already here. Neither
        is a worker the server has NEVER seen: a fleet member still
        paying its multi-second startup (jax import, first compile) is
        slow, not dead — declaring it would silently shrink the oracle's
        barrier at startup. Never-started workers are the supervisor's
        problem (respawn or abandon), not the barrier's."""
        can_connect = hasattr(server, "connected")
        silent = None if can_connect else server.stragglers(degrade_after)
        for w in range(n_workers):
            if w in dead_workers or pending[w] or w not in server.last_seen:
                continue
            alive = server.connected(w) if can_connect else (w not in silent)
            if not alive:
                if tree_mode and w not in tree_members and w in tree_joined:
                    # a fallback leaf that closed its root socket went
                    # BACK to its respawned leader — it leaves the
                    # barrier's membership instead of being carried as
                    # a dead worker (which would count every later
                    # healthy round degraded); a fresh direct push
                    # re-joins it
                    tree_joined.discard(w)
                    continue
                dead_workers.add(w)
                if rec is not None:
                    rec.event("serve.worker_declared_dead", worker=w)

    def _try_complete_round(only_queued: bool = False) -> bool:
        """Complete one sync round over the ACTIVE (not declared-dead)
        workers if each has a queued gradient; degraded rounds (fewer
        than n_workers contributions) are counted, never hung on.
        Numerics-quarantined workers under the ``skip`` policy are
        excluded too: their pushes never enter ``pending``, so waiting
        on them would hang the barrier exactly like a dead worker —
        and unlike one, their socket stays open. ``only_queued`` (tree
        drain tail) completes a partial round over whatever is queued
        so no consumed frame is silently dropped from the lineage."""
        nonlocal params, state, applied, degraded_rounds, wait_t0, round_t0
        nonlocal next_numerics_probe
        if tree_mode:
            # membership-dynamic barrier: every tree member (leaders by
            # construction, fallen-back leaf workers by observation)
            # that is not declared dead must have a frame queued
            active = [w for w in sorted(tree_members | tree_joined)
                      if w not in dead_workers]
            if only_queued:
                active = [w for w in active if pending[w]]
        else:
            active = [w for w in range(n_workers) if w not in dead_workers]
        if numon is not None and numon.knobs["policy"] == "skip":
            active = [w for w in active if not numon.is_quarantined(w)]
        if ctl is not None:
            # controller-evicted (churn-verdict) workers leave the
            # barrier exactly like quarantined ones: the round completes
            # degraded over the survivors, their queued pushes are held,
            # and the backoff readmission re-includes them — the
            # existing degraded-round rejoin machinery, driven by a
            # verdict instead of a dead transport
            active = [w for w in active if not ctl.is_evicted(w)]
        if not active or any(not pending[w] for w in active):
            return False
        up_t0 = time.perf_counter()
        entries = [pending[w].popleft() for w in active]
        # read the server's CURRENT wire, not the boot-time capture: a
        # controller renegotiation replaces server.wire mid-run
        cur_wire = server.wire
        if _agg_now() and all(e[3] is cur_wire for e in entries):
            # compressed-domain round: fold one queued payload per
            # active worker into the wire aggregator, then ONE decode
            # (never a [world, ...] decoded stack, never per-push
            # decodes) — the averaged result feeds the same jitted
            # update the decode-sum path does. Folding requires every
            # entry raw AND encoded with the CURRENT wire (entries
            # carry their encode wire — a renegotiation between queue
            # and round sends them down the decode path instead). The
            # mean's denominator is the COMPOSED push count (frames
            # carry group sums in tree mode; 1 per frame otherwise, so
            # this is exactly the old 1/len(active)). Controller LR
            # weights do NOT apply here — a compressed payload cannot
            # be scaled per push (documented in docs/OPERATIONS.md).
            agg = cur_wire.agg_begin()
            total_comp = 0
            for buf, comp_n, _wgt, _wire in entries:
                agg.fold(buf)
                total_comp += comp_n
            server.decodes_done += 1
            inv = np.float32(1.0 / total_comp)
            summed = jax.tree.map(lambda x: x * inv, agg.finalize())
            n_contrib = agg.frames
        else:
            batch_grads, wgts = [], []
            total_comp = 0
            for g, comp_n, wgt, enc_wire in entries:
                if enc_wire is not None:
                    # a payload queued raw before the controller
                    # suspended aggregation (or before an epoch bump):
                    # decode it now with the wire it was ENCODED with
                    # (counted in decodes_done like any decode-sum push)
                    g = server._decode_payload(g, wire=enc_wire)
                batch_grads.append(g)
                wgts.append(float(wgt))
                total_comp += comp_n
            if all(wt == 1.0 for wt in wgts):
                # bit-identical to the pre-control decode-sum round
                summed = jax.tree.map(
                    lambda *gs: sum(gs) / total_comp, *batch_grads)
            else:
                # staleness-aware per-push LR scaling (AsySG-InCon):
                # de-weighted pushes contribute a smaller step; the
                # denominator stays the composed count, so a weight
                # only ever SHRINKS the stale worker's effective LR
                summed = jax.tree.map(
                    lambda *gs: sum(wt * gg for wt, gg
                                    in zip(wgts, gs)) / total_comp,
                    *batch_grads)
            n_contrib = len(batch_grads)
        probe = numon is not None and applied >= next_numerics_probe
        old_params = params if probe else None
        params, state = update(params, summed, state)
        applied += n_contrib
        if probe:
            numon.observe_update(old_params, params,
                                 applied_before + applied)
            next_numerics_probe = applied + numerics_probe_every
        if monitor is not None:
            # bill the round's critical path to the last-ready worker,
            # then reopen the book: a fast worker with another gradient
            # already queued is ready for the NEXT round right now
            monitor.observe_round(round_ready, active)
            round_ready.clear()
            for w2 in range(n_workers):
                if pending[w2]:
                    round_ready[w2] = up_t0
        degraded = (bool(dead_workers) if tree_mode
                    else n_contrib < n_workers)
        if degraded:
            degraded_rounds += 1
            c_degraded.inc()
            if rec is not None:
                rec.event("serve.degraded_round", step=applied,
                          absent=sorted(dead_workers))
        _post_update(up_t0, lineage_workers=active)
        wait_t0 = round_t0 = time.perf_counter()
        return True

    while keep_going() and time.perf_counter() < deadline:
        now = time.perf_counter()
        if now >= next_tick:
            next_tick = now + tick_interval
            if on_tick is not None:
                on_tick()
            # monitor upkeep (beacon/probe tailing), same thread
            core.tick()
            if ctl is not None:
                # the verdict→action sweep (self-throttled): builds one
                # input row, persists it, runs the decision engine,
                # executes any actions — all on this thread
                ctl.tick()
                if agg_armed:
                    server.agg_mode = 1.0 if _agg_now() else 0.0
            if stop_when is not None and not draining and stop_when():
                draining = True  # consume what's queued, then return
            if sync_barrier and now - round_t0 > degrade_after:
                _mark_dead_workers()
                while _try_complete_round():
                    pass
        pair = _next_item()
        if pair is None:
            if draining:
                break
            time.sleep(0.0005)
            continue
        (wid, grad_version, grad), item_wire = pair
        item_raw = item_wire is not None
        # tree mode: the frame's composed worker-push count (from its
        # lineage trailer), queued by the framed consume path in item
        # order — the round mean's per-frame weight; 1 otherwise
        comp_n = (server._composed_queue.popleft()
                  if tree_mode and getattr(server, "tree_slots", 0) else 1)
        if item_raw:
            # payload-level non-finite screen (the aggregation path's
            # stand-in for the numerics monitor's decoded-tree check,
            # which can't run here — arming requires numon off): a push
            # whose float payload leaves are non-finite would poison the
            # compressed accumulator, so reject it like any bad frame
            # and let the barrier wait for the worker's next push (the
            # same consumed-but-skipped discipline as numerics "skip")
            if not item_wire.payload_finite(grad):
                server._reject_frame(wid, "nonfinite")
                if lint is not None:
                    lint.discard_last(wid, reason="nonfinite")
                wait_t0 = time.perf_counter()
                continue
            # grad is the validated payload BYTES (a view into the
            # receive buffer): one payload-sized copy queues it for its
            # round — the per-push cost, in place of a jitted decode +
            # full-tree rebuild
            grad = np.copy(grad)
        elif agg_armed:
            # the controller suspended folding (codec-renegotiation
            # window) so this push arrived DECODED — but the numerics
            # monitor is off by the agg arming rule, so the aggregation
            # path's non-finite screen must follow the push onto the
            # decode path or a NaN gradient would reach the optimizer
            # during exactly the transition window
            if not all(bool(np.all(np.isfinite(np.asarray(leaf))))
                       for leaf in jax.tree.leaves(grad)):
                server._reject_frame(wid, "nonfinite")
                if lint is not None:
                    lint.discard_last(wid, reason="nonfinite")
                wait_t0 = time.perf_counter()
                continue
        elif agg_req == "on":
            server.agg_fallbacks += 1
        wait_s = time.perf_counter() - wait_t0
        h_wait.observe(wait_s)
        staleness = max(0, server.version - grad_version)
        if rec is not None:
            rec.event("serve.grad", worker=wid, staleness=staleness,
                      step=applied, version=grad_version)
        if monitor is not None:
            monitor.observe_grad(wid, staleness, wait_s)
        if ctl is not None:
            # the controller's consume-site feed: per-worker staleness
            # (the lr_scale rule's fallback input when lineage's exact
            # windows are unarmed)
            ctl.observe_push(wid, staleness)
        if numon is not None:
            # numerics validation BEFORE the gradient can touch the
            # optimizer: count/quarantine non-finite pushes, then let
            # the policy decide the frame's fate
            action = numon.observe_push(wid, grad, applied_before + applied)
            if action == "abort":
                numerics_stop = True
                if lint is not None:
                    # the consumed push will never compose a version —
                    # give it its own drop row instead of leaking it
                    # into the next publish's lineage
                    lint.discard_last(wid, reason="numerics")
                break
            if action == "skip":
                if lint is not None:
                    lint.discard_last(wid, reason="numerics")
                wait_t0 = time.perf_counter()
                continue
            if action == "zero":
                from pytorch_ps_mpi_tpu.telemetry.numerics import (
                    sanitize_tree,
                )

                grad = sanitize_tree(grad)
        if sync_barrier:
            # synchronous oracle: a round completes when every active
            # worker has at least one queued gradient; one per worker is
            # consumed. A gradient from a declared-dead worker proves it
            # back alive (elastic replacement) — it rejoins the barrier.
            dead_workers.discard(wid)
            if tree_mode:
                tree_joined.add(wid)
            if ctl is not None and ctl.is_evicted(wid):
                # a backoff-evicted worker's pushes are DROPPED, not
                # queued: an unbounded pending backlog would re-apply
                # seconds-stale gradients one round at a time after
                # readmission. Same consumed-but-skipped discipline as
                # numerics "skip" — minus the rejection counter, which
                # feeds the churn verdict and would re-evict the worker
                # the moment it was readmitted. It rejoins the barrier
                # with its first post-readmission push.
                if lint is not None:
                    lint.discard_last(wid, reason="evicted")
                if rec is not None:
                    rec.event("serve.evicted_drop", worker=wid)
                wait_t0 = time.perf_counter()
                continue
            pending[wid].append((
                grad, comp_n,
                ctl.push_weight(wid) if ctl is not None else 1.0,
                item_wire))
            if monitor is not None and wid not in round_ready:
                round_ready[wid] = time.perf_counter()
            if not _try_complete_round():
                wait_t0 = time.perf_counter()
        else:
            up_t0 = time.perf_counter()
            probe = numon is not None and applied >= next_numerics_probe
            old_params = params if probe else None
            wgt = ctl.push_weight(wid) if ctl is not None else 1.0
            if wgt != 1.0:
                # staleness-aware per-push LR scaling (AsySG-InCon
                # bound): the stale worker's update shrinks; comp_n
                # folds into the same map below
                grad = jax.tree.map(lambda x: x * wgt / comp_n, grad)
            elif comp_n > 1:
                # a composed frame carries its group's SUM: apply the
                # group mean so the async step size is load-independent
                grad = jax.tree.map(lambda x: x / comp_n, grad)
            params, state = update(params, grad, state)
            applied += 1
            if probe:
                # ||dp||/||p|| at probe cadence only — the old params
                # are retained just long enough for one jitted diff
                numon.observe_update(old_params, params,
                                     applied_before + applied)
                next_numerics_probe = applied + numerics_probe_every
            _post_update(up_t0)
            wait_t0 = time.perf_counter()
    if tree_mode and sync_barrier:
        # drain tail: frames consumed but still queued when the stop
        # condition fired compose one final partial round each, so
        # every consumed push lands in some version's lineage
        while _try_complete_round(only_queued=True):
            pass
    wall = time.perf_counter() - t0
    if cadence:  # final state always captured, whatever the stop reason
        cadence.final_save(params, state, server, applied_before + applied)
    if numon is not None:
        # drain the last worker probe rows BEFORE any metrics snapshot:
        # server.metrics() (and the /health snapshot below) read the
        # probe-derived gauges, and the workers' final rows typically
        # land after the loop's last tick
        numon.tick()
        # one closing trajectory row so offline tooling sees the FINAL
        # grad-norm/nonfinite state, not the last probe-cadence sample
        numon._trajectory_row(applied_before + applied)
    m = dict(server.metrics())
    m.update(
        applied=float(applied),
        applied_total=float(applied_before + applied),
        wall_s=wall,
        updates_per_sec=applied / wall if wall > 0 else 0.0,
        loss_initial=loss0,
        loss_final=float(eval_loss(params, eval_batch)),
        staleness_hist={int(k): int(v) for k, v in server.staleness_seen.items()},
        publish_version=float(server.version),
        degraded_rounds=float(degraded_rounds),
        frames_rejected_by_worker={
            int(k): int(v)
            for k, v in getattr(server, "frames_rejected", {}).items()
        },
    )
    if metrics_http_port is not None:
        m["metrics_port"] = metrics_http_port
    if core.armed:
        # read-tier rollup (ring occupancy, read counts, shed/coalesce);
        # the read server itself stays up until server.close(), exactly
        # like the /metrics endpoint
        m["serving"] = core.serving_snapshot()
        if core.read_port is not None:
            m["read_port"] = core.read_port
    if monitor is not None:
        m["health"] = monitor.snapshot()
    if numon is not None:
        m["numerics"] = numon.snapshot()
        if numerics_stop:
            m["numerics_abort"] = numon.aborted
        numon.close()
    if lint is not None:
        m["lineage"] = lint.snapshot()
        lint.close()
    if core.anatomy is not None:
        # the round-anatomy section: per-stage critical-path shares and
        # the ranked what-if advisor (projected round-time savings) —
        # what tools/whatif_smoke.py gates and RESULTS.md tabulates
        m["anatomy"] = core.anatomy.snapshot()
        core.anatomy.close()
    if ctl is not None:
        snap = ctl.snapshot()
        # zero-frame-loss accounting for codec renegotiations: every
        # old-epoch frame consumed during a transition is counted here
        # (they would have been "config" rejections without the epoch
        # table)
        snap["epoch_old_frames"] = int(
            getattr(server, "epoch_old_frames", 0))
        m["control"] = snap
        ctl.close()
    if server.timeseries_db is not None:
        # one closing sample so the retained history ends on the FINAL
        # counter state, not the last tick-cadence snapshot (force: the
        # ingest throttle must not drop the run's last word)
        server.timeseries_db.sample(server.metrics(), force=True)
    obs = server.finalize_observability()
    if obs:
        # the observability-plane sections: "history" (TSDB meta),
        # "slo" (rule states + verdicts), "profile" (top-N + file).
        # /history and /fleet stay scrapable — and the fleet
        # registration stays live — until server.close().
        m.update(obs)
    if cfg.get("telemetry_dir"):
        # final scrape snapshot for offline tooling: telemetry_report
        # tabulates the labeled series (per-worker rejections, anomaly
        # counts) from this file next to the recorder JSONLs
        prom_path = os.path.join(cfg["telemetry_dir"], "metrics.prom")
        os.makedirs(cfg["telemetry_dir"], exist_ok=True)
        with open(prom_path, "w") as f:
            f.write(server.prometheus_text())
        m["metrics_prom"] = prom_path
    jsonl = _dump_recorder(cfg, rec, "server.jsonl")
    if jsonl is not None:
        m["telemetry_jsonl"] = jsonl
    return params, m


def spawn_worker(name: str, worker_id: int, cfg: Dict[str, Any],
                 env: Optional[Dict[str, str]] = None):
    """Launch ``worker_main`` in a fresh OS process (its own JAX runtime,
    pinned to the host backend so tests/benches never contend for the one
    tunneled TPU chip)."""
    import json
    import os
    import subprocess
    import sys

    src = (
        "import json,sys\n"
        # the axon TPU plugin ignores the JAX_PLATFORMS env var; the
        # config flag is the pin it respects (workers must never contend
        # for the one tunneled chip)
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_ps_mpi_tpu.parallel.async_train import worker_main\n"
        "name, wid, cfg = sys.argv[1], int(sys.argv[2]), json.loads(sys.argv[3])\n"
        "sys.exit(0 if worker_main(name, wid, cfg) >= 0 else 1)\n"
    )
    e = dict(os.environ)
    e.update({"JAX_PLATFORMS": "cpu"})
    e.update(env or {})
    return subprocess.Popen(
        [sys.executable, "-c", src, name, str(worker_id), json.dumps(cfg)],
        env=e,
    )


def join_workers(procs, timeout: float = 120.0):
    """Reap a fleet of spawned worker processes without ever leaking one.

    Waits up to ``timeout`` seconds TOTAL for the fleet, then terminates
    (SIGTERM, escalating to SIGKILL) whatever is still running — on the
    happy path a plain join, on every failure path (timeout, exception
    mid-join, stuck worker) a guaranteed reap. Returns the list of exit
    codes in ``procs`` order (negative = killed by that signal), so
    callers can assert ``== [0, ...]`` where they used to loop
    ``p.wait()`` — which leaked every later process when an earlier one
    failed the assert.
    """
    import subprocess

    codes = [None] * len(procs)
    deadline = time.time() + timeout
    try:
        for i, p in enumerate(procs):
            left = deadline - time.time()
            if left <= 0:
                break
            try:
                codes[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                pass  # reaped in finally
    finally:
        for i, p in enumerate(procs):
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass  # unkillable (kernel-stuck); nothing left to do
            if codes[i] is None:
                codes[i] = p.returncode
    return codes
