"""Parallelism strategies.

The reference is data-parallel only (SURVEY §2.5); this package provides
its two DP topologies plus the async mode, and completes the parallelism
matrix beyond it — sequence/context (ring attention), tensor (Megatron),
pipeline (GPipe), and expert (GShard MoE) parallelism, all composing
over one ``jax.sharding.Mesh``.

- ``dp``: functional sync data-parallel train-step builder (decentralized
  allgather-sum and leader-PS topologies — reference ``ps.py:75`` and
  ``mpi_comms.py:60-133``).
- ``async_ps``: AsySG-InCon bounded-staleness asynchronous training
  (reference README.md:56-81, Lian et al. 2015).
- ``async_train``: the full async stack across OS processes with real
  jitted compute (workers: jitted value_and_grad -> codec encode -> shm
  payload bytes; server: jitted decode + fused updates in arrival order).
- ``dcn``: the multi-process shared-memory PS transport + codec wire.
- ``tcp``: the cross-host PS transport (native TCP, the DCN role) with
  the same server/worker surface as ``dcn`` — ``async_train`` runs over
  either via ``cfg["transport"]``.
- ``sharded``: sharded parameter servers over TCP (Li et al. OSDI'14) —
  S server processes each owning a slice of the flat parameter vector,
  per-shard versions/staleness; the cross-host instantiation of the
  ZeRO-1 partitioning the in-XLA leader mode does on-device.
- ``ring``: ring attention over a sequence-sharded mesh axis (context
  parallelism; no reference analog — TPU-first extension).
- ``ulysses``: the all-to-all flavor of sequence parallelism (DeepSpeed-
  Ulysses): one head/seq exchange each way, plain attention in between.
- ``tp``: Megatron column/row tensor parallelism (one psum per block).
- ``pp``: GPipe microbatch pipeline parallelism (scan + ppermute,
  backward via autodiff; vma-checked shard_map required).
- ``ep``: GShard top-1 MoE expert parallelism (capacity dispatch +
  all_to_all; vma-checked shard_map when differentiating).
"""

from pytorch_ps_mpi_tpu.parallel.dp import make_sync_train_step
from pytorch_ps_mpi_tpu.parallel.async_ps import AsyncPS
from pytorch_ps_mpi_tpu.parallel.ring import ring_attention, ring_self_attention
from pytorch_ps_mpi_tpu.parallel.ulysses import ulysses_attention
from pytorch_ps_mpi_tpu.parallel.tp import tp_mlp, tp_self_attention
from pytorch_ps_mpi_tpu.parallel.pp import pipeline_apply, pipeline_loss
from pytorch_ps_mpi_tpu.parallel.ep import moe_apply, moe_dense_oracle

__all__ = [
    "make_sync_train_step",
    "AsyncPS",
    "ring_attention",
    "ring_self_attention",
    "ulysses_attention",
    "tp_mlp",
    "tp_self_attention",
    "pipeline_apply",
    "pipeline_loss",
    "moe_apply",
    "moe_dense_oracle",
]
