"""Parallelism strategies.

The reference is data-parallel only (SURVEY §2.5); this package provides
its two DP topologies plus the async mode, and goes beyond it with
sequence/context parallelism (ring attention) — the natural extension the
comms layer's ``ppermute`` ring primitive enables.

- ``dp``: functional sync data-parallel train-step builder (decentralized
  allgather-sum and leader-PS topologies — reference ``ps.py:75`` and
  ``mpi_comms.py:60-133``).
- ``async_ps``: AsySG-InCon bounded-staleness asynchronous training
  (reference README.md:56-81, Lian et al. 2015).
- ``ring``: ring attention over a sequence-sharded mesh axis (context
  parallelism; no reference analog — TPU-first extension).
"""

from pytorch_ps_mpi_tpu.parallel.dp import make_sync_train_step
from pytorch_ps_mpi_tpu.parallel.async_ps import AsyncPS
from pytorch_ps_mpi_tpu.parallel.ring import ring_attention, ring_self_attention

__all__ = [
    "make_sync_train_step",
    "AsyncPS",
    "ring_attention",
    "ring_self_attention",
]
