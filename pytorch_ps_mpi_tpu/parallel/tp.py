"""Tensor parallelism: Megatron-style column/row-parallel layers.

No reference analog — the reference's stated constraint is "models fit on
one device" (``README.md:6``, SURVEY §2.5 marks TP "out of reference
scope") — but the mesh design leaves the ``model`` axis open and this
module fills it: the canonical two-matmul TP block that keeps activations
sharded between a column-parallel and a row-parallel linear so each
transformer MLP/attention costs exactly ONE ``psum`` on the ICI, not two
all-gathers (Shoeybi et al. 2019, arXiv:1909.08053 — public technique).

Layout convention: TP parameter leaves carry a leading ``[tp]`` shard axis
(the same convention the optimizer uses for codec state), sharded
``P(tp_axis)`` host-side; inside ``shard_map`` each worker sees its
``[1, ...]`` slice and squeezes it. All functions here run INSIDE
``shard_map`` with ``tp_axis`` bound.

Composition: the heads dimension is batch-like to attention, so TP over
heads composes transparently with ring attention over the sequence axis
(``parallel/ring.py``) — q/k/v simply carry ``heads/tp`` local heads.
``__graft_entry__.dryrun_multichip`` runs the full DP x SP x TP train
step built from these pieces.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def column_parallel(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None):
    """``y_local = x @ w_local (+ b_local)`` — weight sharded on the
    OUTPUT dim; input replicated (within the tp axis), output sharded.
    No communication."""
    y = x @ w
    return y + b if b is not None else y


def row_parallel(
    y_local: jax.Array, w: jax.Array, tp_axis: str,
    b: Optional[jax.Array] = None, local_grads: bool = False,
):
    """``out = psum_tp(y_local @ w_local) (+ b)`` — weight sharded on the
    INPUT dim; input sharded, output replicated. The block's single
    collective.

    ``local_grads=True`` lowers the reduction through
    :func:`comms.psum_fwd_identity_bwd` so differentiating inside a
    vma-UNCHECKED shard_map (``MPI_PS``'s fused step) yields correct
    per-device gradients — under ``check_vma=False`` a plain psum
    transposes into another psum and scales gradients by the axis size."""
    from pytorch_ps_mpi_tpu import comms

    yw = y_local @ w
    out = (comms.psum_fwd_identity_bwd(yw, tp_axis) if local_grads
           else lax.psum(yw, tp_axis))
    return out + b if b is not None else out


def _sq(x):
    """Squeeze the leading local [1, ...] shard axis shard_map leaves."""
    return x[0]


def tp_mlp(x: jax.Array, params: Dict[str, jax.Array], tp_axis: str,
           local_grads: bool = False):
    """Transformer MLP: column-parallel up-projection + gelu +
    row-parallel down-projection; one psum total.

    ``params`` leaves (host-side, leading [tp] axis): ``w1 [tp, d, f/tp]``,
    ``b1 [tp, f/tp]``, ``w2 [tp, f/tp, d]``, ``b2 [d]`` (replicated — added
    once after the psum).

    ``local_grads=True``: Megatron f/g region markers replace the bare
    psum so gradients are correct under ``check_vma=False`` (the
    ``MPI_PS`` fused-step contract) — the replicated input's gradient is
    psum'd across ``tp_axis`` (every shard contributes) and the output
    reduction back-propagates as identity.
    """
    if local_grads:
        from pytorch_ps_mpi_tpu import comms

        x = comms.identity_fwd_psum_bwd(x, tp_axis)
    h = jax.nn.gelu(column_parallel(x, _sq(params["w1"]), _sq(params["b1"])))
    return row_parallel(h, _sq(params["w2"]), tp_axis, params["b2"],
                        local_grads=local_grads)


def tp_self_attention(
    x: jax.Array,
    params: Dict[str, jax.Array],
    tp_axis: str,
    *,
    seq_axis: Optional[str] = None,
    causal: bool = False,
    sp: str = "ring",
    local_grads: bool = False,
):
    """Self-attention with heads split over ``tp_axis``: the QKV
    projection is column-parallel (each worker computes its local heads),
    attention runs on local heads (sequence-parallel over ``seq_axis``
    when given — SP x TP composition, ``sp`` selecting ring or ulysses),
    and the output projection is row-parallel. One psum total.

    ``params`` (host-side): ``wqkv [tp, d, 3, h/tp, hd]``,
    ``wo [tp, (h/tp)*hd, d]``, ``bo [d]``. With ``sp='ulysses'`` the
    LOCAL head count (h/tp) must divide by the seq-axis size — the two
    parallelism axes both slice heads in that composition.
    """
    if sp not in ("ring", "ulysses"):
        raise ValueError(f"sp must be 'ring' or 'ulysses', got {sp!r}")
    if local_grads:
        # Megatron 'f' at region entry: every head shard consumes the
        # replicated x, so its true gradient is the psum of per-shard
        # contributions (see tp_mlp; sequence-axis collectives inside
        # ring/ulysses are ppermute/all-to-all, whose transposes are
        # already correct without vma checking)
        from pytorch_ps_mpi_tpu import comms

        x = comms.identity_fwd_psum_bwd(x, tp_axis)
    wqkv = _sq(params["wqkv"])                     # [d, 3, h_loc, hd]
    qkv = jnp.einsum("bld,dche->blche", x, wqkv)   # [b, l, 3, h_loc, hd]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if seq_axis is not None and sp == "ulysses":
        from pytorch_ps_mpi_tpu.parallel.ulysses import ulysses_attention

        out = ulysses_attention(q, k, v, seq_axis, causal=causal)
    elif seq_axis is not None:
        from pytorch_ps_mpi_tpu.parallel.ring import ring_attention

        out = ring_attention(q, k, v, seq_axis, causal=causal)
    else:
        d = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / d ** 0.5
        if causal:
            l = q.shape[1]
            mask = jnp.tril(jnp.ones((l, l), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    flat = out.reshape(out.shape[0], out.shape[1], -1)   # [b, l, h_loc*hd]
    return row_parallel(flat, _sq(params["wo"]), tp_axis, params["bo"],
                        local_grads=local_grads)


# ---------------------------------------------------------------------------
# Host-side parameter construction (leading [tp] shard axis)
# ---------------------------------------------------------------------------

def init_tp_mlp(key, d: int, f: int, tp: int, scale: float = 0.02) -> PyTree:
    assert f % tp == 0, (f, tp)
    k1, k2 = jax.random.split(key)
    return {
        "w1": scale * jax.random.normal(k1, (tp, d, f // tp), jnp.float32),
        "b1": jnp.zeros((tp, f // tp), jnp.float32),
        "w2": scale * jax.random.normal(k2, (tp, f // tp, d), jnp.float32),
        "b2": jnp.zeros((d,), jnp.float32),
    }


def init_tp_attention(
    key, d: int, heads: int, tp: int, scale: float = 0.02
) -> PyTree:
    assert heads % tp == 0 and d % heads == 0, (d, heads, tp)
    hd = d // heads
    k1, k2 = jax.random.split(key)
    return {
        "wqkv": scale
        * jax.random.normal(k1, (tp, d, 3, heads // tp, hd), jnp.float32),
        "wo": scale
        * jax.random.normal(k2, (tp, (heads // tp) * hd, d), jnp.float32),
        "bo": jnp.zeros((d,), jnp.float32),
    }


def tp_param_spec(params: PyTree, tp_axis: str):
    """PartitionSpec pytree: leaves with the leading [tp] axis are sharded
    over ``tp_axis``; replicated otherwise. Convention: sharded leaves are
    exactly those with ndim > 1 here (b2/bo are the 1-D replicated ones)."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda x: P(tp_axis) if x.ndim > 1 else P(), params
    )


def dense_equivalent_mlp(params: PyTree):
    """Concatenate the TP shards back into the dense weights (test oracle)."""
    w1 = jnp.concatenate([params["w1"][i] for i in range(params["w1"].shape[0])], axis=-1)
    b1 = jnp.concatenate([params["b1"][i] for i in range(params["b1"].shape[0])], axis=-1)
    w2 = jnp.concatenate([params["w2"][i] for i in range(params["w2"].shape[0])], axis=0)
    return w1, b1, w2, params["b2"]


def dense_equivalent_attention(params: PyTree):
    wqkv = jnp.concatenate(
        [params["wqkv"][i] for i in range(params["wqkv"].shape[0])], axis=2
    )                                                  # [d, 3, h, hd]
    wo = jnp.concatenate(
        [params["wo"][i] for i in range(params["wo"].shape[0])], axis=0
    )                                                  # [h*hd, d]
    return wqkv, wo, params["bo"]
