"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second canonical long-context design (Jacobs et al. 2023, DeepSpeed-
Ulysses, arXiv:2309.14509 — public technique), complementing
``parallel/ring.py``: where ring attention keeps heads whole and rotates
K/V blocks around the ring (N-1 ppermute hops, O(L_local²) memory),
Ulysses transposes the sharding with ONE ``lax.all_to_all`` each way —
tokens-sharded activations become heads-sharded, every device then runs
ordinary full-sequence attention for its subset of heads, and a second
all_to_all restores token sharding. Two collectives total, O(L²/N) score
memory per device, requires ``heads % axis_size == 0``.

When to choose which (both ride the same mesh axis):
- ring: unbounded sequence growth, heads can be few; overlaps compute
  with neighbor hops.
- ulysses: plenty of heads, wants the plain fused attention kernel
  unchanged; minimal collective count.

Call inside ``shard_map`` with q/k/v sharded on the sequence axis
(``[batch, seq_local, heads, head_dim]`` — same convention as ring).
No reference analog (the reference never scales sequence length,
``README.md:6``); the all_to_all is the op class its MPI exploration
stopped at (``test_mpi.py:20`` Ialltoallv).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[b, l_loc, h, d] (seq-sharded) -> [b, l_loc*N, h_loc, d]
    (head-sharded, full sequence) with one all_to_all."""
    n = lax.axis_size(axis_name)
    b, l_loc, h, d = x.shape
    h_loc = h // n
    # [b, l_loc, n, h_loc, d] -> [n, b, l_loc, h_loc, d]
    x = x.reshape(b, l_loc, n, h_loc, d).transpose(2, 0, 1, 3, 4)
    # send head-group j to device j; receive every device's tokens for
    # MY head group: leading dim becomes the source (= seq block) index
    x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0)
    # [n, b, l_loc, h_loc, d] -> [b, n*l_loc, h_loc, d] (seq blocks in
    # device order = global token order)
    return x.transpose(1, 0, 2, 3, 4).reshape(b, n * l_loc, h_loc, d)


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """Inverse of :func:`_seq_to_heads`."""
    n = lax.axis_size(axis_name)
    b, l_full, h_loc, d = x.shape
    l_loc = l_full // n
    x = x.reshape(b, n, l_loc, h_loc, d).transpose(1, 0, 2, 3, 4)
    x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0)
    # leading dim now indexes head groups -> fold back into the head axis
    return x.transpose(1, 2, 0, 3, 4).reshape(b, l_loc, n * h_loc, d)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Full-sequence attention under Ulysses sequence parallelism.

    Args:
      q, k, v: ``[batch, seq_local, heads, head_dim]`` — this device's
        sequence shard; ``heads`` must divide by the axis size.
      axis_name: mesh axis the sequence is sharded over.
      causal: standard causal mask (global coordinates are naturally
        correct here — every device sees the full sequence).
      scale: logit scale; default ``head_dim ** -0.5``.
      use_flash: run the post-exchange local attention through the
        Pallas flash kernel (this is exactly Ulysses' selling point —
        "the plain fused attention kernel unchanged"). Default: auto
        (kernel on TPU when the full sequence tiles).

    Returns ``[batch, seq_local, heads, head_dim]``.
    """
    if q.shape[2] % lax.axis_size(axis_name) != 0:
        raise ValueError(
            f"heads={q.shape[2]} must divide by axis size "
            f"{lax.axis_size(axis_name)} for Ulysses SP (use ring "
            "attention when heads are scarce)"
        )
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    # one outbound exchange for all of q/k/v (identical shape+sharding):
    # stacking keeps the module's two-collectives-total cost claim true
    qkv = _seq_to_heads(
        jnp.concatenate([q, k, v], axis=0), axis_name
    )                                                   # [3b, L, h_loc, d]
    b = q.shape[0]
    qh, kh, vh = qkv[:b], qkv[b:2 * b], qkv[2 * b:]
    l_full = qh.shape[1]
    if use_flash is None:
        from pytorch_ps_mpi_tpu.ops.attention_pallas import flash_auto_ok

        use_flash = flash_auto_ok(l_full, l_full, d, qh.dtype)
    if use_flash:
        from pytorch_ps_mpi_tpu.ops.attention_pallas import flash_attention

        out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
        if causal:
            mask = jnp.tril(jnp.ones((l_full, l_full), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, vh)     # [b, L, h_loc, d]
    return _heads_to_seq(out, axis_name)
