"""Multi-process async parameter server over native shared memory.

The cross-process face of AsySG-InCon (the in-XLA single-program form
lives in ``async_ps.py``): a server process owns the parameters and
applies gradient updates in arrival order; worker processes read the
latest published snapshot whenever they like (inconsistent reads) and push
gradients tagged with the version they used. Transport is the C++
``native/psqueue.cpp`` segment (seqlock parameter board + per-worker
gradient mailboxes) — the role mpi4py's nonblocking collectives played for
the reference (``mpi_comms.py:88,132``), with staleness bounded by the
server dropping gradients older than ``max_staleness`` versions.

Across real pod slices the same server loop runs on each slice controller
with DCN transfers in place of shm; this module is the single-host
(multi-process) instantiation and the protocol reference.
"""

from __future__ import annotations

import ctypes
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from pytorch_ps_mpi_tpu.telemetry import PSServerTelemetry

PyTree = Any

_lib: Optional[ctypes.CDLL] = None


def get_lib() -> Optional[ctypes.CDLL]:
    """Build (once) and load native/psqueue.cpp; None without a toolchain."""
    global _lib
    if _lib is not None:
        return _lib
    from pytorch_ps_mpi_tpu.utils.native import build_and_load

    lib = build_and_load("psqueue.cpp")
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.psq_create.restype = ctypes.c_void_p
    lib.psq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                               ctypes.c_uint64, ctypes.c_uint64]
    lib.psq_open.restype = ctypes.c_void_p
    lib.psq_open.argtypes = [ctypes.c_char_p]
    lib.psq_close.argtypes = [ctypes.c_void_p]
    lib.psq_n_workers.restype = ctypes.c_uint32
    lib.psq_n_workers.argtypes = [ctypes.c_void_p]
    lib.psq_publish_params.restype = ctypes.c_int
    lib.psq_publish_params.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64,
                                       ctypes.c_uint64]
    lib.psq_read_params.restype = ctypes.c_int64
    lib.psq_read_params.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64,
                                    ctypes.POINTER(ctypes.c_uint64)]
    lib.psq_push_grad.restype = ctypes.c_int
    lib.psq_push_grad.argtypes = [ctypes.c_void_p, ctypes.c_uint32, u8p,
                                  ctypes.c_uint64, ctypes.c_uint64]
    lib.psq_pop_grad.restype = ctypes.c_int64
    lib.psq_pop_grad.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64,
                                 ctypes.POINTER(ctypes.c_uint32),
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.POINTER(ctypes.c_uint32)]
    lib.psq_grad_pending.restype = ctypes.c_int
    lib.psq_grad_pending.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.psq_reset_slot.restype = ctypes.c_int
    lib.psq_reset_slot.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.psq_params_version.restype = ctypes.c_uint64
    lib.psq_params_version.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _flat_size(template: PyTree) -> int:
    import jax

    return sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(template))


def _flatten(tree: PyTree) -> np.ndarray:
    import jax

    return np.concatenate(
        [np.asarray(x, np.float32).reshape(-1) for x in jax.tree.leaves(tree)]
    ) if jax.tree.leaves(tree) else np.zeros(0, np.float32)


def _unflatten(flat: np.ndarray, template: PyTree) -> PyTree:
    import jax

    leaves, treedef = jax.tree.flatten(template)
    out, off = [], 0
    for leaf in leaves:
        n = int(np.prod(np.shape(leaf)))
        out.append(flat[off : off + n].reshape(np.shape(leaf)).astype(np.float32))
        off += n
    return jax.tree.unflatten(treedef, out)


class CodecWire:
    """Fixed-spec byte wire for codec payloads over the shm mailboxes.

    The reference's codec placement — encode before send, decode on
    receive (``ps.py:94,166``) — applied to the async PS path: the worker
    encodes on device and ships the payload *bytes*; the server decodes
    back to a gradient. Because payload shapes are static, the wire spec
    (unit shapes/dtypes/order) is fixed at construction — the reference's
    per-message two-phase size exchange (``mpi_comms.py:144-174``)
    collapses to a one-time agreement, and the mailbox slot is sized to
    the spec exactly (no ``max_bytes`` high-water growth).

    ``bucket_mb > 0`` with a ``Codec.bucketable`` codec makes the wire
    UNIT a dtype-grouped flat bucket (``bucketing.BucketPlan``) instead
    of a pytree leaf: one push then ships a handful of contiguous
    ~MB-scale payload buffers instead of hundreds of per-leaf fragments
    (fewer per-unit scale/index sidecars on the wire, one big memcpy per
    unit on each end). Worker and server MUST agree on ``bucket_mb`` —
    it joins the codec config in the one-time wire agreement and should
    come from the same config source on both ends (``async_train`` plumbs
    ``cfg["bucket_mb"]`` to server and workers alike). The ``poll_grad``
    size check catches a mismatch whenever it changes total wire bytes
    (any codec with per-unit sidecars); like a same-size codec-config
    disagreement, a mismatch that preserves the byte count (identity
    codec over a mixed-dtype tree) is NOT detectable from the frame
    alone — single-source the config.

    The byte packing itself is double-buffered and chunked:
    ``encode_to_bytes`` first starts ASYNC device→host transfers for
    every payload array, then packs them into one of two preallocated
    ping-pong wire buffers — the DMA of payload *k+1* overlaps the host
    memcpy of payload *k* (serialization overlapping I/O), and the
    ping-pong lets a transport still draining buffer A (kernel socket
    buffer, shm seqlock reader) coexist with the next step encoding into
    buffer B. No ``b"".join`` double copy anywhere on the path.
    """

    def __init__(self, code, template: PyTree, seed: int = 0,
                 bucket_mb: float = 0.0):
        import jax
        import jax.numpy as jnp

        from pytorch_ps_mpi_tpu.bucketing import plan_buckets

        self.code = code
        leaves, self.treedef = jax.tree.flatten(template)
        self.plan = (
            plan_buckets(template, bucket_mb)
            if (bucket_mb > 0 and getattr(code, "bucketable", False))
            else None
        )
        if self.plan is not None:
            # wire units are flat dtype-grouped buckets
            self.shapes = [(b.size,) for b in self.plan.buckets]
            self.dtypes = [np.dtype(b.dtype) for b in self.plan.buckets]
        else:
            self.shapes = [tuple(np.shape(l)) for l in leaves]
            self.dtypes = [np.asarray(l).dtype for l in leaves]

        def one_struct(shape, dtype):
            return jax.eval_shape(
                lambda: code.encode(
                    jnp.zeros(shape, dtype),
                    code.init_state(shape, dtype),
                    jax.random.key(0) if code.needs_rng else None,
                )
            )[0]

        self._payload_structs = [
            one_struct(s, d) for s, d in zip(self.shapes, self.dtypes)
        ]
        self._flat_specs = [  # (shape, dtype) in wire order
            (tuple(x.shape), np.dtype(x.dtype))
            for ps in self._payload_structs
            for x in jax.tree.leaves(ps)
        ]
        self.wire_bytes = sum(
            int(np.prod(s)) * d.itemsize if s else d.itemsize
            for s, d in self._flat_specs
        )
        self.raw_bytes = _flat_size(template) * 4
        self._states = [
            code.init_state(s, d) for s, d in zip(self.shapes, self.dtypes)
        ]
        self._rng = jax.random.key(seed)
        # ping-pong wire buffers, preallocated once to the exact spec
        self._send_bufs = [
            np.empty(self.wire_bytes, np.uint8),
            np.empty(self.wire_bytes, np.uint8),
        ]
        self._send_idx = 0
        plan = self.plan

        def enc_all(grad_leaves, states, keys):
            units = (
                plan.pack_leaves(grad_leaves) if plan is not None
                else grad_leaves
            )
            payloads, new_states = [], []
            for i, (g, st) in enumerate(zip(units, states)):
                k = keys[i] if keys is not None else None
                p, s2 = code.encode(g, st, k)
                payloads.append(p)
                new_states.append(s2)
            return payloads, new_states

        def dec_all(payloads):
            units = [
                code.decode(p, s, d)
                for p, s, d in zip(payloads, self.shapes, self.dtypes)
            ]
            return (
                plan.unpack_leaves(units) if plan is not None else units
            )

        self._enc = jax.jit(enc_all)
        self._dec = jax.jit(dec_all)

    def encode_to_bytes(self, grad_tree: PyTree) -> np.ndarray:
        """Encode + pack into one contiguous preallocated wire buffer
        (a uint8 ndarray of exactly ``wire_bytes``; bytes-like for every
        transport). The returned buffer stays valid until the NEXT-next
        call (two-deep ping-pong)."""
        import jax

        grad_leaves = self.treedef.flatten_up_to(grad_tree)
        keys = None
        if self.code.needs_rng:
            self._rng, sub = jax.random.split(self._rng)
            keys = list(jax.random.split(sub, len(self.shapes)))
        payloads, self._states = self._enc(grad_leaves, self._states, keys)
        flat = [x for p in payloads for x in jax.tree.leaves(p)]
        # start all device->host DMAs before touching any bytes: the
        # transfer of payload k+1 overlaps the memcpy of payload k below
        for x in flat:
            copy_async = getattr(x, "copy_to_host_async", None)
            if copy_async is not None:
                try:
                    copy_async()
                except Exception:
                    pass  # backend without async host copies
        from pytorch_ps_mpi_tpu.utils.serialization import pack_arrays_into

        buf = self._send_bufs[self._send_idx]
        self._send_idx ^= 1
        pack_arrays_into(buf, flat)
        return buf

    def probe_fidelity(self, grad_tree: PyTree) -> Dict[str, Any]:
        """Online codec-fidelity probe on the LARGEST wire unit (the
        sampled bucket, or the biggest leaf on the per-leaf wire):
        decode-after-encode relative L2 error, cosine similarity, and
        achieved bits-per-parameter via ``Codec.fidelity_probe``.
        Read-only — the wire's codec states and PRNG stream are
        untouched (the probe folds its own fixed key), so probing at any
        cadence never perturbs what actually ships."""
        import jax

        grad_leaves = self.treedef.flatten_up_to(grad_tree)
        units = (
            self.plan.pack_leaves(grad_leaves) if self.plan is not None
            else grad_leaves
        )
        i = max(range(len(units)),
                key=lambda j: int(np.prod(self.shapes[j]) or 1))
        rng = jax.random.key(0x9E3779B9) if self.code.needs_rng else None
        out = self.code.fidelity_probe(units[i], self._states[i], rng)
        out["unit"] = i
        out["codec"] = type(self.code).__name__
        return out

    def payloads_from_bytes(self, buf) -> list:
        """Parse a wire buffer into the per-unit payload pytrees as
        ZERO-COPY numpy views (valid only while ``buf`` is — consumers
        that retain anything must copy)."""
        import jax

        from pytorch_ps_mpi_tpu.utils.serialization import read_arrays

        arrays = read_arrays(buf, self._flat_specs, copy=False)
        payloads, i = [], 0
        for ps in self._payload_structs:
            struct = jax.tree.structure(ps)
            payloads.append(
                jax.tree.unflatten(struct, arrays[i:i + struct.num_leaves])
            )
            i += struct.num_leaves
        return payloads

    def decode_from_bytes(self, buf) -> PyTree:
        """Decode a wire buffer (``bytes``, ``bytearray``, ``memoryview``
        or uint8 ndarray) back into the template-structured gradient tree.
        Payload arrays are zero-copy views through one ``memoryview`` —
        the device transfer inside the jitted decode is the only copy.
        A buffer shorter than the wire spec raises a clear ValueError."""
        import jax

        decoded = self._dec(self.payloads_from_bytes(buf))
        return jax.tree.unflatten(
            self.treedef, [np.asarray(x) for x in decoded]
        )

    @property
    def agg_supported(self) -> bool:
        """True when EVERY wire unit can aggregate in the compressed
        domain (``Codec.supports_aggregate`` + the per-unit
        ``can_aggregate`` refinement). False means the serve loop keeps
        the decode-sum path — the automatic fallback."""
        return bool(getattr(self.code, "supports_aggregate", False)) and all(
            self.code.can_aggregate(s, d)
            for s, d in zip(self.shapes, self.dtypes)
        )

    def agg_begin(self) -> "WireAggregator":
        """Fresh compressed-domain accumulator for one aggregation round
        (one published version). Fold every composing push's payload
        bytes in, then ``finalize()`` for the ONE decode."""
        return WireAggregator(self)

    def payload_finite(self, buf) -> bool:
        """Cheap payload-level non-finite screen: checks only the FLOAT
        leaves of the wire payload (scales, norms, sparse values — for
        int8 that is one scalar per unit). A payload whose float leaves
        are finite decodes to a finite gradient for every registered
        codec, so this is the aggregation path's stand-in for the
        decoded-tree check the numerics monitor runs. Float-ness is
        decided by an UPCAST probe, not ``dtype.kind``: the ml_dtypes
        wire types (bf16's numpy dtype has kind 'V', not 'f') must be
        screened — they are exactly the payloads an identity/bf16 wire
        carries."""
        import jax

        for p in self.payloads_from_bytes(buf):
            for leaf in jax.tree.leaves(p):
                if leaf.dtype.kind in "iub":
                    continue  # integer payload domain (q, indices, votes)
                if not np.all(np.isfinite(np.asarray(leaf, np.float32))):
                    return False
        return True


class WireAggregator:
    """One aggregation round's compressed accumulator over a
    :class:`CodecWire`: ``fold`` ingests one push's payload bytes per
    call (host-side numpy, no jit dispatch, no tree rebuild — the
    per-push cost is a function of PAYLOAD size), ``finalize`` performs
    exactly one decode and returns the summed gradient tree. The
    serve-loop half of the THC/SparCML recipe; the SPMD half lives in
    ``ps.decode_sum_payloads``."""

    def __init__(self, wire: "CodecWire"):
        self.wire = wire
        code = wire.code
        self._accs = [
            code.agg_init(s, d) for s, d in zip(wire.shapes, wire.dtypes)
        ]
        self.frames = 0

    def fold(self, buf) -> None:
        """Fold one push's payload bytes (any bytes-like of exactly
        ``wire.wire_bytes``) into the accumulator. The parse is
        zero-copy; codec folds copy only what they retain."""
        payloads = self.wire.payloads_from_bytes(buf)
        code = self.wire.code
        for acc, p in zip(self._accs, payloads):
            code.agg_fold(acc, p)
        self.frames += 1

    def finalize(self) -> PyTree:
        """The ONE decode per published version: per-unit finalize,
        bucket unpack (when the wire is bucketed), tree rebuild. Returns
        the SUM over folded pushes."""
        import jax

        wire = self.wire
        code = wire.code
        units = [
            np.asarray(code.agg_finalize(acc, s, d))
            for acc, s, d in zip(self._accs, wire.shapes, wire.dtypes)
        ]
        if wire.plan is not None:
            units = [np.asarray(x) for x in wire.plan.unpack_leaves(units)]
        return jax.tree.unflatten(wire.treedef, units)

    def __del__(self):
        # an abandoned round (degraded sync, dropped worker set) must
        # hand its pooled sparse buffers back, or the pool stays cold
        # and every later round pays the fresh-zeros allocation
        try:
            from pytorch_ps_mpi_tpu.codecs.base import sparse_agg_release

            for acc in self._accs:
                if isinstance(acc, dict):
                    sparse_agg_release(acc)
        except Exception:
            pass  # interpreter teardown


def _renegotiate_common(server, code, bucket_mb: float = 0.0) -> None:
    """The shared server half of a codec/bucket_mb renegotiation (shm
    and TCP): build the new wire, keep the old epoch accepted, make the
    new fingerprint current. The epoch bump is executed entirely through
    the PR 3 frame handshake — the fingerprint IS the epoch
    discriminator, so no transport protocol change is needed."""
    if not server.frame:
        raise RuntimeError("wire renegotiation requires frame_check "
                           "(the fingerprint is the epoch handshake)")
    if server.wire is None:
        raise RuntimeError("wire renegotiation requires a codec wire")
    if getattr(server, "tree_slots", 0):
        raise RuntimeError("wire renegotiation is not supported on tree "
                           "wires (the hop codec is the tree's own "
                           "agreement)")
    if getattr(server, "agg_mode", 0.0):
        raise RuntimeError("suspend compressed-domain aggregation before "
                           "renegotiating (mixed-epoch payloads cannot "
                           "share one accumulator)")
    from pytorch_ps_mpi_tpu.resilience import frames as _frames

    new_wire = CodecWire(code, server.template, bucket_mb=bucket_mb)
    new_frame = new_wire.wire_bytes + _frames.HEADER_BYTES
    # the cap is the BOOT wire's frame size, latched at the first
    # renegotiation (when server.wire IS still the boot wire) — not the
    # receive buffer, which on TCP is sized to max(snapshot, frame) and
    # would admit entries every WORKER's boot-sized frame buffer must
    # then decline (a fleet-wide silent config rejection after retire)
    cap = server.__dict__.setdefault(
        "_reneg_frame_cap", server._expected_payload + _frames.HEADER_BYTES)
    if new_frame > cap:
        raise ValueError(
            f"renegotiated wire needs {new_frame} B frames but the "
            f"boot wire (and every worker's frame buffer) was sized "
            f"for {cap} B — ladder entries must not exceed the boot "
            "wire's payload size")
    table = server.__dict__.setdefault("_epoch_table", {})
    table[server._fingerprint] = {
        "wire": server.wire,
        "expected": server._expected_payload,
        "epoch": getattr(server, "_epoch", 0),
    }
    while len(table) > 2:  # at most two retiring epochs in flight
        table.pop(next(iter(table)))
    server._epoch = getattr(server, "_epoch", 0) + 1
    server.wire = new_wire
    server._fingerprint = _frames.wire_fingerprint(
        new_wire, server.template)
    server._expected_payload = new_wire.wire_bytes
    server._wire_payload_bytes = new_wire.wire_bytes
    server._epoch_transition = True


def _worker_renegotiate_common(worker, code,
                               bucket_mb: float = 0.0) -> bool:
    """The shared worker half of a renegotiation: rebuild the codec
    wire (same per-worker seed, so stochastic codecs keep distinct
    streams) and recompute the fingerprint. Returns False — declining,
    never raising — when this worker cannot switch (unframed wire, no
    codec, tree trailer wire, or a payload the boot-sized frame buffer
    cannot hold); a declining worker keeps pushing its old epoch, which
    the server consumes until that epoch retires."""
    if (not getattr(worker, "frame", False) or worker.wire is None
            or getattr(worker, "tree_slots", 0)):
        return False
    from pytorch_ps_mpi_tpu.resilience import frames as _frames

    new_wire = CodecWire(code, worker.template,
                         seed=getattr(worker, "_seed", 0),
                         bucket_mb=bucket_mb)
    if (_frames.HEADER_BYTES + new_wire.wire_bytes
            > worker._frame_buf.nbytes):
        return False
    worker.wire = new_wire
    worker._fingerprint = _frames.wire_fingerprint(
        new_wire, worker.template)
    return True


class ShmPSServer(PSServerTelemetry):
    """Owns params; publishes snapshots, consumes gradients in arrival
    order (the PS side of the reference's rank-0 loop, README.md:61-77).
    With ``code=`` the mailboxes carry encoded payload bytes (see
    :class:`CodecWire`) and the server decodes on receive.

    Telemetry (:class:`PSServerTelemetry`): ``metrics()`` returns the
    canonical schema shared with ``TcpPSServer`` — the reference's
    ``msg_bytes``/``packaged_bytes`` pair (``ps.py:135-136``) measured
    on the live async path — ``prometheus_text()`` is the in-process
    scrape method, and ``start_metrics_http()`` serves the same registry
    (plus the ``/health`` diagnosis JSON) over HTTP: the endpoint only
    renders Python state on a daemon thread, so the shm transport gets
    the same ops surface as TCP."""

    def __init__(self, name: str, num_workers: int, template: PyTree,
                 max_staleness: int = 4, code=None, bucket_mb: float = 0.0,
                 frame: bool = False, tree_slots: int = 0):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native psqueue unavailable (no g++?)")
        self._lib = lib
        self.template = template
        self.num_workers = num_workers
        self.max_staleness = max_staleness
        # bucket_mb is part of the one-time wire agreement: every worker
        # must be constructed with the same value (the poll-side size
        # check catches disagreement loudly)
        self.wire = (
            CodecWire(code, template, bucket_mb=bucket_mb)
            if code is not None else None
        )
        nbytes = _flat_size(template) * 4
        payload_bytes = self.wire.wire_bytes if self.wire else nbytes
        # tree_slots > 0: aggregation-tree parent — every push carries a
        # fixed-size composed-lineage trailer (parallel.tree; needs
        # frames, the trailer rides inside the CRC'd frame payload)
        self.tree_slots = int(tree_slots)
        self.tree_composed = 0
        self._wire_payload_bytes = payload_bytes
        if self.tree_slots:
            if not frame:
                raise ValueError("tree_slots requires frame=True (the "
                                 "lineage trailer rides the framed wire)")
            import collections as _collections

            from pytorch_ps_mpi_tpu.resilience import frames as _fr

            payload_bytes += _fr.trailer_bytes(self.tree_slots)
            self._composed_queue = _collections.deque()
        self._expected_payload = payload_bytes
        # frame=True: every push carries a self-verifying header (magic +
        # CRC32 + config fingerprint, resilience.frames) and a bad frame
        # becomes a counted per-worker rejection instead of a crash or a
        # silent mis-decode. Joins the one-time wire agreement: server
        # and every worker must agree on it (cfg["frame_check"]).
        self.frame = bool(frame)
        if self.frame:
            from pytorch_ps_mpi_tpu.resilience import frames as _frames

            self._frames = _frames
            self._fingerprint = _frames.wire_fingerprint(
                self.wire, template, tree_slots=self.tree_slots)
            grad_slot = payload_bytes + _frames.HEADER_BYTES
        else:
            grad_slot = payload_bytes
        self._h = lib.psq_create(name.encode(), num_workers, nbytes, grad_slot)
        if not self._h:
            raise RuntimeError(f"psq_create({name}) failed")
        self.version = 0
        if self.frame:
            self._grad_buf = np.empty(grad_slot, np.uint8)
        elif self.wire:
            self._grad_buf = np.empty(self.wire.wire_bytes, np.uint8)
        else:
            self._grad_buf = np.empty(_flat_size(template), np.float32)
        self.stale_drops = 0
        self.staleness_seen: Dict[int, int] = {}
        self.grads_received = 0
        self.bytes_received = 0
        # failure/straggler detection (absent in the reference, SURVEY
        # §5.3: MPI aborted the whole job; here the server observes)
        self.last_seen: Dict[int, float] = {}
        self._t0 = time.time()
        # uptime anchor for the canonical ts/uptime_s keys: monotonic,
        # per server GENERATION (a supervisor restart resets it)
        self._t0_mono = time.monotonic()

    def publish(self, params: PyTree) -> None:
        self.publish_flat(_flatten(params))

    def publish_flat(self, flat: np.ndarray) -> None:
        """Publish a pre-flattened f32 snapshot (the serving-core path:
        one flatten feeds the transport AND the snapshot ring)."""
        flat = np.ascontiguousarray(flat, np.float32)
        self.version += 1
        rc = self._lib.psq_publish_params(
            self._h, _u8(flat.view(np.uint8)), flat.nbytes, self.version
        )
        if rc != 0:
            raise RuntimeError("psq_publish_params failed")

    def _decode_payload(self, payload: np.ndarray,
                        wire=None) -> PyTree:
        """Payload bytes (a view into the receive buffer) → gradient
        tree; shared by the framed and legacy poll paths. Counted in
        ``decodes_done`` — the numerator of ``decodes_per_publish``.
        ``wire`` overrides the server's current wire — the old-epoch
        decode path during a codec renegotiation transition."""
        self.decodes_done += 1
        wire = wire if wire is not None else self.wire
        if wire:
            # zero-copy: decode reads the receive buffer through a
            # memoryview; the jitted decode's device transfer is the copy
            return wire.decode_from_bytes(payload)
        flat = np.frombuffer(payload, np.float32).copy()
        return _unflatten(flat, self.template)

    def renegotiate_wire(self, code, bucket_mb: float = 0.0) -> None:
        """Install a NEW codec wire as the current epoch (the
        controller's codec/bucket_mb renegotiation). The old epoch's
        wire stays in ``_epoch_table`` so in-flight old-fingerprint
        frames are consumed — decoded with their own wire — instead of
        rejected; :meth:`finish_renegotiation` retires it once the
        fleet has switched. The new wire's framed payload must fit the
        boot-sized transport buffers (mailbox slots are sized once at
        creation), so a ladder can only move between the boot config
        and anything smaller."""
        _renegotiate_common(self, code, bucket_mb)

    def finish_renegotiation(self) -> None:
        """Retire every old epoch: frames carrying a retired fingerprint
        become counted ``"config"`` rejections again (the pre-transition
        behavior for config drift)."""
        self._epoch_table = {}
        self._epoch_transition = False

    def _poll_grad_framed(self, raw: bool = False
                          ) -> Optional[Tuple[int, int, PyTree]]:
        """Frame-checking poll — the shared ``frames.framed_poll`` loop
        (validate → reject-and-count → bounded staleness → decode) over
        this transport's mailbox pop."""
        worker = ctypes.c_uint32()
        version = ctypes.c_uint64()
        cursor = getattr(self, "_cursor", None)
        if cursor is None:
            cursor = self._cursor = ctypes.c_uint32(0)

        def pop_once():
            n = self._lib.psq_pop_grad(
                self._h, _u8(self._grad_buf.view(np.uint8)),
                self._grad_buf.nbytes,
                ctypes.byref(worker), ctypes.byref(version),
                ctypes.byref(cursor),
            )
            return int(n), int(worker.value), int(version.value)

        return self._frames.framed_poll(self, pop_once, raw=raw)

    def poll_grad(self, raw: bool = False
                  ) -> Optional[Tuple[int, int, PyTree]]:
        """One pending gradient as (worker, version, grad_tree), or None.
        Gradients staler than max_staleness are dropped (bounded
        staleness), counted in ``stale_drops``. ``raw=True`` (the
        homomorphic-aggregation mode) skips the decode and returns the
        validated payload BYTES as a view into the receive buffer —
        copy or fold before the next poll."""
        if raw and not self.wire:
            # without a codec wire the receive buffer is f32-typed and
            # there is no payload format to hand back — a [:n] slice
            # would be a silently mis-sized view, not bytes
            raise ValueError("poll_grad(raw=True) needs a codec wire")
        if self.frame:
            return self._poll_grad_framed(raw=raw)
        worker = ctypes.c_uint32()
        version = ctypes.c_uint64()
        cursor = getattr(self, "_cursor", None)
        if cursor is None:
            cursor = self._cursor = ctypes.c_uint32(0)
        while True:  # iterative stale drain — a deep backlog of stale
            # gradients (one slow worker after a long server pause) must
            # not grow the Python stack
            n = self._lib.psq_pop_grad(
                self._h, _u8(self._grad_buf.view(np.uint8)),
                self._grad_buf.nbytes,
                ctypes.byref(worker), ctypes.byref(version),
                ctypes.byref(cursor),
            )
            if n <= 0:
                return None
            # clamp at 0: a future version (worker outliving a server
            # restart) is simply fresh; a negative key would corrupt the
            # histogram and dodge the drop check
            staleness = max(0, self.version - int(version.value))
            self.staleness_seen[staleness] = (
                self.staleness_seen.get(staleness, 0) + 1
            )
            self.last_seen[int(worker.value)] = time.time()
            self.grads_received += 1
            self.bytes_received += int(n)
            if staleness <= self.max_staleness:
                break
            self.stale_drops += 1
        expected = self.wire.wire_bytes if self.wire else _flat_size(self.template) * 4
        if int(n) != expected:
            # the wire spec is a one-time agreement — enforce it, or a
            # worker running a different codec config would crash the
            # decode (short payload) or silently corrupt gradients
            # (same-size different layout)
            raise RuntimeError(
                f"payload size {n} != wire spec {expected} bytes: worker "
                "and server codec configs disagree"
            )
        if raw:
            # aggregation mode (codec wire only): the validated payload
            # bytes, a view into the receive buffer
            grad = self._grad_buf[:n]
        elif self.wire:
            grad = self._decode_payload(self._grad_buf[:n])
        else:
            # the no-codec receive buffer is f32-typed: slice elements
            grad = self._decode_payload(self._grad_buf[: n // 4])
        return int(worker.value), int(version.value), grad

    def reset_worker_slot(self, worker: int) -> None:
        """Elastic replacement of a CRASHED worker: forcibly empty its
        mailbox (a process killed while its slot was in the WRITING state
        of the EMPTY/WRITING/FULL machine leaves it wedged, so a
        replacement could never push). Call only after confirming the
        previous owner is dead — a half-written payload is discarded,
        which the async protocol tolerates (one lost gradient). Also
        restarts the worker's liveness clock so ``stragglers()`` gives
        the replacement its startup grace instead of instantly re-
        flagging the id it inherits."""
        rc = self._lib.psq_reset_slot(self._h, worker)
        if rc != 0:
            raise ValueError(f"psq_reset_slot({worker}) -> {rc}")
        self.last_seen[int(worker)] = time.time()

    def stragglers(self, timeout: float) -> Dict[int, float]:
        """Workers with no sign of life for ``timeout`` seconds: no
        gradient consumed from them recently AND nothing pending in their
        mailbox (a pushed-but-unpolled gradient counts as alive, so server
        polling pauses don't misreport healthy workers). Never-seen
        workers age from server start. The failure-detection surface the
        reference lacked (its MPI default killed the whole job on any rank
        failure, SURVEY §5.3); the async protocol tolerates stragglers by
        design — this makes them observable."""
        now = time.time()
        out = {}
        for w in range(self.num_workers):
            if self._lib.psq_grad_pending(self._h, w) == 1:
                continue  # pushed, awaiting consumption: alive
            age = now - self.last_seen.get(w, self._t0)
            if age > timeout:
                out[w] = age
        return out

    def close(self):
        # the /metrics + /health endpoint (PSServerTelemetry mixin) dies
        # with the server — a supervisor restart can never leak a socket;
        # the serving core's read tier follows the same rule, and the
        # observability plane (profiler thread, TSDB flush, fleet
        # registration) is torn down the same way
        self.close_observability()
        self.close_metrics_http()
        sc = getattr(self, "serving_core", None)
        if sc is not None:
            sc.close()
        if self._h:
            self._lib.psq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShmPSWorker:
    """Reads the latest params whenever it likes; pushes version-tagged
    gradients (the worker side of AsySG-InCon's inconsistent reads)."""

    def __init__(self, name: str, worker_id: int, template: PyTree,
                 timeout: float = 30.0, code=None, seed: int = 0,
                 bucket_mb: float = 0.0, frame: bool = False,
                 cached_reads: bool = False, tree_slots: int = 0):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native psqueue unavailable (no g++?)")
        self._lib = lib
        deadline = time.time() + timeout
        self._h = None
        while time.time() < deadline:
            h = lib.psq_open(name.encode())
            if h:
                self._h = h
                break
            time.sleep(0.05)
        if not self._h:
            raise TimeoutError(f"psq_open({name}) timed out")
        self.worker_id = worker_id
        self.template = template
        # worker's wire must agree with the server's (same codec config
        # AND bucket_mb); stochastic codecs get a per-worker PRNG stream
        self._seed = seed + worker_id  # re-used by renegotiate()
        self.wire = (
            CodecWire(code, template, seed=self._seed,
                      bucket_mb=bucket_mb)
            if code is not None else None
        )
        # frame must match the server's (wire agreement); the fingerprint
        # is computed from THIS side's config — drift fails the compare
        self.frame = bool(frame)
        self._tamper = None  # one-shot outgoing-bytes hook (fault injection)
        self._wire_delay_s = 0.0  # one-shot post-seal delay (wire_delay)
        # monotonic push sequence for the frame trace ID — the fallback
        # when the caller doesn't pass an explicit lineage=(step, seq)
        self._auto_seq = 0
        # tree_slots > 0: pushes to an aggregation-tree parent carry a
        # fixed-capacity composed-lineage trailer (default: self)
        self.tree_slots = int(tree_slots)
        if self.tree_slots and not self.frame:
            raise ValueError("tree_slots requires frame=True")
        if self.frame:
            from pytorch_ps_mpi_tpu.resilience import frames as _frames

            self._frames = _frames
            self._fingerprint = _frames.wire_fingerprint(
                self.wire, template, tree_slots=self.tree_slots)
            payload_bytes = (self.wire.wire_bytes if self.wire
                             else _flat_size(template) * 4)
            self._frame_buf = np.empty(
                _frames.HEADER_BYTES + payload_bytes
                + _frames.trailer_bytes(self.tree_slots), np.uint8
            )
        self._param_buf = np.empty(_flat_size(template), np.float32)
        # version-conditional read cache (OPT-IN here, unlike TCP where
        # it defaults on): when the published version is unchanged (one
        # atomic peek — psq_params_version) the full seqlock copy +
        # unflatten is skipped and the cached tree returned, counted in
        # reads_not_modified. Off by default because a shm read is
        # already just a local memcpy — making it ~free changes the
        # pacing of tight read→push training loops (more same-version
        # pushes between publishes), whereas on TCP the request/reply
        # RTT still paces the reader and only the payload is saved.
        self.cached_reads = bool(cached_reads)
        self._cached_tree: Optional[PyTree] = None
        self._cached_version = 0
        self.reads_total = 0
        self.reads_not_modified = 0

    def read_params(self, timeout: float = 30.0) -> Tuple[PyTree, int]:
        """Latest published snapshot (blocks until the server's first
        publish; after that, never blocks on the writer — seqlock).
        With ``cached_reads=True`` (opt-in — see the constructor note)
        an unchanged version costs one atomic load instead of a full
        snapshot copy, and the SAME cached tree object is returned —
        callers opting in must not mutate it."""
        self.reads_total += 1
        if self.cached_reads and self._cached_tree is not None:
            v = int(self._lib.psq_params_version(self._h))
            if v == self._cached_version and v > 0:
                self.reads_not_modified += 1
                return self._cached_tree, v
        version = ctypes.c_uint64()
        deadline = time.time() + timeout
        while True:
            n = self._lib.psq_read_params(
                self._h, _u8(self._param_buf.view(np.uint8)),
                self._param_buf.nbytes, ctypes.byref(version),
            )
            if n == -2:
                # seqlock starved (server republishing faster than this
                # reader gets scheduled) — retriable until the deadline
                if time.time() > deadline:
                    raise TimeoutError("psq_read_params starved (seqlock)")
                time.sleep(0.01)
                continue
            if n < 0:
                raise RuntimeError(f"psq_read_params -> {n}")
            if version.value > 0:
                break
            if time.time() > deadline:
                raise TimeoutError("no parameter snapshot published yet")
            time.sleep(0.002)
        tree = _unflatten(self._param_buf[: n // 4].copy(), self.template)
        if self.cached_reads:
            self._cached_tree, self._cached_version = tree, int(version.value)
        return tree, int(version.value)

    def push_grad(self, grad: PyTree, version: int,
                  timeout: float = 30.0,
                  lineage: Optional[Tuple[int, int]] = None,
                  composed=None) -> None:
        """``lineage=(step, seq)`` stamps the push's trace ID into the
        v2 frame header (worker id travels in the transport); without it
        a per-transport auto-incrementing seq is used. Ignored on the
        unframed wire — there is nowhere to carry it. On a tree wire,
        ``composed`` lists the constituent trace IDs for the lineage
        trailer (default: this worker itself)."""
        if self.wire:
            # encode-before-send (reference ps.py:94): only payload bytes
            # ever enter the mailbox. encode_to_bytes hands back its
            # preallocated ping-pong buffer — valid through this push's
            # retry loop, no defensive copy needed.
            flat = self.wire.encode_to_bytes(grad)
        else:
            flat = _flatten(grad)
        self.push_payload(flat, version, timeout=timeout, lineage=lineage,
                          composed=composed)

    def push_payload(self, flat: np.ndarray, version: int,
                     timeout: float = 30.0,
                     lineage: Optional[Tuple[int, int]] = None,
                     composed=None) -> None:
        """Push pre-encoded payload bytes — the tree leader's hop path
        (it encodes explicitly so error feedback can decode the exact
        payload that shipped)."""
        if self.frame:
            step, seq = lineage if lineage is not None else (0, self._auto_seq)
            self._auto_seq += 1
            if self.tree_slots and composed is None:
                composed = [(self.worker_id, step, seq, time.time())]
            flat = self._frames.seal_frame(self._frame_buf, flat,
                                           self._fingerprint,
                                           step=step, seq=seq,
                                           composed=composed,
                                           tree_slots=self.tree_slots)
        if self._tamper is not None:
            # fault injection: corrupt the outgoing bytes AFTER sealing,
            # so the CRC no longer matches what travels
            t, self._tamper = self._tamper, None
            t(flat.view(np.uint8))
        d, self._wire_delay_s = self._wire_delay_s, 0.0
        if d:
            # fault injection (kind "wire_delay"): emulated wire latency
            # — the frame is sealed (send_wall stamped at the encode
            # site) but the bytes travel late, exactly the window the
            # lineage wire stage measures
            time.sleep(d)
        deadline = time.time() + timeout
        while time.time() < deadline:
            rc = self._lib.psq_push_grad(
                self._h, self.worker_id, _u8(flat.view(np.uint8)),
                flat.nbytes, version,
            )
            if rc == 1:
                return
            if rc < 0:
                raise RuntimeError("psq_push_grad failed")
            time.sleep(0.002)  # mailbox full: server hasn't consumed yet
        raise TimeoutError("push_grad timed out")

    def renegotiate(self, code, bucket_mb: float = 0.0) -> bool:
        """Switch this worker's wire to a renegotiated codec epoch (the
        controller published it via ``control-epoch.json``). Returns
        False when declined — see :func:`_worker_renegotiate_common`."""
        return _worker_renegotiate_common(self, code, bucket_mb=bucket_mb)

    def close(self):
        if self._h:
            self._lib.psq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
