"""Ring attention: sequence/context parallelism over a mesh axis.

No reference analog — the reference scales workers, never sequence length
(constraint "models fit on one device", reference ``README.md:6``; SURVEY
§5.7) — but long-context is first-class here. Each device holds a shard of
the sequence; K/V blocks rotate around the ring via ``lax.ppermute`` (one
neighbor ICI hop per step) while attention accumulates online with the
numerically-stable streaming softmax (Milakov & Gimelshein / flash-
attention style max-shift rescaling). Peak memory per chip is O(L_local²)
instead of O(L²), and XLA overlaps each block's compute with the next
block's permute — the collective/compute overlap the reference built from
threads + MPI requests (``ps.py:65-66``), here falling out of the dataflow.

Call inside ``shard_map`` with q/k/v sharded on the sequence axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Ring attention over sequence shards.

    Args:
      q, k, v: ``[batch, seq_local, heads, head_dim]`` — this device's
        sequence shard (global seq = seq_local × axis_size).
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a causal mask in *global* sequence coordinates.
      scale: logit scale; default ``head_dim ** -0.5``.
      use_flash: compute each rotating block with the Pallas flash
        kernel (``ops/attention_pallas.py``) instead of a dense jnp
        block — per-block outputs combine via their logsumexp (the lse
        cotangent path keeps it differentiable). Default: auto (kernel
        on TPU when the local shard tiles; dense jnp otherwise).

    Returns ``[batch, seq_local, heads, head_dim]``: this shard's rows of
    full-sequence attention.
    """
    n = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, l_q, h, d = q.shape
    l_k = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    if use_flash is None:
        from pytorch_ps_mpi_tpu.ops.attention_pallas import flash_auto_ok

        use_flash = flash_auto_ok(l_q, l_k, d, q.dtype)

    q_pos = my_idx * l_q + jnp.arange(l_q)            # global query positions

    def block(q, k_blk, v_blk, src_idx):
        """Attend local q against one rotating K/V block."""
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        if causal:
            k_pos = src_idx * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
            mask = k_pos[None, :] <= q_pos[:, None]    # [q, k]
            s = jnp.where(mask[None, None], s, _NEG_BIG)
        return s

    def step(carry, _):
        k_cur, v_cur, src_idx, num, den, mx = carry
        if use_flash:
            # block attention in VMEM; combine normalized block outputs
            # by their logsumexp (max-shift weights — same streaming
            # softmax, one level up)
            from pytorch_ps_mpi_tpu.ops.attention_pallas import (
                flash_attention,
            )

            o_blk, lse_blk = flash_attention(
                q, k_cur, v_cur, causal=causal, scale=scale,
                q_offset=(my_idx * l_q).astype(jnp.int32),
                k_offset=(src_idx * l_k).astype(jnp.int32),
                return_lse=True,
            )
            o_blk = o_blk.transpose(0, 2, 1, 3)        # [b, h, q, d]
            new_mx = jnp.maximum(mx, lse_blk)
            corr = jnp.exp(mx - new_mx)
            # explicit guard: a fully-masked block's lse is ~-1e30; if mx
            # is ALSO still at its init floor, exp(lse-new_mx)=exp(0)=1
            # would smuggle the masked block in
            w = jnp.where(lse_blk > -1e29,
                          jnp.exp(lse_blk - new_mx), 0.0)
            num = num * corr[..., None] + o_blk * w[..., None]
            den = den * corr + w
        else:
            s = block(q, k_cur, v_cur, src_idx)        # [b, h, q, k]
            blk_max = s.max(axis=-1)                   # [b, h, q]
            new_mx = jnp.maximum(mx, blk_max)
            corr = jnp.exp(mx - new_mx)
            p = jnp.exp(s - new_mx[..., None])         # [b, h, q, k]
            num = num * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, v_cur
            )
            den = den * corr + p.sum(axis=-1)
        # rotate K/V to the next rank; we now hold the previous rank's block
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        src_nxt = (src_idx - 1) % n
        return (k_nxt, v_nxt, src_nxt, num, den, new_mx), None

    # Under shard_map with check_vma=True the scan carry's
    # varying-manual-axes type must be loop-invariant; freshly-built
    # zeros are device-invariant while the loop body makes them vary over
    # every axis q varies over (seq, plus data/model when composed with
    # DP/TP). Deriving the initial accumulators FROM q inherits exactly
    # q's vma — version-portable, and XLA folds the arithmetic away.
    # The isfinite select keeps ±inf activations (overflowed upstream)
    # from poisoning the accumulators via 0 * inf = NaN.
    zq = jnp.transpose(q, (0, 2, 1, 3))                # [b, h, l_q, d]
    z = jnp.where(jnp.isfinite(zq), zq * 0, 0.0)
    num0 = z
    den0 = z[..., 0]
    mx0 = z[..., 0] + _NEG_BIG
    carry0 = (k, v, my_idx, num0, den0, mx0)
    (_, _, _, num, den, _), _ = lax.scan(step, carry0, None, length=n)

    out = num / jnp.maximum(den, 1e-30)[..., None]     # [b, h, q, d]
    return out.transpose(0, 2, 1, 3)                   # [b, q, h, d]


def ring_self_attention(
    x_qkv: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """Convenience wrapper: ``x_qkv`` is ``[3, batch, seq_local, heads,
    head_dim]`` (stacked q/k/v)."""
    return ring_attention(x_qkv[0], x_qkv[1], x_qkv[2], axis_name, causal=causal)
