"""Cross-host async parameter server over native TCP (the DCN role).

The second transport for the AsySG-InCon protocol: ``dcn.py`` moves bytes
between co-hosted processes through shared memory; this module moves the
same bytes between HOSTS through ``native/tcpps.cpp`` — the deployment
shape the reference got from MPI over Ethernet/IB (reference
``README.md:19-23``, ``mpi_comms.py:88,132``), realized as the plain TCP
a TPU pod's data-center network exposes to host code. On a pod, the
server runs on one slice's controller and workers on other slices'
controllers; each host's in-XLA compute path (jit/pjit over its own
chips) is unchanged.

:class:`TcpPSServer` / :class:`TcpPSWorker` present the same surface as
``ShmPSServer`` / ``ShmPSWorker`` — ``publish`` / ``poll_grad`` /
``metrics`` / ``stragglers`` and ``read_params`` / ``push_grad`` — so
``async_train.serve`` and ``async_train.worker_main`` run over either
transport unmodified (``cfg["transport"] = "shm" | "tcp"``). Semantics
preserved across the swap:

- inconsistent reads: a worker gets the latest snapshot whenever it asks;
  no barrier, concurrent workers may see different versions;
- bounded staleness: the server drops gradients older than
  ``max_staleness`` versions, counted in ``stale_drops``;
- push back-pressure: a push is acknowledged by the server, so a worker
  has at most one unacknowledged gradient in flight (the shm single-slot
  mailbox's property, carried by protocol instead of memory layout);
- codec wire: with ``code=`` only encoded payload BYTES travel
  (``CodecWire``), decoded server-side — encode-before-send, reference
  ``ps.py:94,166``.

What TCP adds over shm: worker crash == socket EOF, an explicit liveness
signal (``connected``), and elastic replacement is just a reconnect — no
``reset_worker_slot`` surgery needed.
"""

from __future__ import annotations

import ctypes
import time
from typing import Dict, Optional, Tuple

import numpy as np

from pytorch_ps_mpi_tpu.parallel.dcn import (
    CodecWire,
    PyTree,
    _flat_size,
    _flatten,
    _u8,
    _unflatten,
)
from pytorch_ps_mpi_tpu.telemetry import PSServerTelemetry

_lib: Optional[ctypes.CDLL] = None


class _BatchMeta(ctypes.Structure):
    """Mirror of native/tcpps.cpp BatchMeta (48 bytes, packed)."""

    _pack_ = 1
    _fields_ = [
        ("worker", ctypes.c_uint32),
        ("status", ctypes.c_uint32),
        ("version", ctypes.c_uint64),
        ("off", ctypes.c_uint64),
        ("len", ctypes.c_uint64),
        ("step", ctypes.c_uint32),
        ("seq", ctypes.c_uint32),
        ("send_wall", ctypes.c_double),
    ]


assert ctypes.sizeof(_BatchMeta) == 48


class _HopStamp(ctypes.Structure):
    """Mirror of native/tcpps.cpp HopStamp (32 bytes, packed) — one
    per-frame validate/ingest stamp from the batched pop, drained by the
    hop-anatomy plane through ``tps_hop_stamps_drain`` (pump-owning
    thread only). Size-checked at load via ``tps_abi_hop_stamp_bytes``
    and diffed field-for-field by the psanalyze ABI-drift rule."""

    _pack_ = 1
    _fields_ = [
        ("t_ns", ctypes.c_uint64),
        ("validate_ns", ctypes.c_uint64),
        ("bytes", ctypes.c_uint64),
        ("worker", ctypes.c_uint32),
        ("status", ctypes.c_uint32),
    ]


assert ctypes.sizeof(_HopStamp) == 32


def get_lib() -> Optional[ctypes.CDLL]:
    """Build (once) and load native/tcpps.cpp; None without a toolchain."""
    global _lib
    if _lib is not None:
        return _lib
    from pytorch_ps_mpi_tpu.utils.native import build_and_load

    lib = build_and_load("tcpps.cpp")
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.tps_server_create.restype = ctypes.c_void_p
    lib.tps_server_create.argtypes = [ctypes.c_uint16, ctypes.c_uint32,
                                      ctypes.c_uint64]
    lib.tps_server_port.restype = ctypes.c_uint16
    lib.tps_server_port.argtypes = [ctypes.c_void_p]
    lib.tps_server_publish.restype = ctypes.c_int
    lib.tps_server_publish.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64,
                                       ctypes.c_uint64]
    lib.tps_server_pump.restype = ctypes.c_int
    lib.tps_server_pump.argtypes = [ctypes.c_void_p]
    lib.tps_server_pop_grad.restype = ctypes.c_int64
    lib.tps_server_pop_grad.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.tps_server_pending.restype = ctypes.c_int
    lib.tps_server_pending.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.tps_server_connected.restype = ctypes.c_int
    lib.tps_server_connected.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.tps_server_read_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.tps_server_close.argtypes = [ctypes.c_void_p]
    lib.tps_worker_connect.restype = ctypes.c_void_p
    lib.tps_worker_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                       ctypes.c_uint32, ctypes.c_int]
    lib.tps_worker_read_params.restype = ctypes.c_int64
    lib.tps_worker_read_params.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_uint64,
    ]
    lib.tps_worker_push_grad.restype = ctypes.c_int
    lib.tps_worker_push_grad.argtypes = [ctypes.c_void_p, u8p,
                                         ctypes.c_uint64, ctypes.c_uint64,
                                         ctypes.c_int]
    lib.tps_worker_close.argtypes = [ctypes.c_void_p]
    # batched ingest + in-C++ frame validation (absent from a stale
    # cached .so built before they existed; the mtime rebuild makes this
    # guard a hand-copied-library corner case)
    try:
        lib.tps_server_set_frame_check.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.tps_server_pop_grad_batch.restype = ctypes.c_int
        lib.tps_server_pop_grad_batch.argtypes = [
            ctypes.c_void_p, u8p, ctypes.c_uint64,
            ctypes.POINTER(_BatchMeta), ctypes.c_int]
        lib._has_batch = True
    except AttributeError:
        lib._has_batch = False
    # per-frame ingest stamp ring (hop anatomy) — own probe, so a stale
    # library with batch but no ring degrades only the ring
    try:
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.tps_abi_hop_stamp_bytes.argtypes = []
        lib.tps_abi_hop_stamp_bytes.restype = ctypes.c_uint32
        lib.tps_hop_stamps_arm.argtypes = [ctypes.c_uint32]
        lib.tps_hop_stamps_arm.restype = ctypes.c_int
        lib.tps_hop_stamps_drain.argtypes = [
            ctypes.POINTER(_HopStamp), ctypes.c_uint32, u64p]
        lib.tps_hop_stamps_drain.restype = ctypes.c_uint32
        lib._has_hop_stamps = True
    except AttributeError:
        lib._has_hop_stamps = False
    _verify_abi(lib)
    _lib = lib
    return _lib


def _verify_abi(lib: ctypes.CDLL) -> None:
    """Load-time twin of psanalyze's abi-drift rule: re-read the PSF2
    wire constants from the loaded library and refuse it on any
    mismatch with ``resilience/frames.py`` — drift becomes a loud load
    failure instead of a silent mis-decode. A library predating the
    ``tps_abi_*`` exports (hand-copied; the mtime check rebuilds any
    stale cache) skips the check rather than failing every import."""
    if not hasattr(lib, "tps_abi_psf_header_bytes"):
        return
    from pytorch_ps_mpi_tpu.resilience import frames as _frames

    lib.tps_abi_psf_magic.restype = ctypes.c_uint32
    lib.tps_abi_psf_magic_v1.restype = ctypes.c_uint32
    lib.tps_abi_psf_header_bytes.restype = ctypes.c_uint32
    lib.tps_abi_batch_meta_bytes.restype = ctypes.c_uint32
    lib.tps_abi_frame_status_name.restype = ctypes.c_char_p
    lib.tps_abi_frame_status_name.argtypes = [ctypes.c_uint32]
    checks = (
        ("PSF2 header bytes", int(lib.tps_abi_psf_header_bytes()),
         _frames.HEADER_BYTES),
        ("PSF2 magic", int(lib.tps_abi_psf_magic()),
         _frames.FRAME_MAGIC),
        ("PSF1 magic", int(lib.tps_abi_psf_magic_v1()),
         _frames.FRAME_MAGIC_V1),
        ("BatchMeta bytes", int(lib.tps_abi_batch_meta_bytes()),
         ctypes.sizeof(_BatchMeta)),
    )
    if getattr(lib, "_has_hop_stamps", False):
        checks += (("HopStamp bytes", int(lib.tps_abi_hop_stamp_bytes()),
                    ctypes.sizeof(_HopStamp)),)
    for what, native_v, py_v in checks:
        if native_v != py_v:
            raise RuntimeError(
                f"native/tcpps.cpp ABI drift: {what} is {native_v} in "
                f"the loaded library but {py_v} on the Python side — "
                "rebuild native/_build or reconcile the constants")
    for code, want in _frames.BATCH_REASONS.items():
        got = lib.tps_abi_frame_status_name(code)
        got = got.decode() if got is not None else None
        if got != want:
            raise RuntimeError(
                "native/tcpps.cpp ABI drift: frame-status code "
                f"{code} is {got!r} in the loaded library but "
                f"{want!r} in frames.BATCH_REASONS")


def native_profile_stats() -> Optional[dict]:
    """The epoll-pump cycle counters (calls / events / wall ns / frames
    validated) — the native half of continuous profiling
    (telemetry.profiler). Reads the ALREADY-loaded library only (never
    triggers a build); None when unavailable or built before the
    counters existed."""
    lib = _lib
    if lib is None or not hasattr(lib, "tps_profile_stats"):
        return None
    calls = ctypes.c_uint64()
    events = ctypes.c_uint64()
    ns = ctypes.c_uint64()
    frames = ctypes.c_uint64()
    lib.tps_profile_stats(ctypes.byref(calls), ctypes.byref(events),
                          ctypes.byref(ns), ctypes.byref(frames))
    return {"pump_calls": int(calls.value),
            "pump_events": int(events.value),
            "pump_ns": int(ns.value),
            "frames_validated": int(frames.value)}


class TcpPSServer(PSServerTelemetry):
    """Owns params; serves snapshots and consumes gradients arriving over
    TCP in arrival order. Same role/surface as ``ShmPSServer``; pass
    ``port=0`` to auto-assign (read back via ``.port`` for workers).

    Telemetry (:class:`PSServerTelemetry`): ``metrics()`` returns the
    canonical schema shared with ``ShmPSServer``, and
    :meth:`start_metrics_http` serves the same registry as a
    Prometheus-text ``/metrics`` HTTP endpoint — the deployment shape
    where a scraper on another host watches the PS. There is no
    transport-drop counter in the schema: an acknowledged push is never
    discarded (a full queue back-pressures the pushing worker via its
    withheld ack), so ``stale_drops`` is the only way a consumed
    gradient can fail to be applied."""

    def __init__(self, port: int, num_workers: int, template: PyTree,
                 max_staleness: int = 4, code=None, bucket_mb: float = 0.0,
                 frame: bool = False, tree_slots: int = 0):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native tcpps unavailable (no g++?)")
        self._lib = lib
        self.template = template
        self.num_workers = num_workers
        self.max_staleness = max_staleness
        # bucket_mb joins the one-time wire agreement (same value on
        # every worker; the per-frame size check catches disagreement)
        self.wire = (
            CodecWire(code, template, bucket_mb=bucket_mb)
            if code is not None else None
        )
        nbytes = _flat_size(template) * 4
        payload_bytes = self.wire.wire_bytes if self.wire else nbytes
        # tree_slots > 0: this server is an aggregation-tree parent —
        # every push's payload additionally carries a fixed-size
        # hop-composed lineage trailer (parallel.tree; requires frames,
        # the trailer rides inside the CRC'd frame payload)
        self.tree_slots = int(tree_slots)
        self.tree_composed = 0
        self._wire_payload_bytes = payload_bytes
        if self.tree_slots:
            if not frame:
                raise ValueError("tree_slots requires frame=True (the "
                                 "lineage trailer rides the framed wire)")
            import collections as _collections

            from pytorch_ps_mpi_tpu.resilience import frames as _fr

            payload_bytes += _fr.trailer_bytes(self.tree_slots)
            self._composed_queue = _collections.deque()
        self._expected_payload = payload_bytes
        # frame=True: self-verifying headers on every push (magic + CRC32
        # + config fingerprint, resilience.frames); a bad frame — size
        # mismatch from a misconfigured worker included — becomes a
        # counted per-worker rejection instead of a RuntimeError into the
        # serve loop. Joins the wire agreement (cfg["frame_check"]).
        self.frame = bool(frame)
        if self.frame:
            from pytorch_ps_mpi_tpu.resilience import frames as _frames

            self._frames = _frames
            self._fingerprint = _frames.wire_fingerprint(
                self.wire, template, tree_slots=self.tree_slots)
            grad_bytes = payload_bytes + _frames.HEADER_BYTES
        else:
            grad_bytes = payload_bytes
        # one frame must fit the larger of a snapshot or a payload
        max_msg = max(nbytes, grad_bytes)
        self._h = lib.tps_server_create(port, num_workers, max_msg)
        if not self._h:
            raise RuntimeError(f"tps_server_create(port={port}) failed")
        self.port = int(lib.tps_server_port(self._h))
        # native batched ingest (poll_grad_batch): the C++ side validates
        # each inner PSF2 frame (magic/version, size, fingerprint, CRC32)
        # and hands back only reason-coded metas + validated payload
        # views, so the per-push Python cost is bookkeeping, not parsing.
        # Armed whenever frames are on and the library has the entry
        # points; PS_NO_NATIVE is consulted per call, not here.
        self._batch_max = 0
        if self.frame and getattr(lib, "_has_batch", False):
            lib.tps_server_set_frame_check(
                self._h, self._fingerprint, payload_bytes)
            # batch buffer: up to 64 payloads, capped at ~16 MB so a
            # BERT-scale identity wire doesn't allocate gigabytes
            self._batch_max = max(1, min(64, (16 << 20) //
                                         max(payload_bytes, 1)))
            self._batch_buf = np.empty(
                self._batch_max * payload_bytes, np.uint8)
            self._batch_metas = (_BatchMeta * self._batch_max)()
        self.native_batches = 0
        self.native_batch_frames = 0
        self.version = 0
        if self.frame:
            # headroom to max_msg: a mismatched worker's oversized frame
            # (still <= max_msg or the transport closes its connection)
            # pops cleanly and is judged by the header, never a fatal -1
            self._grad_buf = np.empty(max_msg, np.uint8)
        elif self.wire:
            self._grad_buf = np.empty(self.wire.wire_bytes, np.uint8)
        else:
            self._grad_buf = np.empty(_flat_size(template), np.float32)
        self.stale_drops = 0
        self.staleness_seen: Dict[int, int] = {}
        self.grads_received = 0
        self.bytes_received = 0
        self.last_seen: Dict[int, float] = {}
        self._ever_connected: set = set()
        self._t0 = time.time()
        # uptime anchor for the canonical ts/uptime_s keys: monotonic,
        # per server GENERATION (a supervisor restart resets it)
        self._t0_mono = time.monotonic()
        # /metrics + /health HTTP: start_metrics_http / close_metrics_http
        # live on PSServerTelemetry (shared with the shm server)
        self._metrics_http = None
        # native GET_PARAMS accounting (total, not_modified) — refreshed
        # from the pump thread only (poll_grad/publish), so scrape
        # threads read a plain Python tuple, never the native handle
        self._native_read_stats = (0, 0)

    def _refresh_read_stats(self) -> None:
        total = ctypes.c_uint64()
        nm = ctypes.c_uint64()
        self._lib.tps_server_read_stats(self._h, ctypes.byref(total),
                                        ctypes.byref(nm))
        self._native_read_stats = (int(total.value), int(nm.value))

    def hop_stamps_arm(self, capacity: int) -> bool:
        """Arm (capacity > 0) or disarm (0) the native per-frame ingest
        stamp ring the hop-anatomy plane drains. Returns True when the
        ring is live. PS_NO_NATIVE keeps the pure-Python stamp fallback
        in charge; call only from the pump-owning thread (the same
        thread-affinity contract as ``tps_server_read_stats``)."""
        from pytorch_ps_mpi_tpu.utils import native as _native

        if _native.fast_path_disabled():
            return False
        if not getattr(self._lib, "_has_hop_stamps", False):
            return False
        ok = int(self._lib.tps_hop_stamps_arm(int(capacity))) == 0
        self._hop_stamps_armed = ok and capacity > 0
        return self._hop_stamps_armed

    def drain_hop_stamps(self, max_stamps: int = 4096
                         ) -> Optional[Tuple[list, int]]:
        """Batched drain of the armed stamp ring: ``([(t_ns,
        validate_ns, bytes, worker, status), ...], dropped)`` — oldest
        first, overflow-drop counter reset per drain — or None when the
        ring is unarmed/unavailable. Pump-owning thread only; callers
        mirror the result into plain Python state before any other
        thread reads it (the ``_native_read_stats`` discipline)."""
        if not getattr(self, "_hop_stamps_armed", False):
            return None
        buf = (_HopStamp * int(max_stamps))()
        dropped = ctypes.c_uint64()
        n = int(self._lib.tps_hop_stamps_drain(
            buf, int(max_stamps), ctypes.byref(dropped)))
        stamps = [(int(buf[i].t_ns), int(buf[i].validate_ns),
                   int(buf[i].bytes), int(buf[i].worker),
                   int(buf[i].status)) for i in range(n)]
        return stamps, int(dropped.value)

    def publish(self, params: PyTree) -> None:
        self.publish_flat(_flatten(params))

    def publish_flat(self, flat: np.ndarray) -> None:
        """Publish a pre-flattened f32 snapshot (the serving-core path:
        one flatten feeds the transport AND the snapshot ring)."""
        flat = np.ascontiguousarray(flat, np.float32)
        self.version += 1
        rc = self._lib.tps_server_publish(
            self._h, _u8(flat.view(np.uint8)), flat.nbytes, self.version
        )
        if rc != 0:
            raise RuntimeError("tps_server_publish failed")
        self._lib.tps_server_pump(self._h)  # serve waiting readers promptly
        self._refresh_read_stats()

    def _decode_payload(self, payload: np.ndarray,
                        wire=None) -> PyTree:
        """Payload bytes (a view into the receive buffer) → gradient
        tree; shared by the framed and legacy poll paths. Counted in
        ``decodes_done`` — the numerator of ``decodes_per_publish``.
        ``wire`` overrides the server's current wire — the old-epoch
        decode path during a codec renegotiation transition."""
        self.decodes_done += 1
        wire = wire if wire is not None else self.wire
        if wire:
            # zero-copy: decode reads the receive buffer via memoryview
            return wire.decode_from_bytes(payload)
        flat = np.frombuffer(payload, np.float32).copy()
        return _unflatten(flat, self.template)

    def renegotiate_wire(self, code, bucket_mb: float = 0.0) -> None:
        """Install a NEW codec wire as the current epoch (the
        controller's codec/bucket_mb renegotiation). During the
        transition the native batched-ingest fast path is bypassed —
        its in-C++ validator knows one fingerprint — and the Python
        framed poll consumes BOTH epochs; :meth:`finish_renegotiation`
        re-arms the native validator on the new fingerprint. Ladder
        entries must not exceed the boot wire's payload size (the
        transport's max_msg is fixed at bind time)."""
        from pytorch_ps_mpi_tpu.parallel.dcn import _renegotiate_common

        _renegotiate_common(self, code, bucket_mb)

    def finish_renegotiation(self) -> None:
        """Retire every old epoch and re-point the native frame
        validator (and the batch buffer sizing) at the current wire."""
        self._epoch_table = {}
        self._epoch_transition = False
        if self._batch_max:
            payload_bytes = self._expected_payload
            self._lib.tps_server_set_frame_check(
                self._h, self._fingerprint, payload_bytes)
            batch_max = max(1, min(64, (16 << 20)
                                   // max(payload_bytes, 1)))
            if batch_max * payload_bytes > self._batch_buf.nbytes:
                self._batch_buf = np.empty(
                    batch_max * payload_bytes, np.uint8)
            if batch_max != self._batch_max:
                self._batch_metas = (_BatchMeta * batch_max)()
                self._batch_max = batch_max

    def _note_connections(self) -> None:
        """Latch first-connect times: a worker's liveness clock starts
        when it first connects, not at server start — so ``stragglers``
        can tell a worker that died mid-run (ages from its last sign of
        life) from one that NEVER showed up (reported immediately)."""
        now = time.time()
        for w in range(self.num_workers):
            if w in self._ever_connected:
                continue
            if self._lib.tps_server_connected(self._h, w):
                self._ever_connected.add(w)
                self.last_seen.setdefault(w, now)

    def _poll_grad_framed(self, raw: bool = False
                          ) -> Optional[Tuple[int, int, PyTree]]:
        """Frame-checking poll — the shared ``frames.framed_poll`` loop
        (validate → reject-and-count → bounded staleness → decode, the
        fix for one misconfigured worker's size-mismatched frame killing
        the PS with a RuntimeError) over this transport's queue pop."""
        worker = ctypes.c_uint32()
        version = ctypes.c_uint64()
        self._lib.tps_server_pump(self._h)
        self._refresh_read_stats()

        def pop_once():
            n = self._lib.tps_server_pop_grad(
                self._h, _u8(self._grad_buf.view(np.uint8)),
                self._grad_buf.nbytes,
                ctypes.byref(worker), ctypes.byref(version),
            )
            if n < 0:  # unreachable: the buffer is sized to max_msg
                raise RuntimeError("tps_server_pop_grad: payload exceeds "
                                   "the transport's own frame cap")
            wid = int(worker.value)
            if n > 0:
                self._ever_connected.add(wid)
            return int(n), wid, int(version.value)

        return self._frames.framed_poll(self, pop_once, raw=raw)

    def poll_grad_batch(self, raw: bool = False) -> Optional[list]:
        """Native batched ingest: ONE pump + ONE C++ pop drains up to
        ``_batch_max`` queued pushes, each already validated (magic/
        version, size, config fingerprint, CRC32) on the native side —
        the serve loop's per-push cost drops to bookkeeping plus, in
        ``raw`` mode, handing the validated payload VIEW straight to the
        native fold. Returns the consumed ``(worker, version, grad)``
        list ([] = nothing pending), or None when the fast path is
        unavailable (frames off, stale library, or ``PS_NO_NATIVE``) —
        callers fall back to :meth:`poll_grad`. Views returned in raw
        mode alias the batch buffer: copy or fold before the NEXT
        batched pop."""
        from pytorch_ps_mpi_tpu.utils import native as _native

        if not self._batch_max or _native.fast_path_disabled():
            return None
        if getattr(self, "_epoch_transition", False):
            # mid-renegotiation: the in-C++ validator knows only one
            # fingerprint — fall back to the Python framed poll, which
            # consumes both epochs, until finish_renegotiation()
            return None
        if raw and not self.wire:
            raise ValueError("poll_grad_batch(raw=True) needs a codec wire")
        self._lib.tps_server_pump(self._h)
        self._refresh_read_stats()
        n = self._lib.tps_server_pop_grad_batch(
            self._h, _u8(self._batch_buf), self._batch_buf.nbytes,
            self._batch_metas, self._batch_max)
        if n <= 0:
            return []
        self.native_batches += 1
        self.native_batch_frames += int(n)

        def gen():
            for i in range(n):
                m = self._batch_metas[i]
                wid = int(m.worker)
                self._ever_connected.add(wid)
                payload = (self._batch_buf[int(m.off):int(m.off) + int(m.len)]
                           if not m.status else None)
                yield (wid, int(m.version), int(m.status), payload,
                       int(m.step), int(m.seq), float(m.send_wall))

        return self._frames.framed_batch_consume(self, gen(), raw=raw)

    def poll_grad(self, raw: bool = False
                  ) -> Optional[Tuple[int, int, PyTree]]:
        """One pending gradient as (worker, version, grad_tree), or None.
        Pumps the sockets, then drains stale gradients iteratively (same
        bounded-staleness discipline as the shm server). ``raw=True``
        (the homomorphic-aggregation mode) skips the decode and returns
        the validated payload BYTES as a view into the receive buffer —
        copy or fold before the next poll."""
        if raw and not self.wire:
            # without a codec wire the receive buffer is f32-typed and
            # there is no payload format to hand back — a [:n] slice
            # would be a silently mis-sized view, not bytes
            raise ValueError("poll_grad(raw=True) needs a codec wire")
        if self.frame:
            return self._poll_grad_framed(raw=raw)
        worker = ctypes.c_uint32()
        version = ctypes.c_uint64()
        self._lib.tps_server_pump(self._h)
        self._refresh_read_stats()
        expected = self.wire.wire_bytes if self.wire else _flat_size(self.template) * 4
        while True:
            n = self._lib.tps_server_pop_grad(
                self._h, _u8(self._grad_buf.view(np.uint8)),
                self._grad_buf.nbytes,
                ctypes.byref(worker), ctypes.byref(version),
            )
            if n == 0:
                return None
            if n < 0:
                raise RuntimeError(
                    "tps_server_pop_grad: payload exceeds wire spec — worker "
                    "and server codec configs disagree"
                )
            if int(n) != expected:
                # same one-time wire agreement the shm path enforces — and
                # checked for EVERY popped frame, stale-dropped ones
                # included: a codec-config mismatch on a straggling worker
                # must raise loudly, not be silently absorbed by the
                # staleness drop
                raise RuntimeError(
                    f"payload size {n} != wire spec {expected} bytes: worker "
                    "and server codec configs disagree"
                )
            # clamp at 0: a version from the future (e.g. a worker that
            # outlived a server restart) is simply fresh, and a negative
            # key would corrupt the histogram and dodge the drop check
            staleness = max(0, self.version - int(version.value))
            self.staleness_seen[staleness] = (
                self.staleness_seen.get(staleness, 0) + 1
            )
            self.last_seen[int(worker.value)] = time.time()
            self.grads_received += 1
            self.bytes_received += int(n)
            if staleness <= self.max_staleness:
                break
            self.stale_drops += 1
        if raw:
            # aggregation mode (codec wire only): the validated payload
            # bytes, a view into the receive buffer
            grad = self._grad_buf[:n]
        elif self.wire:
            grad = self._decode_payload(self._grad_buf[:n])
        else:
            # the no-codec receive buffer is f32-typed: slice elements
            grad = self._decode_payload(self._grad_buf[: n // 4])
        return int(worker.value), int(version.value), grad

    def connected(self, worker: int) -> bool:
        """Transport-level liveness: does a socket claiming this worker id
        exist right now? A crashed worker's connection closes (EOF/RST) —
        the positive failure signal shm can't give (SURVEY §5.3)."""
        self._lib.tps_server_pump(self._h)
        self._note_connections()
        return bool(self._lib.tps_server_connected(self._h, worker))

    def stragglers(self, timeout: float) -> Dict[int, float]:
        """Workers silent for ``timeout`` seconds: nothing consumed from
        them recently, nothing queued from them, and (stronger than shm)
        no open connection claiming their id — so a live worker that is
        merely mid-way through one long jitted step is never flagged, and
        acting on this report (elastic replacement) only ever targets
        dead sockets. A worker that NEVER connected has no liveness clock
        to age (``last_seen`` is latched on first connect, not at server
        start) and is reported immediately, whatever ``timeout`` — its
        age is measured from server start. The trade-off: a worker wedged
        WITH its socket open is not reported; watch ``last_seen`` ages
        for that."""
        self._lib.tps_server_pump(self._h)
        self._note_connections()
        now = time.time()
        out = {}
        for w in range(self.num_workers):
            if self._lib.tps_server_pending(self._h, w) > 0:
                continue  # pushed, awaiting consumption: alive
            if self._lib.tps_server_connected(self._h, w) == 1:
                continue  # open socket: alive (maybe slow), not lost
            if w not in self._ever_connected and w not in self.last_seen:
                # missing from the start: report NOW, no silence grace
                out[w] = now - self._t0
                continue
            age = now - self.last_seen.get(w, self._t0)
            if age > timeout:
                out[w] = age
        return out

    def close(self):
        # observability plane first (profiler thread, TSDB flush, fleet
        # deregistration), then the endpoint it was served from
        self.close_observability()
        self.close_metrics_http()
        # the read tier dies with the server (same rule as the /metrics
        # endpoint): a supervisor restart can never leak its listener
        sc = getattr(self, "serving_core", None)
        if sc is not None:
            sc.close()
        if self._h:
            self._lib.tps_server_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TcpPSWorker:
    """Connects to a :class:`TcpPSServer` (possibly on another host),
    reads the latest params whenever it likes, pushes version-tagged
    gradients. Same surface as ``ShmPSWorker``."""

    def __init__(self, host: str, port: int, worker_id: int, template: PyTree,
                 timeout: float = 30.0, code=None, seed: int = 0,
                 bucket_mb: float = 0.0, frame: bool = False,
                 cached_reads: bool = True, tree_slots: int = 0):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native tcpps unavailable (no g++?)")
        self._lib = lib
        # the native side takes a dotted-quad only; resolve hostnames here
        # so a bad name fails loudly as what it is, not as a timeout
        import socket

        try:
            addr = socket.gethostbyname(host)
        except OSError as e:
            raise RuntimeError(f"cannot resolve PS host {host!r}: {e}") from e
        self._h = lib.tps_worker_connect(
            addr.encode(), port, worker_id, int(timeout * 1000)
        )
        if not self._h:
            raise TimeoutError(
                f"tps_worker_connect({host}={addr}:{port}) timed out"
            )
        self.worker_id = worker_id
        self.template = template
        self._seed = seed + worker_id  # re-used by renegotiate()
        self.wire = (
            CodecWire(code, template, seed=self._seed,
                      bucket_mb=bucket_mb)
            if code is not None else None
        )
        # frame must match the server's (wire agreement); the fingerprint
        # is computed from THIS side's config — drift fails the compare
        self.frame = bool(frame)
        self._tamper = None  # one-shot outgoing-bytes hook (fault injection)
        self._wire_delay_s = 0.0  # one-shot post-seal delay (wire_delay)
        # monotonic push sequence for the frame trace ID — the fallback
        # when the caller doesn't pass an explicit lineage=(step, seq)
        self._auto_seq = 0
        # tree_slots > 0: pushes to an aggregation-tree parent — every
        # frame carries a fixed-capacity composed-lineage trailer (a
        # leaf pushing directly composes only itself)
        self.tree_slots = int(tree_slots)
        if self.tree_slots and not self.frame:
            raise ValueError("tree_slots requires frame=True")
        if self.frame:
            from pytorch_ps_mpi_tpu.resilience import frames as _frames

            self._frames = _frames
            self._fingerprint = _frames.wire_fingerprint(
                self.wire, template, tree_slots=self.tree_slots)
            payload_bytes = (self.wire.wire_bytes if self.wire
                             else _flat_size(template) * 4)
            self._frame_buf = np.empty(
                _frames.HEADER_BYTES + payload_bytes
                + _frames.trailer_bytes(self.tree_slots), np.uint8
            )
        self._param_buf = np.empty(_flat_size(template), np.float32)
        # version-conditional read cache: the request carries "I have v"
        # and an unchanged snapshot comes back as a cheap zero-payload
        # not-modified reply instead of the full re-shipped snapshot —
        # the fix for read_params re-shipping identical bytes every call.
        # Only the FLAT bytes are cached; every return still builds a
        # fresh tree, so callers that mutate returned params in place
        # (legal before this cache existed) stay correct.
        self.cached_reads = bool(cached_reads)
        self._cached_flat: Optional[np.ndarray] = None
        self._cached_version = 0
        self.reads_total = 0
        self.reads_not_modified = 0

    def read_params(self, timeout: float = 30.0) -> Tuple[PyTree, int]:
        """Latest published snapshot (blocks until the server's first
        publish, then one request/reply round trip per read). With
        ``cached_reads`` (default) the request is version-conditional:
        an unchanged snapshot costs a 28-byte header reply, not the full
        payload — the tree is rebuilt locally from the cached bytes."""
        self.reads_total += 1
        version = ctypes.c_uint64()
        deadline = time.time() + timeout
        have = (self._cached_version
                if self.cached_reads and self._cached_flat is not None
                else 0)
        while True:
            left_ms = max(1, int((deadline - time.time()) * 1000))
            n = self._lib.tps_worker_read_params(
                self._h, _u8(self._param_buf.view(np.uint8)),
                self._param_buf.nbytes, ctypes.byref(version), left_ms,
                have,
            )
            if n == -4:
                # not modified: the server confirmed our cached version;
                # fresh arrays from the cached bytes (mutation-safe)
                self.reads_not_modified += 1
                return (_unflatten(self._cached_flat, self.template),
                        self._cached_version)
            if n == -2:
                raise TimeoutError("tps_worker_read_params timed out")
            if n < 0:
                raise RuntimeError(f"tps_worker_read_params -> {n}")
            if version.value > 0:
                break
            if time.time() > deadline:
                raise TimeoutError("no parameter snapshot published yet")
            time.sleep(0.002)
        flat = self._param_buf[: n // 4].copy()
        if self.cached_reads:
            self._cached_flat, self._cached_version = flat, int(version.value)
        return _unflatten(flat, self.template), int(version.value)

    def push_grad(self, grad: PyTree, version: int,
                  timeout: float = 30.0,
                  lineage: Optional[Tuple[int, int]] = None,
                  composed=None) -> None:
        """``lineage=(step, seq)`` stamps the push's trace ID into the
        v2 frame header — same contract as ``ShmPSWorker.push_grad``.
        On a tree wire (``tree_slots > 0``), ``composed`` lists the
        constituent ``(worker, step, seq, send_wall)`` trace IDs for the
        lineage trailer; default is this worker's own trace ID (the
        direct-push / fallback case)."""
        if self.wire:
            # encode_to_bytes returns its preallocated ping-pong wire
            # buffer (one contiguous bucket payload per push) — the native
            # send consumes it synchronously, no defensive copy
            flat = self.wire.encode_to_bytes(grad)
        else:
            flat = _flatten(grad)
        self.push_payload(flat, version, timeout=timeout, lineage=lineage,
                          composed=composed)

    def push_payload(self, flat: np.ndarray, version: int,
                     timeout: float = 30.0,
                     lineage: Optional[Tuple[int, int]] = None,
                     composed=None) -> None:
        """Push pre-encoded payload bytes (exactly ``wire.wire_bytes``,
        or the flat f32 vector on a codec-less wire). The tree leader's
        hop path: it encodes explicitly (error feedback needs the
        payload AND its decode), then ships the bytes here."""
        if self.frame:
            step, seq = lineage if lineage is not None else (0, self._auto_seq)
            self._auto_seq += 1
            if self.tree_slots and composed is None:
                composed = [(self.worker_id, step, seq, time.time())]
            flat = self._frames.seal_frame(self._frame_buf, flat,
                                           self._fingerprint,
                                           step=step, seq=seq,
                                           composed=composed,
                                           tree_slots=self.tree_slots)
        if self._tamper is not None:
            # fault injection: corrupt the outgoing bytes AFTER sealing,
            # so the CRC no longer matches what travels
            t, self._tamper = self._tamper, None
            t(flat.view(np.uint8))
        d, self._wire_delay_s = self._wire_delay_s, 0.0
        if d:
            # fault injection (kind "wire_delay"): emulated wire latency
            # — sealed (send_wall stamped) but traveling late, the
            # window the lineage wire stage measures
            time.sleep(d)
        rc = self._lib.tps_worker_push_grad(
            self._h, _u8(flat.view(np.uint8)), flat.nbytes, version,
            int(timeout * 1000),
        )
        if rc == -2:
            raise TimeoutError("push_grad timed out awaiting server ack")
        if rc != 1:
            raise RuntimeError(f"tps_worker_push_grad -> {rc}")

    def renegotiate(self, code, bucket_mb: float = 0.0) -> bool:
        """Switch this worker's wire to a renegotiated codec epoch (the
        controller published it via ``control-epoch.json``). Returns
        False when declined — see
        :func:`~pytorch_ps_mpi_tpu.parallel.dcn._worker_renegotiate_common`."""
        from pytorch_ps_mpi_tpu.parallel.dcn import (
            _worker_renegotiate_common,
        )

        return _worker_renegotiate_common(self, code, bucket_mb=bucket_mb)

    def close(self):
        if self._h:
            self._lib.tps_worker_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
