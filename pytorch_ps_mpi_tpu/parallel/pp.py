"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

No reference analog (SURVEY §2.5 marks PP "not required for parity" —
the reference's constraint was models-fit-on-one-device) — added so the
parallelism matrix (DP × SP × TP × PP) is complete. TPU-first shape:

- **Homogeneous stages.** The pipelined body is S copies of one stage
  function (the standard homogeneous-transformer-stack setting); stage
  s's parameters carry a leading ``[pipe]`` shard axis, sharded
  ``P(pipe_axis)`` host-side — each device owns exactly its stage's
  weights AND (because grads come back shard-local) its stage's
  optimizer state: pipeline parallelism shards the optimizer for free.
- **One XLA program.** The schedule is a ``lax.scan`` over S + M − 1
  ticks inside ``shard_map``: at tick t, device s runs the stage on
  microbatch t − s (garbage-in, masked-out when t − s is outside
  [0, M)), then hands its activation to stage s+1 with a one-hop
  ``lax.ppermute`` — the same neighbor primitive ring attention uses.
  XLA overlaps the permute with the next tick's compute.
- **Training via autodiff.** ``jax.grad`` through the scan + ppermute
  yields the reverse pipeline automatically (ppermute's transpose is the
  reverse hop), so ``value_and_grad(pipeline loss)`` IS the backward
  schedule — no hand-written 1F1B state machine to get wrong. The cost
  is GPipe's bubble (S − 1 idle ticks per direction), amortized by M.

All functions run INSIDE ``shard_map`` with ``pipe_axis`` bound, mirroring
``parallel/tp.py``'s convention (leading local shard axis squeezed with
``x[0]``).

IMPORTANT: wrap these in ``shard_map`` with vma checking ENABLED (the
default ``check_vma=True``). With ``check_vma=False`` the transpose of
``lax.psum`` degrades to another psum, so differentiating through the
final loss/output replication multiplies every gradient by the stage
count (observed: exactly S× too large). The scan initializers below are
built device-varying (the ring.py trick) so the carry typechecks under
vma."""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def pipeline_apply(
    stage_params: PyTree,
    x_mb: jax.Array,
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    pipe_axis: str,
    local_grads: bool = False,
) -> jax.Array:
    """Run ``stage_fn`` S times (once per pipeline stage) over M
    microbatches.

    Args:
      stage_params: THIS device's stage parameters (leaves carry the
        local ``[1, ...]`` shard axis of the host-side ``[pipe, ...]``
        stack; squeezed here).
      x_mb: ``[M, mb, ...]`` microbatched input, replicated across the
        pipe axis (stage 0 consumes it).
      stage_fn: ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``
        (homogeneous stages; the transformer-stack case).
      pipe_axis: mesh axis name the stages live on.

    Returns ``[M, mb, ...]`` outputs of the final stage, replicated
    across the pipe axis (devices other than the last contribute zeros
    to a psum, so every device returns the same value — out_specs P()).
    """
    out, is_last = _pipeline_scan(stage_params, x_mb, stage_fn, pipe_axis)
    # only the last stage holds real outputs; replicate via psum.
    # local_grads: the psum here is a replication of one live copy, so
    # its correct transpose is identity (comms.psum_fwd_identity_bwd) —
    # required when differentiating under check_vma=False (the MPI_PS
    # fused-step contract; see module docstring for the failure mode)
    masked = jnp.where(is_last, out, 0.0)
    if local_grads:
        from pytorch_ps_mpi_tpu import comms

        return comms.psum_fwd_identity_bwd(masked, pipe_axis)
    return lax.psum(masked, pipe_axis)


def _pipeline_scan(stage_params, x_mb, stage_fn, pipe_axis):
    """The tick schedule. Returns (out, is_last): ``out`` holds the real
    final-stage outputs only on the last stage (zeros elsewhere) —
    consumers mask with ``is_last`` and psum to replicate."""
    params = jax.tree.map(lambda p: p[0], stage_params)
    s_count = lax.axis_size(pipe_axis)
    my_stage = lax.axis_index(pipe_axis)
    m = x_mb.shape[0]
    is_first = my_stage == 0
    is_last = my_stage == s_count - 1
    ticks = s_count + m - 1
    # device-varying zero (axis_index varies over the pipe axis): the
    # scan carries are written with stage-varying data every tick, so
    # their initial vma type must already vary or check_vma rejects the
    # loop (same trick as ring.py's accumulator init)
    vzero = (my_stage * 0).astype(x_mb.dtype)

    def tick(carry, t):
        cur, out = carry
        mb_idx = t - my_stage                     # microbatch this stage sees
        valid = (mb_idx >= 0) & (mb_idx < m)
        feed = x_mb[jnp.clip(t, 0, m - 1)]
        x_in = jnp.where(is_first, feed, cur)
        # double-where: on warmup/drain ticks this stage holds garbage
        # (zeros or a dead activation); substitute a benign input BEFORE
        # the stage so fns with data-dependent division (RMS-norm etc.)
        # stay finite — otherwise the NaN reaches the banked outputs via
        # 0*NaN in the mask (forward) or the zero-cotangent VJP (backward)
        safe_in = jnp.where(valid, x_in, jnp.ones_like(x_in))
        y = stage_fn(params, safe_in)
        # last stage banks finished microbatches (select, not multiply)
        slot = jnp.clip(mb_idx, 0, m - 1)
        write = is_last & valid
        out = out.at[slot].add(jnp.where(write, y, jnp.zeros_like(y)))
        # hand the activation to the next stage (ring hop; the wrap-around
        # S-1 -> 0 edge carries garbage that stage 0 ignores via is_first)
        nxt = lax.ppermute(
            y, pipe_axis, [(i, (i + 1) % s_count) for i in range(s_count)]
        )
        return (nxt, out), None

    out0 = x_mb * 0 + vzero
    cur0 = x_mb[0] * 0 + vzero
    (_, out), _ = lax.scan(tick, (cur0, out0), jnp.arange(ticks))
    return out, is_last


def pipeline_loss(
    stage_params: PyTree,
    x_mb: jax.Array,
    y_mb: jax.Array,
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    pipe_axis: str,
    local_grads: bool = False,
) -> jax.Array:
    """Mean of ``loss_fn(pipeline(x_mb), y_mb)`` over microbatches —
    differentiate THIS with ``jax.grad`` for the backward pipeline; the
    returned gradients for ``stage_params`` are shard-local (each device
    gets d/d(its own stage's weights)).

    The scalar is computed on the LAST stage only and psum-replicated —
    one live loss copy, one cotangent stream through the reverse ring.
    Requires a vma-checked shard_map (module docstring) UNLESS
    ``local_grads=True``, which lowers the replication through
    ``comms.psum_fwd_identity_bwd`` (correct transpose explicitly, for
    the optimizer's vma-unchecked fused step)."""
    out, is_last = _pipeline_scan(stage_params, x_mb, stage_fn, pipe_axis)
    local_loss = jax.vmap(loss_fn)(out, y_mb).mean()
    masked = jnp.where(is_last, local_loss, 0.0)
    if local_grads:
        from pytorch_ps_mpi_tpu import comms

        return comms.psum_fwd_identity_bwd(masked, pipe_axis)
    return lax.psum(masked, pipe_axis)


def init_stage_stack(key, s_count: int, init_one: Callable) -> PyTree:
    """Host-side ``[pipe]``-stacked parameters: ``init_one(key_i)`` per
    stage, leaves stacked on a new leading axis for ``P(pipe_axis)``
    sharding (the tp.py convention)."""
    keys = jax.random.split(key, s_count)
    stages = [init_one(k) for k in keys]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def stage_spec(params: PyTree, pipe_axis: str):
    """PartitionSpec pytree: every stacked leaf sharded over the pipe
    axis."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _: P(pipe_axis), params)
