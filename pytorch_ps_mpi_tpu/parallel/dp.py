"""Functional sync data-parallel train step.

The same pipeline ``MPI_PS.step`` runs (grad → encode → collective →
decode+sum → fused update), exposed as a pure function builder for users
who want explicit state threading instead of the optimizer object — the
idiomatic-JAX face of the reference's ``step`` engine (``ps.py:103-193``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_ps_mpi_tpu.bucketing import plan_buckets
from pytorch_ps_mpi_tpu.codecs import Codec, IdentityCodec
from pytorch_ps_mpi_tpu.mesh import DATA_AXIS
from pytorch_ps_mpi_tpu.optim import OPTIMIZERS
from pytorch_ps_mpi_tpu.ps import (
    aggregate,
    bucketed_aggregate,
    encode_tree,
    fused_allreduce_tree,
    leader_init_state,
    leader_scatter_shards,
    leader_shard_update,
    leader_slice_shards,
    leader_state_spec,
)

PyTree = Any


def make_sync_train_step(
    loss_fn: Callable[[PyTree, PyTree], jax.Array],
    mesh: Mesh,
    *,
    optim: str = "sgd",
    code: Optional[Codec] = None,
    axis_name: str = DATA_AXIS,
    mode: str = "allgather",
    average: bool = False,
    donate: bool = True,
    bucket_mb: float = 0.0,
    **hyper,
):
    """Build ``(init_fn, step_fn)``.

    ``init_fn(params) -> (opt_state, codec_state)``;
    ``step_fn(params, opt_state, codec_state, batch, rng) ->
    (params, opt_state, codec_state, loss)`` — one fused XLA program,
    batch sharded over ``axis_name``, params replicated.

    ``bucket_mb > 0`` fuses the aggregation collectives into dtype-grouped
    flat buckets (``bucketing.BucketPlan``) for ``mode='allgather'`` with a
    bucketable codec — bit-exact for identity/cast, one launch per bucket.
    The functional leader mode keeps the per-leaf path (its ZeRO-1 state
    layout is built by ``init_fn`` per leaf); use ``MPI_PS(mode='leader',
    bucket_mb=...)`` for bucket-sharded ZeRO-1.
    """
    code = code if code is not None else IdentityCodec()
    hyper_cls, init_state, update_fn = OPTIMIZERS[optim]
    h = hyper_cls(**hyper)
    size = int(mesh.shape[axis_name])

    def init_fn(params):
        def leaf(p):
            s = code.init_state(p.shape, p.dtype)
            return jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (size,) + x.shape), s
            )
        codec_state = jax.tree.map(leaf, params)
        if mode == "leader":
            # ZeRO-1: master param shards + sharded inner state (see
            # ps.LeaderState); the step all-gathers fresh replicated params
            return leader_init_state(params, init_state, size), codec_state
        return init_state(params), codec_state

    bucketed = (
        bucket_mb > 0 and mode == "allgather"
        and code.bucketable and not code.supports_fused_allreduce
    )
    if bucketed and jax.tree.leaves(code.init_state((1,), jnp.float32)):
        # same contract MPI_PS enforces: a bucketable codec must be
        # stateless, or the bucketed branch would silently freeze its
        # state (see codecs.base.Codec.bucketable)
        raise TypeError(
            f"{type(code).__name__}.bucketable=True but init_state is "
            "non-empty — bucketable codecs must be stateless"
        )

    def spmd(params, opt_state, codec_state, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = lax.pmean(loss, axis_name)
        if bucketed:
            # one collective per dtype-grouped flat bucket; the codec is
            # stateless by the bucketable contract, state passes through
            plan = plan_buckets(grads, bucket_mb)
            summed = bucketed_aggregate(
                code, grads, plan, axis_name, average, size, rng=rng
            )
            new_params, new_opt_state = update_fn(params, summed, opt_state, h)
            return new_params, new_opt_state, codec_state, loss
        if code.supports_fused_allreduce:
            # collective-protocol codec (PowerSGD two-psum): aggregation
            # IS the codec — same lowering as MPI_PS's fused step
            summed, new_codec_state = fused_allreduce_tree(
                code, grads, codec_state, axis_name, average, size
            )
        else:
            payloads, new_codec_state = encode_tree(
                code, grads, codec_state, rng, axis_name
            )
            summed = None
        if mode == "leader":
            if summed is not None:
                grad_shards = leader_slice_shards(summed, axis_name, size)
            elif code.supports_psum:
                grad_shards = leader_scatter_shards(
                    grads, axis_name, size,
                    getattr(code, "wire_dtype", None), average,
                )
            else:
                summed = aggregate(code, grads, payloads, axis_name, average, size)
                grad_shards = leader_slice_shards(summed, axis_name, size)
            new_params, new_opt_state = leader_shard_update(
                params, opt_state, grad_shards, update_fn, h, axis_name
            )
        else:
            if summed is None:
                summed = aggregate(code, grads, payloads, axis_name, average, size)
            new_params, new_opt_state = update_fn(params, summed, opt_state, h)
        return new_params, new_opt_state, new_codec_state, loss

    def step_fn(params, opt_state, codec_state, batch, rng):
        state_spec = jax.tree.map(lambda _: P(axis_name), codec_state)
        opt_spec = (
            leader_state_spec(opt_state, axis_name) if mode == "leader" else P()
        )
        mapped = jax.shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(), opt_spec, state_spec, P(axis_name), P()),
            out_specs=(P(), opt_spec, state_spec, P()),
            check_vma=False,
        )
        return mapped(params, opt_state, codec_state, batch, rng)

    return init_fn, jax.jit(step_fn, donate_argnums=(0, 1, 2) if donate else ())
