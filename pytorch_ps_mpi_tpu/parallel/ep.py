"""Expert parallelism: GShard-style top-k MoE over a mesh axis
(top-1 Switch gate by default; ``top_k=2`` is the classic GShard gate
with the chosen experts' probs renormalized per token).

No reference analog (SURVEY §2.5: EP absent — out of reference scope) —
added to complete the parallelism matrix (DP × SP × TP × PP × EP). The
design is the canonical TPU one (Lepikhin et al. 2020, GShard,
arXiv:2006.16668 — public technique): static-shape capacity-limited
dispatch so XLA sees fixed tensors, and ``lax.all_to_all`` over the
expert axis as the only collective — the exact op class the reference's
MPI stack explored but never shipped (``test_mpi.py:20`` Ialltoallv).

Shapes (inside ``shard_map`` with ``expert_axis`` of size D bound):

- tokens ``x [n_loc, d]`` — this device's slice of the batch.
- every device holds ``e_loc = E // D`` experts' FFN weights, stacked on
  a leading local axis (host-side ``[E, ...]`` sharded ``P(expert_axis)``).
- router weights ``wr [d, E]`` replicated.

Per device: route → build per-expert capacity buffers ``[E, C, d]`` →
``all_to_all`` (each device sends every other device the buffer slots of
THAT device's experts, receives its own experts' tokens from everyone)
→ run local experts → ``all_to_all`` back → combine with the gate.

Capacity semantics: ``C`` is per **(expert, source device)** — each
device dispatches at most C of ITS tokens to any one expert, so an
expert serves up to ``n_dev * C`` tokens per step and the dispatch/
all_to_all buffers are ``[E, C, d]`` *per device*. Sizing against a
GShard-style global per-expert budget B means ``capacity = B / n_dev``.
Overflowing tokens are dropped (output 0 for them — GShard semantics);
size C generously in tests to compare exactly against the dense oracle.

Like ``parallel/pp.py``: wrap in a vma-checked ``shard_map`` (the default
``check_vma=True``) when differentiating, so the collective transposes
are exact; shard tokens over the expert axis (or jointly over data ×
expert — the GShard layout) so each device contributes its own slice.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def init_moe(key, d: int, f: int, n_experts: int, scale: float = 0.1) -> PyTree:
    """Host-side MoE params: router (replicated) + per-expert FFN weights
    stacked on a leading ``[E]`` axis for ``P(expert_axis)`` sharding."""
    kr, k1, k2 = jax.random.split(key, 3)
    return {
        "wr": scale * jax.random.normal(kr, (d, n_experts), jnp.float32),
        "w1": scale * jax.random.normal(k1, (n_experts, d, f), jnp.float32),
        "w2": scale * jax.random.normal(k2, (n_experts, f, d), jnp.float32),
    }


def moe_spec(params: PyTree, expert_axis: str):
    from jax.sharding import PartitionSpec as P

    return {
        "wr": P(),
        "w1": P(expert_axis),
        "w2": P(expert_axis),
    }


def _route_top1(x, wr) -> Tuple[jax.Array, jax.Array]:
    """(expert index, gate) per token — softmax prob of the argmax."""
    probs = jax.nn.softmax(x @ wr, axis=-1)          # [n, E]
    eidx = jnp.argmax(probs, axis=-1)                # [n]
    gate = jnp.take_along_axis(probs, eidx[:, None], axis=1)[:, 0]
    return eidx, gate


def _route_topk(x, wr, k: int) -> Tuple[jax.Array, jax.Array]:
    """(expert indices [n, k], gates [n, k]) — softmax probs of the
    top-k experts, renormalized to sum to 1 per token (the GShard top-2
    convention: the chosen experts split the token's whole weight)."""
    probs = jax.nn.softmax(x @ wr, axis=-1)          # [n, E]
    gates, eidx = lax.top_k(probs, k)                # [n, k] each
    gates = gates / jnp.maximum(gates.sum(axis=-1, keepdims=True), 1e-9)
    return eidx, gates


def _dispatch_combine(x, eidx_k, gate_k, w1, w2, expert_axis, capacity):
    """Dispatch→expert→combine for a top-k assignment in ONE all_to_all
    round trip: choice rank c writes its tokens into slots
    ``[c*C, (c+1)*C)`` of a single ``[E, k*C, d]`` buffer (each choice
    has its own independent capacity budget, so a token can lose its
    2nd choice to capacity while keeping its 1st), the experts process
    all k*C slots together, and each choice combines from its slice.
    k=1 reduces exactly to the original top-1 machinery; k>1 costs the
    same two all_to_all launches per layer, not 2k.

    ``eidx_k``/``gate_k``: [n, k]."""
    n_loc, d = x.shape
    k = eidx_k.shape[1]
    n_dev = lax.axis_size(expert_axis)
    e_loc = w1.shape[0]
    n_experts = n_dev * e_loc

    buf = jnp.zeros((n_experts, k * capacity, d), x.dtype)
    keeps, slots = [], []
    for c in range(k):
        eidx = eidx_k[:, c]
        # slot of each token within its expert's capacity buffer for THIS
        # choice rank (among this device's tokens): running count of
        # same-expert tokens before it
        onehot = jax.nn.one_hot(eidx, n_experts, dtype=jnp.int32)   # [n, E]
        pos = jnp.cumsum(onehot, axis=0) * onehot                    # 1-based
        slot0 = pos.max(axis=1) - 1                                  # [n]
        keep = (slot0 >= 0) & (slot0 < capacity)
        slot = jnp.clip(slot0, 0, capacity - 1)
        buf = buf.at[eidx, c * capacity + slot].add(
            jnp.where(keep[:, None], x, jnp.zeros_like(x))
        )
        keeps.append(keep)
        slots.append(slot)

    # one all_to_all over the expert axis: send device j its experts'
    # slots (all k choices at once), receive my experts' tokens
    buf = buf.reshape(n_dev, e_loc, k * capacity, d)
    recv = lax.all_to_all(buf, expert_axis, split_axis=0, concat_axis=0)
    # [n_dev, e_loc, k*C, d] — recv[j] = device j's tokens for MY experts

    tok = recv.transpose(1, 0, 2, 3).reshape(e_loc, n_dev * k * capacity, d)
    h = jax.nn.gelu(jnp.einsum("etd,edf->etf", tok, w1))
    y = jnp.einsum("etf,efd->etd", h, w2)
    y = y.reshape(e_loc, n_dev, k * capacity, d).transpose(1, 0, 2, 3)

    # return trip: outputs for device j's tokens go back to device j
    back = lax.all_to_all(y, expert_axis, split_axis=0, concat_axis=0)
    out_buf = back.reshape(n_experts, k * capacity, d)

    # combine: each kept (token, choice) reads its slot, scaled by gate
    out = jnp.zeros_like(x)
    for c in range(k):
        tok_out = out_buf[eidx_k[:, c], c * capacity + slots[c]]
        tok_out = tok_out * gate_k[:, c][:, None]
        out = out + jnp.where(keeps[c][:, None], tok_out,
                              jnp.zeros_like(tok_out))
    return out


def moe_apply(
    x: jax.Array,
    params: Dict[str, jax.Array],
    expert_axis: str,
    *,
    capacity: int,
    top_k: int = 1,
) -> jax.Array:
    """Top-k MoE forward for this device's tokens (default top-1, the
    Switch/GShard-minimal config; ``top_k=2`` is the classic GShard
    gate with the chosen experts' probs renormalized per token).

    Returns ``[n_loc, d]``: each token's gated expert output (zeros for
    capacity-dropped choices). Differentiable end to end — the dispatch/
    combine are scatter-adds/gathers and the collective is all_to_all
    (whose transpose is the reverse all_to_all). Each choice rank owns
    an independent capacity budget inside ONE shared ``[E, k*C, d]``
    buffer (2x the slots at top-2 — GShard's budget), so a token can
    lose its 2nd choice to capacity while keeping its 1st — and every
    layer pays exactly one all_to_all round trip regardless of k.
    """
    w1, w2 = params["w1"], params["w2"]         # [e_loc, d, f], [e_loc, f, d]
    n_dev = lax.axis_size(expert_axis)
    assert params["wr"].shape[1] == n_dev * w1.shape[0], (
        params["wr"].shape, n_dev, w1.shape)
    if top_k == 1:
        eidx, gate = _route_top1(x, params["wr"])
        eidx_k, gate_k = eidx[:, None], gate[:, None]
    else:
        eidx_k, gate_k = _route_topk(x, params["wr"], top_k)
    return _dispatch_combine(x, eidx_k, gate_k, w1, w2, expert_axis, capacity)


def load_balance_loss(x: jax.Array, wr: jax.Array, top_k: int = 1,
                      expert_axis: str = None) -> jax.Array:
    """Switch/GShard auxiliary load-balancing loss for this device's
    tokens: ``E * sum_e f_e * P_e`` where ``f_e`` is the fraction of
    (token, choice) assignments routed to expert e and ``P_e`` the mean
    router probability of e (Fedus et al. 2021 eq. 4; Lepikhin et al.
    2020 §3.2 — public techniques). Minimized (value 1.0) at a perfectly
    uniform assignment; without it the router collapses onto a few
    experts and the capacity buffers drop everything else.

    Differentiable through ``P_e`` (the f_e counts are stop-gradient
    by construction — argmax/top_k are non-differentiable). With
    ``expert_axis`` bound, f/P are psum-averaged so every device
    penalizes the GLOBAL balance, not its local slice. The router
    forward here duplicates the dispatch path's textually, but under
    jit XLA's common-subexpression elimination merges the identical
    ``x @ wr`` / softmax; ``lax.top_k`` breaks ties lowest-index-first
    exactly like ``_route_top1``'s argmax, so the assignment counted is
    the assignment dispatched."""
    probs = jax.nn.softmax(x @ wr, axis=-1)              # [n, E]
    n_experts = wr.shape[1]
    _, eidx = lax.top_k(probs, top_k)                    # [n, k]
    counts = jax.nn.one_hot(eidx, n_experts, dtype=probs.dtype).sum(
        axis=(0, 1))                                     # [E]
    n_assign = jnp.asarray(eidx.size, probs.dtype)
    p_mean = probs.mean(axis=0)                          # [E]
    if expert_axis is not None:
        counts = lax.psum(counts, expert_axis)
        n_assign = lax.psum(n_assign, expert_axis)
        p_mean = lax.pmean(p_mean, expert_axis)
    f = counts / jnp.maximum(n_assign, 1.0)
    return n_experts * jnp.sum(f * p_mean)


def moe_dense_oracle(x: jax.Array, params: Dict[str, jax.Array],
                     top_k: int = 1) -> jax.Array:
    """Single-device reference: every token through its own top-k
    expert(s) (no capacity limit) — the equality oracle for tests AND
    the dense fallback ``models/moe.py`` runs outside ``shard_map``.

    Computes all experts for all tokens and combines with a one-hot
    select (n·E·f work) rather than gathering per-token weight copies: a
    ``w1[eidx]`` gather materializes ``[n, d, f]`` — 4.3 GB per layer at
    8K tokens for BERT-ish sizes — while the all-experts activations are
    ``[n, E, f]``, ~30x smaller there. Gradients are identical: the
    one-hot zeroes non-selected experts' paths exactly like the gather.
    """
    h = jax.nn.gelu(jnp.einsum("td,edf->tef", x, params["w1"]))
    y_all = jnp.einsum("tef,efd->ted", h, params["w2"])
    n_experts = params["wr"].shape[1]
    if top_k == 1:
        eidx, gate = _route_top1(x, params["wr"])
        onehot = jax.nn.one_hot(eidx, n_experts, dtype=x.dtype)
        return jnp.einsum("ted,te->td", y_all, onehot) * gate[:, None]
    eidx, gates = _route_topk(x, params["wr"], top_k)
    out = jnp.zeros_like(x)
    for c in range(top_k):
        onehot = jax.nn.one_hot(eidx[:, c], n_experts, dtype=x.dtype)
        out = out + (jnp.einsum("ted,te->td", y_all, onehot)
                     * gates[:, c][:, None])
    return out
