"""Sharded parameter servers: the PS scaling axis, across processes/hosts.

The reference's topology is one rank-0 server owning every parameter
(reference ``ps.py:103-193`` — the centralized PS its ``igather``/
``ibcast`` implement); that single server is the bandwidth and update-rate
bottleneck as workers scale. The classic fix (Li et al., OSDI'14,
"Scaling Distributed Machine Learning with the Parameter Server") is to
PARTITION the parameter vector across S server shards: each server owns a
contiguous slice, applies updates for its slice only, and workers
read/push per-slice. This module is that topology over the cross-host TCP
transport (``parallel/tcp.py``), composing with everything the
single-server async path already has — jitted worker compute, codec-
compressed payload bytes, per-shard bounded staleness, ack back-pressure.

In-XLA, the same idea is the ZeRO-1 ``mode='leader'`` lowering in
``ps.py:94-166`` (optimizer state partitioned 1/world per device); here it
is the host-process/DCN instantiation: S OS processes (one per host in
deployment), each a full :class:`~pytorch_ps_mpi_tpu.parallel.tcp.TcpPSServer`
for its slice. Asynchrony is genuinely per-shard — each shard advances its
own version counter at its own pace, so a worker's snapshot is a vector of
per-shard versions (the "inconsistent read" of AsySG-InCon, now also
inconsistent ACROSS shards), and staleness is measured and bounded
shard-locally.

Everything is flat-f32-slice based: optimizer update rules (SGD/momentum,
Adam) are elementwise, so updating each slice independently is EXACTLY the
single-server update — sharding changes where state lives, never the math
(tested: 1-shard and 2-shard runs from the same seed agree when run
synchronously).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pytorch_ps_mpi_tpu.parallel.dcn import _flat_size, _flatten, _unflatten

PyTree = Any


def shard_plan(n_total: int, n_shards: int) -> List[Tuple[int, int]]:
    """Balanced contiguous [start, stop) slices of a length-``n_total``
    flat vector; earlier shards get the remainder (sizes differ by ≤1)."""
    if not 1 <= n_shards <= n_total:
        raise ValueError(f"need 1 <= n_shards <= {n_total}, got {n_shards}")
    base, rem = divmod(n_total, n_shards)
    plan, start = [], 0
    for s in range(n_shards):
        stop = start + base + (1 if s < rem else 0)
        plan.append((start, stop))
        start = stop
    return plan


def planned_shards(control_dir: Optional[str], default: int) -> int:
    """The shard count the NEXT server generation should boot with:
    the structural controller's shard split/merge verdict is recorded
    as a PLAN in ``control-topo.json`` (never applied to a live
    generation — a shard move rehashes the whole key space), and every
    sharded driver consults this at spawn time.  Falls back to
    ``default`` (the cfg value) when no plan exists."""
    from pytorch_ps_mpi_tpu.control.topo import planned_shards as _planned

    return _planned(control_dir, default)


def _slice_template(n: int) -> PyTree:
    return {"flat": np.zeros((n,), np.float32)}


def server_main(shard_id: int, n_shards: int, port: int,
                cfg: Dict[str, Any], out_path: str) -> None:
    """One shard-server process body: own slice ``shard_id`` of the flat
    parameter vector, apply jitted elementwise optimizer updates in
    arrival order with shard-local bounded staleness, and on completion
    write the final slice + metrics to ``out_path`` (.npz).

    Stops after consuming ``expected`` pushes (applied + stale-dropped):
    every worker pushes once per step per shard, so the count is exact.
    ``cfg["server_slow_ms"][str(shard_id)]`` injects a per-update sleep —
    a deliberately slow SHARD for tests to force per-shard version
    divergence (the asynchrony axis single-server PS doesn't have).

    Failure story matches the single-server loop: with
    ``cfg["checkpoint_dir"]`` set, each shard snapshots ITS OWN slice +
    optimizer state under ``<dir>/shard<i>`` every
    ``cfg["checkpoint_every"]`` applied updates; ``cfg["resume"]``
    restores it with the same crash-window version jump — shards recover
    INDEPENDENTLY (a replacement for shard 1 does not touch shard 0,
    the horizontal-recovery property Li et al.'s design calls out).
    """
    import jax

    from pytorch_ps_mpi_tpu.optim import OPTIMIZERS
    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
    from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSServer

    code = None
    if cfg.get("codec"):
        from pytorch_ps_mpi_tpu.codecs import get_codec

        code = get_codec(cfg["codec"], **cfg.get("codec_kw", {}))

    _, params0, _, _ = make_problem(cfg)
    flat0 = _flatten(params0)
    start, stop = shard_plan(flat0.size, n_shards)[shard_id]
    template = _slice_template(stop - start)
    params = {"flat": flat0[start:stop].copy()}

    hyper_cls, init_state, update_fn = OPTIMIZERS[cfg.get("optim", "sgd")]
    h = hyper_cls(**cfg.get("hyper", {"lr": 0.05}))
    state = init_state(params)
    update = jax.jit(lambda p, g, s: update_fn(p, g, s, h))

    from pytorch_ps_mpi_tpu.parallel.async_train import worker_cfg

    n_workers = int(cfg["n_workers"])
    expected = sum(worker_cfg(cfg, w)[1] for w in range(n_workers))
    slow_ms = 0.0
    if isinstance(cfg.get("server_slow_ms"), dict):
        slow_ms = float(cfg["server_slow_ms"].get(str(shard_id), 0.0))

    # hierarchical-tree composition (cfg["tree"], parallel.tree): the
    # shard's pushers are group LEADERS (ids past n_workers) shipping
    # composed group sums with lineage trailers — path-sharding stacks
    # on key-sharding. Stop/accounting switch from frames to the exact
    # composed worker-push count the trailers carry.
    tree_mode = bool(cfg.get("tree"))
    tree_slots = int(cfg.get("tree_slots", 0) or 0) if tree_mode else 0
    id_space = n_workers + len(cfg.get("tree_members") or ())
    server = TcpPSServer(port, num_workers=id_space, template=template,
                         max_staleness=int(cfg.get("max_staleness", 4)),
                         code=code, frame=bool(cfg.get("frame_check")),
                         tree_slots=tree_slots)

    # per-shard online diagnosis: each shard server gets its own
    # HealthMonitor and /metrics + /health endpoint (port auto-assigned
    # — S shards cannot share one pinned port; the bound port rides the
    # stdout handshake line below as "health_port")
    monitor = None
    health_port = None
    if (cfg.get("health") or cfg.get("health_dir")
            or cfg.get("health_port") is not None
            or cfg.get("metrics_port") is not None):
        from pytorch_ps_mpi_tpu.telemetry.diagnosis import HealthMonitor

        monitor = HealthMonitor(server, cfg)
        if (cfg.get("health_port") is not None
                or cfg.get("metrics_port") is not None):
            health_port = server.start_metrics_http(0)

    # per-shard gradient lineage: each shard tracks the trace IDs its
    # own framed pushes carry (staleness is shard-local, so lineage is
    # too) into lineage-shard<i>.jsonl — same arming rule as serve()
    tracker = None
    if ((cfg.get("lineage") or cfg.get("lineage_dir"))
            and cfg.get("frame_check")):
        from pytorch_ps_mpi_tpu.telemetry.lineage import LineageTracker

        tracker = LineageTracker(server, cfg, name=f"shard{shard_id}")
        if cfg.get("anatomy", "auto") not in (False, "off", 0):
            # per-shard round anatomy (same auto-with-lineage rule as
            # serve()): anatomy-shard<i>.jsonl rows + the anatomy_*
            # canonical keys on this shard's endpoint — a sharded
            # fleet's per-shard critical paths stay separable
            from pytorch_ps_mpi_tpu.telemetry.anatomy import RoundAnatomy

            tracker.anatomy = RoundAnatomy(server, cfg,
                                           name=f"shard{shard_id}")

    # per-shard read tier (the ServingCore extraction's point): each
    # shard serves ITS slice under a per-tenant namespace — no trainer
    # loop involved, readers hit the shard's own read port with tenant
    # "shard<i>" (the bound port rides the stdout handshake). monitors
    # stay the shard's own (built above), so monitors=False here.
    core = None
    if cfg.get("serving") or cfg.get("read_port") is not None:
        from pytorch_ps_mpi_tpu.serving import ServingCore

        # S shards on one host cannot share a pinned read port: each
        # shard auto-assigns and reports it in the handshake line
        scfg = dict(cfg)
        if cfg.get("read_port") is not None:
            scfg["read_port"] = 0
        core = ServingCore(server, scfg, monitors=False,
                           tenant=f"shard{shard_id}")

    # per-shard fleet observability plane: retained metrics history +
    # SLO watchdog + continuous profiler, and — with cfg["fleet_dir"] —
    # registration of THIS shard's endpoint under "shard<i>" so one
    # /fleet scrape covers the whole sharded fleet (a restarted shard
    # re-registers under the same name and rejoins the pane). Fleet
    # membership NEEDS a live endpoint: a fleet_dir with no explicit
    # metrics/health port still binds one (auto-assigned, in the hello)
    if (cfg.get("fleet_dir") or cfg.get("fleet")) and health_port is None:
        health_port = server.start_metrics_http(0)
    ocfg = dict(cfg)
    ocfg["fleet_role"] = "shard"
    ocfg.pop("fleet_name", None)
    server.arm_observability(ocfg, name=f"shard{shard_id}")

    # per-shard control plane: staleness LR scaling + read-tier tuning
    # on this shard's own verdicts (control-shard<i>.jsonl). The codec
    # rule is forced off — a shard cannot renegotiate the wire
    # unilaterally, every shard's fingerprint must move together with
    # the workers' (single-server runs own the epoch file).
    ctl = None
    if cfg.get("control") or cfg.get("control_kw") or cfg.get("control_dir"):
        from pytorch_ps_mpi_tpu.control import Controller

        ccfg = dict(cfg)
        ccfg["control_kw"] = {**(cfg.get("control_kw") or {}),
                              "ladder": None}
        ctl = Controller(server, ccfg, core=core,
                         name=f"shard{shard_id}")

    ckpt = None
    applied_before = 0
    checkpoint_every = int(cfg.get("checkpoint_every", 50))
    if cfg.get("resume") and not cfg.get("checkpoint_dir"):
        raise ValueError("cfg['resume'] requires cfg['checkpoint_dir']")
    if cfg.get("checkpoint_dir"):
        from pytorch_ps_mpi_tpu.parallel.async_train import (
            _restore_ps_checkpoint,
        )
        from pytorch_ps_mpi_tpu.utils.checkpoint import CheckpointManager

        ckpt = CheckpointManager(
            os.path.join(cfg["checkpoint_dir"], f"shard{shard_id}")
        )
        if cfg.get("resume"):
            params, state, applied_before, server.version = (
                _restore_ps_checkpoint(ckpt, params, state, checkpoint_every)
            )

    # the coordinator reads the auto-assigned port from this line
    hello = {"shard": shard_id, "port": server.port}
    if health_port is not None:
        hello["health_port"] = health_port
    if core is not None and core.read_port is not None:
        hello["read_port"] = core.read_port
    print(json.dumps(hello), flush=True)

    def _publish(p):
        if core is not None:
            core.publish(p)
        else:
            server.publish(p)

    try:
        _publish(params)
        applied = 0
        cadence = None
        if ckpt:
            from pytorch_ps_mpi_tpu.parallel.async_train import (
                _PSCheckpointCadence,
            )

            cadence = _PSCheckpointCadence(ckpt, checkpoint_every,
                                           applied_before)
        # Resume contract: a replacement server expects the FULL job push
        # count, because workers restart from step 0 alongside it (the
        # parameter snapshot carries the training progress; worker step
        # indices are only push bookkeeping — see
        # test_sharded_checkpoint_resume_continues_independently, where
        # phase-2 applied_total accumulates on top of applied_before).
        # Workers that instead survive a server crash and push only their
        # remaining steps exit via the bounded server_timeout, not a hang.
        deadline = time.time() + float(cfg.get("server_timeout", 300.0))
        next_tick = 0.0

        def _consumed() -> int:
            # tree mode counts composed worker pushes (the trailers'
            # exact accounting); star mode counts frames
            return (server.tree_composed if tree_mode
                    else server.grads_received)

        while _consumed() < expected and time.time() < deadline:
            now = time.monotonic()
            if now >= next_tick:
                next_tick = now + float(cfg.get("tick_interval", 0.2))
                if server.timeseries_db is not None:
                    # TSDB sample + SLO sweep, serve-thread only — the
                    # same tick discipline as the single-server loop
                    server.observability_tick()
                if ctl is not None:
                    ctl.tick()
            item = server.poll_grad()
            if item is None:
                time.sleep(0.0005)
                continue
            wid, ver, grad = item
            staleness = max(0, server.version - ver)
            if monitor is not None:
                monitor.observe_grad(wid, staleness)
            if ctl is not None:
                ctl.observe_push(wid, staleness)
            up_t0 = time.perf_counter()
            comp_n = 1
            if tree_slots:
                comp_n = (server._composed_queue.popleft()
                          if server._composed_queue else 1)
            wgt = ctl.push_weight(wid) if ctl is not None else 1.0
            if wgt != 1.0:
                # per-push staleness LR weight, shard-local (the
                # controller's lr_scale rule); comp_n folds in too
                grad = jax.tree.map(lambda x: x * wgt / comp_n, grad)
            elif comp_n > 1:
                # a leader frame carries its group's SUM — apply the
                # group mean (same rule as the tree root's loop)
                grad = jax.tree.map(lambda x: x / comp_n, grad)
            params, state = update(params, grad, state)
            applied += 1
            if slow_ms:
                time.sleep(slow_ms / 1e3)
            _publish(jax.tree.map(np.asarray, params))
            if tracker is not None:
                tracker.observe_publish(server.version,
                                        time.perf_counter() - up_t0)
            if cadence:
                cadence.maybe_save(params, state, server,
                                   applied_before + applied)
        if cadence:
            cadence.final_save(params, state, server,
                               applied_before + applied)
        m = server.metrics()
        np.savez(
            out_path,
            flat=np.asarray(params["flat"]),
            start=start,
            stop=stop,
            version=server.version,
            applied_total=applied_before + applied,
            grads_received=m["grads_received"],
            stale_drops=m["stale_drops"],
            compression_ratio=m["compression_ratio"],
            staleness_hist=json.dumps(
                {int(k): int(v) for k, v in server.staleness_seen.items()}
            ),
            health=(monitor.render_json() if monitor is not None else "{}"),
            lineage=json.dumps(tracker.snapshot()
                               if tracker is not None else {}),
            serving=json.dumps(core.serving_snapshot()
                               if core is not None else {}),
            slo=json.dumps(server.slo_watchdog.snapshot()
                           if server.slo_watchdog is not None else {}),
            control=json.dumps(ctl.snapshot()
                               if ctl is not None else {}),
        )
    finally:
        if ctl is not None:
            ctl.close()
        if tracker is not None:
            if tracker.anatomy is not None:
                tracker.anatomy.close()
            tracker.close()
        server.close()


def worker_main_sharded(addrs: Sequence[str], worker_id: int,
                        cfg: Dict[str, Any],
                        out_path: Optional[str] = None) -> int:
    """Worker process body against S shard servers: one jitted
    ``value_and_grad`` per step, then slice the flat gradient and push
    each slice to its shard tagged with THAT shard's snapshot version.
    Reads are per-shard (S request/reply round trips) and the versions
    they return may disagree — recorded and written to ``out_path`` so
    tests can assert cross-shard divergence actually happened."""
    import jax

    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
    from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSWorker

    code = None
    if cfg.get("codec"):
        from pytorch_ps_mpi_tpu.codecs import get_codec

        code = get_codec(cfg["codec"], **cfg.get("codec_kw", {}))

    _, params0, batch_fn, loss_fn = make_problem(cfg)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))  # ONLY grad source
    flat0 = _flatten(params0)
    plan = shard_plan(flat0.size, len(addrs))

    conns = []
    for (start, stop), addr in zip(plan, addrs):
        host, port = addr.rsplit(":", 1)
        tmpl = _slice_template(stop - start)

        def make_conn(host=host, port=int(port), tmpl=tmpl):
            return TcpPSWorker(
                host, port, worker_id, tmpl, code=code,
                timeout=float(cfg.get("open_timeout", 60.0)),
                frame=bool(cfg.get("frame_check")),
            )

        if cfg.get("resilient"):
            # per-shard resilience: each connection retries/reconnects
            # independently, so one shard's restart-from-checkpoint never
            # takes down pushes to the healthy shards
            from pytorch_ps_mpi_tpu.resilience.worker import ResilientWorker

            conns.append(ResilientWorker(
                make_conn, worker_id=worker_id,
                seed=int(cfg.get("fault_seed", cfg.get("seed", 0))),
                **cfg.get("resilience_kw", {})))
        else:
            conns.append(make_conn())

    from pytorch_ps_mpi_tpu.parallel.async_train import worker_cfg

    slow_ms, steps = worker_cfg(cfg, worker_id)

    pushed = 0
    max_version_spread = 0
    try:
        flat = np.empty_like(flat0)
        for step in range(steps):
            versions = []
            for (start, stop), w in zip(plan, conns):
                slice_params, ver = w.read_params(
                    timeout=float(cfg.get("open_timeout", 60.0)))
                flat[start:stop] = slice_params["flat"]
                versions.append(ver)
            max_version_spread = max(max_version_spread,
                                     max(versions) - min(versions))
            params = _unflatten(flat, params0)
            loss, grads = grad_fn(params, batch_fn(step, worker_id))
            jax.block_until_ready(grads)
            if slow_ms:
                time.sleep(slow_ms / 1e3)
            g_flat = _flatten(grads)
            for (start, stop), ver, w in zip(plan, versions, conns):
                # one push per shard per step: the step doubles as the
                # monotonic per-connection push seq in the trace ID
                w.push_grad({"flat": g_flat[start:stop]}, ver,
                            timeout=float(cfg.get("push_timeout", 60.0)),
                            lineage=(step, step))
            pushed += 1
    finally:
        for w in conns:
            w.close()
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"pushed": pushed,
                       "max_version_spread": max_version_spread}, f)
    return pushed


def assemble(paths: Sequence[str], template: PyTree) -> PyTree:
    """Reassemble the full parameter tree from the shard .npz files the
    servers wrote (validates the slices tile the flat vector exactly)."""
    flat = np.empty(_flat_size(template), np.float32)
    covered = 0
    for p in paths:
        z = np.load(p, allow_pickle=False)
        start, stop = int(z["start"]), int(z["stop"])
        flat[start:stop] = z["flat"]
        covered += stop - start
    if covered != flat.size:
        raise ValueError(f"shards cover {covered} of {flat.size} elements")
    return _unflatten(flat, template)


def spawn_shard_server(shard_id: int, n_shards: int, cfg: Dict[str, Any],
                       out_path: str,
                       env: Optional[Dict[str, str]] = None):
    """Launch ``server_main`` in a fresh OS process (port auto-assigned;
    the child prints ``{"shard": i, "port": p}`` on stdout — use
    :func:`read_server_port`). Pinned to the host backend like
    ``async_train.spawn_worker``."""
    src = (
        "import json,sys\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_ps_mpi_tpu.parallel.sharded import server_main\n"
        "sid, ns, cfg, out = (int(sys.argv[1]), int(sys.argv[2]),\n"
        "                     json.loads(sys.argv[3]), sys.argv[4])\n"
        "server_main(sid, ns, 0, cfg, out)\n"
    )
    e = dict(os.environ)
    e.update({"JAX_PLATFORMS": "cpu"})
    e.update(env or {})
    return subprocess.Popen(
        [sys.executable, "-c", src, str(shard_id), str(n_shards),
         json.dumps(cfg), out_path],
        env=e, stdout=subprocess.PIPE, text=True,
    )


def read_server_port(proc, timeout: float = 120.0) -> int:
    """Block until a spawned shard server prints its port line."""
    import select

    deadline = time.time() + timeout
    while time.time() < deadline:
        r, _, _ = select.select([proc.stdout], [], [], 0.5)
        if r:
            line = proc.stdout.readline()
            if line:
                return int(json.loads(line)["port"])
        if proc.poll() is not None:
            raise RuntimeError(f"shard server exited early: {proc.returncode}")
    raise TimeoutError("shard server never reported its port")


def spawn_sharded_worker(addrs: Sequence[str], worker_id: int,
                         cfg: Dict[str, Any], out_path: str,
                         env: Optional[Dict[str, str]] = None):
    """Launch ``worker_main_sharded`` in a fresh OS process."""
    src = (
        "import json,sys\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_ps_mpi_tpu.parallel.sharded import worker_main_sharded\n"
        "addrs, wid, cfg, out = (json.loads(sys.argv[1]), int(sys.argv[2]),\n"
        "                        json.loads(sys.argv[3]), sys.argv[4])\n"
        "sys.exit(0 if worker_main_sharded(addrs, wid, cfg, out) >= 0 else 1)\n"
    )
    e = dict(os.environ)
    e.update({"JAX_PLATFORMS": "cpu"})
    e.update(env or {})
    return subprocess.Popen(
        [sys.executable, "-c", src, json.dumps(list(addrs)), str(worker_id),
         json.dumps(cfg), out_path],
        env=e,
    )
