"""AsySG-InCon: asynchronous SGD with inconsistent reads.

The algorithm the reference implements (Lian et al. 2015,
arXiv:1506.08272, cited reference ``README.md:56-59``): workers compute
gradients against *stale* parameter snapshots — each worker may hold a
different version ("inconsistent reads") — and the server applies their
updates sequentially as they arrive.

The reference got asynchrony from OS threads + nonblocking MPI requests
(``ps.py:65-66,85``). Neither exists inside an XLA program, so the
TPU-native design makes staleness *explicit data*: a ring buffer of recent
parameter versions lives on device; each round every worker grad is taken
at ``history[now - staleness_i]`` (vmapped — all workers' backward passes
run as one batched XLA program), then the server applies the updates one
at a time with ``lax.scan`` (update *i* sees the params produced by update
*i-1*, exactly the arrival-order semantics of the MPI PS). Bounded
staleness is the buffer depth. Across pod slices the same construct runs
over DCN with per-slice histories; within a slice sync aggregation is
cheaper (ICI) and preferred — SURVEY §2.5's disposition.

Codec compression applies on the simulated wire: each worker's gradient
goes encode → decode before the server sees it, matching the reference's
encode-before-send/decode-on-receive placement (``ps.py:94,166``).

Scope note: this module is the *algorithm-semantics* vehicle — bounded
staleness as explicit data inside one XLA program, with per-round lags
SAMPLED from a distribution (optionally the measured arrival histogram
of a real multi-process run, via :func:`staleness_probs_from_histogram`;
a fixed schedule remains available for deterministic tests). The
*wall-clock* benefit asynchrony exists for — fast workers streaming
past a straggler — is demonstrated by the multi-process stack with real
jitted compute in ``parallel/async_train.py`` (measured 2.7× a
synchronous barrier under a forced straggler,
``benchmarks/async_bench.py``); the two are tied together by
``tests/test_async_train.py::
test_inxla_sampled_staleness_matches_shm_arrival_histogram``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pytorch_ps_mpi_tpu.codecs import Codec, IdentityCodec
from pytorch_ps_mpi_tpu.optim import OPTIMIZERS
from pytorch_ps_mpi_tpu.telemetry import get_recorder

PyTree = Any


def staleness_probs_from_histogram(
    hist: Dict[int, int], max_staleness: int
) -> np.ndarray:
    """Measured arrival histogram → sampling distribution for
    :class:`AsyncPS`.

    ``hist`` is a ``{staleness: count}`` dict as produced by the
    multi-process servers (``ShmPSServer.staleness_seen``,
    ``TcpPSServer.staleness_seen``) — measured wall-clock arrival
    behavior. Lags beyond ``max_staleness`` were *dropped* by those
    servers (never applied), so they are excluded here too: the returned
    distribution is over the lags that actually reached the optimizer.
    """
    probs = np.zeros(max_staleness + 1, np.float64)
    for lag, count in hist.items():
        if 0 <= int(lag) <= max_staleness:
            probs[int(lag)] = float(count)
    if probs.sum() <= 0:
        raise ValueError(
            f"histogram has no mass in 0..{max_staleness}: {hist}"
        )
    return probs / probs.sum()


class AsyncPS:
    """Bounded-staleness asynchronous parameter server.

    Args:
      params: initial parameter pytree.
      loss_fn: ``loss_fn(params, batch) -> scalar``.
      num_workers: worker count (the reference's MPI world size).
      optim: ``'sgd'`` or ``'adam'``.
      code: gradient codec applied on the simulated wire.
      max_staleness: ring-buffer depth; worker *i*'s read lag is
        ``staleness[i] <= max_staleness``.
      staleness: optional FIXED per-worker lags (a deterministic
        schedule, for tests/repro). When omitted, lags are SAMPLED fresh
        each round inside the jitted program — AsySG-InCon's
        inconsistent reads are stochastic arrival effects, not a
        round-robin (VERDICT r3 item 7).
      staleness_probs: distribution over lags ``0..max_staleness`` the
        per-round sampling draws from; default uniform. Feed it a
        *measured* arrival histogram (e.g. a ShmPSServer/TcpPSServer
        run's ``staleness_seen`` via
        :func:`staleness_probs_from_histogram`) to replay real cluster
        arrival behavior inside the XLA program.
      seed: PRNG seed for stochastic codecs AND the staleness sampling.
      **hyper: optimizer hyperparameters.

    ``self.staleness_hist`` accumulates the lags actually used (a
    ``{lag: count}`` dict), directly comparable to the multi-process
    servers' ``staleness_seen``.
    """

    def __init__(
        self,
        params: PyTree,
        loss_fn: Callable,
        *,
        num_workers: int,
        optim: str = "sgd",
        code: Optional[Codec] = None,
        max_staleness: int = 2,
        staleness: Optional[Sequence[int]] = None,
        staleness_probs: Optional[Sequence[float]] = None,
        seed: int = 0,
        **hyper,
    ):
        hyper_cls, init_state, update_fn = OPTIMIZERS[optim]
        self.hyper = hyper_cls(**hyper)
        self._update_fn = update_fn
        self.loss_fn = loss_fn
        self.num_workers = int(num_workers)
        self.code = code if code is not None else IdentityCodec()
        self.max_staleness = int(max_staleness)
        if staleness is not None and staleness_probs is not None:
            raise ValueError("give staleness (fixed) OR staleness_probs, not both")
        if staleness is not None:
            if (len(staleness) != num_workers
                    or max(staleness) > self.max_staleness
                    or min(staleness) < 0):
                raise ValueError(
                    "need num_workers staleness values in 0..max_staleness"
                )
            self.staleness = jnp.asarray(staleness, jnp.int32)
            self._staleness_logits = None
        else:
            if staleness_probs is None:
                staleness_probs = [1.0] * (self.max_staleness + 1)
            probs = np.asarray(staleness_probs, np.float64)
            if probs.shape != (self.max_staleness + 1,) or probs.min() < 0 \
                    or probs.sum() <= 0:
                raise ValueError(
                    "staleness_probs must be max_staleness+1 nonnegative "
                    "weights with positive sum"
                )
            self.staleness = None
            self._staleness_logits = jnp.log(
                jnp.asarray(probs / probs.sum(), jnp.float32) + 1e-30
            )
        self.staleness_hist: Dict[int, int] = {}
        self.params = params
        self.opt_state = init_state(params)
        # history[0] = newest … history[max_staleness] = oldest, stacked.
        self.history = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (self.max_staleness + 1,) + p.shape),
            params,
        )
        self.codec_state = jax.tree.map(
            lambda p: jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (self.num_workers,) + x.shape),
                self.code.init_state(p.shape, p.dtype),
            ),
            params,
        )
        self._rng = jax.random.key(seed)
        self._round = jax.jit(self._make_round())
        self.step_count = 0

    def _wire(self, grads, codec_state, rng):
        """encode → decode round trip for one worker's gradient pytree
        (the simulated network; reference ``ps.py:94,166``)."""
        leaves, treedef = jax.tree.flatten(grads)
        flat_states = treedef.flatten_up_to(codec_state)
        keys = (
            list(jax.random.split(rng, len(leaves)))
            if self.code.needs_rng
            else [None] * len(leaves)
        )
        outs, states = [], []
        for g, st, k in zip(leaves, flat_states, keys):
            payload, new_st = self.code.encode(g, st, k)
            outs.append(self.code.decode(payload, g.shape, g.dtype))
            states.append(new_st)
        return jax.tree.unflatten(treedef, outs), jax.tree.unflatten(treedef, states)

    def _make_round(self):
        grad_fn = jax.grad(self.loss_fn)

        def round_fn(params, opt_state, history, codec_state, batches, rng):
            # 1. Inconsistent reads: worker i reads version history[lag_i].
            #    Sampled mode draws fresh lags every round from the
            #    (possibly measured) arrival distribution — stochastic
            #    inconsistent reads, not a schedule.
            if self._staleness_logits is not None:
                rng, k = jax.random.split(rng)
                lags = jax.random.categorical(
                    k, self._staleness_logits, shape=(self.num_workers,)
                ).astype(jnp.int32)
            else:
                lags = self.staleness
            stale = jax.tree.map(lambda h: h[lags], history)
            # 2. All workers' backward passes as one batched program.
            grads = jax.vmap(grad_fn)(stale, batches)
            # 3. Simulated wire: per-worker encode/decode (+ codec state).
            def per_worker(w_grads, w_state, k):
                return self._wire(w_grads, w_state, k)
            keys = jax.random.split(rng, self.num_workers)
            grads, new_codec_state = jax.vmap(per_worker)(grads, codec_state, keys)
            # 4. Server applies updates in arrival order (scan = sequential
            #    inconsistent updates, AsySG-InCon's core).
            def apply_one(carry, g):
                p, s = carry
                p, s = self._update_fn(p, g, s, self.hyper)
                return (p, s), None
            (params, opt_state), _ = lax.scan(apply_one, (params, opt_state), grads)
            # 5. Push the new version into the history ring.
            history = jax.tree.map(
                lambda h, p: jnp.concatenate([p[None], h[:-1]], axis=0),
                history,
                params,
            )
            return params, opt_state, history, new_codec_state, lags

        return round_fn

    def step(self, batches: PyTree) -> Tuple[None, Dict[str, float]]:
        """One async round: every worker contributes one (stale) gradient.

        ``batches``: pytree whose leaves have a leading ``[num_workers]``
        axis (each worker's local batch). Returns ``(None, data)`` in the
        reference's ``(loss, data)`` shape (``ps.py:193``).
        """
        import time

        t0 = time.perf_counter()
        self._rng, rng = jax.random.split(self._rng)
        (self.params, self.opt_state, self.history, self.codec_state,
         lags) = self._round(
            self.params, self.opt_state, self.history, self.codec_state,
            batches, rng,
        )
        jax.block_until_ready(self.params)
        for lag in np.asarray(lags).tolist():
            self.staleness_hist[lag] = self.staleness_hist.get(lag, 0) + 1
        self.step_count += 1
        dur = time.perf_counter() - t0
        rec = get_recorder()
        if rec is not None:
            rec.event("async_ps.round", kind="span",
                      ts=time.monotonic() - dur, dur=dur,
                      step=self.step_count,
                      updates_applied=self.num_workers,
                      lags=np.asarray(lags).tolist())
        return None, {"step_time": dur,
                      "updates_applied": float(self.num_workers)}
