"""Mesh & runtime bootstrap.

Replaces the reference's ambient ``MPI.COMM_WORLD`` created at import time
(reference ``mpi_comms.py:11-13``) with explicit device-mesh construction.
Rank/size become mesh axis index/size; SPMD launch via ``mpirun``
(reference ``Makefile:2-3``) becomes ``jax.distributed.initialize`` +
one XLA program over the mesh.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap (DCN). No-op on a single process.

    The TPU analog of MPI_Init-at-import (reference ``mpi_comms.py:6-13``),
    made explicit and idempotent.
    """
    if coordinator_address is None:
        return  # single-process: nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a device mesh.

    Defaults to a 1-D data-parallel mesh over all visible devices — the
    TPU analog of ``MPI.COMM_WORLD`` (reference ``mpi_comms.py:11``), but
    constructed explicitly and passed around instead of living as module
    state.
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if shape is None:
        shape = (devices.size,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != devices.size:
        raise ValueError(
            f"mesh shape {tuple(shape)} needs {int(np.prod(shape))} devices, "
            f"have {devices.size}"
        )
    return Mesh(devices.reshape(shape), axis_names=tuple(axis_names))


def mesh_rank() -> int:
    """This process's id (host-side; the reference's ``rank``, ``ps.py:71-72``).
    Inside jitted code use ``jax.lax.axis_index(axis)`` instead — per-device
    rank is a traced value under SPMD, not ambient state."""
    return jax.process_index()


def mesh_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """World size along ``axis`` (reference ``ps.py:73``)."""
    return int(mesh.shape[axis])


def data_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding that splits the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (parameters in pure data-parallel mode)."""
    return NamedSharding(mesh, P())


@contextlib.contextmanager
def maybe_mesh(mesh: Optional[Mesh]):
    """Enter ``mesh`` as the ambient mesh if given."""
    if mesh is None:
        yield
    else:
        with mesh:
            yield
