"""Fused optimizer update rules: SGD (momentum/nesterov/dampening/weight
decay) and Adam (amsgrad/bias correction/weight decay).

The math mirrors the reference's PS-fused reimplementations —
``SGD.optim_step`` (``ps.py:195-214``) and ``Adam.optim_step``
(``ps.py:217-261``) — which themselves mirror ``torch.optim``. Here each
rule is a pure per-leaf function tree-mapped over the parameter pytree and
fused by XLA into the jitted train step, instead of an eager per-parameter
Python loop run redundantly on every rank (``ps.py:190``).

Semantics checked against optax in ``tests/test_optim.py``. Notable
reference quirk preserved: the momentum buffer is *initialized to the first
d_p* (``ps.py:203-205``, torch semantics), not to zero.

Learning-rate schedules: ``lr`` may be a float (the reference's only
option, constant ``ps.py:197``) or a callable ``step -> scalar`` from
:data:`SCHEDULES` (or any user function built from jnp ops). A schedule is
evaluated on the optimizer state's traced step counter INSIDE the compiled
program, so the lr varies per step with zero recompiles — the TPU-native
shape of torch's host-side ``lr_scheduler.step()`` mutation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

PyTree = Any
LR = Union[float, Callable[[jax.Array], jax.Array]]


def _lr_at(lr: LR, step: jax.Array):
    """Resolve a constant-or-schedule lr at a (traced) 0-based step."""
    return lr(step) if callable(lr) else lr


# -- schedules (each returns step -> scalar; all jnp, trace-safe) ------------

def constant_lr(base: float) -> Callable:
    return lambda step: jnp.float32(base)


def warmup_cosine(base: float, total_steps: int, warmup_steps: int = 0,
                  final_scale: float = 0.0) -> Callable:
    """Linear warmup 0 -> base over ``warmup_steps``, then cosine decay to
    ``final_scale * base`` at ``total_steps`` (flat afterwards). The
    de-facto standard schedule of the BERT/ResNet training recipes the
    BASELINE configs name."""
    if total_steps <= warmup_steps:
        raise ValueError("total_steps must exceed warmup_steps")

    def f(step):
        s = step.astype(jnp.float32)
        warm = s / max(warmup_steps, 1)
        t = jnp.clip((s - warmup_steps) / (total_steps - warmup_steps), 0.0, 1.0)
        cos = final_scale + (1.0 - final_scale) * 0.5 * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.float32(base) * jnp.where(s < warmup_steps, warm, cos)

    return f


def step_decay(base: float, boundaries: Tuple[int, ...],
               scale: float = 0.1) -> Callable:
    """Multiply by ``scale`` at each boundary step (torch MultiStepLR, the
    classic ResNet recipe)."""
    bounds = jnp.asarray(boundaries, jnp.int32)

    def f(step):
        k = jnp.sum(step >= bounds).astype(jnp.float32)
        return jnp.float32(base) * jnp.float32(scale) ** k

    return f


SCHEDULES: Dict[str, Callable[..., Callable]] = {
    "constant": constant_lr,
    "warmup_cosine": warmup_cosine,
    "step_decay": step_decay,
}


class SGDHyper(NamedTuple):
    lr: LR = 0.01
    momentum: float = 0.0
    dampening: float = 0.0
    weight_decay: float = 0.0
    nesterov: bool = False


class AdamHyper(NamedTuple):
    lr: LR = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    amsgrad: bool = False
    # False: torch.optim.Adam's coupled L2 (wd added to the gradient,
    # the reference's semantics); True: AdamW (Loshchilov & Hutter
    # 2019) — decay applied directly to params, outside the adaptive
    # rescaling, the modern default for transformer training
    decoupled_weight_decay: bool = False


class SGDState(NamedTuple):
    step: jax.Array          # scalar int32
    momentum_buf: PyTree     # per-leaf buffers (zeros when momentum == 0)


class AdamState(NamedTuple):
    step: jax.Array
    exp_avg: PyTree
    exp_avg_sq: PyTree
    max_exp_avg_sq: PyTree   # used only when amsgrad


def init_sgd_state(params: PyTree) -> SGDState:
    return SGDState(
        step=jnp.zeros((), jnp.int32),
        momentum_buf=jax.tree.map(jnp.zeros_like, params),
    )


def init_adam_state(params: PyTree) -> AdamState:
    zeros = lambda: jax.tree.map(jnp.zeros_like, params)
    return AdamState(jnp.zeros((), jnp.int32), zeros(), zeros(), zeros())


def sgd_update(
    params: PyTree, grads: PyTree, state: SGDState, h: SGDHyper
) -> Tuple[PyTree, SGDState]:
    """One fused SGD step on the aggregated gradient (reference
    ``ps.py:197-214``)."""
    first = state.step == 0
    lr = _lr_at(h.lr, state.step)

    def leaf(p, g, buf):
        d_p = g + h.weight_decay * p if h.weight_decay else g
        if h.momentum:
            # torch/reference init: buf <- d_p on first step (ps.py:203-205)
            new_buf = jnp.where(
                first, d_p, h.momentum * buf + (1.0 - h.dampening) * d_p
            )
            d_p = d_p + h.momentum * new_buf if h.nesterov else new_buf
        else:
            new_buf = buf
        return p - lr * d_p, new_buf

    out = jax.tree.map(leaf, params, grads, state.momentum_buf)
    new_params = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
    new_bufs = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
    return new_params, SGDState(state.step + 1, new_bufs)


def adam_update(
    params: PyTree, grads: PyTree, state: AdamState, h: AdamHyper
) -> Tuple[PyTree, AdamState]:
    """One fused Adam step (reference ``ps.py:218-261``): moment updates,
    optional amsgrad max-denominator, bias-corrected parameter update."""
    step = state.step + 1
    lr = _lr_at(h.lr, state.step)
    bias1 = 1.0 - h.b1 ** step.astype(jnp.float32)
    bias2 = 1.0 - h.b2 ** step.astype(jnp.float32)

    def leaf(p, g, m, v, vmax):
        if h.weight_decay and not h.decoupled_weight_decay:
            g = g + h.weight_decay * p  # coupled L2 (torch Adam)
        m_new = h.b1 * m + (1.0 - h.b1) * g
        v_new = h.b2 * v + (1.0 - h.b2) * (g * g)
        if h.amsgrad:
            vmax_new = jnp.maximum(vmax, v_new)
            denom = jnp.sqrt(vmax_new) + h.eps
        else:
            vmax_new = vmax
            denom = jnp.sqrt(v_new) + h.eps
        step_size = lr * jnp.sqrt(bias2) / bias1
        p_new = p - step_size * m_new / denom
        if h.weight_decay and h.decoupled_weight_decay:
            p_new = p_new - lr * h.weight_decay * p  # AdamW
        return p_new, m_new, v_new, vmax_new

    out = jax.tree.map(
        leaf, params, grads, state.exp_avg, state.exp_avg_sq, state.max_exp_avg_sq
    )
    pick = lambda i: jax.tree.map(
        lambda o: o[i], out, is_leaf=lambda x: isinstance(x, tuple)
    )
    return pick(0), AdamState(step, pick(1), pick(2), pick(3))


# ---------------------------------------------------------------------------
# Adafactor (Shazeer & Stern 2018) — the TPU-native memory-efficient
# optimizer: second moments of [n, m] leaves are stored FACTORED as a
# row vector + column vector (sublinear optimizer state; the rank-1
# reconstruction is exact at the optimum of the I-divergence, paper
# §3). Beyond the reference's SGD/Adam family — at BERT/GPT scale the
# optimizer state drops from 2x params (Adam) to ~1/128th of one copy,
# which is HBM that goes back to batch size. No-momentum form (the
# paper's memory-efficient default; Adam covers the momentum niche).
# Semantics mirror optax.adafactor leaf-for-leaf (factoring over the
# two LARGEST dims, clip-by-block-rms, optional parameter-scale
# multiply) and are pinned to it in tests/test_optim.py — with ONE
# deliberate divergence: ``lr=None`` here applies the paper's relative
# step size rho_t = min(1e-2, 1/sqrt(t)) (Shazeer & Stern Alg. 4),
# whereas ``optax.adafactor(learning_rate=None)`` simply OMITS the lr
# scaling stage (the update magnitude then comes only from the
# parameter scale). The paper default is the right zero-config
# behavior for a drop-in optimizer; the two are reconciled in
# tests/test_optim.py::
# test_adafactor_relative_step_matches_optax_explicit_schedule, which
# pins our lr=None path against optax given rho_t as an EXPLICIT
# schedule.

_FACTOR_MIN = 128  # fixed at init (registry inits see params only)


def _factored_dims(shape) -> Optional[Tuple[int, int]]:
    """The two largest axes (d1, d0), or None when the second-largest
    is below the factoring threshold — optax's rule exactly."""
    if len(shape) < 2:
        return None
    order = sorted(range(len(shape)), key=lambda i: shape[i])
    if shape[order[-2]] < _FACTOR_MIN:
        return None
    return order[-2], order[-1]


class AdafactorHyper(NamedTuple):
    lr: LR = None                 # None -> relative step min(1e-2, t^-0.5)
    decay_rate: float = 0.8       # beta2_t = 1 - t^-decay_rate
    eps1: float = 1e-30           # squared-gradient regularizer
    eps2: float = 1e-3            # parameter-scale floor (paper alg. 4)
    clip_threshold: float = 1.0   # update block-RMS clip
    weight_decay: float = 0.0     # added to the update un-lr-scaled
    # (optax add_decayed_weights semantics)
    multiply_by_parameter_scale: bool = True


class AdafactorState(NamedTuple):
    step: jax.Array
    v_row: PyTree   # factored leaves: [shape minus largest dim];
    v_col: PyTree   # [shape minus second-largest]; zeros((1,)) sentinel
    v_full: PyTree  # unfactored leaves: full shape; sentinel otherwise


def init_adafactor_state(params: PyTree) -> AdafactorState:
    def vr(p):
        d = _factored_dims(p.shape)
        if d is None:
            return jnp.zeros((1,), p.dtype)
        return jnp.zeros(tuple(np.delete(p.shape, d[1])), p.dtype)

    def vc(p):
        d = _factored_dims(p.shape)
        if d is None:
            return jnp.zeros((1,), p.dtype)
        return jnp.zeros(tuple(np.delete(p.shape, d[0])), p.dtype)

    def vf(p):
        return (jnp.zeros_like(p) if _factored_dims(p.shape) is None
                else jnp.zeros((1,), p.dtype))

    return AdafactorState(
        step=jnp.zeros((), jnp.int32),
        v_row=jax.tree.map(vr, params),
        v_col=jax.tree.map(vc, params),
        v_full=jax.tree.map(vf, params),
    )


def adafactor_update(
    params: PyTree, grads: PyTree, state: AdafactorState, h: AdafactorHyper,
    scalar_mean: Optional[Callable] = None,
) -> Tuple[PyTree, AdafactorState]:
    """One fused Adafactor step on the aggregated gradient.

    ``scalar_mean`` turns the two per-leaf SCALAR reductions (the
    update-clip RMS and the parameter-scale RMS) into global means
    under sharded execution: pass ``lambda s: lax.pmean(s, model_axes)``
    inside shard_map and — because uniform shards have equal sizes —
    the pmean of per-shard means IS the global mean, while replicated
    leaves pmean to themselves. The factored row/col means never need
    it: :func:`adafactor_check_sharding` guarantees the factored dims
    are unsharded, so those reductions are shard-local by construction.
    """
    step = state.step + 1
    t = step.astype(jnp.float32)
    beta2t = 1.0 - t ** (-h.decay_rate)
    if h.lr is None:
        lr = jnp.minimum(1e-2, 1.0 / jnp.sqrt(t))
    else:
        lr = _lr_at(h.lr, state.step)

    mean_sq = scalar_mean if scalar_mean is not None else (lambda x: x)

    def leaf(p, g, vr, vc, vf):
        dims = _factored_dims(p.shape)
        g2 = g * g + h.eps1
        if dims is not None:
            d1, d0 = dims
            vr_new = beta2t * vr + (1.0 - beta2t) * jnp.mean(g2, axis=d0)
            vc_new = beta2t * vc + (1.0 - beta2t) * jnp.mean(g2, axis=d1)
            # the per-row mean normalizer lives in the row factor
            reduced_d1 = d1 - 1 if d1 > d0 else d1
            row_mean = jnp.mean(vr_new, axis=reduced_d1, keepdims=True)
            u = (g * jnp.expand_dims((vr_new / row_mean) ** -0.5, d0)
                 * jnp.expand_dims(vc_new ** -0.5, d1))
            vf_new = vf
        else:
            vf_new = beta2t * vf + (1.0 - beta2t) * g2
            u = g * vf_new ** -0.5
            vr_new, vc_new = vr, vc
        rms_u = jnp.sqrt(mean_sq(jnp.mean(u * u)))
        u = u / jnp.maximum(1.0, rms_u / h.clip_threshold)
        scale = lr
        if h.multiply_by_parameter_scale:
            scale = scale * jnp.maximum(
                h.eps2,
                jnp.sqrt(mean_sq(jnp.mean(p.astype(jnp.float32) ** 2))),
            )
        p_new = p - scale * u
        if h.weight_decay:
            p_new = p_new - h.weight_decay * p
        return p_new, vr_new, vc_new, vf_new

    out = jax.tree.map(
        leaf, params, grads, state.v_row, state.v_col, state.v_full
    )
    pick = lambda i: jax.tree.map(
        lambda o: o[i], out, is_leaf=lambda x: isinstance(x, tuple)
    )
    return pick(0), AdafactorState(step, pick(1), pick(2), pick(3))


def adafactor_check_sharding(params: PyTree, param_specs: PyTree) -> None:
    """Reject leaves whose GLOBAL factored dims are sharded: the
    row/col means would then span devices, and a shard-local mean
    silently computes a different (and shape-corrupting, once the
    replicated-state broadcast joins in) update. Sharding any OTHER
    axis is exactly decomposable — the factored means stay shard-local
    and the scalar reductions go through ``scalar_mean``."""
    spec_leaves = jax.tree.structure(params).flatten_up_to(param_specs)
    for p, sp in zip(jax.tree.leaves(params), spec_leaves):
        dims = _factored_dims(p.shape)
        if dims is None:
            continue  # v_full mirrors the leaf: elementwise, any sharding
        entries = tuple(sp) if sp is not None else ()
        sharded = {i for i, e in enumerate(entries) if e is not None}
        if sharded & set(dims):
            raise NotImplementedError(
                "optim='adafactor': leaf with global shape "
                f"{p.shape} factors over dims {dims}, but spec {sp} "
                "shards one of them — the row/col second-moment means "
                "would span devices. Shard a non-factored axis (e.g. a "
                "leading stack axis) or use optim='adam'/'sgd'"
            )


def _delete_spec_dim(sp, ndim: int, d: int):
    entries = (tuple(sp) if sp is not None else ()) + (None,) * ndim
    entries = entries[:ndim]
    kept = entries[:d] + entries[d + 1:]
    return PartitionSpec(*kept)


def adafactor_state_specs(params: PyTree, param_specs: PyTree):
    """Per-leaf shard_map specs for :class:`AdafactorState` under
    model-parallel ``param_specs``: v_row/v_col inherit the leaf's spec
    minus the deleted (factored, guaranteed-unsharded) dim; v_full
    mirrors the leaf for unfactored leaves; sentinels replicate."""
    P_ = PartitionSpec
    treedef = jax.tree.structure(params)
    spec_leaves = treedef.flatten_up_to(param_specs)
    p_leaves = jax.tree.leaves(params)

    def per_leaf(which):
        out = []
        for p, sp in zip(p_leaves, spec_leaves):
            dims = _factored_dims(p.shape)
            if which == "v_full":
                out.append(P_() if dims is not None
                           else (sp if sp is not None else P_()))
            elif dims is None:
                out.append(P_())
            else:
                d1, d0 = dims
                d = d0 if which == "v_row" else d1
                out.append(_delete_spec_dim(sp, len(p.shape), d))
        return jax.tree.unflatten(treedef, out)

    return AdafactorState(
        step=P_(),
        v_row=per_leaf("v_row"),
        v_col=per_leaf("v_col"),
        v_full=per_leaf("v_full"),
    )


OPTIMIZERS: Dict[str, Any] = {
    "sgd": (SGDHyper, init_sgd_state, sgd_update),
    "adam": (AdamHyper, init_adam_state, adam_update),
    "adafactor": (AdafactorHyper, init_adafactor_state, adafactor_update),
}
