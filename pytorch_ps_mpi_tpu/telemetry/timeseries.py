"""MetricsHistory: the in-process time-series database behind ``/history``.

Every observability surface before this module was *instantaneous* —
counters, EWMAs and quantile gauges with no retained history. This is
the missing substrate: a dependency-free TSDB that retains a bounded
window of every canonical metric key, sampled on the serve thread at
the existing ``on_tick`` cadence (no new threads ever touch a native
transport handle), and queryable while the run is still going.

Design:

- **one ring per (key, tier)** — a raw ring holds every sample
  ``(t, value)``; downsampled tiers (default 1 s / 10 s / 60 s
  resolutions) hold per-bucket aggregates ``(t, last, min, max, sum,
  n)`` folded in as samples arrive, so a 60 s-tier point costs the same
  whether the raw cadence was 5 Hz or 50 Hz. Memory is fixed at
  construction: ``capacity × keys`` per tier, regardless of run length.
- **queries pick the finest tier that still covers the window** —
  ``range(key, t0, t1)`` walks raw first, then 1 s, 10 s, 60 s; windowed
  quantiles/rates (:meth:`quantile`, :meth:`rate`,
  :meth:`window_stats`) weight downsampled points by their fold count,
  so a p95 over an aged window degrades gracefully ("within
  downsampling error") instead of returning nothing.
- **persistence** — raw samples append (buffered, ``flush_every``) to
  ``timeseries-<name>.jsonl`` rows ``{"t": wall, "m": {key: value}}``
  with bounded retention: past ``retention_rows`` the file is compacted
  in place to its newest half, so a week-long run cannot fill the disk.
  :func:`load_timeseries_rows` / :func:`history_from_rows` rebuild a
  queryable history offline (``tools/telemetry_report.py``'s history
  section, SLO replay).
- **HTTP** — :meth:`render_http` backs the ``/history?key=...&window=``
  route the :class:`~.registry.PSServerTelemetry` mixin serves on both
  transports, torn down by ``server.close()`` like ``/metrics`` and
  ``/health``. Reads are lock-free snapshots of append-only deques
  (atomic under the GIL), safe from the scrape thread while the serve
  thread samples.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: tuning knobs and their defaults (overridable via ``cfg["timeseries_kw"]``)
TS_KNOBS: Dict[str, Any] = {
    # (resolution_s, ring_capacity) per downsampled tier — 15 min at 1 s,
    # 90 min at 10 s, 6 h at 60 s
    "tiers": ((1.0, 900), (10.0, 540), (60.0, 360)),
    "raw_capacity": 2048,        # raw samples kept (at tick cadence ~7 min)
    "sample_min_interval_s": 0.2,  # ingest throttle under a fast tick
    "flush_every": 64,           # buffered rows per persistence append
    "retention_rows": 20000,     # jsonl rows before in-place compaction
    "max_points": 400,           # /history reply size bound (strided)
}

#: a downsampled point: (bucket_t, last, min, max, sum, n)
_Bucket = Tuple[float, float, float, float, float, int]


def timeseries_path(ts_dir: str, name: str) -> str:
    return os.path.join(ts_dir, f"timeseries-{name}.jsonl")


def _weighted_quantile(pairs: List[Tuple[float, int]], q: float) -> float:
    """Exact weighted q-quantile of ``[(value, weight)]`` — the same
    discipline as ``registry.staleness_quantile``; NaN when empty."""
    if not pairs:
        return math.nan
    items = sorted(pairs)
    total = sum(n for _, n in items)
    target = q * total
    cum = 0
    for v, n in items:
        cum += n
        if cum >= target:
            return float(v)
    return float(items[-1][0])


class MetricsHistory:
    """Fixed-memory retained history for a flat ``{key: float}`` stream.

    ``keys=None`` admits every numeric key the first sample carries (plus
    any later ones); pass an explicit tuple to pin the schema. ``dir``
    arms persistence (``timeseries-<name>.jsonl``); None keeps the TSDB
    purely in-memory. All timestamps are wall-clock (``time.time()``)
    so fleet tooling can order samples across processes — the satellite
    ``ts`` field in ``/metrics``/``/health`` exists for the same reason.
    """

    def __init__(self, keys: Optional[Sequence[str]] = None,
                 dir: Optional[str] = None, name: str = "server",
                 **overrides: Any):
        self.knobs = dict(TS_KNOBS)
        self.knobs.update(overrides)
        self.name = str(name)
        self._keys_pinned = keys is not None
        self._raw: Dict[str, deque] = {}
        if keys:
            for k in keys:
                self._raw[k] = deque(maxlen=int(self.knobs["raw_capacity"]))
        tiers = tuple(self.knobs["tiers"])
        self._tier_res: List[float] = [float(r) for r, _ in tiers]
        self._tier_cap: List[int] = [int(c) for _, c in tiers]
        # closed buckets per tier: key -> deque[_Bucket]
        self._tiers: List[Dict[str, deque]] = [{} for _ in tiers]
        # open (still-folding) bucket per tier: key -> [t, last, mn, mx, s, n]
        self._open: List[Dict[str, list]] = [{} for _ in tiers]
        self.samples = 0
        self.last_t: Optional[float] = None
        self.overhead_s = 0.0  # self-timed sample() cost (the ≤5% story)
        self._t0 = time.time()

        self.path: Optional[str] = None
        self._buf: List[str] = []
        self._rows_written = 0
        if dir:
            os.makedirs(dir, exist_ok=True)
            self.path = timeseries_path(dir, self.name)

    # -- ingest -----------------------------------------------------------
    def sample(self, metrics: Dict[str, Any],
               now: Optional[float] = None, force: bool = False) -> bool:
        """Fold one ``{key: value}`` snapshot in; returns False when the
        sample was throttled (non-monotone timestamp or below the min
        interval — ``force=True`` skips the throttle, for the one
        closing sample that must capture the FINAL counter state).
        Serve-thread only, like every monitor feed point."""
        # self-cost in THREAD CPU time: on an oversubscribed box a
        # wall-clock timer bills scheduler preemption (5 ms "samples"
        # that cost 200 us of CPU) to the observability plane — the
        # ≤5% budget gates what the plane actually takes from the
        # serve thread
        t0 = time.thread_time()
        t = time.time() if now is None else float(now)
        if self.last_t is not None:
            if t <= self.last_t:
                return False  # clock went backwards / duplicate tick
            # epsilon keeps an exactly-at-cadence stream (t += 0.2 with
            # float accumulation error) from dropping alternate samples
            if (not force and t - self.last_t
                    < float(self.knobs["sample_min_interval_s"]) - 1e-6):
                return False
        row: Dict[str, float] = {}
        for k, v in metrics.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            v = float(v)
            if math.isnan(v) or math.isinf(v):
                continue
            ring = self._raw.get(k)
            if ring is None:
                if self._keys_pinned:
                    continue
                ring = self._raw.setdefault(
                    k, deque(maxlen=int(self.knobs["raw_capacity"])))
            ring.append((t, v))
            row[k] = v
            for ti, res in enumerate(self._tier_res):
                bt = math.floor(t / res) * res
                ob = self._open[ti].get(k)
                if ob is None:
                    self._open[ti][k] = [bt, v, v, v, v, 1]
                elif ob[0] == bt:
                    ob[1] = v
                    ob[2] = min(ob[2], v)
                    ob[3] = max(ob[3], v)
                    ob[4] += v
                    ob[5] += 1
                else:  # bucket boundary crossed: close the old one
                    ring2 = self._tiers[ti].setdefault(
                        k, deque(maxlen=self._tier_cap[ti]))
                    ring2.append(tuple(ob))
                    self._open[ti][k] = [bt, v, v, v, v, 1]
        self.samples += 1
        self.last_t = t
        if self.path is not None and row:
            # full precision on purpose: SLO replay re-derives verdicts
            # from these rows, and a rounded timestamp can move a sample
            # across a window boundary (replay != live)
            self._buf.append(json.dumps({"t": t, "m": row}))
            if len(self._buf) >= int(self.knobs["flush_every"]):
                self.flush()
        self.overhead_s += time.thread_time() - t0
        return True

    # -- persistence ------------------------------------------------------
    def flush(self) -> None:
        if self.path is None or not self._buf:
            return
        with open(self.path, "a") as f:
            f.write("\n".join(self._buf) + "\n")
        self._rows_written += len(self._buf)
        self._buf = []
        if self._rows_written > int(self.knobs["retention_rows"]):
            self._compact()

    def _compact(self) -> None:
        """Bounded retention: rewrite the file keeping the newest half,
        so the append path stays O(1) and the file stays O(retention)."""
        keep = int(self.knobs["retention_rows"]) // 2
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return
        tail = lines[-keep:]
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(tail)
        os.replace(tmp, self.path)
        self._rows_written = len(tail)

    def close(self) -> None:
        self.flush()

    # -- queries ----------------------------------------------------------
    def keys(self) -> List[str]:
        return sorted(self._raw)

    def _series(self, key: str, t0: float,
                tier: Optional[int] = None
                ) -> Tuple[float, List[Tuple[float, float, int]]]:
        """(resolution_s, [(t, value, weight)]) for the finest tier whose
        ring still covers ``t0`` (raw = resolution 0). ``tier`` pins one:
        -1 raw, 0.. downsampled."""
        if tier is not None:
            if tier < 0:
                ring = self._raw.get(key) or ()
                return 0.0, [(t, v, 1) for t, v in ring if t >= t0]
            ring2 = list(self._tiers[tier].get(key) or ())
            ob = self._open[tier].get(key)
            if ob is not None:
                ring2.append(tuple(ob))
            return self._tier_res[tier], [
                (b[0], b[4] / b[5], b[5]) for b in ring2 if b[0] >= t0]
        ring = self._raw.get(key)
        if ring and (ring[0][0] <= t0 or len(ring) < ring.maxlen):
            # raw still reaches back to t0 (or the run is younger than
            # the ring) — exact samples, weight 1
            return 0.0, [(t, v, 1) for t, v in ring if t >= t0]
        for ti in range(len(self._tier_res)):
            ring2 = self._tiers[ti].get(key)
            if ring2 and (ring2[0][0] <= t0
                          or len(ring2) < self._tier_cap[ti]):
                return self._series(key, t0, tier=ti)
        # nothing covers that far back: coarsest tier is the best we have
        return self._series(key, t0,
                            tier=len(self._tier_res) - 1
                            if self._tier_res else -1)

    def range(self, key: str, t0: Optional[float] = None,
              t1: Optional[float] = None,
              tier: Optional[int] = None) -> List[Tuple[float, float]]:
        """``[(t, value)]`` within ``[t0, t1]`` (defaults: everything
        retained .. now) from the finest covering tier. Downsampled
        points carry the bucket mean at the bucket start time."""
        # default = everything retained, NOT construction time: a
        # history rebuilt from persisted rows (history_from_rows) holds
        # samples that predate its own construction
        lo = float("-inf") if t0 is None else float(t0)
        hi = float("inf") if t1 is None else float(t1)
        _, pts = self._series(key, lo, tier=tier)
        return [(t, v) for t, v, _ in pts if t <= hi]

    def window_stats(self, key: str, window_s: float,
                     now: Optional[float] = None) -> Dict[str, float]:
        """min/max/mean/p50/p95/last/rate over the trailing window —
        the one-call summary ``/history`` and the SLO watchdog read."""
        now = time.time() if now is None else float(now)
        res, pts = self._series(key, now - float(window_s))
        if not pts:
            return {"n": 0, "tier_s": res}
        vals = [v for _, v, _ in pts]
        wq = [(v, n) for _, v, n in pts]
        n_samples = sum(n for _, _, n in pts)
        first_t, last_t = pts[0][0], pts[-1][0]
        out = {
            "n": n_samples,
            "points": len(pts),
            "tier_s": res,
            "first_t": first_t,
            "last_t": last_t,
            "last": vals[-1],
            "min": min(vals),
            "max": max(vals),
            "mean": sum(v * n for _, v, n in pts) / max(1, n_samples),
            "p50": _weighted_quantile(wq, 0.50),
            "p95": _weighted_quantile(wq, 0.95),
        }
        if last_t > first_t:
            # counter reading: per-second delta over the window (negative
            # deltas — a counter reset across a restart — clamp to 0)
            out["rate_per_s"] = max(
                0.0, (vals[-1] - vals[0]) / (last_t - first_t))
        else:
            out["rate_per_s"] = 0.0
        return out

    def quantile(self, key: str, q: float, window_s: float,
                 now: Optional[float] = None) -> float:
        """Windowed q-quantile of the sampled series (weighted by fold
        count on downsampled tiers); NaN when the window is empty."""
        now = time.time() if now is None else float(now)
        _, pts = self._series(key, now - float(window_s))
        return _weighted_quantile([(v, n) for _, v, n in pts], q)

    def rate(self, key: str, window_s: float,
             now: Optional[float] = None) -> float:
        return self.window_stats(key, window_s, now=now).get(
            "rate_per_s", 0.0)

    # -- surfaces ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {
            "armed": True,
            "name": self.name,
            "keys": len(self._raw),
            "samples": self.samples,
            "last_t": self.last_t,
            "overhead_s": round(self.overhead_s, 6),
            "tiers": [{"res_s": r, "capacity": c}
                      for r, c in zip(self._tier_res, self._tier_cap)],
            "file": self.path,
            "rows_written": self._rows_written + len(self._buf),
        }

    def query(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """The ``/history`` document. No ``key`` → the key listing +
        meta; with ``key`` (and optional ``window`` seconds, ``tier``,
        ``q``) → strided points + windowed stats."""
        key = params.get("key")
        if not key:
            return {**self.snapshot(), "key_names": self.keys()}
        key = str(key)
        if key not in self._raw:
            return {"error": f"unknown key {key!r}",
                    "key_names": self.keys()}
        window = float(params.get("window", 300.0))
        # the window is anchored at the NEWEST sample, not the wall
        # clock: a drained run (or an offline replay) keeps answering
        # with its data instead of an empty aged-out window
        now = self.last_t if self.last_t is not None else time.time()
        tier = params.get("tier")
        tier = int(tier) if tier not in (None, "") else None
        res, pts = self._series(key, now - window, tier=tier)
        stride = max(1, -(-len(pts) // int(self.knobs["max_points"])))
        points = [[round(t, 4), v] for t, v, _ in pts[::stride]]
        out = {
            "key": key,
            "window_s": window,
            "tier_s": res,
            "points": points,
            "stats": self.window_stats(key, window, now=now),
        }
        q = params.get("q")
        if q not in (None, ""):
            out["quantile"] = {"q": float(q),
                               "value": self.quantile(key, float(q),
                                                      window, now=now)}
        return out

    def render_http(self, query: Optional[Dict[str, Any]] = None
                    ) -> Tuple[str, str]:
        return json.dumps(self.query(query or {})), "application/json"


# ---------------------------------------------------------------------------
# offline: reload a persisted history (report sections, SLO replay)
# ---------------------------------------------------------------------------

def load_timeseries_rows(path: str) -> List[Dict[str, Any]]:
    """``timeseries-*.jsonl`` → ``[{"t": .., "m": {..}}]`` (torn trailing
    lines skipped — the writer appends whole lines, but a crash can cut
    the last one)."""
    rows: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if isinstance(r, dict) and "t" in r and isinstance(
                    r.get("m"), dict):
                rows.append(r)
    return rows


def history_from_rows(rows: List[Dict[str, Any]], name: str = "replay",
                      **overrides: Any) -> MetricsHistory:
    """Rebuild a queryable (in-memory) history from persisted rows —
    deterministic: the same rows produce the same windows, which is what
    makes SLO verdicts replayable."""
    h = MetricsHistory(dir=None, name=name, **overrides)
    for r in rows:
        h.sample(r["m"], now=float(r["t"]))
    return h
