"""Read-path freshness plane — version propagation from root publish to
edge reader.

The write path has exact causal accounting (lineage trace IDs survive
every hop into the published composition), but the trace used to die at
``ServingCore.publish()``: the read plane reported only
``replica_lag_versions``, a version count with no wall-clock meaning.
This module gives every published version a **birth record** that rides
the PSR1 delta stream as an opt-in trailer (FRS1), gains one bounded
**hop record** per follower relay, and is turned by a
:class:`FreshnessTracker` into publish→visible latency distributions,
an age-of-information gauge, and reader-delivery rows that join back to
write-path lineage — one causal chain from worker encode to the wall
age at which an edge replica served the containing version.

Wire format (FRS1, little-endian, appended AFTER the PSR1 payload; the
reply header's previously-zero ``pad1`` byte carries the trailer
length, so a reader that never sets ``FLAG_WANT_FRESH`` receives
byte-identical replies — the native-vs-Python reply-parity invariant
is preserved):

- 32-byte birth header: ``u32 magic 'FRS1', u8 hop_count, u8 cap,
  u16 reserved, u64 version, f64 publish_wall, u32 root_gen,
  u32 reserved2``;
- ``hop_count`` × 16-byte hop records: ``u16 hop_index, u16 reserved,
  f32 skew_ms, f64 arrival_wall``.

``publish_wall`` is stamped on the ROOT's clock; each hop's
``arrival_wall`` is stamped on THAT hop's clock, and ``skew_ms`` is the
hop's lower-envelope estimate (PR 6's ``estimate_clock_offset``) of its
own clock minus its upstream's. Summing ``skew_ms`` down the chain
therefore re-expresses the birth wall in the local clock — see
:func:`birth_wall_local` — which is what makes cross-host age numbers
meaningful at all. The cap (:data:`FRESH_HOP_CAP`) bounds the trailer
at 160 bytes (fits the u8 length byte with room to spare); appends past
the cap are dropped, not wrapped, so ``hop_count`` saturates and the
deepest hops go unrecorded rather than corrupting the birth record.

Skew caveat: a follower only observes (upstream stamp, local receive)
pairs through its *polled* pulls, so the lower-envelope fit absorbs the
minimum poll delay into the offset estimate — ages are accurate to
roughly one poll interval plus genuine clock drift, not to the
microsecond. OPERATIONS.md documents the operational consequences.
"""

from __future__ import annotations

import json
import os
import struct
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = [
    "FRESH_MAGIC", "FRESH_HOP_CAP", "FRESH_MAX_BYTES",
    "pack_birth", "append_hop", "unpack_trailer",
    "total_skew_s", "birth_wall_local", "age_ms", "visible_latency_ms",
    "hop_latencies_ms", "FreshnessTracker", "fresh_path",
    "load_fresh_rows", "freshness_flow_events",
]

#: ``b"FRS1"`` read as a little-endian u32 — same derivation as the
#: PSR1 magic in :mod:`pytorch_ps_mpi_tpu.serving.net`.
FRESH_MAGIC = 0x31535246

#: hop records retained per trailer; appends past this saturate
FRESH_HOP_CAP = 8

_BIRTH = struct.Struct("<IBBHQdII")   # 32 B birth header
_HOP = struct.Struct("<HHfd")         # 16 B per-hop record

#: the largest trailer the wire can carry (must fit the u8 pad1 byte)
FRESH_MAX_BYTES = _BIRTH.size + FRESH_HOP_CAP * _HOP.size
assert _BIRTH.size == 32 and _HOP.size == 16 and FRESH_MAX_BYTES <= 255


# -- trailer codec ----------------------------------------------------------

def pack_birth(version: int, publish_wall: float,
               root_gen: int = 0) -> bytes:
    """A hop-less birth record — what the ROOT stamps at publish."""
    return _BIRTH.pack(FRESH_MAGIC, 0, FRESH_HOP_CAP, 0, int(version),
                       float(publish_wall), int(root_gen) & 0xFFFFFFFF, 0)


def append_hop(blob: bytes, hop_index: int, arrival_wall: float,
               skew_ms: float = 0.0) -> bytes:
    """Return ``blob`` with one hop record appended (validates first).
    At the cap the trailer is returned UNCHANGED — bounded, never
    reordered or wrapped."""
    doc = unpack_trailer(blob)  # raises on malformed input
    if len(doc["hops"]) >= doc["cap"]:
        return bytes(blob)
    head = bytearray(blob[:_BIRTH.size])
    head[4] = doc["hop_count"] + 1
    return (bytes(head) + blob[_BIRTH.size:]
            + _HOP.pack(int(hop_index) & 0xFFFF, 0, float(skew_ms),
                        float(arrival_wall)))


def unpack_trailer(blob: bytes) -> Dict[str, Any]:
    """Decode an FRS1 trailer. Raises ``ValueError`` on bad magic, a
    short header, truncated hop records, or trailing bytes — a
    truncated trailer is DROPPED by callers, never half-trusted."""
    if len(blob) < _BIRTH.size:
        raise ValueError(
            f"freshness trailer too short: {len(blob)} < {_BIRTH.size}")
    (magic, hop_count, cap, _r0, version, publish_wall, root_gen,
     _r1) = _BIRTH.unpack_from(blob, 0)
    if magic != FRESH_MAGIC:
        raise ValueError(f"bad freshness magic 0x{magic:08x}")
    want = _BIRTH.size + hop_count * _HOP.size
    if len(blob) != want:
        raise ValueError(
            f"freshness trailer is {len(blob)} bytes but header "
            f"declares {hop_count} hop(s) ({want} bytes)")
    hops: List[Dict[str, float]] = []
    for i in range(hop_count):
        idx, _r, skew_ms, arrival = _HOP.unpack_from(
            blob, _BIRTH.size + i * _HOP.size)
        hops.append({"hop_index": int(idx), "skew_ms": float(skew_ms),
                     "arrival_wall": float(arrival)})
    return {"version": int(version), "publish_wall": float(publish_wall),
            "root_gen": int(root_gen), "hop_count": int(hop_count),
            "cap": int(cap), "hops": hops}


# -- clock algebra ----------------------------------------------------------

def total_skew_s(doc: Dict[str, Any]) -> float:
    """Cumulative (local clock − root clock) down the recorded chain."""
    return sum(h["skew_ms"] for h in doc["hops"]) * 1e-3


def birth_wall_local(doc: Dict[str, Any]) -> float:
    """The publish wall re-expressed in the LAST hop's clock (the clock
    of whoever holds the trailer) — the zero point for local ages."""
    return doc["publish_wall"] + total_skew_s(doc)


def age_ms(doc: Dict[str, Any], now: Optional[float] = None) -> float:
    """Wall age of the version described by ``doc``, in the local
    clock. Clamped at 0 — a skew mis-estimate must never report a
    version as younger than freshly published."""
    t = time.time() if now is None else float(now)
    return max(0.0, (t - birth_wall_local(doc)) * 1e3)


def visible_latency_ms(doc: Dict[str, Any]) -> Optional[float]:
    """Publish→visible latency at the last recorded hop (``None`` for a
    hop-less root trailer): the last arrival and the corrected birth
    are both in that hop's clock, so the difference is a real
    duration."""
    if not doc["hops"]:
        return None
    return max(0.0,
               (doc["hops"][-1]["arrival_wall"] - birth_wall_local(doc))
               * 1e3)


def hop_latencies_ms(doc: Dict[str, Any]) -> List[float]:
    """Per-hop propagation latencies, skew-corrected: each arrival is
    re-expressed in the ROOT clock (subtract the cumulative skew up to
    and including that hop) and differenced against the previous
    stamp. Negative offsets (a hop's clock BEHIND its upstream's)
    correct in the same pass — the estimator's sign convention is
    receiver minus sender throughout."""
    out: List[float] = []
    prev_root = doc["publish_wall"]
    skew_s = 0.0
    for h in doc["hops"]:
        skew_s += h["skew_ms"] * 1e-3
        arrival_root = h["arrival_wall"] - skew_s
        out.append(max(0.0, (arrival_root - prev_root) * 1e3))
        prev_root = arrival_root
    return out


# -- sidecar rows -----------------------------------------------------------

def fresh_path(dir: str, name: str) -> str:
    return os.path.join(dir, f"freshness-{name}.jsonl")


def load_fresh_rows(path: str) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def _q(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


class FreshnessTracker:
    """Turns FRS1 trailers into distributions, rows, and flow events.

    Attached via ``arm_observability`` (the SLOWatchdog pattern):
    ``server.freshness_tracker = self`` plus scrape instruments. The
    serving core calls :meth:`note_publish` with each installed
    trailer document; reader owners (followers, benches, smokes) call
    :meth:`note_delivery` with :meth:`~pytorch_ps_mpi_tpu.serving.net.
    ServingReader.fresh_delivery_row` dicts. Both append to
    ``freshness-<name>.jsonl`` when a directory is armed, so the whole
    plane replays offline. Self-timed: ``overhead_s`` is the CPU this
    tracker cost, same discipline as the TSDB and the watchdog."""

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, name: str = "server", dir: Optional[str] = None,
                 window: int = 512, core=None, **overrides: Any):
        cfg = cfg or {}
        kw = dict(cfg.get("freshness_kw") or {})
        kw.update(overrides)
        self.name = str(name)
        self.window = int(kw.get("window", window))
        self.server = server
        #: standalone serving core (no PS server around it — replicas,
        #: benches): the age source when ``server.serving_core`` is gone
        self.core = core
        #: hop_index → recent skew-corrected hop latencies (ms)
        self._hop_lat: Dict[int, Deque[float]] = {}
        #: recent end-to-end publish→visible latencies at this node (ms)
        self._visible: Deque[float] = deque(maxlen=self.window)
        #: recent delivery ages observed by local readers (ms)
        self._delivery_age: Deque[float] = deque(maxlen=self.window)
        self.publishes = 0
        self.deliveries = 0
        self.dropped = 0  # malformed/truncated trailers rejected
        self.overhead_s = 0.0
        self.path: Optional[str] = None
        self._f = None
        if dir:
            os.makedirs(dir, exist_ok=True)
            self.path = fresh_path(dir, self.name)
            self._f = open(self.path, "a")
        if server is not None:
            server.freshness_tracker = self
            reg = getattr(server, "scrape_registry", None)
            if reg is not None:
                self.register(reg())
        if core is not None:
            # standalone-core attach: publishes flow straight through
            # core._stamp_fresh -> note_publish without a PS server
            core.freshness_tracker = self

    # -- ingestion --------------------------------------------------------
    def note_publish(self, tenant: str, doc: Dict[str, Any],
                     now: Optional[float] = None) -> None:
        """One version installed locally (root stamp or follower
        republish) — fold its chain into the per-hop windows and write
        the row."""
        t0 = time.thread_time()
        t = time.time() if now is None else float(now)
        lats = hop_latencies_ms(doc)
        for h, lat in zip(doc["hops"], lats):
            win = self._hop_lat.get(h["hop_index"])
            if win is None:
                win = self._hop_lat[h["hop_index"]] = deque(
                    maxlen=self.window)
            win.append(lat)
        vis = visible_latency_ms(doc)
        if vis is not None:
            self._visible.append(vis)
        self.publishes += 1
        self._write({"kind": "publish", "t": round(t, 4),
                     "tenant": tenant, "version": doc["version"],
                     "publish_wall": doc["publish_wall"],
                     "root_gen": doc["root_gen"],
                     "hop_count": doc["hop_count"],
                     "hops": doc["hops"],
                     "visible_ms": (round(vis, 3)
                                    if vis is not None else None)})
        self.overhead_s += time.thread_time() - t0

    def note_delivery(self, row: Dict[str, Any]) -> None:
        """One reader delivery (a ``fresh_delivery_row`` dict): the
        edge of the causal chain."""
        t0 = time.thread_time()
        self.deliveries += 1
        if "age_ms" in row:
            self._delivery_age.append(float(row["age_ms"]))
        out = dict(row)
        out["kind"] = "delivery"
        out.setdefault("t", time.time())
        self._write(out)
        self.overhead_s += time.thread_time() - t0

    def note_reject(self) -> None:
        self.dropped += 1

    def _write(self, row: Dict[str, Any]) -> None:
        if self._f is not None:
            self._f.write(json.dumps(row) + "\n")
            self._f.flush()

    # -- read-out ---------------------------------------------------------
    def hop_quantiles_ms(self) -> Dict[int, Dict[str, float]]:
        return {idx: {"p50": round(_q(list(w), 0.50), 3),
                      "p95": round(_q(list(w), 0.95), 3),
                      "n": float(len(w))}
                for idx, w in sorted(self._hop_lat.items())}

    def snapshot(self) -> Dict[str, Any]:
        sc = self.core if self.core is not None \
            else getattr(self.server, "serving_core", None)
        ages = sc.fresh_ages_ms() if sc is not None else {}
        return {
            "publishes": self.publishes,
            "deliveries": self.deliveries,
            "dropped": self.dropped,
            "visible_p50_ms": round(_q(list(self._visible), 0.50), 3),
            "visible_p95_ms": round(_q(list(self._visible), 0.95), 3),
            "delivery_age_p95_ms": round(
                _q(list(self._delivery_age), 0.95), 3),
            "hops": {str(k): v for k, v in self.hop_quantiles_ms().items()},
            "serving_age_ms": {k: round(v, 3) for k, v in ages.items()},
            "overhead_s": round(self.overhead_s, 6),
            "file": self.path,
        }

    def register(self, registry) -> None:
        def collect(r) -> None:
            r.counter("ps_fresh_publishes_total",
                      "versions with freshness birth records installed "
                      "on this node").set(float(self.publishes))
            r.counter("ps_fresh_deliveries_total",
                      "reader deliveries folded into the freshness "
                      "plane").set(float(self.deliveries))
            r.counter("ps_fresh_dropped_total",
                      "malformed/truncated freshness trailers "
                      "rejected").set(float(self.dropped))

        registry.add_collector(collect)

    def close(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            f.close()


# -- Chrome flow events -----------------------------------------------------

def freshness_flow_events(rows: List[Dict[str, Any]],
                          lineage_rows: Optional[List[Dict[str, Any]]]
                          = None,
                          t0_wall: float = 0.0) -> List[Dict[str, Any]]:
    """Render persisted freshness rows as Chrome trace flow events: one
    flow per (tenant, version) from the root publish instant through
    each hop arrival to every reader delivery. When write-path lineage
    rows are supplied, each flow's publish step carries the worker
    push ``trace_ids`` folded into that version, completing the causal
    chain worker encode → published version → replica hops → reader
    read in one ``chrome://tracing`` / Perfetto view."""
    from pytorch_ps_mpi_tpu.telemetry.lineage import trace_id

    by_version: Dict[int, List[str]] = {}
    for lr in lineage_rows or []:
        if lr.get("kind") == "publish" and "version" in lr:
            ids = []
            for p in lr.get("pushes", []):
                tid = p.get("trace_id")
                if tid is None and "worker" in p and "seq" in p:
                    # real LineageTracker rows carry the id as its
                    # (worker, step, seq) parts, not a pre-joined string
                    tid = trace_id(p["worker"], p.get("step", 0),
                                   p["seq"])
                if tid:
                    ids.append(tid)
            by_version.setdefault(int(lr["version"]), []).extend(ids)
    ev: List[Dict[str, Any]] = []

    def _flow(ph: str, fid: str, ts_s: float, pid: str, tid: str,
              nm: str, args: Dict[str, Any]) -> None:
        ev.append({"name": nm, "cat": "freshness", "ph": ph,
                   "id": fid, "ts": (ts_s - t0_wall) * 1e6,
                   "pid": pid, "tid": tid, "args": args})

    seen_pub = set()
    for row in rows:
        tenant = str(row.get("tenant", "default"))
        ver = int(row.get("version", 0))
        fid = f"fresh:{tenant}/{ver}"
        if row.get("kind") == "publish":
            pw = float(row.get("publish_wall", row.get("t", 0.0)))
            if (tenant, ver) not in seen_pub:
                seen_pub.add((tenant, ver))
                _flow("s", fid, pw, "root", "publish",
                      f"publish v{ver}",
                      {"tenant": tenant, "version": ver,
                       "trace_ids": by_version.get(ver, [])})
            skew_s = 0.0
            for h in row.get("hops", []):
                skew_s += float(h.get("skew_ms", 0.0)) * 1e-3
                _flow("t", fid, float(h["arrival_wall"]) - skew_s,
                      f"hop{h['hop_index']}", "relay",
                      f"hop {h['hop_index']} v{ver}",
                      {"skew_ms": h.get("skew_ms", 0.0)})
        elif row.get("kind") == "delivery":
            _flow("f", fid, float(row.get("t", 0.0)), "reader",
                  str(row.get("reader", "reader")),
                  f"read v{ver} age {row.get('age_ms', 0.0):.1f}ms",
                  {"age_ms": row.get("age_ms", 0.0),
                   "hop_count": row.get("hop_count", 0)})
    return ev
