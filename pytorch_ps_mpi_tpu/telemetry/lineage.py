"""End-to-end gradient lineage: causal push IDs from encode to publish.

Every observability layer before this one was per-process: recorder
spans (PR 1), ``/health`` verdicts (PR 4) and numerics stats (PR 5) each
see only their own side of the wire, so per-push latency and staleness
were *estimated* from interarrival EWMAs rather than *measured*, and a
divergence postmortem could not say which worker pushes composed the bad
published version. This module closes that gap with a **trace ID**
stamped into every framed gradient push at the worker's encode site —

    ``(worker id, worker step, monotonic push seq)``

— carried by the v2 frame header (``resilience.frames``: step, seq and
the worker's ``send_wall`` timestamp ride beside the CRC and config
fingerprint) through BOTH transports, and consumed server-side by a
:class:`LineageTracker` fed from the shared ``framed_poll`` loop and the
serve loop's publish site. The tracker gives every published version a
recorded **lineage**: the exact set of (worker, step, staleness, bytes,
per-stage wall times) pushes that composed it, written as
``lineage-<name>.jsonl`` rows beside the recorder dumps.

On top of the raw lineage:

- **exact distributions** — per-push end-to-end latency (worker encode →
  version published) and exact per-push staleness replace/validate the
  PR 4 EWMA estimates; they surface as new canonical
  ``PS_SERVER_METRIC_KEYS`` and as ``ps_push_e2e_seconds`` /
  ``ps_push_wire_seconds`` histograms on both transports;
- **clock-skew estimation** — :func:`estimate_clock_offset` fits a
  per-worker offset from the frame (send_wall, recv_wall) timestamp
  pairs so ``trace_export`` can merge worker + server recorder spans
  into ONE Chrome trace with flow events (arrows) linking a worker's
  push span to the server's consume span;
- **critical-path extraction** — for sync-barrier rounds, which
  worker's which *stage* (produce / wire / decode) gated the round,
  sharpening PR 4's last-ready attribution into a stage-level answer;
- **postmortem lineage** — ``telemetry.numerics`` embeds the offending
  worker's recent pushes and the last published composition into its
  ``postmortem-*.json`` captures.

Zero-cost-when-disabled like every other telemetry layer: the framed
poll and the serve loop each pay one ``None``-check per push when
lineage is off, and the tracker self-times its own bookkeeping
(``overhead_s``) so ``make trace-smoke`` can hold it to the standing
<=5% telemetry budget.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

PyTree = Any

#: push-latency histogram buckets (seconds): sub-ms shm hops through
#: multi-second straggler waits
LATENCY_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                   5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: the per-push stage names critical-path extraction attributes to
STAGES = ("produce", "wire", "decode")

#: tuning knobs and their defaults (overridable via ``cfg["lineage_kw"]``)
LINEAGE_KNOBS: Dict[str, Any] = {
    "window": 4096,      # e2e/wire/staleness sample windows (pushes)
    "ring": 256,         # recent composed pushes kept for postmortems
    "flush_every": 64,   # JSONL rows buffered between flushes
}


def trace_id(worker: int, step: int, seq: int) -> str:
    """The canonical string form of a push trace ID — what flow events
    in the merged Chrome trace use as their ``id``."""
    return f"{int(worker)}/{int(step)}/{int(seq)}"


def estimate_clock_offset(
    pairs: Sequence[Tuple[float, float]]
) -> float:
    """Estimate the clock offset between two processes from
    ``(send_ts, recv_ts)`` wall-timestamp pairs of the same frames
    (sender's clock stamps ``send_ts``, receiver's stamps ``recv_ts``).

    Returns the estimated ``receiver_clock - sender_clock`` offset in
    seconds, using the classic one-way lower-envelope estimator:
    ``min(recv - send)`` over all pairs. Since the true one-way latency
    is non-negative, the minimum difference bounds the offset from
    above and is achieved by the fastest frame — so the estimate is
    biased by (at most) the *minimum* network latency, not the jittery
    mean. The degenerate single-pair case returns that pair's
    difference. Raises ``ValueError`` on an empty input (there is no
    offset to estimate)."""
    diffs = [float(r) - float(s) for s, r in pairs]
    if not diffs:
        raise ValueError("need at least one (send, recv) pair")
    return min(diffs)


def clock_offsets_from_rows(
    rows: Iterable[Dict[str, Any]]
) -> Dict[int, float]:
    """Per-worker clock offsets (``server_clock - worker_clock``
    estimates) from lineage JSONL rows — every push in every
    ``publish``/``drop`` row contributes its (send_wall, recv_wall)
    pair. Workers with no pushes are absent from the result."""
    pairs: Dict[int, List[Tuple[float, float]]] = {}
    for row in rows:
        pushes = list(row.get("pushes") or [])
        if "push" in row:
            pushes.append(row["push"])
        for p in pushes:
            s, r = p.get("send_wall"), p.get("recv_wall")
            if s is None or r is None:
                continue
            pairs.setdefault(int(p["worker"]), []).append(
                (float(s), float(r)))
    return {w: estimate_clock_offset(ps) for w, ps in pairs.items()}


def load_lineage_rows(path: str) -> List[Dict[str, Any]]:
    """Read a ``lineage-*.jsonl`` file back into its row list (torn
    trailing lines skipped — the writer flushes whole lines)."""
    rows: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    return rows


# the one nearest-rank percentile shared with the diagnosis layer —
# exact-vs-EWMA comparisons must use ONE quantile definition
from pytorch_ps_mpi_tpu.telemetry.diagnosis import _percentile


class _WorkerLineage:
    __slots__ = ("pushes", "stale_last", "stale_win", "e2e_last",
                 "e2e_win", "gated_rounds")

    def __init__(self, window: int):
        self.pushes = 0
        self.stale_last: Optional[int] = None
        self.stale_win: deque = deque(maxlen=window)
        self.e2e_last: Optional[float] = None
        self.e2e_win: deque = deque(maxlen=window)
        self.gated_rounds = 0


class LineageTracker:
    """Server-side lineage: consumes the trace IDs ``framed_poll``
    decodes from the v2 frame headers and bills every published version
    with the exact pushes that composed it.

    Feed points (all same-thread with the serve loop):

    - :meth:`observe_consume` for EVERY counted pop of a valid frame
      (``framed_poll`` calls it — applied and stale-dropped pushes
      alike), with the push meta the frame header carried;
    - :meth:`discard_last` when the serve loop drops a consumed push
      before applying it (numerics skip/abort) — the push gets a
      ``drop`` lineage row instead of silently joining the next
      version's composition;
    - :meth:`observe_publish` right after each ``server.publish`` with
      the new version and the measured apply+publish wall — pops the
      uncomposed pushes (one per ``workers`` entry in sync-barrier
      mode, everything pending in async mode), stamps their end-to-end
      latency, and writes the ``publish`` lineage row.

    ``server`` is any PS server carrying the
    :class:`~pytorch_ps_mpi_tpu.telemetry.registry.PSServerTelemetry`
    surface; passing it attaches the tracker
    (``server.lineage_tracker`` — the canonical-schema source for the
    new ``lineage_pushes`` / ``push_e2e_p*_ms`` keys and ``framed_poll``'s
    feed hook) and registers the scrape instruments. Tests may pass
    ``num_workers`` and drive the feed points directly.
    """

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, num_workers: Optional[int] = None, name: str = "server",
                 **overrides):
        cfg = cfg or {}
        self.knobs = dict(LINEAGE_KNOBS)
        self.knobs.update(cfg.get("lineage_kw") or {})
        self.knobs.update(overrides)
        self.server = server
        if num_workers is None:
            if server is None:
                raise ValueError("need a server or num_workers")
            num_workers = int(server.num_workers)
        self.num_workers = int(num_workers)
        self.name = name
        self.dir = cfg.get("lineage_dir") or cfg.get("telemetry_dir")
        win = int(self.knobs["window"])
        self._w = [_WorkerLineage(win) for _ in range(self.num_workers)]
        self.consumed = 0        # valid frames counted (applied + dropped)
        self.composed = 0        # pushes billed to a published version
        self.drops = 0           # stale/numerics-dropped pushes
        self.publishes = 0
        self.rounds = 0          # multi-push publishes (sync rounds)
        self.staleness_exact: Dict[int, int] = {}
        self.e2e_win: deque = deque(maxlen=win)
        self.wire_win: deque = deque(maxlen=win)
        self._uncomposed: Dict[int, deque] = {
            w: deque() for w in range(self.num_workers)
        }
        self._recent: deque = deque(maxlen=int(self.knobs["ring"]))
        self.last_publish: Optional[Dict[str, Any]] = None
        #: (worker, stage) → rounds that worker's stage gated
        self.critical_path: Dict[Tuple[int, str], int] = {}
        self.overhead_s = 0.0    # self-timed bookkeeping cost
        self._f = None
        self._rows_since_flush = 0
        self._h_e2e = None
        self._h_wire = None
        #: the attached round-anatomy engine (telemetry.anatomy) — fed
        #: one publish row per published version; None when unarmed
        #: (one None-check per publish)
        self.anatomy = None
        if server is not None:
            server.lineage_tracker = self
            self.register(server.scrape_registry())

    # -- feed points ------------------------------------------------------
    def observe_consume(self, meta: Dict[str, Any]) -> None:
        """One valid frame popped by ``framed_poll``. ``meta`` carries
        ``worker/step/seq/version_read/staleness/bytes/send_wall/
        recv_wall`` (+ ``decode_s`` when decoded, ``stale_drop=True``
        when the bounded-staleness gate dropped it)."""
        t0 = time.perf_counter()
        w = int(meta["worker"])
        if not 0 <= w < self.num_workers:
            return  # rogue ids are the frame layer's problem
        self.consumed += 1
        stale = int(meta.get("staleness", 0))
        self.staleness_exact[stale] = self.staleness_exact.get(stale, 0) + 1
        h = self._w[w]
        h.pushes += 1
        h.stale_last = stale
        h.stale_win.append(float(stale))
        if meta.get("stale_drop"):
            self.drops += 1
            self._write_row({"kind": "drop", "reason": "stale",
                            "t": meta.get("recv_wall", time.time()),
                             "push": meta})
        else:
            self._uncomposed[w].append(meta)
        self.overhead_s += time.perf_counter() - t0

    def discard_last(self, worker: int, reason: str = "numerics") -> None:
        """The serve loop consumed this worker's latest push but will
        never apply it (numerics skip/abort): pull it back out of the
        composition queue and give it its own ``drop`` row."""
        t0 = time.perf_counter()
        q = self._uncomposed.get(int(worker))
        if q:
            meta = q.pop()
            self.drops += 1
            self._write_row({"kind": "drop", "reason": reason,
                             "t": time.time(), "push": meta})
        self.overhead_s += time.perf_counter() - t0

    def observe_publish(self, version: int, apply_s: float,
                        workers: Optional[Sequence[int]] = None,
                        now: Optional[float] = None) -> Dict[str, Any]:
        """Bill the new published ``version`` with its composing pushes.
        ``workers`` (sync-barrier mode) pops exactly one queued push per
        listed worker — mirroring the serve loop's own
        ``pending[w].popleft()`` — while ``None`` (async mode) pops
        everything uncomposed (exactly the one push just applied)."""
        t0 = time.perf_counter()
        now = time.time() if now is None else float(now)
        pushes: List[Dict[str, Any]] = []
        if workers is None:
            for w in range(self.num_workers):
                while self._uncomposed[w]:
                    pushes.append(self._uncomposed[w].popleft())
        else:
            for w in workers:
                q = self._uncomposed.get(int(w))
                if q:
                    pushes.append(q.popleft())
        for p in pushes:
            send = p.get("send_wall")
            recv = p.get("recv_wall")
            # RAW cross-clock differences, deliberately unclamped: a
            # negative wire_s is the documented NTP-skew smell (the
            # worker's clock runs ahead of the server's by more than
            # the wire latency) — clamping would hide exactly the
            # condition the runbook tells operators to look for
            p["e2e_s"] = None if send is None else now - send
            p["wire_s"] = (None if send is None or recv is None
                           else recv - send)
            h = self._w[int(p["worker"])]
            if p["e2e_s"] is not None:
                h.e2e_last = p["e2e_s"]
                h.e2e_win.append(p["e2e_s"])
                self.e2e_win.append(p["e2e_s"])
                if self._h_e2e is not None:
                    self._h_e2e.observe(p["e2e_s"])
            if p["wire_s"] is not None:
                self.wire_win.append(p["wire_s"])
                if self._h_wire is not None:
                    self._h_wire.observe(p["wire_s"])
            self._recent.append(p)
        self.composed += len(pushes)
        self.publishes += 1
        row = {"kind": "publish", "version": int(version), "t": now,
               "apply_s": round(float(apply_s), 6), "pushes": pushes}
        self.last_publish = row
        self._write_row(row)
        if len(pushes) >= 2:
            self._observe_round(row)
        self.overhead_s += time.perf_counter() - t0
        if self.anatomy is not None:
            # the round-anatomy engine decomposes the SAME row this
            # tracker just wrote — exact critical paths and what-if
            # projections from the one causal record (self-timed there,
            # deliberately outside this tracker's overhead clock)
            self.anatomy.observe_publish(row)
        return row

    def _observe_round(self, publish_row: Dict[str, Any]) -> None:
        """Stage-level critical path of one multi-push (sync-barrier)
        round: the LAST push to arrive gated it; its dominant stage —
        ``produce`` (gap since that worker's previous send: compute +
        read + any straggle), ``wire`` (send→recv transfer+queue) or
        ``decode`` — is the round's answer. Sharpens PR 4's last-ready
        worker attribution into *which stage of whose pipeline*."""
        pushes = publish_row["pushes"]
        gate = max(pushes, key=lambda p: p.get("recv_wall") or 0.0)
        w = int(gate["worker"])
        stages: Dict[str, Optional[float]] = {
            "wire": gate.get("wire_s"),
            "decode": gate.get("decode_s"),
        }
        prev_send = self._prev_send_wall(w, gate)
        stages["produce"] = (
            None if prev_send is None or gate.get("send_wall") is None
            else max(0.0, gate["send_wall"] - prev_send)
        )
        known = {k: v for k, v in stages.items() if v is not None}
        if not known:
            return
        stage = max(known, key=known.get)
        self.rounds += 1
        self._w[w].gated_rounds += 1
        key = (w, stage)
        self.critical_path[key] = self.critical_path.get(key, 0) + 1
        self._write_row({
            "kind": "round", "round": self.rounds,
            "version": publish_row["version"], "t": publish_row["t"],
            "gating_worker": w, "stage": stage,
            "stage_s": round(known[stage], 6),
            "stages": {k: (None if v is None else round(v, 6))
                       for k, v in stages.items()},
            "trace": trace_id(w, gate.get("step", 0), gate.get("seq", 0)),
        })

    def _prev_send_wall(self, worker: int,
                        gate: Dict[str, Any]) -> Optional[float]:
        """The gating worker's previous composed push's send time —
        scan the recent ring backwards past the gating push itself."""
        seen_gate = False
        for p in reversed(self._recent):
            if p is gate:
                seen_gate = True
                continue
            if seen_gate and int(p["worker"]) == worker:
                return p.get("send_wall")
        return None

    # -- disk -------------------------------------------------------------
    def _write_row(self, row: Dict[str, Any]) -> None:
        if not self.dir:
            return
        if self._f is None:
            os.makedirs(self.dir, exist_ok=True)
            self._f = open(lineage_path(self.dir, self.name), "a")
        self._f.write(json.dumps(row) + "\n")
        self._rows_since_flush += 1
        if self._rows_since_flush >= int(self.knobs["flush_every"]):
            self._f.flush()
            self._rows_since_flush = 0

    def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            f.flush()
            f.close()

    # -- read side --------------------------------------------------------
    def recent(self, k: int = 16,
               worker: Optional[int] = None) -> List[Dict[str, Any]]:
        """The last ``k`` composed pushes (optionally one worker's) —
        what a numerics postmortem embeds as the offender's history."""
        rows = [p for p in self._recent
                if worker is None or int(p["worker"]) == int(worker)]
        return rows[-int(k):]

    def e2e_ms_quantile(self, q: float) -> float:
        return 1e3 * _percentile(list(self.e2e_win), q)

    def wire_ms_quantile(self, q: float) -> float:
        return 1e3 * _percentile(list(self.wire_win), q)

    def staleness_quantile(self, q: float) -> float:
        """Exact weighted quantile over every consumed push's frame-
        carried staleness — the measured number the PR 4 EWMAs estimate."""
        from pytorch_ps_mpi_tpu.telemetry.registry import staleness_quantile

        return staleness_quantile(self.staleness_exact, q)

    def worker_summary(self, worker: int) -> Optional[Dict[str, Any]]:
        """Per-worker lineage digest for ``/health`` rows and
        ``ps_top``'s ``stale(exact)`` / ``e2e ms`` columns."""
        if not 0 <= worker < self.num_workers:
            return None
        h = self._w[worker]
        return {
            "pushes": h.pushes,
            "stale_last": h.stale_last,
            "stale_p50": _percentile(list(h.stale_win), 0.50),
            "e2e_ms_last": (None if h.e2e_last is None
                            else round(1e3 * h.e2e_last, 3)),
            "e2e_ms_p50": round(1e3 * _percentile(list(h.e2e_win), 0.50),
                                3),
            "gated_rounds": h.gated_rounds,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The lineage section of the serve call's returned metrics (and
        of ``/health`` when diagnosis is armed). Pure reads."""
        return {
            "armed": True,
            "consumed": self.consumed,
            "composed": self.composed,
            "drops": self.drops,
            "publishes": self.publishes,
            "rounds": self.rounds,
            "e2e_ms": {"p50": round(self.e2e_ms_quantile(0.50), 3),
                       "p95": round(self.e2e_ms_quantile(0.95), 3),
                       "p99": round(self.e2e_ms_quantile(0.99), 3)},
            "wire_ms": {"p50": round(self.wire_ms_quantile(0.50), 3),
                        "p95": round(self.wire_ms_quantile(0.95), 3)},
            # snapshot in ONE C-level call first: /health scrapes run on
            # the HTTP thread while the serve thread inserts new keys
            # (same hazard registry.staleness_quantile documents)
            "staleness_exact": {int(k): int(v) for k, v
                                in list(self.staleness_exact.items())},
            "critical_path": [
                {"worker": w, "stage": s, "rounds": n}
                for (w, s), n in sorted(list(
                    self.critical_path.items()))
            ],
            "overhead_s": round(self.overhead_s, 6),
            "workers": [self.worker_summary(w)
                        for w in range(self.num_workers)],
        }

    # -- scrape registry --------------------------------------------------
    def register(self, registry) -> None:
        """Histograms observed at publish time + scrape-time gauges for
        the exact quantiles — the measured numbers beside (and
        validating) the PR 4 EWMA estimates."""
        self._h_e2e = registry.histogram(
            "ps_push_e2e_seconds", LATENCY_BUCKETS,
            "exact per-push end-to-end latency: worker encode (frame "
            "send_wall) to the composed version's publish",
        )
        self._h_wire = registry.histogram(
            "ps_push_wire_seconds", LATENCY_BUCKETS,
            "exact per-push wire latency: frame send_wall to the "
            "server's pop (cross-clock; see clock-skew caveats)",
        )

        def collect(r) -> None:
            r.counter(
                "ps_lineage_pushes_total",
                "pushes billed to a published version (composed lineage)",
            ).set(float(self.composed))
            r.counter(
                "ps_lineage_drops_total",
                "consumed pushes that never composed a version "
                "(stale drop, numerics skip)",
            ).set(float(self.drops))
            r.gauge(
                "ps_push_e2e_p50_ms",
                "exact per-push end-to-end latency p50 (ms)",
            ).set(self.e2e_ms_quantile(0.50))
            r.gauge(
                "ps_push_e2e_p95_ms",
                "exact per-push end-to-end latency p95 (ms)",
            ).set(self.e2e_ms_quantile(0.95))
            r.gauge(
                "ps_staleness_exact_p50",
                "exact per-push staleness p50 from frame trace IDs "
                "(versions)",
            ).set(self.staleness_quantile(0.50))
            r.gauge(
                "ps_staleness_exact_p95",
                "exact per-push staleness p95 from frame trace IDs "
                "(versions)",
            ).set(self.staleness_quantile(0.95))

        registry.add_collector(collect)


def lineage_path(lineage_dir: str, name) -> str:
    """``lineage-<name>.jsonl`` — the ``lineage-`` prefix keeps these
    rows out of recorder-JSONL merges, like ``beacon-``/``numerics-``."""
    return os.path.join(lineage_dir, f"lineage-{name}.jsonl")
