"""Merged host+device Chrome/Perfetto trace export.

Host side: :class:`~.recorder.FlightRecorder` spans/events (from live
recorders or their JSONL dumps — several processes' files merge into one
timeline on the ``wall`` clock each record carries). Device side: the
``*.xplane.pb`` files a ``jax.profiler`` trace directory holds, read
through :func:`pytorch_ps_mpi_tpu.utils.tracing._iter_hlo_events` — the
same event source the comm/compute split uses.

Clock honesty: host rows are placed by their ``wall`` timestamps (one
clock across processes, NTP-grade alignment). When gradient lineage is
armed, worker-process rows are additionally shifted by the per-worker
clock offsets :func:`~.lineage.clock_offsets_from_rows` fits from the
frame (send_wall, recv_wall) timestamp pairs — see
:func:`apply_clock_offsets` — so worker and server spans line up to
~min-wire-latency accuracy even across hosts with skewed clocks. Device
ops only carry the profiler's own timebase, so they are placed relative
to the wall time at which the trace capture started (``device_t0_wall``,
recorded by the caller at ``start_trace``; defaults to the host
timeline's start). The alignment is therefore approximate at the ~ms
level — good for "which step was the device idle in", not for ns-level
attribution.

Cross-process causality: pass ``lineage_rows`` (the ``publish``/``drop``
rows of a ``lineage-*.jsonl``) and every composed push whose worker
``worker.push_grad`` span and server ``serve.consume`` span both made it
into the recorder dumps gets a Chrome **flow event** pair (``ph: "s"``
→ ``ph: "f"``, id = the push's ``worker/step/seq`` trace ID) — the
arrows Perfetto draws from the worker's push to the server's consume.

Output is standard Chrome ``traceEvents`` JSON: load it at
``ui.perfetto.dev`` or ``chrome://tracing``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

HOST_PID = 1
DEVICE_PID_BASE = 1000


def apply_clock_offsets(
    events: Iterable[Dict[str, Any]],
    offsets: Optional[Dict[Any, float]],
) -> List[Dict[str, Any]]:
    """Shift each record's ``wall`` by its worker's estimated clock
    offset (``server_clock - worker_clock``, from
    :func:`~.lineage.clock_offsets_from_rows`), moving every worker
    process onto the server's clock. Records from workers without an
    estimate (and the server's own, which is the reference) pass
    through untouched. Returns shifted copies — inputs are not
    mutated."""
    if not offsets:
        return list(events)
    out = []
    for e in events:
        off = offsets.get(e.get("worker"))
        if off and "wall" in e:
            e = dict(e)
            e["wall"] = e["wall"] + off
        out.append(e)
    return out


def _host_events(
    events: Iterable[Dict[str, Any]], t0_wall: float
) -> Tuple[List[Dict[str, Any]], Dict[Tuple, Tuple[int, float, float]]]:
    """Returns ``(trace_events, span_index)`` where ``span_index`` maps
    a push trace ID to the (tid, ts_us, dur_us) of its worker push span
    (key ``("push", worker, step, seq)``) or server consume span
    (key ``("consume", worker, step, seq)``) — the anchors flow events
    attach to."""
    out: List[Dict[str, Any]] = []
    tids = {}
    span_index: Dict[Tuple, Tuple[int, float, float]] = {}
    for e in events:
        wall = e.get("wall")
        if wall is None:
            continue
        worker = e.get("worker", "host")
        tid = tids.setdefault(worker, len(tids) + 1)
        args = dict(e.get("attrs") or {})
        for k in ("step", "staleness", "worker"):
            if k in e:
                args[k] = e[k]
        ts_us = (wall - t0_wall) * 1e6
        if e.get("kind") == "span":
            # span rows stamp their START time (every producer passes
            # ts=t0 to FlightRecorder.event; the span() context manager
            # does so itself)
            dur_us = float(e.get("dur", 0.0)) * 1e6
            out.append({
                "ph": "X", "name": e["name"], "cat": "host",
                "pid": HOST_PID, "tid": tid,
                "ts": ts_us, "dur": dur_us,
                "args": args,
            })
            if e["name"] == "worker.push_grad" and "seq" in args:
                span_index[("push", e.get("worker"), e.get("step"),
                            args["seq"])] = (tid, ts_us, dur_us)
            elif e["name"] == "serve.consume" and "seq" in args:
                span_index[("consume", args.get("src_worker"),
                            e.get("step"), args["seq"])] = (
                    tid, ts_us, dur_us)
        else:
            out.append({
                "ph": "i", "s": "t", "name": e["name"], "cat": "host",
                "pid": HOST_PID, "tid": tid, "ts": ts_us, "args": args,
            })
    for worker, tid in tids.items():
        out.append({
            "ph": "M", "name": "thread_name", "pid": HOST_PID, "tid": tid,
            "args": {"name": f"worker {worker}"},
        })
    out.append({
        "ph": "M", "name": "process_name", "pid": HOST_PID,
        "args": {"name": "host (FlightRecorder)"},
    })
    return out, span_index


def _flow_events(
    span_index: Dict[Tuple, Tuple[int, float, float]],
    lineage_rows: Iterable[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """One ``s``→``f`` flow pair per composed push whose BOTH anchor
    spans landed in the recorder dumps (a bounded recorder may have
    evicted either side — missing anchors are skipped, never guessed).
    The flow binds to its enclosing slices by (pid, tid, ts): the start
    sits mid-push-span on the worker's track, the finish mid-consume-
    span on the server's track."""
    from pytorch_ps_mpi_tpu.telemetry.lineage import trace_id

    out: List[Dict[str, Any]] = []
    for row in lineage_rows:
        pushes = list(row.get("pushes") or [])
        if "push" in row:
            pushes.append(row["push"])
        for p in pushes:
            key = (p.get("worker"), p.get("step"), p.get("seq"))
            src = span_index.get(("push",) + key)
            dst = span_index.get(("consume",) + key)
            if src is None or dst is None:
                continue
            # the ONE canonical id form — must match the lineage rows'
            # own trace strings so trace.json cross-references them
            fid = trace_id(*key)
            for ph, (tid, ts, dur), extra in (
                    ("s", src, {}), ("f", dst, {"bp": "e"})):
                out.append({
                    "ph": ph, "cat": "lineage", "name": "grad push",
                    "id": fid, "pid": HOST_PID, "tid": tid,
                    "ts": ts + dur * 0.5, **extra,
                })
    return out


def _device_events(
    trace_dir: str, t0_wall: float, device_t0_wall: Optional[float],
    host_t0_wall: float,
) -> List[Dict[str, Any]]:
    from pytorch_ps_mpi_tpu.utils.tracing import _iter_hlo_events

    raw = list(_iter_hlo_events(trace_dir))
    if not raw:
        return []
    min_ns = min(start for _, _, start, _ in raw)
    anchor = device_t0_wall if device_t0_wall is not None else host_t0_wall
    base_us = (anchor - t0_wall) * 1e6
    out: List[Dict[str, Any]] = []
    pids: Dict[Any, int] = {}
    for dev, name, start_ns, dur_ns in raw:
        pid = pids.setdefault(dev, DEVICE_PID_BASE + len(pids))
        out.append({
            "ph": "X", "name": name, "cat": "device",
            "pid": pid, "tid": 1,
            "ts": base_us + (start_ns - min_ns) / 1e3,
            "dur": dur_ns / 1e3,
        })
    for dev, pid in pids.items():
        out.append({
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": f"device {dev} (jax.profiler)"},
        })
    return out


def merged_trace_events(
    host_events: Iterable[Dict[str, Any]],
    device_trace_dir: Optional[str] = None,
    device_t0_wall: Optional[float] = None,
    lineage_rows: Optional[Iterable[Dict[str, Any]]] = None,
    clock_offsets: Optional[Dict[Any, float]] = None,
    freshness_rows: Optional[Iterable[Dict[str, Any]]] = None,
    hop_rows: Optional[Iterable[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """FlightRecorder records (+ optional jax trace dir) → Chrome
    ``traceEvents`` list, all timestamps relative to the earliest host
    record. ``clock_offsets`` (per-worker, from lineage) are applied to
    worker records first; ``lineage_rows`` add cross-process flow
    events linking push spans to consume spans; ``freshness_rows``
    (delivery rows from ``freshness-*.jsonl``) add read-path flow
    arrows from the root publish through each follower hop to the edge
    reader, joined to write-path lineage when both are given;
    ``hop_rows`` (``hop_round`` rows from ``hop-*.jsonl``) add one
    track per tree leader with the hop's sub-stage spans, whose fold
    spans the composed pushes' lineage arrows thread through (flow
    STEP events, joined by the leaders' lineage hop rows)."""
    host_events = apply_clock_offsets(host_events, clock_offsets)
    walls = [e["wall"] for e in host_events if "wall" in e]
    t0_wall = min(walls) if walls else (device_t0_wall or 0.0)
    out, span_index = _host_events(host_events, t0_wall)
    if lineage_rows is not None:
        lineage_rows = list(lineage_rows)
        out.extend(_flow_events(span_index, lineage_rows))
    if freshness_rows is not None:
        from pytorch_ps_mpi_tpu.telemetry.freshness import (
            freshness_flow_events,
        )

        out.extend(freshness_flow_events(
            freshness_rows, lineage_rows, t0_wall=t0_wall
        ))
    if hop_rows is not None:
        from pytorch_ps_mpi_tpu.telemetry.hop_anatomy import (
            hop_trace_events,
        )

        out.extend(hop_trace_events(
            hop_rows, lineage_rows, t0_wall=t0_wall
        ))
    if device_trace_dir is not None:
        out.extend(_device_events(
            device_trace_dir, t0_wall, device_t0_wall, t0_wall
        ))
    return out


def export_chrome_trace(
    path: str,
    host_events: Iterable[Dict[str, Any]],
    device_trace_dir: Optional[str] = None,
    device_t0_wall: Optional[float] = None,
    lineage_rows: Optional[Iterable[Dict[str, Any]]] = None,
    clock_offsets: Optional[Dict[Any, float]] = None,
    freshness_rows: Optional[Iterable[Dict[str, Any]]] = None,
    hop_rows: Optional[Iterable[Dict[str, Any]]] = None,
) -> Tuple[str, Dict[str, int]]:
    """Write the merged timeline to ``path``; returns ``(path, {"host":
    n, "device": m, "flow": k, "fresh_flow": j, "hop": h})`` so callers
    can assert every side actually landed in the artifact (``flow``
    counts the lineage flow START events — each is half of one
    cross-process arrow; ``fresh_flow`` the read-path publish→edge flow
    starts; ``hop`` the leader-track sub-stage spans)."""
    events = merged_trace_events(
        host_events, device_trace_dir, device_t0_wall,
        lineage_rows=lineage_rows, clock_offsets=clock_offsets,
        freshness_rows=freshness_rows, hop_rows=hop_rows,
    )
    counts = {
        "host": sum(1 for e in events
                    if e.get("cat") == "host" and e["ph"] != "M"),
        "device": sum(1 for e in events
                      if e.get("cat") == "device" and e["ph"] != "M"),
        "flow": sum(1 for e in events if e.get("ph") == "s"
                    and e.get("cat") != "freshness"),
        "fresh_flow": sum(1 for e in events if e.get("ph") == "s"
                          and e.get("cat") == "freshness"),
        "hop": sum(1 for e in events
                   if e.get("cat") == "hop" and e["ph"] == "X"),
    }
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path, counts
