"""Merged host+device Chrome/Perfetto trace export.

Host side: :class:`~.recorder.FlightRecorder` spans/events (from live
recorders or their JSONL dumps — several processes' files merge into one
timeline on the ``wall`` clock each record carries). Device side: the
``*.xplane.pb`` files a ``jax.profiler`` trace directory holds, read
through :func:`pytorch_ps_mpi_tpu.utils.tracing._iter_hlo_events` — the
same event source the comm/compute split uses.

Clock honesty: host rows are placed by their ``wall`` timestamps (one
clock across processes, NTP-grade alignment); device ops only carry the
profiler's own timebase, so they are placed relative to the wall time at
which the trace capture started (``device_t0_wall``, recorded by the
caller at ``start_trace``; defaults to the host timeline's start). The
alignment is therefore approximate at the ~ms level — good for "which
step was the device idle in", not for ns-level attribution.

Output is standard Chrome ``traceEvents`` JSON: load it at
``ui.perfetto.dev`` or ``chrome://tracing``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

HOST_PID = 1
DEVICE_PID_BASE = 1000


def _host_events(
    events: Iterable[Dict[str, Any]], t0_wall: float
) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    tids = {}
    for e in events:
        wall = e.get("wall")
        if wall is None:
            continue
        worker = e.get("worker", "host")
        tid = tids.setdefault(worker, len(tids) + 1)
        args = dict(e.get("attrs") or {})
        for k in ("step", "staleness", "worker"):
            if k in e:
                args[k] = e[k]
        ts_us = (wall - t0_wall) * 1e6
        if e.get("kind") == "span":
            # span rows stamp their START time (every producer passes
            # ts=t0 to FlightRecorder.event; the span() context manager
            # does so itself)
            out.append({
                "ph": "X", "name": e["name"], "cat": "host",
                "pid": HOST_PID, "tid": tid,
                "ts": ts_us, "dur": float(e.get("dur", 0.0)) * 1e6,
                "args": args,
            })
        else:
            out.append({
                "ph": "i", "s": "t", "name": e["name"], "cat": "host",
                "pid": HOST_PID, "tid": tid, "ts": ts_us, "args": args,
            })
    for worker, tid in tids.items():
        out.append({
            "ph": "M", "name": "thread_name", "pid": HOST_PID, "tid": tid,
            "args": {"name": f"worker {worker}"},
        })
    out.append({
        "ph": "M", "name": "process_name", "pid": HOST_PID,
        "args": {"name": "host (FlightRecorder)"},
    })
    return out


def _device_events(
    trace_dir: str, t0_wall: float, device_t0_wall: Optional[float],
    host_t0_wall: float,
) -> List[Dict[str, Any]]:
    from pytorch_ps_mpi_tpu.utils.tracing import _iter_hlo_events

    raw = list(_iter_hlo_events(trace_dir))
    if not raw:
        return []
    min_ns = min(start for _, _, start, _ in raw)
    anchor = device_t0_wall if device_t0_wall is not None else host_t0_wall
    base_us = (anchor - t0_wall) * 1e6
    out: List[Dict[str, Any]] = []
    pids: Dict[Any, int] = {}
    for dev, name, start_ns, dur_ns in raw:
        pid = pids.setdefault(dev, DEVICE_PID_BASE + len(pids))
        out.append({
            "ph": "X", "name": name, "cat": "device",
            "pid": pid, "tid": 1,
            "ts": base_us + (start_ns - min_ns) / 1e3,
            "dur": dur_ns / 1e3,
        })
    for dev, pid in pids.items():
        out.append({
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": f"device {dev} (jax.profiler)"},
        })
    return out


def merged_trace_events(
    host_events: Iterable[Dict[str, Any]],
    device_trace_dir: Optional[str] = None,
    device_t0_wall: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """FlightRecorder records (+ optional jax trace dir) → Chrome
    ``traceEvents`` list, all timestamps relative to the earliest host
    record."""
    host_events = list(host_events)
    walls = [e["wall"] for e in host_events if "wall" in e]
    t0_wall = min(walls) if walls else (device_t0_wall or 0.0)
    out = _host_events(host_events, t0_wall)
    if device_trace_dir is not None:
        out.extend(_device_events(
            device_trace_dir, t0_wall, device_t0_wall, t0_wall
        ))
    return out


def export_chrome_trace(
    path: str,
    host_events: Iterable[Dict[str, Any]],
    device_trace_dir: Optional[str] = None,
    device_t0_wall: Optional[float] = None,
) -> Tuple[str, Dict[str, int]]:
    """Write the merged timeline to ``path``; returns ``(path, {"host":
    n, "device": m})`` so callers can assert both sides actually landed
    in the artifact."""
    events = merged_trace_events(
        host_events, device_trace_dir, device_t0_wall
    )
    counts = {
        "host": sum(1 for e in events
                    if e.get("cat") == "host" and e["ph"] != "M"),
        "device": sum(1 for e in events
                      if e.get("cat") == "device" and e["ph"] != "M"),
    }
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path, counts
