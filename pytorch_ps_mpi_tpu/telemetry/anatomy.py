"""Round-anatomy causal profiler: exact per-round critical paths and
Coz-style what-if projections from recorded lineage.

The lineage layer (PR 6) records exact causal data — every framed push
carries a (worker, step, seq) trace ID plus its encode-site ``send_wall``,
every published version gets a row naming its exact composing pushes,
and composed trailers (PR 13) carry the origin trace IDs through every
tree hop.  What no layer did until now is turn those rows into the
question an operator actually asks: *which stage limits round time, and
what would speeding it up buy?*  :class:`RoundAnatomy` is that layer:

- **causal DAG per published version** — each publish row is
  reconstructed into per-push stage segments using the canonical stage
  taxonomy :data:`STAGES`:

  ===============  =========================================================
  ``produce``      the pushing worker's gap since its previous send
                   (read + backprop + deliberate straggle; same worker
                   clock, so no offset correction is needed)
  ``encode``       the leader's upstream re-encode (hop rows only; a
                   direct push's encode is inside ``produce`` — the
                   frame is sealed at the encode site)
  ``wire``         frame ``send_wall`` → server ``recv_wall``,
                   clock-corrected (below)
  ``leader_fold``  composed pushes: last origin-worker send → the
                   leader's own hop encode (the group fold window)
  ``root_fold``    the server-side decode/fold of the gating push
  ``opt_publish``  the round's optimizer update + publish wall
  ``barrier``      the residual: round time not attributable to any
                   measured segment (degraded-round waits, scheduling).
                   Deliberately NOT a phantom stage — the advisor never
                   projects a speedup for it
  ===============  =========================================================

- **clock-offset correction** — the PR 6 lower-envelope fit, applied
  online: per worker the running envelope ``min(recv − send)`` bounds
  ``server_clock − worker_clock`` from above.  Correction engages only
  when the envelope is NEGATIVE (proof of skew: true wire latency is
  positive, so ``recv − send < 0`` can only be clock offset); a positive
  envelope is trusted, so genuinely constant wire latency (a real WAN
  hop) stays attributed to the wire stage instead of being absorbed into
  the offset estimate.  Either way no stage duration can come out
  negative — the negative-skew case shifts the whole envelope to zero.

- **exact critical path per round** — the gating (last-arriving) push's
  chain decomposes the round; per-stage critical-path shares and
  durations accumulate in bounded windows.

- **Coz-style what-if projections** — for every speedup-able stage the
  engine replays its retained rounds with the stage virtually sped up
  ("stage X 20% faster") and with the gating worker's stage pulled to
  the fleet median ("debottleneck"), and reports the projected
  round-time saving.  Virtual speedups move each push's arrival, so a
  projection correctly shows ~zero saving for a stage that is never on
  the critical path.

- **regime estimation for the controller** — :meth:`regime_estimate`
  derives the fleet wire-vs-compute balance from the measured stage
  windows; ``control.Controller`` consumes it in preference to beacon
  medians when lineage is armed (a worker whose beacons are off or
  skewed cannot hide a wire-bound fleet).  The estimator's outputs ride
  the controller's persisted TSDB input rows, so replay stays
  byte-identical by construction.

Two modes, one engine: live (attached to a PS server, fed by the
:class:`~pytorch_ps_mpi_tpu.telemetry.lineage.LineageTracker` at every
publish, writing ``anatomy-<name>.jsonl`` rows) and offline
(:func:`anatomy_from_rows` over persisted ``lineage-*.jsonl`` +
``lineage-leader*.jsonl`` files — ``tools/telemetry_report.py``'s
anatomy section and ``tools/whatif_smoke.py``'s gate).  Zero cost when
disabled (one ``None`` check per publish) and self-timed
(``overhead_s``) against the standing <=5% telemetry budget.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: the canonical stage taxonomy (order = causal order within a round);
#: ``barrier`` is the residual bucket and is never advised on
STAGES = ("produce", "encode", "wire", "leader_fold", "root_fold",
          "opt_publish", "barrier")

#: stages the what-if advisor may project speedups for (everything
#: measured; ``barrier`` is a residual, not a stage anyone can optimize)
SPEEDUP_STAGES = ("produce", "encode", "wire", "leader_fold", "root_fold",
                  "opt_publish")

#: the advisor's virtual-speedup grid (Coz-style "stage X this much
#: faster"); the 0.2 column is the canonical headline number
WHATIF_FRACS = (0.1, 0.2, 0.5)

#: tuning knobs and their defaults (overridable via ``cfg["anatomy_kw"]``)
ANATOMY_KNOBS: Dict[str, Any] = {
    "window": 512,       # rounds retained for advisor projections
    "stage_window": 1024,  # per-(worker, stage) duration samples kept
    "flush_every": 32,   # JSONL rows buffered between flushes
    "min_rounds": 4,     # rounds before regime_estimate answers
    # a produce gap wildly past the worker's own history (a barrier
    # stall, a supervisor-restart window, a stale-dropped push's hole)
    # is NOT compute: clip it at this multiple of the worker's rolling
    # median so the excess falls into the barrier residual instead of
    # masquerading as a phantom produce stage.  Genuine stragglers (a
    # few x slower) stay measured; only order-of-magnitude stalls clip.
    "produce_cap_x": 8.0,
}


def anatomy_path(out_dir: str, name) -> str:
    """``anatomy-<name>.jsonl`` — a registered sidecar prefix
    (:data:`pytorch_ps_mpi_tpu.telemetry.SIDECAR_PREFIXES`), routed away
    from the recorder-span merge like every other sidecar."""
    return os.path.join(out_dir, f"anatomy-{name}.jsonl")


def _med(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _p(vals: Sequence[float], q: float) -> float:
    s = sorted(vals)
    if not s:
        return math.nan
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


class _Envelope:
    """Running lower-envelope clock fit per worker: ``min(recv − send)``
    bounds ``server − worker`` clock offset from above (PR 6's
    ``estimate_clock_offset``, applied online).  ``shift()`` is the
    correction added to raw ``recv − send`` wire times: 0 while the
    envelope is positive (clocks trusted; constant latency is real
    latency), ``−envelope`` once it goes negative (proof of skew)."""

    __slots__ = ("lo",)

    def __init__(self):
        self.lo: Optional[float] = None

    def feed(self, diff: float) -> None:
        if self.lo is None or diff < self.lo:
            self.lo = diff

    def shift(self) -> float:
        return -self.lo if self.lo is not None and self.lo < 0 else 0.0

    def offset(self) -> Optional[float]:
        return self.lo


class RoundAnatomy:
    """The causal round profiler.  Live construction mirrors the other
    monitors (``RoundAnatomy(server, cfg)`` attaches ``server.anatomy``
    and registers scrape instruments); tests and the offline loaders
    pass ``num_workers`` and drive :meth:`observe_publish` directly.

    Feed point: one call per published version with the lineage publish
    row (the same dict :meth:`LineageTracker.observe_publish` writes —
    ``pushes`` carrying worker/step/seq/send_wall/recv_wall/decode_s and
    optional composed trailers, ``apply_s``, ``t``).  Same-thread with
    the serve loop, like every monitor feed point.
    """

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, num_workers: Optional[int] = None,
                 name: str = "server", **overrides: Any):
        cfg = cfg or {}
        self.knobs = dict(ANATOMY_KNOBS)
        self.knobs.update(cfg.get("anatomy_kw") or {})
        self.knobs.update(overrides)
        self.server = server
        if num_workers is None:
            if server is None:
                raise ValueError("need a server or num_workers")
            num_workers = int(server.num_workers)
        self.num_workers = int(num_workers)
        self.name = str(name)
        self.dir = (cfg.get("lineage_dir") or cfg.get("telemetry_dir"))
        self.rounds = 0
        self.publishes = 0
        self._prev_pub_t: Optional[float] = None
        self._prev_send: Dict[int, float] = {}
        #: worker → its previous push's corrected wire time: the push
        #: protocols BLOCK until the server acks, so the previous wire
        #: transfer is inside the worker's inter-send gap and must be
        #: carved out of ``produce`` (else a wire-delayed worker's delay
        #: double-counts into both stages and the advisor ties)
        self._last_wire: Dict[int, float] = {}
        self._env: Dict[int, _Envelope] = {}
        #: bounded round records the advisor replays
        self._rounds: deque = deque(maxlen=int(self.knobs["window"]))
        #: stage → critical-path rounds (stage gated)
        self.critical: Dict[str, int] = {}
        #: (worker, stage) → bounded duration window
        self._stage_win: Dict[Tuple[int, str], deque] = {}
        #: (origin worker, step, seq) → measured (fold_s, encode_s) from
        #: leader hop rows — joined to composed pushes by trace ID (the
        #: hop row carries the group id, the root push the leader wid;
        #: the composed trace IDs are the one key both sides share)
        self._hop_trace: Dict[Tuple[int, int, int], Tuple[float, float]] = {}
        self._hop_trace_order: deque = deque()
        #: group id → recent per-hop fold walls (the structural
        #: controller's hot-hop attribution input — see :meth:`hot_hop`)
        self._group_fold: Dict[int, deque] = {}
        self.overhead_s = 0.0
        self._f = None
        self._rows_since_flush = 0
        if server is not None:
            server.anatomy = self
            reg = getattr(server, "scrape_registry", None)
            if reg is not None:
                self.register(reg())

    # -- feed points ------------------------------------------------------
    def observe_hop(self, row: Dict[str, Any]) -> None:
        """One leader ``hop`` row (``lineage-leader<g>.jsonl``): per-hop
        fold/re-encode walls sharpen the composed-push expansion the
        trailer alone can only bound.  Offline feed (the report and the
        smoke load leader files beside the server's); joined to the
        root's composed pushes by the trailer trace IDs."""
        fold = float(row.get("fold_s") or 0.0)
        enc = float(row.get("encode_s") or 0.0)
        if "leader" in row:
            g = int(row["leader"])
            if g not in self._group_fold:
                self._group_fold[g] = deque(maxlen=8)
            self._group_fold[g].append(fold)
        cap = 4 * int(self.knobs["stage_window"])
        for e in row.get("composed") or ():
            key = (int(e.get("worker", -1)), int(e.get("step", 0)),
                   int(e.get("seq", 0)))
            if key not in self._hop_trace:
                self._hop_trace_order.append(key)
            self._hop_trace[key] = (fold, enc)
        while len(self._hop_trace_order) > cap:
            old = self._hop_trace_order.popleft()
            self._hop_trace.pop(old, None)

    def hot_hop(self) -> Optional[int]:
        """The group whose recent hops fold slowest (mean over the last
        8 observed hop rows per group) — the structural controller's
        ``hot_group`` input: WHICH leader to split when the advisor
        names ``leader_fold`` the top stage.  ``None`` until at least
        two groups have reported (a single group has no 'hotter')."""
        means = {g: sum(w) / len(w)
                 for g, w in self._group_fold.items() if w}
        if len(means) < 2:
            return None
        return max(means, key=means.get)

    def observe_reader_round(self, row: Dict[str, Any]) -> Dict[str, Any]:
        """One reader/follower poll cycle (the read-plane counterpart of
        a training round): written as a ``kind="reader_round"`` row into
        the same ``anatomy-<name>.jsonl`` sidecar.  The offline loaders
        filter on ``kind == "round"``, so reader rounds ride the file
        without perturbing round reconstruction — ``ps_report``/greppers
        see the replica's pull cadence, lag, and relay volume next to
        the server rounds that produced the versions it relays."""
        out = dict(row)
        out["kind"] = "reader_round"
        out.setdefault("t", time.time())
        out["name"] = self.name
        self._write_row(out)
        return out

    def observe_publish(self, row: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Decompose one publish row into its round anatomy.  Returns the
        anatomy round row (also written to ``anatomy-<name>.jsonl`` when
        a directory is armed), or None for push-less publishes (the
        initial parameter publish)."""
        t0 = time.perf_counter()
        try:
            return self._observe(row)
        finally:
            self.overhead_s += time.perf_counter() - t0

    def _observe(self, row: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        pushes = list(row.get("pushes") or [])
        t_pub = float(row.get("t", 0.0))
        self.publishes += 1
        if not pushes:
            self._prev_pub_t = t_pub
            return None
        # round span: previous publish → this publish; the first round
        # anchors at its earliest send (no previous version exists)
        sends = [float(p["send_wall"]) for p in pushes
                 if p.get("send_wall") is not None]
        t_start = (self._prev_pub_t if self._prev_pub_t is not None
                   else (min(sends) if sends else t_pub))
        round_s = max(0.0, t_pub - t_start)
        apply_s = float(row.get("apply_s") or 0.0)

        # feed the clock envelopes BEFORE decomposing: the correction a
        # push needs may be proven by the push itself (first skewed pair)
        for p in pushes:
            w, s, r = p.get("worker"), p.get("send_wall"), p.get("recv_wall")
            if w is None or s is None or r is None:
                continue
            self._env.setdefault(int(w), _Envelope()).feed(float(r) - float(s))

        segs = [self._segments(p) for p in pushes]
        # the gating push: last arrival on the server clock
        gate_i = max(range(len(pushes)),
                     key=lambda i: pushes[i].get("recv_wall") or 0.0)
        gate = dict(segs[gate_i])
        gate["root_fold"] = float(pushes[gate_i].get("decode_s") or 0.0)
        gate["opt_publish"] = apply_s
        known = {k: v for k, v in gate.items()
                 if k in SPEEDUP_STAGES and v is not None}
        attributed = sum(known.values())
        gate["barrier"] = max(0.0, round_s - attributed)
        # the dominant measured stage gates the round; a round whose
        # residual dwarfs every measurement (a degraded round waiting on
        # the barrier) is attributed to the barrier wait — NEVER to a
        # phantom measured stage
        if known and max(known.values()) >= gate["barrier"]:
            stage = max(known, key=known.get)
        else:
            stage = "barrier"
        self.rounds += 1
        self.critical[stage] = self.critical.get(stage, 0) + 1
        gw = int(pushes[gate_i].get("worker", -1))
        for i, p in enumerate(pushes):
            w = int(p.get("worker", -1))
            for st, v in segs[i].items():
                if v is None:
                    continue
                self._stage_win.setdefault(
                    (w, st), deque(maxlen=int(self.knobs["stage_window"]))
                ).append(float(v))
        for st in ("root_fold", "opt_publish"):
            self._stage_win.setdefault(
                (gw, st), deque(maxlen=int(self.knobs["stage_window"]))
            ).append(float(gate[st]))
        rec = {
            "kind": "round",
            "version": int(row.get("version", 0)),
            "t": t_pub,
            "round_s": round(round_s, 6),
            "gating_worker": gw,
            "stage": stage,
            "stages": {k: (None if gate.get(k) is None
                           else round(float(gate[k]), 6))
                       for k in STAGES},
            # per-push arrival offsets relative to round start + their
            # speedup-able chains — what the advisor replays
            "pushes": [
                {"worker": int(p.get("worker", -1)),
                 "arrive_s": round(max(
                     0.0, float(p.get("recv_wall") or t_start) - t_start), 6),
                 "segs": {k: (None if v is None else round(float(v), 6))
                          for k, v in segs[i].items()}}
                for i, p in enumerate(pushes)
            ],
            "post_s": round(gate["root_fold"] + gate["opt_publish"], 6),
        }
        self._rounds.append(rec)
        self._write_row(rec)
        self._prev_pub_t = t_pub
        for i, p in enumerate(pushes):
            if p.get("worker") is not None and p.get("send_wall") is not None:
                self._prev_send[int(p["worker"])] = float(p["send_wall"])
                if segs[i].get("wire") is not None:
                    self._last_wire[int(p["worker"])] = float(
                        segs[i]["wire"])
            # origin workers inside a composed trailer advance their own
            # produce anchors too (their next composed push's gap)
            for e in (p.get("composed") or ()):
                if e.get("worker") is not None and e.get("send_wall"):
                    self._prev_send[int(e["worker"])] = float(e["send_wall"])
        return rec

    def _segments(self, p: Dict[str, Any]) -> Dict[str, Optional[float]]:
        """One push's speedup-able chain segments (produce / encode /
        wire / leader_fold).  All durations are clamped non-negative;
        the wire segment carries the envelope's skew shift."""
        w = p.get("worker")
        send = p.get("send_wall")
        recv = p.get("recv_wall")
        env = self._env.get(int(w)) if w is not None else None
        wire = None
        if send is not None and recv is not None:
            wire = float(recv) - float(send)
            if env is not None:
                wire += env.shift()
            wire = max(0.0, wire)
        composed = p.get("composed") or ()
        leader_fold = None
        encode = None
        if len(composed) >= 1 and send is not None:
            hop = None
            for e in composed:
                hop = self._hop_trace.get((
                    int(e.get("worker", -1)), int(e.get("step", 0)),
                    int(e.get("seq", 0))))
                if hop is not None:
                    break
            if hop is not None:
                # the leader's hop row measured both halves directly
                leader_fold, encode = max(0.0, hop[0]), max(0.0, hop[1])
            else:
                # trailer-only bound: the frame's send_wall is the
                # LEADER's encode site; the trailer's newest origin send
                # bounds when the group fold could have started.
                # Cross-clock (worker → leader), clamped like every
                # segment.
                origin_sends = [float(e["send_wall"]) for e in composed
                                if e.get("send_wall")]
                if origin_sends:
                    leader_fold = max(0.0, float(send) - max(origin_sends))
        produce = None
        if w is not None and send is not None:
            prev = self._prev_send.get(int(w))
            if composed:
                # a composed push's produce is the ORIGIN side's story;
                # the leader's own cadence is fold + upstream push
                prev = None
            if prev is not None:
                # the inter-send gap minus the worker's PREVIOUS wire
                # transfer (a blocking ack-based push sits inside the
                # gap — without the carve-out a slow wire would
                # double-count into produce and the advisor would tie)
                produce = max(0.0, float(send) - prev
                              - self._last_wire.get(int(w), 0.0))
                hist = self._stage_win.get((int(w), "produce"))
                if hist and len(hist) >= 3:
                    # barrier stalls / restart windows / dropped-push
                    # holes inflate the send gap without the worker
                    # computing: clip at produce_cap_x × the worker's
                    # own rolling median — the excess lands in the
                    # round's barrier residual, never a phantom stage
                    cap = float(self.knobs["produce_cap_x"]) * _med(hist)
                    produce = min(produce, cap)
        return {"produce": produce, "encode": encode, "wire": wire,
                "leader_fold": leader_fold}

    # -- what-if engine ---------------------------------------------------
    @staticmethod
    def _project_round(rec: Dict[str, Any], stage: str, *,
                       frac: Optional[float] = None,
                       floor: Optional[float] = None) -> float:
        """One round's projected duration with ``stage`` virtually sped
        up PER PUSH (Coz virtual speedup: every push's arrival moves,
        then the barrier max is re-taken).  Exactly one of ``frac``
        (proportional: each segment loses ``frac`` of itself) or
        ``floor`` (debottleneck: each segment is pulled down to the
        fleet median, never past it) selects the cut.  Per push, not
        per worker — an async/aggregated publish can compose several
        pushes from ONE worker, and a worker-keyed cut would bill the
        last push's cut to all of them.  Post-barrier time rides the
        constant ``slack`` term, so only the barrier max moves under a
        chain-stage speedup."""
        def _cut(seg: float) -> float:
            c = seg * frac if frac is not None else max(0.0, seg - floor)
            return min(seg, c)

        round_s = float(rec["round_s"])
        if stage in ("root_fold", "opt_publish"):
            st = float(rec["stages"].get(stage) or 0.0)
            return max(0.0, round_s - _cut(st))
        arrivals = []
        for p in rec.get("pushes") or ():
            a = float(p["arrive_s"])
            seg = p["segs"].get(stage)
            if seg is not None:
                a -= _cut(float(seg))
            arrivals.append(max(0.0, a))
        if not arrivals:
            return round_s
        old_gate = max(float(p["arrive_s"]) for p in rec["pushes"])
        # slack = everything in the round that is not the barrier max
        # (post-fold, scheduling) — held constant under the projection
        slack = round_s - old_gate
        return max(0.0, max(arrivals) + slack)

    # -- thread-safe read snapshots ---------------------------------------
    # /health and /metrics scrapes run on the HTTP thread while the
    # serve thread appends rounds and stage samples: every reader below
    # snapshots the shared deques/dict in ONE C-level call first (the
    # same hazard registry.staleness_quantile documents) so an append
    # or a first-key insert can never raise "mutated during iteration"
    # into a 500.
    def _rounds_snapshot(self) -> List[Dict[str, Any]]:
        return list(self._rounds)

    def _stage_vals(self, stage: str,
                    worker: Optional[int] = None) -> List[float]:
        """Flattened duration samples for one stage (optionally one
        worker's) from atomically-snapshotted windows."""
        out: List[float] = []
        for (w, st), win in list(self._stage_win.items()):
            if st == stage and (worker is None or w == worker):
                out.extend(win)  # list(win) implicit: extend is C-level
        return out

    def whatif(self, stage: str, frac: float) -> Dict[str, float]:
        """Virtual speedup: ``stage`` ``frac`` faster for EVERY worker.
        Returns projected total/saved seconds and the saving fraction
        over the retained rounds."""
        if stage not in SPEEDUP_STAGES:
            raise ValueError(f"stage {stage!r} is not speedup-able "
                             f"(one of {SPEEDUP_STAGES})")
        total = saved = 0.0
        for rec in self._rounds_snapshot():
            round_s = float(rec["round_s"])
            new_s = self._project_round(rec, stage, frac=float(frac))
            total += round_s
            saved += max(0.0, round_s - new_s)
        return {"stage": stage, "frac": float(frac),
                "total_s": round(total, 6), "saved_s": round(saved, 6),
                "saving_frac": round(saved / total, 6) if total > 0 else 0.0}

    def debottleneck(self, stage: str) -> Dict[str, float]:
        """The "what if this stage were typical" projection: every
        worker's ``stage`` pulled down to the fleet median for that
        stage (never sped past it).  This is the number the what-if
        smoke validates against a measured A/B: removing one worker's
        injected wire delay is exactly a debottleneck of the wire
        stage."""
        if stage not in SPEEDUP_STAGES:
            raise ValueError(f"stage {stage!r} is not speedup-able")
        med = _med(self._stage_vals(stage))
        total = saved = 0.0
        for rec in self._rounds_snapshot():
            round_s = float(rec["round_s"])
            new_s = self._project_round(rec, stage, floor=med)
            total += round_s
            saved += max(0.0, round_s - new_s)
        return {"stage": stage, "fleet_p50_s": round(med, 6),
                "total_s": round(total, 6), "saved_s": round(saved, 6),
                "saving_frac": round(saved / total, 6) if total > 0 else 0.0}

    def advisor(self) -> List[Dict[str, Any]]:
        """The ranked what-if table: one row per speedup-able stage with
        its critical-path share, per-speedup projections, and the
        debottleneck saving — ranked by debottleneck saving (the
        actionable number), then by the 20% projection.  Cached per
        decomposed-round count like :meth:`_whatif20`: ``/health``
        calls this via :meth:`snapshot` per scrape, and replaying the
        retained window ~24× (6 stages × 4 projections) between rounds
        would burn HTTP-thread CPU recomputing identical tables."""
        cached = self.__dict__.get("_advisor_cache")
        if cached is not None and cached[0] == self.rounds:
            return cached[1]
        rows = []
        rounds = max(1, self.rounds)
        for stage in SPEEDUP_STAGES:
            fleet = self._stage_vals(stage)
            if not fleet and not self.critical.get(stage):
                continue
            row: Dict[str, Any] = {
                "stage": stage,
                "critical_rounds": int(self.critical.get(stage, 0)),
                "critical_share": round(
                    self.critical.get(stage, 0) / rounds, 4),
                "p50_ms": round(1e3 * _med(fleet), 3) if fleet else None,
                "p95_ms": (round(1e3 * _p(fleet, 0.95), 3)
                           if fleet else None),
                "debottleneck": self.debottleneck(stage),
            }
            for f in WHATIF_FRACS:
                row[f"whatif_{int(f * 100)}"] = self.whatif(stage, f)
            rows.append(row)
        rows.sort(key=lambda r: (-r["debottleneck"]["saving_frac"],
                                 -r["whatif_20"]["saving_frac"],
                                 r["stage"]))
        self.__dict__["_advisor_cache"] = (self.rounds, rows)
        return rows

    # -- controller estimator ---------------------------------------------
    def regime_estimate(self) -> Optional[Dict[str, float]]:
        """The lineage-derived wire-vs-compute balance: fleet MEDIAN of
        per-worker wire/produce medians over the measured stage windows
        (median-of-medians — one skewed or delayed worker cannot drag
        the fleet's regime, the same robustness argument as the beacon
        path it replaces).  None until ``min_rounds`` rounds have been
        decomposed — the controller falls back to beacon medians."""
        if self.rounds < int(self.knobs["min_rounds"]):
            return None
        wires, computes = [], []
        for w in range(self.num_workers):
            wWin = self._stage_vals("wire", worker=w)
            pWin = self._stage_vals("produce", worker=w)
            if wWin:
                wires.append(_med(wWin))
            if pWin:
                computes.append(_med(pWin))
        if not wires or not computes:
            # BOTH sides or nothing: a tree root only sees composed
            # hops (produce is the origin side's story, never filled
            # here), so a wire-only estimate would read as wire_frac
            # 1.0 and drive the codec rule to maximum compression on a
            # fleet whose compute it cannot see — fall back to beacons
            return None
        return {"wire_s": _med(wires),
                "compute_s": _med(computes),
                "n": float(self.rounds)}

    # -- surfaces ---------------------------------------------------------
    def wire_share(self) -> float:
        """Fraction of decomposed rounds gated by the wire stage."""
        return (self.critical.get("wire", 0) / self.rounds
                if self.rounds else 0.0)

    def _whatif20(self, stage: str) -> float:
        """``whatif(stage, 0.2)["saving_frac"]``, cached per decomposed-
        round count: the canonical metrics dict is built at TSDB tick
        cadence (~5 Hz) and scrape collectors run per scrape — replaying
        the retained window that often would bill real serve/HTTP-thread
        time for numbers that only change per round."""
        cache = self.__dict__.setdefault("_whatif20_cache", {})
        hit = cache.get(stage)
        if hit is not None and hit[0] == self.rounds:
            return hit[1]
        v = self.whatif(stage, 0.2)["saving_frac"]
        cache[stage] = (self.rounds, v)
        return v

    def top_saving_frac(self) -> float:
        """The advisor's best projected saving at the canonical 20%
        virtual speedup — the headline "what would speeding something
        up buy" gauge (round-cached, see :meth:`_whatif20`)."""
        best = 0.0
        for stage in SPEEDUP_STAGES:
            if not self.critical.get(stage):
                continue
            best = max(best, self._whatif20(stage))
        return best

    def snapshot(self) -> Dict[str, Any]:
        """The anatomy section of ``/health`` and the serve metrics —
        pure reads over the bounded windows."""
        rounds = max(1, self.rounds)
        return {
            "armed": True,
            "rounds": self.rounds,
            "publishes": self.publishes,
            "critical_path": [
                {"stage": s, "rounds": n,
                 "share": round(n / rounds, 4)}
                for s, n in sorted(list(self.critical.items()),
                                   key=lambda kv: -kv[1])
            ],
            "stages": {
                s: {"p50_ms": round(1e3 * _med(vals), 3),
                    "p95_ms": round(1e3 * _p(vals, 0.95), 3)}
                for s, vals in ((s, self._stage_vals(s))
                                for s in STAGES)
                if vals
            },
            "clock_offsets": {
                int(w): (None if e.offset() is None
                         else round(e.offset(), 6))
                for w, e in sorted(list(self._env.items()))
            },
            "advisor": self.advisor()[:4],
            "regime": self.regime_estimate(),
            "overhead_s": round(self.overhead_s, 6),
        }

    def register(self, registry) -> None:
        """Scrape instruments: the canonical-key twins plus per-stage
        labeled gauges (share / p50 / 20%-what-if saving per stage)."""

        def collect(r) -> None:
            r.counter(
                "ps_anatomy_rounds_total",
                "rounds decomposed into exact critical paths",
            ).set(float(self.rounds))
            r.gauge(
                "ps_anatomy_wire_share",
                "fraction of decomposed rounds whose critical path is "
                "the wire stage",
            ).set(self.wire_share())
            r.gauge(
                "ps_anatomy_top_saving_frac",
                "best projected round-time saving at a 20% virtual "
                "stage speedup (Coz-style what-if)",
            ).set(self.top_saving_frac())
            rounds = max(1, self.rounds)
            for stage in STAGES:
                vals = self._stage_vals(stage)
                share = self.critical.get(stage, 0) / rounds
                r.gauge("ps_anatomy_stage_share",
                        "critical-path share per stage",
                        labels={"stage": stage}).set(share)
                if vals:
                    r.gauge("ps_anatomy_stage_p50_ms",
                            "per-stage duration p50 (ms)",
                            labels={"stage": stage}).set(
                                1e3 * _med(vals))
                if stage in SPEEDUP_STAGES and self.rounds:
                    r.gauge("ps_anatomy_whatif_saving_frac",
                            "projected round-time saving fraction at a "
                            "20% virtual speedup of this stage",
                            labels={"stage": stage}).set(
                                self._whatif20(stage))

        registry.add_collector(collect)

    # -- disk -------------------------------------------------------------
    def _write_row(self, row: Dict[str, Any]) -> None:
        if not self.dir:
            return
        if self._f is None:
            os.makedirs(self.dir, exist_ok=True)
            self._f = open(anatomy_path(self.dir, self.name), "a")
        self._f.write(json.dumps(row) + "\n")
        self._rows_since_flush += 1
        if self._rows_since_flush >= int(self.knobs["flush_every"]):
            self._f.flush()
            self._rows_since_flush = 0

    def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            f.flush()
            f.close()


# ---------------------------------------------------------------------------
# offline reconstruction (report sections, smokes, tests)
# ---------------------------------------------------------------------------

def load_anatomy_rows(path: str) -> List[Dict[str, Any]]:
    """``anatomy-*.jsonl`` → row list (torn trailing lines skipped)."""
    rows: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    return rows


def anatomy_from_round_rows(round_rows: Iterable[Dict[str, Any]],
                            num_workers: Optional[int] = None,
                            **overrides: Any) -> RoundAnatomy:
    """Rebuild a :class:`RoundAnatomy` from its OWN persisted
    ``anatomy-*.jsonl`` round rows (the report's preferred source: the
    live engine already decomposed them).  Owned here — beside
    :meth:`RoundAnatomy._observe`, which populates the same windows
    live — so the offline and live state can never desynchronize."""
    rows = sorted((r for r in round_rows
                   if isinstance(r, dict) and r.get("kind") == "round"),
                  key=lambda r: float(r.get("t", 0.0)))
    if num_workers is None:
        ws = [int(p.get("worker", 0)) for r in rows
              for p in (r.get("pushes") or ())]
        num_workers = (max(ws) + 1) if ws else 1
    eng = RoundAnatomy(num_workers=num_workers, **overrides)
    cap = int(eng.knobs["stage_window"])
    for r in rows:
        eng._rounds.append(r)
        eng.rounds += 1
        eng.publishes += 1
        stage = r.get("stage", "barrier")
        eng.critical[stage] = eng.critical.get(stage, 0) + 1
        for p in r.get("pushes") or ():
            for st, v in (p.get("segs") or {}).items():
                if v is None:
                    continue
                eng._stage_win.setdefault(
                    (int(p.get("worker", -1)), st),
                    deque(maxlen=cap)).append(float(v))
        gw = int(r.get("gating_worker", -1))
        for st in ("root_fold", "opt_publish"):
            v = (r.get("stages") or {}).get(st)
            if v is not None:
                eng._stage_win.setdefault(
                    (gw, st), deque(maxlen=cap)).append(float(v))
    return eng


def anatomy_from_rows(lineage_rows: Iterable[Dict[str, Any]],
                      num_workers: Optional[int] = None,
                      **overrides: Any) -> RoundAnatomy:
    """Rebuild a :class:`RoundAnatomy` offline from persisted lineage
    rows (server ``publish``/``drop`` rows + leader ``hop`` rows mixed
    freely — they are split here).  Rows are replayed in time order, so
    the offline engine decomposes the same rounds the live one did —
    the determinism the offline advisor and the tests lean on."""
    rows = sorted((r for r in lineage_rows if isinstance(r, dict)),
                  key=lambda r: float(r.get("t", 0.0)))
    if num_workers is None:
        ws = [int(p.get("worker", 0))
              for r in rows if r.get("kind") == "publish"
              for p in (r.get("pushes") or ())]
        for r in rows:
            if r.get("kind") == "hop":
                ws.extend(int(e.get("worker", 0))
                          for e in (r.get("composed") or ()))
        num_workers = (max(ws) + 1) if ws else 1
    eng = RoundAnatomy(num_workers=num_workers, **overrides)
    for r in rows:
        kind = r.get("kind")
        if kind == "hop":
            eng.observe_hop(r)
        elif kind == "publish":
            eng.observe_publish(r)
    return eng
