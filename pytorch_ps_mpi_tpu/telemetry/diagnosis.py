"""Online health diagnosis: per-worker verdicts from live telemetry.

PR 1 gave the stack raw streams (FlightRecorder spans, the canonical
server metrics) and PR 3 gave it failure counters (rejected frames,
retries, respawns) — but nothing turned those into *answers*. This
module is that layer: a :class:`HealthMonitor` that runs INSIDE the
serve loop (fed from the same thread via the PR 3 ``on_tick`` hook and
the per-gradient consume site — no new thread ever touches a native
transport handle) and continuously derives, per worker:

- **push-latency and staleness EWMAs** with **median+MAD anomaly
  flags** (robust to the scheduler spikes a mean/stddev gate trips on);
- **straggler attribution**: ``compute-bound`` vs ``wire-bound`` vs
  ``reconnect-churning``, using the span timings the worker loop
  already measures (shipped as tiny per-step *beacon* JSONL rows into
  ``cfg["health_dir"]`` — the worker-process half of the recorder
  story, readable online instead of only at exit) plus the PR 3
  retry/reconnect counters and the server-side frame-rejection counts;
- **round critical-path analysis** for ``sync_barrier`` mode: which
  worker gated each round (last to become ready) and its cumulative
  gating seconds — the per-worker bill for the straggler effect the
  async protocol exists to dodge.

Verdicts surface three ways: the ``/health`` JSON route on the
``/metrics`` HTTP endpoint (both transports — the endpoint lives on
:class:`~pytorch_ps_mpi_tpu.telemetry.registry.PSServerTelemetry` now),
``tools/ps_top.py`` (a live terminal dashboard polling ``/health``),
and scrape-registry instruments (``ps_worker_anomaly_total``,
``ps_round_gating_seconds``, ``ps_worker_health`` — beside the
``ps_staleness_p50/p95/p99`` gauges every server now emits).

Everything here is plain-Python state updated by O(1) calls; the serve
loop pays one None-check per gradient when diagnosis is off, matching
the recorder's zero-cost-when-disabled contract.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: verdict → numeric code for the ``ps_worker_health`` gauge
VERDICT_CODES = {"ok": 0.0, "slow": 1.0, "churning": 2.0, "missing": 3.0,
                 "quarantined": 4.0}


class Ewma:
    """Exponentially weighted moving average; ``None`` until the first
    update (a 0.0 prior would drown early samples)."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.25):
        self.alpha = float(alpha)
        self.value: Optional[float] = None

    def update(self, x: float) -> float:
        v = self.value
        self.value = float(x) if v is None else v + self.alpha * (x - v)
        return self.value


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _percentile(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


class MadWindow:
    """Bounded sample window with a median+MAD anomaly gate.

    A sample is anomalous when it exceeds the window median by more than
    ``k * 1.4826 * MAD`` (1.4826 scales MAD to a normal's sigma) with an
    absolute ``floor`` so a near-zero-variance window (MAD 0 — common
    for integer staleness and for tightly-clocked steps) doesn't flag
    every jitter. Robust: a minority of past anomalies in the window
    shifts the median/MAD far less than it would a mean/stddev."""

    def __init__(self, maxlen: int = 128, k: float = 4.0,
                 floor: float = 0.05, min_samples: int = 5):
        self.win: deque = deque(maxlen=int(maxlen))
        self.k = float(k)
        self.floor = float(floor)
        self.min_samples = int(min_samples)

    def check_and_add(self, x: float) -> bool:
        """True iff ``x`` is anomalous vs the CURRENT window; ``x`` is
        then added either way (bounded window: old anomalies age out)."""
        anomalous = False
        if len(self.win) >= self.min_samples:
            med = _median(list(self.win))
            mad = _median([abs(v - med) for v in self.win])
            anomalous = (x - med) > max(self.k * 1.4826 * mad, self.floor)
        self.win.append(float(x))
        return anomalous

    def stats(self) -> Dict[str, float]:
        xs = list(self.win)
        if not xs:
            return {"p50": 0.0, "p95": 0.0, "n": 0}
        return {"p50": _percentile(xs, 0.50),
                "p95": _percentile(xs, 0.95), "n": len(xs)}


class BeaconWriter:
    """The worker-process half of online diagnosis: one tiny JSONL row
    per step into ``<health_dir>/beacon-<worker>.jsonl`` with the SAME
    durations the recorder spans measure (compute, wire, deliberate
    straggle) plus the PR 3 resilience counters — appended and flushed
    so the server-side monitor can tail it live, unlike the recorder
    dump which only lands at process exit."""

    def __init__(self, health_dir: str, worker: int):
        os.makedirs(health_dir, exist_ok=True)
        self.path = beacon_path(health_dir, worker)
        self.worker = int(worker)
        self._f = open(self.path, "a")

    def step(self, step: int, compute_s: float, wire_s: float,
             straggle_s: float = 0.0, retries: int = 0,
             reconnects: int = 0) -> None:
        self._f.write(json.dumps({
            "worker": self.worker, "step": int(step), "t": time.time(),
            "compute_s": round(float(compute_s), 6),
            "wire_s": round(float(wire_s), 6),
            "straggle_s": round(float(straggle_s), 6),
            "retries": int(retries), "reconnects": int(reconnects),
        }) + "\n")
        self._f.flush()

    def close(self, retries: int = 0, reconnects: int = 0) -> None:
        if self._f is None:
            return
        try:
            self._f.write(json.dumps({
                "worker": self.worker, "done": True, "t": time.time(),
                "retries": int(retries), "reconnects": int(reconnects),
            }) + "\n")
            self._f.flush()
        finally:
            f, self._f = self._f, None
            f.close()


def beacon_path(health_dir: str, worker: int) -> str:
    return os.path.join(health_dir, f"beacon-{int(worker)}.jsonl")


def read_beacon_rows(path: str, offset: int) -> "tuple[List[dict], int]":
    """Incrementally read COMPLETE lines appended past ``offset``;
    returns (rows, new_offset). A partially-written trailing line is
    left for the next call — the tail-follower contract."""
    if not os.path.exists(path):
        return [], offset
    rows: List[dict] = []
    with open(path, "rb") as f:
        f.seek(offset)
        buf = f.read()
    end = buf.rfind(b"\n")
    if end < 0:
        return [], offset
    for line in buf[: end + 1].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rows.append(json.loads(line))
        except ValueError:
            pass  # torn write; the writer flushes whole lines, rare
    return rows, offset + end + 1


class _WorkerState:
    __slots__ = (
        "grads", "last_arrival", "inter_ewma", "inter_win", "wait_ewma",
        "stale_ewma", "stale_win", "stale_last", "anomalies",
        "last_anomaly", "compute_ewma", "wire_ewma", "straggle_total",
        "retries", "reconnects", "steps_beaconed", "done",
        "gated_rounds", "gating_s", "beacon_offset",
    )

    def __init__(self, knobs: Dict[str, Any]):
        self.grads = 0
        self.last_arrival: Optional[float] = None
        self.wait_ewma = Ewma(knobs["ewma_alpha"])
        self.inter_ewma = Ewma(knobs["ewma_alpha"])
        self.inter_win = MadWindow(knobs["window"], knobs["mad_k"],
                                   knobs["mad_floor_s"],
                                   knobs["min_samples"])
        self.stale_ewma = Ewma(knobs["ewma_alpha"])
        self.stale_win = MadWindow(knobs["window"], knobs["mad_k"],
                                   knobs["stale_floor"],
                                   knobs["min_samples"])
        self.stale_last = 0
        self.anomalies = 0
        self.last_anomaly: Optional[Dict[str, Any]] = None
        self.compute_ewma = Ewma(knobs["ewma_alpha"])
        self.wire_ewma = Ewma(knobs["ewma_alpha"])
        self.straggle_total = 0.0
        self.retries = 0
        self.reconnects = 0
        self.steps_beaconed = 0
        self.done = False
        self.gated_rounds = 0
        self.gating_s = 0.0
        self.beacon_offset = 0


#: tuning knobs and their defaults (overridable via ``cfg["health_kw"]``)
DEFAULT_KNOBS: Dict[str, Any] = {
    "window": 128,          # MAD window length (samples)
    "mad_k": 4.0,           # anomaly gate: x - median > k * 1.4826 * MAD
    "mad_floor_s": 0.05,    # absolute latency gate floor (seconds)
    "stale_floor": 2.0,     # absolute staleness gate floor (versions)
    "min_samples": 5,       # window warmup before the gate arms
    "ewma_alpha": 0.25,
    "slow_factor": 4.0,     # EWMA vs fleet-median multiplier for "slow"
    "anomaly_decay_s": 30.0,  # a flagged worker stays "slow" this long
    "churn_threshold": 3,   # retries+reconnects (or rejected frames)
    "missing_after_s": 30.0,
}


class HealthMonitor:
    """Derives per-worker health verdicts from the live streams.

    Feed points (all same-thread with the serve loop):

    - :meth:`observe_grad` at every consumed gradient (worker id,
      staleness, the poll-wait preceding it);
    - :meth:`observe_round` when a ``sync_barrier`` round completes,
      with each participant's first-ready time — critical-path
      attribution;
    - :meth:`tick` at the serve loop's tick cadence — tails the worker
      beacon files in ``cfg["health_dir"]``.

    ``server`` is any PS server carrying the
    :class:`~pytorch_ps_mpi_tpu.telemetry.registry.PSServerTelemetry`
    surface; passing it wires the monitor into the server's scrape
    registry and ``/health`` route. Tests may instead pass
    ``num_workers`` and drive the feed points directly.
    """

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, num_workers: Optional[int] = None, **overrides):
        cfg = cfg or {}
        self.knobs = dict(DEFAULT_KNOBS)
        self.knobs.update(cfg.get("health_kw") or {})
        self.knobs.update(overrides)
        self.server = server
        if num_workers is None:
            if server is None:
                raise ValueError("need a server or num_workers")
            num_workers = int(server.num_workers)
        self.num_workers = int(num_workers)
        self.health_dir = cfg.get("health_dir")
        self._w = [_WorkerState(self.knobs) for _ in range(self.num_workers)]
        self._t0 = time.monotonic()
        self._t0_wall = time.time()
        self.rounds = 0
        if server is not None:
            server.health_monitor = self
            self.register(server.scrape_registry())

    # -- feed points ------------------------------------------------------
    def observe_grad(self, worker: int, staleness: int,
                     wait_s: float = 0.0, now: Optional[float] = None
                     ) -> None:
        if not 0 <= worker < self.num_workers:
            return  # a rogue id is the frame layer's problem, not ours
        t = time.monotonic() if now is None else float(now)
        h = self._w[worker]
        h.grads += 1
        # the idle poll time the server spent waiting before this
        # gradient — the serve loop's straggler-wait, per worker
        h.wait_ewma.update(float(wait_s))
        h.stale_last = int(staleness)
        h.stale_ewma.update(float(staleness))
        if h.stale_win.check_and_add(float(staleness)):
            self._flag(h, worker, "staleness", float(staleness), t)
        if h.last_arrival is not None:
            inter = t - h.last_arrival
            h.inter_ewma.update(inter)
            if h.inter_win.check_and_add(inter):
                self._flag(h, worker, "push_latency", inter, t)
        h.last_arrival = t

    def observe_round(self, ready_at: Dict[int, float],
                      active: List[int]) -> None:
        """Critical path of one completed sync round: the LAST worker to
        become ready gated it; its gating time is how long it kept the
        round open past the second-slowest participant."""
        self.rounds += 1
        times = sorted((t, w) for w, t in ready_at.items() if w in active)
        if len(times) < 2:
            return  # a 1-worker round has no critical path to bill
        gate_s = times[-1][0] - times[-2][0]
        w = times[-1][1]
        self._w[w].gated_rounds += 1
        self._w[w].gating_s += max(0.0, gate_s)

    def tick(self, now: Optional[float] = None) -> None:
        """Tail the worker beacon files (same thread as the serve loop —
        file reads only, no native handles)."""
        if not self.health_dir:
            return
        for wid in range(self.num_workers):
            h = self._w[wid]
            rows, h.beacon_offset = read_beacon_rows(
                beacon_path(self.health_dir, wid), h.beacon_offset)
            for r in rows:
                if r.get("done"):
                    h.done = True
                else:
                    h.steps_beaconed += 1
                    h.compute_ewma.update(float(r.get("compute_s", 0.0)))
                    h.wire_ewma.update(float(r.get("wire_s", 0.0)))
                    h.straggle_total += float(r.get("straggle_s", 0.0))
                # counters are absolute in every row: take the latest
                h.retries = int(r.get("retries", h.retries))
                h.reconnects = int(r.get("reconnects", h.reconnects))

    # -- verdicts ---------------------------------------------------------
    def _flag(self, h: _WorkerState, worker: int, kind: str,
              value: float, now: float) -> None:
        h.anomalies += 1
        h.last_anomaly = {"kind": kind, "value": round(value, 6),
                          "t_mono": now}
        from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

        record_event("diag.anomaly", worker=worker, anomaly=kind,
                     value=value)

    def _frames_rejected(self, worker: int) -> int:
        if self.server is None:
            return 0
        return int(getattr(self.server, "frames_rejected", {}
                           ).get(worker, 0))

    def _verdict(self, worker: int, fleet_inter_med: Optional[float],
                 now: Optional[float] = None
                 ) -> "tuple[str, Optional[str]]":
        h = self._w[worker]
        k = self.knobs
        now = time.monotonic() if now is None else float(now)
        nm = getattr(self.server, "numerics_monitor", None)
        if nm is not None and nm.is_quarantined(worker):
            # numerics outranks everything: a worker emitting NaNs is
            # broken whatever its latency looks like
            return "quarantined", "nonfinite"
        if h.grads == 0 and not h.done:
            if now - self._t0 > k["missing_after_s"]:
                return "missing", None
            return "ok", None  # startup grace (jax import, first compile)
        if (not h.done and h.last_arrival is not None
                and now - h.last_arrival > k["missing_after_s"]):
            return "missing", None
        churn = h.retries + h.reconnects
        if (churn >= k["churn_threshold"]
                or self._frames_rejected(worker) >= k["churn_threshold"]):
            return "churning", "reconnect-churn"
        recent_anomaly = (
            h.last_anomaly is not None
            and now - h.last_anomaly["t_mono"] <= k["anomaly_decay_s"]
        )
        ewma_slow = (
            fleet_inter_med is not None and fleet_inter_med > 0
            and h.inter_ewma.value is not None
            and h.inter_ewma.value > k["slow_factor"] * fleet_inter_med
        )
        if recent_anomaly or ewma_slow:
            return "slow", self._attribution(h)
        return "ok", None

    @staticmethod
    def _attribution(h: _WorkerState) -> str:
        """compute-bound vs wire-bound from the beacon span EWMAs: the
        deliberate straggler sleep counts as compute (it emulates slow
        compute); injected delays, pushes, reads, and retry backoff all
        land in the wire bucket (see the worker loop's accounting)."""
        c, w = h.compute_ewma.value, h.wire_ewma.value
        if c is None and w is None:
            return "unknown"  # no beacons: can't split the step
        return "wire-bound" if (w or 0.0) > (c or 0.0) else "compute-bound"

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/health`` document: fleet rollup + one verdict row per
        worker. Pure reads — safe at scrape time from the HTTP thread.
        ``now`` (monotonic-clock override) lets deterministic tests run
        the verdicts on a synthetic timeline."""
        now = time.monotonic() if now is None else float(now)
        inter_ewmas = [h.inter_ewma.value for h in self._w
                       if h.inter_ewma.value is not None]
        fleet_med = _median(inter_ewmas) if inter_ewmas else None
        nm = getattr(self.server, "numerics_monitor", None)
        # ONE numerics snapshot, indexed per worker below — the verdict
        # section and the per-worker rows can never drift apart
        nsnap = nm.snapshot() if nm is not None else None
        lt = getattr(self.server, "lineage_tracker", None)
        workers = []
        for wid in range(self.num_workers):
            h = self._w[wid]
            verdict, cause = self._verdict(wid, fleet_med, now)
            last_age = (
                None if h.last_arrival is None
                else round(now - h.last_arrival, 3)
            )
            num_row = nsnap["workers"][wid] if nsnap is not None else None
            workers.append({
                "worker": wid,
                "verdict": verdict,
                "cause": cause,
                "done": h.done,
                "grads": h.grads,
                "push_interarrival_s": {
                    "ewma": h.inter_ewma.value,
                    **{k: round(v, 6) if k != "n" else v
                       for k, v in h.inter_win.stats().items()},
                },
                "staleness": {"ewma": h.stale_ewma.value,
                              "last": h.stale_last},
                "anomalies": h.anomalies,
                "last_anomaly": h.last_anomaly,
                "server_wait_ewma_s": h.wait_ewma.value,
                "compute_ewma_s": h.compute_ewma.value,
                "wire_ewma_s": h.wire_ewma.value,
                "steps_beaconed": h.steps_beaconed,
                "straggle_total_s": round(h.straggle_total, 6),
                "retries": h.retries,
                "reconnects": h.reconnects,
                "frames_rejected": self._frames_rejected(wid),
                "last_seen_age_s": last_age,
                "gating": {"rounds": h.gated_rounds,
                           "seconds": round(h.gating_s, 6)},
                "numerics": num_row,
                # exact per-push staleness/e2e from the frame trace IDs
                # (telemetry.lineage) — the measured numbers beside the
                # EWMA estimates above; None when lineage is unarmed
                "lineage": (lt.worker_summary(wid)
                            if lt is not None else None),
            })
        fleet: Dict[str, Any] = {
            "anomaly_total": sum(h.anomalies for h in self._w),
            "rounds": self.rounds,
            "slow_workers": sum(1 for w in workers
                                if w["verdict"] == "slow"),
        }
        if self.server is not None:
            from pytorch_ps_mpi_tpu.telemetry.registry import (
                HEALTH_FLEET_ROLLUP_KEYS,
                ps_server_metrics,
            )

            m = ps_server_metrics(self.server)
            # the rollup subset is IMPORTED from the canonical schema's
            # home (not hand-listed here) so the two can never drift —
            # psanalyze's metrics-surface rule checks it statically too
            fleet.update({k: m[k] for k in HEALTH_FLEET_ROLLUP_KEYS})
        t_wall = time.time()
        out = {
            "armed": True,
            "t_wall": t_wall,
            # canonical sample-ordering fields (this PR's satellite):
            # every /health payload carries ts + uptime_s so the fleet
            # poller can order and age member samples uniformly
            "ts": t_wall,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "n_workers": self.num_workers,
            "fleet": fleet,
            "workers": workers,
        }
        if nsnap is not None:
            # the numerics verdict section: quarantine state, grad-norm
            # trajectory summary, latest codec-fidelity probe, postmortems
            out["numerics"] = nsnap
        if lt is not None:
            # the lineage section: exact e2e/staleness distributions,
            # composition counters, stage-level critical paths
            out["lineage"] = lt.snapshot()
        an = getattr(self.server, "anatomy", None)
        if an is not None:
            # the anatomy section: per-round stage decomposition,
            # critical-path shares, the ranked what-if advisor — the
            # pane ps_top renders and the report tabulates
            out["anatomy"] = an.snapshot()
        ha = getattr(self.server, "hop_anatomy", None)
        if ha is not None:
            # the hop section: leader-pipeline sub-stage occupancy,
            # per-leader busy fractions, the streaming-headroom board
            out["hop"] = ha.snapshot()
        sc = getattr(self.server, "serving_core", None)
        if sc is not None and sc.armed:
            # the serving section: snapshot-ring occupancy, read queue
            # depth, per-tenant read counts, shed/coalesce counters —
            # the read tier's half of the fleet picture
            out["serving"] = sc.serving_snapshot()
        wd = getattr(self.server, "slo_watchdog", None)
        if wd is not None:
            # the slo section: per-rule burn rates, latched breach
            # states, recent verdicts — what the fleet pane rolls up
            out["slo"] = wd.snapshot()
        cl = getattr(self.server, "controller", None)
        if cl is not None:
            # the control section: executed actions, wire epoch, LR
            # weights, eviction/probation state — the verdict→action
            # half of the pane (ps_top renders it as the control pane)
            out["control"] = cl.snapshot()
        db = getattr(self.server, "timeseries_db", None)
        if db is not None:
            out["history"] = db.snapshot()
        return out

    def render_json(self) -> str:
        return json.dumps(self.snapshot())

    # -- scrape registry --------------------------------------------------
    def register(self, registry) -> None:
        """Mirror verdict/anomaly/gating state into scrape instruments —
        the same per-worker-labeled-series discipline as
        ``ps_frames_rejected_total`` (no unlabeled sibling that would
        double PromQL sums)."""

        def collect(r) -> None:
            inter_ewmas = [h.inter_ewma.value for h in self._w
                           if h.inter_ewma.value is not None]
            fleet_med = _median(inter_ewmas) if inter_ewmas else None
            for wid in range(self.num_workers):
                h = self._w[wid]
                lab = {"worker": str(wid)}
                r.counter(
                    "ps_worker_anomaly_total",
                    "push-latency/staleness observations flagged by the "
                    "median+MAD gate", labels=lab).set(float(h.anomalies))
                r.counter(
                    "ps_round_gating_seconds",
                    "cumulative sync-round critical-path time this "
                    "worker gated (last-ready attribution)",
                    labels=lab).set(h.gating_s)
                r.counter(
                    "ps_rounds_gated_total",
                    "sync rounds whose critical path ended on this "
                    "worker", labels=lab).set(float(h.gated_rounds))
                verdict, _ = self._verdict(wid, fleet_med)
                r.gauge(
                    "ps_worker_health",
                    "verdict code: 0 ok, 1 slow, 2 churning, 3 missing",
                    labels=lab).set(VERDICT_CODES[verdict])

        registry.add_collector(collect)
