"""Prometheus-text ``/metrics`` HTTP endpoint (stdlib only).

Serves whatever a render callable returns — typically
``registry.prometheus_text`` — on a daemon thread, so the PS serve loop
is never blocked by a scraper. One scrape is one GET; the registry's
collectors refresh instrument values from live server state at render
time, so there is no per-gradient bookkeeping behind this endpoint.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """``GET /metrics`` → the render callable's text; anything else 404.

    ``port=0`` auto-assigns (read back via ``.port``). ``close()`` shuts
    the listener down; the object is also a context manager.
    """

    def __init__(self, render: Callable[[], str], port: int = 0,
                 host: str = "0.0.0.0"):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("/metrics", ""):
                    self.send_error(404)
                    return
                try:
                    body = outer._render().encode()
                except Exception as e:  # a scrape must never kill serving
                    self.send_error(500, f"{type(e).__name__}: {e}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", _CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not stdout news
                pass

        self._render = render
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name=f"metrics-http:{self.port}",
        )
        self._thread.start()

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
            self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
