"""Prometheus-text ``/metrics`` (+ JSON ``/health``) HTTP endpoint.

Serves whatever a render callable returns — typically
``registry.prometheus_text`` — on a daemon thread, so the PS serve loop
is never blocked by a scraper. One scrape is one GET; the registry's
collectors refresh instrument values from live server state at render
time, so there is no per-gradient bookkeeping behind this endpoint.

Beyond ``/metrics``, the server takes a ``routes`` dict mapping extra
paths to render callables returning ``(body_str, content_type)`` — the
ops side-channel the diagnosis layer uses for its ``/health`` JSON
(:mod:`.diagnosis`). Routes are resolved at REQUEST time, so a route
registered after construction (a health monitor attached mid-run) is
served without restarting the listener. A route callable that accepts
a positional argument receives the parsed query string as a flat dict
(last value wins) — how ``/history?key=...&window=...`` and
``/fleet?force=1`` take parameters without a second dispatch layer.
"""

from __future__ import annotations

import inspect
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple


def _wants_query(fn: Callable) -> bool:
    """True when ``fn`` can take the query dict as its one positional
    argument (bound methods and lambdas alike); resolved ONCE at
    registration, so request dispatch stays a plain call."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            return True
        if p.kind == p.VAR_POSITIONAL:
            return True
    return False

_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """``GET /metrics`` → the render callable's text; ``GET <route>`` →
    that route's ``(body, content_type)``; anything else 404.

    ``port=0`` auto-assigns (read back via ``.port``). ``close()`` shuts
    the listener down; the object is also a context manager.
    """

    def __init__(self, render: Callable[[], str], port: int = 0,
                 host: str = "0.0.0.0",
                 routes: Optional[
                     Dict[str, Callable[[], Tuple[str, str]]]] = None):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path, _, qs = self.path.partition("?")
                path = path.rstrip("/")
                try:
                    if path in ("/metrics", ""):
                        body, ctype = outer._render(), _CONTENT_TYPE
                    elif path in outer.routes:
                        fn, wants_query = outer.routes[path]
                        if wants_query:
                            query = {k: v[-1] for k, v in
                                     urllib.parse.parse_qs(qs).items()}
                            body, ctype = fn(query)
                        else:
                            body, ctype = fn()
                    else:
                        self.send_error(404)
                        return
                    payload = body.encode()
                except Exception as e:  # a scrape must never kill serving
                    self.send_error(500, f"{type(e).__name__}: {e}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):  # scrapes are not stdout news
                pass

        self._render = render
        # path -> (callable, wants_query) — signature resolved once here
        self.routes: Dict[str, Tuple[Callable, bool]] = {}
        for p, fn in (routes or {}).items():
            self.routes[p.rstrip("/") or p] = (fn, _wants_query(fn))
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name=f"metrics-http:{self.port}",
        )
        self._thread.start()

    def add_route(self, path: str,
                  render: Callable[..., Tuple[str, str]]) -> None:
        """Register ``path`` → ``render([query]) -> (body, content_type)``
        on the live listener (request-time dispatch — no restart)."""
        self.routes[path.rstrip("/")] = (render, _wants_query(render))

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
            self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
