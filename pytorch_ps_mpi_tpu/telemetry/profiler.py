"""SamplingProfiler: always-on low-overhead continuous profiling.

The recorder spans (PR 1) time what the code *chose* to instrument;
this module answers "where does the time actually go" without touching
the instrumented paths at all: a daemon thread samples every Python
thread's stack (``sys._current_frames()``) at ~100 Hz and aggregates
the walks into collapsed-stack flamegraph text (Brendan Gregg's
``stack;frames;deepest count`` format — feed ``profile-*.txt`` straight
to ``flamegraph.pl`` or speedscope) plus a top-N self-time table.

Safety and cost:

- the sampler reads **Python frame objects only** — it never touches a
  native transport handle, so it coexists with the serve loop's
  same-thread pump discipline (the sampled threads don't cooperate or
  even know);
- a **hard self-overhead budget**: the wall cost of every sampling pass
  is measured, and when the running overhead fraction exceeds
  ``max_frac`` the sampling interval doubles (down to ``min_hz``) until
  it fits — the profiler throttles itself before it can distort what it
  measures. The achieved rate and overhead ride :meth:`snapshot` and
  the profile header, so a throttled profile is visibly throttled.

Native half: the C++ hot paths (``wirecodec.cpp`` folds, ``tcpps.cpp``
batched ingest) are invisible to a Python stack sampler — they run
inside one opaque ``ctypes`` call. They keep their own cycle counters
(calls / elements / nanoseconds), read through
:func:`native_counters` the same "refresh a plain tuple, never hand the
scrape thread a native handle" way as ``_native_read_stats``, and ride
the profile header + report table.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: tuning knobs and their defaults (overridable via ``cfg["profile_kw"]``)
PROFILER_KNOBS: Dict[str, Any] = {
    "hz": 100.0,        # target sampling rate
    "min_hz": 5.0,      # throttle floor
    "max_frac": 0.02,   # hard self-overhead budget (fraction of wall)
    "max_stack": 48,    # frames kept per sample (deepest first)
    "adjust_every": 64,  # samples between overhead re-checks
}


def profile_path(profile_dir: str, name: str) -> str:
    return os.path.join(profile_dir, f"profile-{name}.txt")


def _frame_label(frame) -> str:
    code = frame.f_code
    return f"{code.co_name} ({os.path.basename(code.co_filename)})"


class SamplingProfiler:
    """Collapsed-stack sampling profiler for the current process.

    ``start()``/``stop()`` bound the capture; ``write()`` lands
    ``profile-<name>.txt`` (header comment lines + collapsed stacks).
    ``threads="all"`` samples every live thread rooted at its thread
    name; pass a thread ident iterable to restrict."""

    def __init__(self, name: str = "server", dir: Optional[str] = None,
                 threads: Any = "all", **overrides: Any):
        self.knobs = dict(PROFILER_KNOBS)
        self.knobs.update(overrides)
        self.name = str(name)
        self.dir = dir
        self._only = (None if threads == "all"
                      else {int(t) for t in threads})
        self.counts: Dict[str, int] = {}
        self.samples = 0
        self.sample_cost_s = 0.0
        self.started_at: Optional[float] = None
        self.stopped_at: Optional[float] = None
        self._interval = 1.0 / float(self.knobs["hz"])
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- capture ----------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self.started_at = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"profiler:{self.name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
        self.stopped_at = time.monotonic()

    def _run(self) -> None:
        me = threading.get_ident()
        max_stack = int(self.knobs["max_stack"])
        adjust_every = int(self.knobs["adjust_every"])
        while not self._stop.is_set():
            t0 = time.perf_counter()
            # self-cost in THREAD CPU time (wall above only paces the
            # loop): a preempted pass on an oversubscribed box costs
            # milliseconds of wall but ~100 us of CPU, and the budget
            # gates what the sampler actually takes from the machine
            c0 = time.thread_time()
            names = {t.ident: t.name for t in threading.enumerate()}
            try:
                frames = sys._current_frames()
            except Exception:
                frames = {}
            for tid, frame in frames.items():
                if tid == me:
                    continue
                if self._only is not None and tid not in self._only:
                    continue
                tname = names.get(tid, f"thread-{tid}")
                if tname.startswith(("metrics-http", "profiler:")):
                    continue  # idle endpoint poll loops are noise
                stack: List[str] = []
                while frame is not None and len(stack) < max_stack:
                    stack.append(_frame_label(frame))
                    frame = frame.f_back
                stack.append(tname)  # root = thread name
                key = ";".join(reversed(stack))
                with self._lock:
                    self.counts[key] = self.counts.get(key, 0) + 1
            self.samples += 1
            self.sample_cost_s += time.thread_time() - c0
            if self.samples % adjust_every == 0:
                self._adjust()
            # sleep whatever is left of the interval (never negative)
            left = self._interval - (time.perf_counter() - t0)
            if left > 0:
                self._stop.wait(left)

    def _adjust(self) -> None:
        """Enforce the self-overhead budget: double the interval while
        the measured fraction is over budget; creep back toward the
        target rate when comfortably under it."""
        frac = self.self_overhead_frac()
        base = 1.0 / float(self.knobs["hz"])
        max_int = 1.0 / float(self.knobs["min_hz"])
        if frac > float(self.knobs["max_frac"]):
            self._interval = min(max_int, self._interval * 2.0)
        elif frac < float(self.knobs["max_frac"]) / 4.0 \
                and self._interval > base:
            self._interval = max(base, self._interval / 2.0)

    # -- readout ----------------------------------------------------------
    def self_overhead_frac(self) -> float:
        t0 = self.started_at
        if t0 is None:
            return 0.0
        end = self.stopped_at if self.stopped_at is not None \
            else time.monotonic()
        wall = max(end - t0, 1e-9)
        return self.sample_cost_s / wall

    def hz_effective(self) -> float:
        t0 = self.started_at
        if t0 is None or not self.samples:
            return 0.0
        end = self.stopped_at if self.stopped_at is not None \
            else time.monotonic()
        return self.samples / max(end - t0, 1e-9)

    def collapsed(self) -> str:
        """The flamegraph text: one ``root;...;leaf count`` line per
        distinct stack, sorted for stable diffs."""
        with self._lock:
            items = sorted(self.counts.items())
        return "\n".join(f"{k} {n}" for k, n in items)

    def top(self, n: int = 15) -> List[Dict[str, Any]]:
        with self._lock:
            counts = dict(self.counts)
        return top_frames(counts, n)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "armed": True,
            "name": self.name,
            "samples": self.samples,
            "stacks": len(self.counts),
            "hz_effective": round(self.hz_effective(), 2),
            "interval_s": round(self._interval, 5),
            "overhead_frac": round(self.self_overhead_frac(), 6),
            "budget_frac": float(self.knobs["max_frac"]),
            "top": self.top(8),
            "native": native_counters(),
        }

    def write(self, path: Optional[str] = None) -> Optional[str]:
        """Land ``profile-<name>.txt``: ``# meta`` + ``# native`` header
        comments, then the collapsed stacks."""
        if path is None:
            if not self.dir:
                return None
            os.makedirs(self.dir, exist_ok=True)
            path = profile_path(self.dir, self.name)
        meta = {k: v for k, v in self.snapshot().items()
                if k not in ("top", "native")}
        with open(path, "w") as f:
            f.write("# meta " + json.dumps(meta) + "\n")
            f.write("# native " + json.dumps(native_counters()) + "\n")
            body = self.collapsed()
            if body:
                f.write(body + "\n")
        return path


# ---------------------------------------------------------------------------
# collapsed-profile files: load / merge (telemetry_report's profile section)
# ---------------------------------------------------------------------------

def load_profile(path: str) -> Tuple[Dict[str, Any], Dict[str, int]]:
    """``profile-*.txt`` → (meta, {stack: count}). Meta merges the
    ``# meta`` and ``# native`` header docs; malformed lines skipped."""
    meta: Dict[str, Any] = {}
    counts: Dict[str, int] = {}
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            if line.startswith("# meta "):
                try:
                    meta.update(json.loads(line[len("# meta "):]))
                except ValueError:
                    pass
                continue
            if line.startswith("# native "):
                try:
                    meta["native"] = json.loads(line[len("# native "):])
                except ValueError:
                    pass
                continue
            if line.startswith("#"):
                continue
            stack, _, n = line.rpartition(" ")
            if not stack:
                continue
            try:
                counts[stack] = counts.get(stack, 0) + int(n)
            except ValueError:
                continue
    return meta, counts


def merge_profiles(paths: List[str]) -> Dict[str, int]:
    merged: Dict[str, int] = {}
    for p in paths:
        for stack, n in load_profile(p)[1].items():
            merged[stack] = merged.get(stack, 0) + n
    return merged


def top_frames(counts: Dict[str, int], n: int = 15
               ) -> List[Dict[str, Any]]:
    """Self-time table from collapsed counts: the LEAF frame of each
    stack is billed its count (self), every frame anywhere on the stack
    is billed cumulative."""
    self_c: Dict[str, int] = {}
    cum_c: Dict[str, int] = {}
    total = 0
    for stack, c in counts.items():
        frames = stack.split(";")
        total += c
        if frames:
            self_c[frames[-1]] = self_c.get(frames[-1], 0) + c
        for fr in set(frames):
            cum_c[fr] = cum_c.get(fr, 0) + c
    rows = [{"frame": fr, "self": c, "cum": cum_c.get(fr, c),
             "self_frac": round(c / total, 4) if total else 0.0}
            for fr, c in self_c.items()]
    rows.sort(key=lambda r: (-r["self"], r["frame"]))
    return rows[:n]


# ---------------------------------------------------------------------------
# native cycle counters (wirecodec folds, tcpps batched ingest)
# ---------------------------------------------------------------------------

def native_counters() -> Dict[str, Any]:
    """Process-global C++ hot-path counters, read from libraries that
    are ALREADY loaded (never triggers a build): ``wc_*`` fold kernels
    and ``tps_*`` epoll pump. Empty dict when nothing native is armed."""
    out: Dict[str, Any] = {}
    try:
        from pytorch_ps_mpi_tpu.utils import native as _wc

        stats = _wc.fold_profile_stats()
        if stats is not None:
            out["wirecodec"] = stats
    except Exception:
        pass
    try:
        from pytorch_ps_mpi_tpu.parallel import tcp as _tcp

        stats = _tcp.native_profile_stats()
        if stats is not None:
            out["tcpps"] = stats
    except Exception:
        pass
    return out
