"""Numerics observability: gradient statistics, codec fidelity, quarantine.

The systems layers (PR 1 telemetry, PR 3 resilience, PR 4 diagnosis) can
say *who* is slow and *which* frames were corrupt — but nothing in the
stack could say whether the numbers themselves were sane: a single
worker emitting NaNs silently poisoned the aggregate, and none of the
lossy codecs reported what they actually do to the gradients they
compress ("On the Utility of Gradient Compression in Distributed
Training Systems" shows those wins evaporate or corrupt convergence
depending on regime — only safe to run when measured online). This
module is the numerics layer, three legs:

- **On-device gradient statistics.** :func:`tree_stats` is one jitted
  program per tree structure returning per-leaf finite sum-of-squares
  and non-finite counts (two tiny vectors fetched per call — no
  per-element host work ever). The sync optimizers fuse the same
  reductions into their lowered step programs (``MPI_PS(numerics=True)``
  → ``grad_norm`` / ``nonfinite_total`` / ``update_ratio`` /
  ``bucket_grad_norms`` in every step's metrics dict); the async serve
  loop calls it per consumed push.
- **Online codec-fidelity probes.** ``Codec.fidelity_probe`` (decode-
  after-encode relative L2 error, cosine similarity, achieved
  bits-per-parameter; ``ErrorFeedback`` adds its residual norm) runs in
  each worker every ``probe_every`` steps on the PRE-encode gradient —
  the only place the true input exists; re-encoding the server's decoded
  gradient would measure ~0 for sign-like codecs — and the rows land in
  ``numerics-<worker>.jsonl`` files the :class:`NumericsMonitor` tails
  at tick cadence (the beacon pattern from :mod:`.diagnosis`).
- **Non-finite quarantine + divergence postmortems.** The monitor
  validates every consumed push BEFORE it can touch the optimizer:
  a non-finite push is counted per worker (through the PR 3
  ``_reject_frame`` machinery when not applied), the worker is
  quarantined after ``quarantine_after`` offenses, and the configured
  ``policy`` decides the frame's fate — ``skip`` (drop it, keep
  serving), ``zero`` (sanitize the non-finite elements, apply the
  rest), or ``abort`` (stop the serve loop cleanly). A NaN or a
  grad-norm spike (``spike_factor``× the fleet EWMA) trips a
  **postmortem capture**: the last-``ring`` step-stats rows, a per-leaf
  snapshot of the offending gradient, and the tail of the flight
  recorder, written as ``postmortem-*.json`` into the telemetry dir for
  ``tools/telemetry_report.py`` to triage.

Metrics surface: ``grad_norm`` / ``nonfinite_total`` / ``update_ratio``
/ ``codec_rel_error`` / ``ef_residual_norm`` join the canonical
``PS_SERVER_METRIC_KEYS`` on both transports, scrape as
``ps_grad_norm`` / ``ps_nonfinite_total`` / ``ps_update_ratio`` /
``ps_codec_rel_error`` / ``ps_ef_residual_norm`` (plus per-worker
``ps_worker_nonfinite_total`` labeled series), and ride ``/health`` as
the ``numerics`` section rendered by ``tools/ps_top.py``.

Zero-cost-when-disabled, like every other telemetry layer: the serve
loop pays one ``None``-check per gradient when numerics is off.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

PyTree = Any

#: tuning knobs and their defaults (overridable via ``cfg["numerics_kw"]``)
NUMERICS_KNOBS: Dict[str, Any] = {
    "policy": "skip",        # non-finite push fate: skip | zero | abort
    "quarantine_after": 1,   # non-finite pushes before the worker is marked
    "spike_factor": 20.0,    # grad_norm > factor * fleet EWMA => postmortem
    "spike_min_samples": 20,  # EWMA warmup before the spike gate arms
    "spike_floor": 1e-6,     # absolute norm below which spikes are noise
    "ring": 64,              # last-k step-stats rows kept for postmortems
    "probe_every": 25,       # worker probe / server trajectory cadence
    "max_postmortems": 4,    # disk-write bound per serve call
    "cooldown_pushes": 50,   # min pushes between two spike postmortems
    "ewma_alpha": 0.25,
    "recorder_tail": 200,    # flight-recorder events embedded per dump
}

POLICIES = ("skip", "zero", "abort")

_jitted = {"stats": None, "sanitize": None, "ratio": None}


def _get_stats_fn():
    """One jitted stats program, traced per tree structure by jit's own
    cache: per-leaf finite sum-of-squares (f32) and non-finite counts
    (i32) — the entire per-push device work of the quarantine leg."""
    if _jitted["stats"] is None:
        import jax
        import jax.numpy as jnp

        def impl(tree):
            leaves = jax.tree.leaves(tree)
            sumsq, nonf = [], []
            for leaf in leaves:
                x = jnp.asarray(leaf).astype(jnp.float32).reshape(-1)
                finite = jnp.isfinite(x)
                sumsq.append(jnp.sum(jnp.square(jnp.where(finite, x, 0.0))))
                nonf.append(jnp.sum(~finite).astype(jnp.int32))
            return jnp.stack(sumsq), jnp.stack(nonf)

        _jitted["stats"] = jax.jit(impl)
    return _jitted["stats"]


def tree_stats(tree: PyTree) -> Tuple[np.ndarray, np.ndarray]:
    """Per-leaf ``(finite_sumsq[f32], nonfinite_count[i32])`` vectors of
    a gradient pytree, computed in one jitted program (empty trees get
    empty vectors). The finite sum-of-squares keeps the norm meaningful
    even on a poisoned gradient — a plain sumsq would be NaN and say
    nothing about the healthy part."""
    import jax

    if not jax.tree.leaves(tree):
        return np.zeros(0, np.float32), np.zeros(0, np.int32)
    s, n = _get_stats_fn()(tree)
    return np.asarray(s), np.asarray(n)


def sanitize_tree(tree: PyTree) -> PyTree:
    """The ``zero`` policy's sanitizer: non-finite elements become 0,
    everything else passes through (one fused ``where`` per leaf)."""
    if _jitted["sanitize"] is None:
        import jax
        import jax.numpy as jnp

        _jitted["sanitize"] = jax.jit(lambda t: jax.tree.map(
            lambda x: jnp.where(jnp.isfinite(x), x,
                                jnp.zeros_like(x)), t))
    import jax

    return jax.tree.map(np.asarray, _jitted["sanitize"](tree))


def update_weight_ratio(old_params: PyTree, new_params: PyTree) -> float:
    """``||new - old|| / ||old||`` over a whole pytree — the
    update-to-weight ratio, the classic divergence early-warning (healthy
    training sits around 1e-3; approaching 1 means the optimizer is
    rewriting the model every step). One jitted program, two scalars
    fetched."""
    if _jitted["ratio"] is None:
        import jax
        import jax.numpy as jnp

        def impl(old, new):
            up = sum(
                jnp.sum(jnp.square(
                    (jnp.asarray(n) - jnp.asarray(o)).astype(jnp.float32)))
                for o, n in zip(jax.tree.leaves(old), jax.tree.leaves(new))
            )
            pn = sum(
                jnp.sum(jnp.square(jnp.asarray(o).astype(jnp.float32)))
                for o in jax.tree.leaves(old)
            )
            return jnp.sqrt(up), jnp.sqrt(pn)

        _jitted["ratio"] = jax.jit(impl)
    up, pn = _jitted["ratio"](old_params, new_params)
    return float(up) / max(float(pn), 1e-30)


def numerics_path(numerics_dir: str, worker) -> str:
    """Per-worker probe trajectory file (``numerics-<worker>.jsonl`` —
    the ``numerics-`` prefix keeps it out of recorder-JSONL merges, like
    ``beacon-``/``faults-``)."""
    return os.path.join(numerics_dir, f"numerics-{worker}.jsonl")


class ProbeWriter:
    """Worker-process half of the codec-fidelity leg: appends one JSONL
    row per probe (rel error, cosine, bits/param, EF residual) into
    ``numerics_path(dir, worker)``, flushed so the server-side monitor
    can tail it live — the :class:`~.diagnosis.BeaconWriter` pattern."""

    def __init__(self, numerics_dir: str, worker):
        os.makedirs(numerics_dir, exist_ok=True)
        self.path = numerics_path(numerics_dir, worker)
        self.worker = worker
        self._f = open(self.path, "a")

    def write(self, step: int, row: Dict[str, Any]) -> None:
        self._f.write(json.dumps({
            "worker": self.worker, "step": int(step), "t": time.time(),
            **{k: (round(v, 8) if isinstance(v, float) else v)
               for k, v in row.items()},
        }) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            f.close()


class _WorkerNumerics:
    __slots__ = ("nonfinite", "nonfinite_elems", "quarantined",
                 "norm_ewma", "last_norm", "probe", "probe_offset")

    def __init__(self):
        self.nonfinite = 0        # non-finite pushes (frames)
        self.nonfinite_elems = 0  # non-finite elements across them
        self.quarantined = False
        self.norm_ewma: Optional[float] = None
        self.last_norm = 0.0
        self.probe: Optional[Dict[str, Any]] = None
        self.probe_offset = 0


class NumericsMonitor:
    """Derives the numerics verdicts for one PS serve call.

    Feed points (all same-thread with the serve loop):

    - :meth:`observe_push` on every consumed gradient BEFORE it is
      applied — returns the action the policy demands (``"apply"`` /
      ``"skip"`` / ``"zero"`` / ``"abort"``) and does all counting,
      quarantine, and postmortem capture;
    - :meth:`observe_update` at probe cadence with the params before and
      after an applied update — the update-to-weight ratio;
    - :meth:`tick` at the serve loop's tick cadence — tails the worker
      probe files in the numerics dir.

    ``server`` is any PS server carrying the
    :class:`~pytorch_ps_mpi_tpu.telemetry.registry.PSServerTelemetry`
    surface; passing it attaches the monitor (``server.numerics_monitor``
    — the canonical-schema and ``/health`` source) and registers the
    scrape instruments. Tests may pass ``num_workers`` and drive the
    feed points directly.
    """

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, num_workers: Optional[int] = None, **overrides):
        cfg = cfg or {}
        self.knobs = dict(NUMERICS_KNOBS)
        self.knobs.update(cfg.get("numerics_kw") or {})
        self.knobs.update(overrides)
        if self.knobs["policy"] not in POLICIES:
            raise ValueError(
                f"numerics policy must be one of {POLICIES}, "
                f"got {self.knobs['policy']!r}"
            )
        # a zero/negative cadence would turn probe modulos into division
        # errors in the workers — clamp once, at the one config seam
        self.knobs["probe_every"] = max(1, int(self.knobs["probe_every"]))
        self.server = server
        if num_workers is None:
            if server is None:
                raise ValueError("need a server or num_workers")
            num_workers = int(server.num_workers)
        self.num_workers = int(num_workers)
        # postmortems + the server-side trajectory rows land here; the
        # worker probe files are tailed from the same place (one dir is
        # the whole numerics surface on disk)
        self.dir = cfg.get("numerics_dir") or cfg.get("telemetry_dir")
        self._w = [_WorkerNumerics() for _ in range(self.num_workers)]
        self.pushes = 0
        self.nonfinite_frames_total = 0
        self.nonfinite_elems_total = 0
        self.readmissions = 0
        self.last_grad_norm = 0.0
        self.norm_ewma: Optional[float] = None
        self._norm_samples = 0
        self.update_ratio: Optional[float] = None
        self.postmortems: List[str] = []
        self.aborted: Optional[Dict[str, Any]] = None
        self._ring: deque = deque(maxlen=int(self.knobs["ring"]))
        self._last_spike_push = -(10 ** 9)
        self._traj_f = None
        if server is not None:
            server.numerics_monitor = self
            self.register(server.scrape_registry())

    # -- feed points ------------------------------------------------------
    def observe_push(self, worker: int, grad: PyTree,
                     applied: int = 0) -> str:
        """Validate one consumed push; returns the action: ``"apply"``
        (healthy), ``"zero"`` (sanitize via :func:`sanitize_tree`, then
        apply), ``"skip"`` (do not apply), ``"abort"`` (stop serving).
        All statistics, quarantine flags, rejection counts, and
        postmortems happen here."""
        if not 0 <= worker < self.num_workers:
            return "apply"  # rogue ids are the frame layer's problem
        self.pushes += 1
        leaf_sumsq, leaf_nonf = tree_stats(grad)
        nonf = int(leaf_nonf.sum())
        gnorm = float(np.sqrt(float(leaf_sumsq.sum())))
        h = self._w[worker]
        h.last_norm = gnorm
        a = self.knobs["ewma_alpha"]
        h.norm_ewma = gnorm if h.norm_ewma is None else (
            h.norm_ewma + a * (gnorm - h.norm_ewma))
        self._ring.append({
            "push": self.pushes, "applied": int(applied),
            "worker": int(worker), "grad_norm": round(gnorm, 8),
            "nonfinite": nonf, "t": time.time(),
        })
        if nonf:
            return self._handle_nonfinite(
                worker, h, nonf, leaf_sumsq, leaf_nonf, grad, applied)
        if h.quarantined and self.knobs["policy"] == "skip":
            # a quarantined worker is untrusted wholesale under the skip
            # policy: its FINITE pushes are dropped too (counted under
            # their own rejection reason), so quarantine actually
            # isolates the worker — and in sync-barrier mode its pushes
            # never pile up in a pending queue the barrier excludes
            if self.server is not None:
                self.server._reject_frame(worker, "quarantined")
            return "skip"
        # healthy push: fleet norm EWMA + the spike gate
        self.last_grad_norm = gnorm
        prev = self.norm_ewma
        self.norm_ewma = gnorm if prev is None else (
            prev + a * (gnorm - prev))
        self._norm_samples += 1
        k = self.knobs
        if (prev is not None
                and self._norm_samples > int(k["spike_min_samples"])
                and gnorm > max(k["spike_factor"] * prev, k["spike_floor"])
                and self.pushes - self._last_spike_push
                >= int(k["cooldown_pushes"])):
            self._last_spike_push = self.pushes
            self._record("numerics.spike", worker=worker, grad_norm=gnorm,
                         ewma=prev)
            self.write_postmortem(
                "norm_spike", worker, grad,
                leaf_sumsq=leaf_sumsq, leaf_nonf=leaf_nonf,
                applied=applied,
                detail={"grad_norm": gnorm, "fleet_ewma": prev,
                        "spike_factor": k["spike_factor"]},
            )
        return "apply"

    def _handle_nonfinite(self, worker: int, h: _WorkerNumerics, nonf: int,
                          leaf_sumsq, leaf_nonf, grad: PyTree,
                          applied: int) -> str:
        k = self.knobs
        h.nonfinite += 1
        h.nonfinite_elems += nonf
        self.nonfinite_frames_total += 1
        self.nonfinite_elems_total += nonf
        first = h.nonfinite == 1
        if h.nonfinite >= int(k["quarantine_after"]):
            h.quarantined = True
        self._record("numerics.nonfinite", worker=worker, elems=nonf,
                     policy=k["policy"])
        policy = k["policy"]
        if policy in ("skip", "abort") and self.server is not None:
            # the PR 3 rejection machinery: a dropped-for-numerics frame
            # is counted per worker exactly like a corrupt one
            self.server._reject_frame(worker, "nonfinite")
        if first or policy == "abort":
            self.write_postmortem(
                "nonfinite", worker, grad,
                leaf_sumsq=leaf_sumsq, leaf_nonf=leaf_nonf,
                applied=applied,
                detail={"nonfinite_elems": nonf, "policy": policy,
                        "worker_nonfinite_pushes": h.nonfinite},
            )
        if policy == "abort":
            self.aborted = {"reason": "nonfinite", "worker": int(worker),
                            "postmortem": (self.postmortems[-1]
                                           if self.postmortems else None)}
            return "abort"
        return "zero" if policy == "zero" else "skip"

    def observe_update(self, old_params: PyTree, new_params: PyTree,
                       applied: int = 0) -> float:
        """Update-to-weight ratio of one applied update (serve calls this
        at probe cadence — the old params are only retained on probe
        steps); also appends the server-side trajectory row."""
        self.update_ratio = update_weight_ratio(old_params, new_params)
        self._trajectory_row(applied)
        return self.update_ratio

    def tick(self) -> None:
        """Tail the worker probe files (file reads only — same contract
        as the diagnosis beacon tail)."""
        if not self.dir:
            return
        from pytorch_ps_mpi_tpu.telemetry.diagnosis import read_beacon_rows

        for wid in range(self.num_workers):
            h = self._w[wid]
            rows, h.probe_offset = read_beacon_rows(
                numerics_path(self.dir, wid), h.probe_offset)
            if rows:
                # a probe taken on a poisoned gradient carries NaN values
                # (Python's json round-trips them, strict parsers don't):
                # sanitize to None so /health stays RFC-valid JSON
                h.probe = {
                    k: (None if isinstance(v, float)
                        and not np.isfinite(v) else v)
                    for k, v in rows[-1].items()
                }

    # -- postmortems ------------------------------------------------------
    def write_postmortem(self, reason: str, worker: int, grad: PyTree,
                         *, leaf_sumsq=None, leaf_nonf=None,
                         applied: int = 0,
                         detail: Optional[Dict[str, Any]] = None
                         ) -> Optional[str]:
        """Capture the divergence context to disk: the last-``ring``
        step-stats rows, a per-leaf snapshot of the offending gradient
        (shape, finite norm, non-finite count, a few leading values of
        the worst leaf), and the tail of the flight recorder. Returns
        the path, or None when unarmed (no dir) or the per-run bound
        (``max_postmortems``) is spent."""
        if not self.dir or len(self.postmortems) >= int(
                self.knobs["max_postmortems"]):
            return None
        import jax

        if leaf_sumsq is None or leaf_nonf is None:
            leaf_sumsq, leaf_nonf = tree_stats(grad)
        leaves = jax.tree.leaves(grad)
        leaf_rows = [
            {"leaf": i, "shape": list(np.shape(l)),
             "finite_norm": round(float(np.sqrt(leaf_sumsq[i])), 8),
             "nonfinite": int(leaf_nonf[i])}
            for i, l in enumerate(leaves)
        ]
        worst = max(range(len(leaves)), default=None,
                    key=lambda i: int(leaf_nonf[i]))
        sample = None
        if worst is not None:
            flat = np.asarray(leaves[worst], np.float32).reshape(-1)
            sample = {"leaf": worst,
                      "values": [float(v) for v in flat[:8]]}
        events = []
        from pytorch_ps_mpi_tpu.telemetry.recorder import get_recorder

        rec = get_recorder()
        if rec is not None:
            events = rec.events()[-int(self.knobs["recorder_tail"]):]
        doc = {
            "kind": "numerics_postmortem",
            "reason": reason,
            "worker": int(worker),
            "applied": int(applied),
            "t_wall": time.time(),
            "policy": self.knobs["policy"],
            "detail": detail or {},
            "step_stats_ring": list(self._ring),
            "offending": {"leaves": leaf_rows, "sample": sample},
            "fleet": {
                "grad_norm_ewma": self.norm_ewma,
                "nonfinite_frames_total": self.nonfinite_frames_total,
                "update_ratio": self.update_ratio,
            },
            "recorder_tail": events,
        }
        lt = getattr(self.server, "lineage_tracker", None)
        if lt is not None:
            # the causal half of the capture (telemetry.lineage): the
            # offending push's trace ID, the offender's recent composed
            # pushes, and the pushes that composed the last published
            # version — "which worker pushes made this version bad"
            # answered from data, not inference
            doc["lineage"] = {
                "offending_push": getattr(self.server, "last_push_meta",
                                          None),
                "offender_recent": lt.recent(8, worker=worker),
                "last_publish": lt.last_publish,
            }
        os.makedirs(self.dir, exist_ok=True)
        import glob as _glob

        # number against the FILES already on disk, not this monitor's
        # list: a supervised restart builds a fresh monitor in the same
        # dir, and restarting at 00 would clobber the pre-crash capture
        n_disk = len(_glob.glob(os.path.join(self.dir, "postmortem-*.json")))
        path = os.path.join(
            self.dir, f"postmortem-{n_disk:02d}-{reason}.json",
        )
        with open(path, "w") as f:
            json.dump(doc, f)
        self.postmortems.append(path)
        self._record("numerics.postmortem", worker=worker, reason=reason,
                     path=path)
        return path

    def _trajectory_row(self, applied: int) -> None:
        """Server-side grad-norm/update-ratio trajectory: one row per
        probe cadence into ``numerics-server.jsonl`` (same dir as the
        worker probe files — ``telemetry_report`` plots them together)."""
        if not self.dir:
            return
        if self._traj_f is None:
            os.makedirs(self.dir, exist_ok=True)
            self._traj_f = open(numerics_path(self.dir, "server"), "a")
        self._traj_f.write(json.dumps({
            "worker": "server", "applied": int(applied), "t": time.time(),
            "grad_norm": round(self.last_grad_norm, 8),
            "grad_norm_ewma": (None if self.norm_ewma is None
                               else round(self.norm_ewma, 8)),
            "update_ratio": (None if self.update_ratio is None
                             else round(self.update_ratio, 10)),
            "nonfinite_total": self.nonfinite_frames_total,
        }) + "\n")
        self._traj_f.flush()

    def close(self) -> None:
        if self._traj_f is not None:
            f, self._traj_f = self._traj_f, None
            f.close()

    @staticmethod
    def _record(name: str, **kw) -> None:
        from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

        record_event(name, **kw)

    def readmit(self, worker: int) -> bool:
        """Probation readmission (the control plane's verdict→action
        loop): clear the worker's quarantine AND its offense count, so
        its next pushes are validated on merit — one fresh non-finite
        push re-quarantines it at ``quarantine_after`` offenses exactly
        like a first offense. Returns False when the worker was not
        quarantined. Counted in ``readmissions`` (the controller's
        probation backoff is what keeps this from flapping)."""
        if not 0 <= worker < self.num_workers:
            return False
        h = self._w[worker]
        if not h.quarantined:
            return False
        h.quarantined = False
        h.nonfinite = 0
        self.readmissions += 1
        self._record("numerics.readmit", worker=worker)
        return True

    # -- read side --------------------------------------------------------
    def is_quarantined(self, worker: int) -> bool:
        return (0 <= worker < self.num_workers
                and self._w[worker].quarantined)

    def worker_nonfinite(self, worker: int) -> int:
        return self._w[worker].nonfinite

    def _latest_probe(self, key: str) -> float:
        """Max of the workers' latest probe values for ``key`` (0.0 when
        no probes landed yet) — the conservative fleet summary the
        gauges export. Non-finite probe values (a probe that landed on a
        poisoned gradient) are excluded rather than poisoning the gauge."""
        vals = []
        for h in self._w:
            if h.probe is None or h.probe.get(key) is None:
                continue
            v = float(h.probe[key])
            if np.isfinite(v):
                vals.append(v)
        return max(vals) if vals else 0.0

    @property
    def codec_rel_error(self) -> float:
        return self._latest_probe("rel_error")

    @property
    def ef_residual_norm(self) -> float:
        return self._latest_probe("ef_residual_norm")

    def snapshot(self) -> Dict[str, Any]:
        """The ``numerics`` section of ``/health`` and of the serve
        call's returned metrics. Pure reads — scrape-safe."""
        workers = []
        for wid in range(self.num_workers):
            h = self._w[wid]
            workers.append({
                "worker": wid,
                "verdict": "quarantined" if h.quarantined else "ok",
                "nonfinite": h.nonfinite,
                "nonfinite_elems": h.nonfinite_elems,
                "grad_norm_ewma": h.norm_ewma,
                "last_grad_norm": h.last_norm,
                "probe": h.probe,
            })
        return {
            "armed": True,
            "policy": self.knobs["policy"],
            "pushes": self.pushes,
            "nonfinite_total": self.nonfinite_frames_total,
            "nonfinite_elems_total": self.nonfinite_elems_total,
            "quarantined": [w["worker"] for w in workers
                            if w["verdict"] == "quarantined"],
            "readmissions": self.readmissions,
            "grad_norm": {"last": self.last_grad_norm,
                          "ewma": self.norm_ewma},
            "update_ratio": self.update_ratio,
            "codec_rel_error": self.codec_rel_error,
            "ef_residual_norm": self.ef_residual_norm,
            "postmortems": list(self.postmortems),
            "aborted": self.aborted,
            "workers": workers,
        }

    # -- scrape registry --------------------------------------------------
    def register(self, registry) -> None:
        """Mirror the numerics state into scrape instruments — unlabeled
        fleet gauges plus the per-worker ``ps_worker_nonfinite_total``
        labeled series (same no-unlabeled-sibling discipline as the
        diagnosis instruments)."""

        def collect(r) -> None:
            r.counter(
                "ps_nonfinite_total",
                "gradient pushes containing NaN/Inf (any worker)",
            ).set(float(self.nonfinite_frames_total))
            r.gauge(
                "ps_grad_norm",
                "L2 norm of the last healthy consumed gradient "
                "(finite elements)",
            ).set(self.last_grad_norm)
            r.gauge(
                "ps_update_ratio",
                "update-to-weight ratio ||dp||/||p|| at the last probe",
            ).set(self.update_ratio or 0.0)
            r.gauge(
                "ps_codec_rel_error",
                "decode-after-encode relative L2 error of the wire codec "
                "(latest worker probe, max over workers)",
            ).set(self.codec_rel_error)
            r.gauge(
                "ps_ef_residual_norm",
                "error-feedback residual-memory norm (latest probe)",
            ).set(self.ef_residual_norm)
            for wid in range(self.num_workers):
                h = self._w[wid]
                lab = {"worker": str(wid)}
                r.counter(
                    "ps_worker_nonfinite_total",
                    "non-finite gradient pushes from this worker",
                    labels=lab).set(float(h.nonfinite))
                r.gauge(
                    "ps_worker_quarantined",
                    "1 when the worker is numerics-quarantined",
                    labels=lab).set(1.0 if h.quarantined else 0.0)

        registry.add_collector(collect)
