"""Unified run-wide telemetry: flight recorder, metrics registry, exports.

The observability layer the reference never had (its only surface was the
wall-clock dict every ``step`` returned, ``ps.py:116-148``) and this repo
previously scattered across per-module shims (``utils/metrics.py``
timers, ``utils/tracing.py`` profiler wrappers, per-server ``metrics()``
dicts). One system, three faces:

- :class:`FlightRecorder` — bounded, thread-safe structured event/span
  log (monotonic timestamps, worker id, step, staleness) with JSONL
  export. A process-global recorder is installed with :func:`configure`;
  every instrumented call site guards on :func:`get_recorder` returning
  ``None``, so a disabled recorder costs one attribute read per step.
- :class:`MetricsRegistry` — counters, gauges, bucketed histograms with
  a Prometheus text rendering; :class:`PSServerTelemetry` gives the shm
  and TCP parameter servers one canonical metric schema, and
  :class:`MetricsHTTPServer` serves it at ``/metrics``.
- :mod:`trace export <.trace_export>` — merges host-side recorder spans
  with ``jax.profiler`` device traces into one Chrome/Perfetto timeline.
- :mod:`diagnosis <.diagnosis>` — the layer that turns the streams into
  ANSWERS: :class:`HealthMonitor` derives per-worker verdicts (EWMA +
  MAD anomaly flags, compute/wire/churn straggler attribution, sync-
  round critical-path gating) served as ``/health`` JSON beside
  ``/metrics`` and rendered live by ``tools/ps_top.py``.
- :mod:`lineage <.lineage>` — the layer that makes the streams CAUSAL:
  every framed gradient push carries a trace ID (worker, step, seq) +
  encode-site timestamp from the v2 frame header; the
  :class:`LineageTracker` bills every published version with the exact
  pushes that composed it, measures exact per-push e2e latency and
  staleness (replacing the PR 4 EWMA estimates), extracts stage-level
  sync-round critical paths, and feeds cross-process clock-skew
  estimation so the merged Chrome trace can draw flow arrows from a
  worker's push span to the server's consume span.
- :mod:`numerics <.numerics>` — the layer that watches the NUMBERS:
  :class:`NumericsMonitor` fuses gradient statistics into the lowered
  step programs (grad norms, NaN/Inf counts, update-to-weight ratio),
  tails online codec-fidelity probes (``Codec.fidelity_probe``),
  quarantines non-finite pushes with a skip/zero/abort policy, and
  writes divergence postmortems.

- :mod:`timeseries <.timeseries>` — the layer that makes the streams
  RETAINED: :class:`MetricsHistory`, a dependency-free in-process TSDB
  (raw + 1 s/10 s/60 s downsampled rings per canonical metric key,
  sampled at the serve loop's tick cadence, persisted with bounded
  retention, served at ``/history``).
- :mod:`profiler <.profiler>` — the layer that watches the TIME:
  :class:`SamplingProfiler`, an always-on ~100 Hz collapsed-stack
  sampler with a hard self-overhead budget, plus the native fold/pump
  cycle counters (``wirecodec``/``tcpps``).
- :mod:`slo <.slo>` — the layer that turns history into ALERTS:
  :class:`SLOWatchdog`, multi-window burn-rate rules over the TSDB with
  bench-derived targets, latched replayable verdicts, and the
  ``ps_slo_*`` scrape instruments.
- :mod:`fleet <.fleet>` — the layer that merges the PANES:
  :class:`FleetMonitor` polls every registered endpoint (sharded
  servers, supervisor generations, the read tier) into one ``/fleet``
  snapshot with summed counters, worst-verdict rollup and per-shard
  skew detection; ``tools/ps_top.py --fleet`` renders it live.

``tools/telemetry_report.py`` turns a recorded JSONL into the per-phase
summary table; ``make telemetry-smoke`` bounds the enabled-recorder
overhead against the disabled path; ``make obs-smoke`` gates the
observability plane end-to-end.
"""

from pytorch_ps_mpi_tpu.telemetry.recorder import (
    FlightRecorder,
    configure,
    disable,
    get_recorder,
    install,
    load_jsonl,
    record_event,
    span,
)
from pytorch_ps_mpi_tpu.telemetry.registry import (
    Counter,
    Gauge,
    HEALTH_FLEET_ROLLUP_KEYS,
    Histogram,
    MetricsRegistry,
    PS_SERVER_METRIC_KEYS,
    PSServerTelemetry,
    ps_server_metrics,
    ps_server_registry,
    staleness_quantile,
)
from pytorch_ps_mpi_tpu.telemetry.http_server import MetricsHTTPServer
from pytorch_ps_mpi_tpu.telemetry.diagnosis import (
    BeaconWriter,
    HealthMonitor,
)
from pytorch_ps_mpi_tpu.telemetry.lineage import (
    LineageTracker,
    clock_offsets_from_rows,
    estimate_clock_offset,
    load_lineage_rows,
    trace_id,
)
from pytorch_ps_mpi_tpu.telemetry.numerics import (
    NumericsMonitor,
    ProbeWriter,
    tree_stats,
    update_weight_ratio,
)
from pytorch_ps_mpi_tpu.telemetry.trace_export import (
    export_chrome_trace,
    merged_trace_events,
)
from pytorch_ps_mpi_tpu.telemetry.timeseries import (
    MetricsHistory,
    history_from_rows,
    load_timeseries_rows,
)
from pytorch_ps_mpi_tpu.telemetry.profiler import (
    SamplingProfiler,
    load_profile,
    merge_profiles,
    top_frames,
)
from pytorch_ps_mpi_tpu.telemetry.slo import (
    SLOWatchdog,
    derive_targets,
)
from pytorch_ps_mpi_tpu.telemetry.fleet import (
    FleetMonitor,
    deregister_endpoint,
    parse_prometheus_text,
    register_endpoint,
)

__all__ = [
    "FlightRecorder",
    "configure",
    "disable",
    "get_recorder",
    "install",
    "load_jsonl",
    "record_event",
    "span",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "HEALTH_FLEET_ROLLUP_KEYS",
    "PS_SERVER_METRIC_KEYS",
    "PSServerTelemetry",
    "ps_server_metrics",
    "ps_server_registry",
    "staleness_quantile",
    "MetricsHTTPServer",
    "BeaconWriter",
    "HealthMonitor",
    "LineageTracker",
    "clock_offsets_from_rows",
    "estimate_clock_offset",
    "load_lineage_rows",
    "trace_id",
    "NumericsMonitor",
    "ProbeWriter",
    "tree_stats",
    "update_weight_ratio",
    "export_chrome_trace",
    "merged_trace_events",
    "MetricsHistory",
    "history_from_rows",
    "load_timeseries_rows",
    "SamplingProfiler",
    "load_profile",
    "merge_profiles",
    "top_frames",
    "SLOWatchdog",
    "derive_targets",
    "FleetMonitor",
    "deregister_endpoint",
    "parse_prometheus_text",
    "register_endpoint",
]
