"""Unified run-wide telemetry: flight recorder, metrics registry, exports.

The observability layer the reference never had (its only surface was the
wall-clock dict every ``step`` returned, ``ps.py:116-148``) and this repo
previously scattered across per-module shims (``utils/metrics.py``
timers, ``utils/tracing.py`` profiler wrappers, per-server ``metrics()``
dicts). One system, three faces:

- :class:`FlightRecorder` — bounded, thread-safe structured event/span
  log (monotonic timestamps, worker id, step, staleness) with JSONL
  export. A process-global recorder is installed with :func:`configure`;
  every instrumented call site guards on :func:`get_recorder` returning
  ``None``, so a disabled recorder costs one attribute read per step.
- :class:`MetricsRegistry` — counters, gauges, bucketed histograms with
  a Prometheus text rendering; :class:`PSServerTelemetry` gives the shm
  and TCP parameter servers one canonical metric schema, and
  :class:`MetricsHTTPServer` serves it at ``/metrics``.
- :mod:`trace export <.trace_export>` — merges host-side recorder spans
  with ``jax.profiler`` device traces into one Chrome/Perfetto timeline.
- :mod:`diagnosis <.diagnosis>` — the layer that turns the streams into
  ANSWERS: :class:`HealthMonitor` derives per-worker verdicts (EWMA +
  MAD anomaly flags, compute/wire/churn straggler attribution, sync-
  round critical-path gating) served as ``/health`` JSON beside
  ``/metrics`` and rendered live by ``tools/ps_top.py``.
- :mod:`lineage <.lineage>` — the layer that makes the streams CAUSAL:
  every framed gradient push carries a trace ID (worker, step, seq) +
  encode-site timestamp from the v2 frame header; the
  :class:`LineageTracker` bills every published version with the exact
  pushes that composed it, measures exact per-push e2e latency and
  staleness (replacing the PR 4 EWMA estimates), extracts stage-level
  sync-round critical paths, and feeds cross-process clock-skew
  estimation so the merged Chrome trace can draw flow arrows from a
  worker's push span to the server's consume span.
- :mod:`numerics <.numerics>` — the layer that watches the NUMBERS:
  :class:`NumericsMonitor` fuses gradient statistics into the lowered
  step programs (grad norms, NaN/Inf counts, update-to-weight ratio),
  tails online codec-fidelity probes (``Codec.fidelity_probe``),
  quarantines non-finite pushes with a skip/zero/abort policy, and
  writes divergence postmortems.

- :mod:`anatomy <.anatomy>` — the layer that makes the streams
  ACTIONABLE: :class:`RoundAnatomy` reconstructs every published
  version's causal DAG from the lineage rows (clock-offset-corrected,
  composed trailers expanding tree hops), extracts the exact per-round
  critical path with stage-level decomposition (produce / encode /
  wire / leader-fold / root-fold / optimizer-publish), and computes
  Coz-style what-if projections ("stage X 20% faster ⇒ round time
  −Y%") — live over the serve loop and offline over persisted rows.
- :mod:`timeseries <.timeseries>` — the layer that makes the streams
  RETAINED: :class:`MetricsHistory`, a dependency-free in-process TSDB
  (raw + 1 s/10 s/60 s downsampled rings per canonical metric key,
  sampled at the serve loop's tick cadence, persisted with bounded
  retention, served at ``/history``).
- :mod:`profiler <.profiler>` — the layer that watches the TIME:
  :class:`SamplingProfiler`, an always-on ~100 Hz collapsed-stack
  sampler with a hard self-overhead budget, plus the native fold/pump
  cycle counters (``wirecodec``/``tcpps``).
- :mod:`slo <.slo>` — the layer that turns history into ALERTS:
  :class:`SLOWatchdog`, multi-window burn-rate rules over the TSDB with
  bench-derived targets, latched replayable verdicts, and the
  ``ps_slo_*`` scrape instruments.
- :mod:`freshness <.freshness>` — the layer that makes the READ path
  causal: FRS1 birth records ride the PSR1 delta stream from root
  publish through every follower hop to the edge reader, and
  :class:`FreshnessTracker` turns them into publish→visible latency
  distributions, the age-of-information gauge, and flow events joined
  to write-path lineage.
- :mod:`hop anatomy <.hop_anatomy>` — the layer that opens the LEADER:
  :class:`HopAnatomy` reconstructs each leader hop round into sub-stage
  intervals (ingest_wait / validate / fold / finalize / encode /
  upstream_push / idle) from bounded native interval rings, computes
  per-leader busy fractions, and projects the streaming-headroom ratio
  — what a pipelined (ingest ⇄ fold ⇄ encode overlapped) hop would buy.
- :mod:`fleet <.fleet>` — the layer that merges the PANES:
  :class:`FleetMonitor` polls every registered endpoint (sharded
  servers, supervisor generations, the read tier) into one ``/fleet``
  snapshot with summed counters, worst-verdict rollup and per-shard
  skew detection; ``tools/ps_top.py --fleet`` renders it live.

``tools/telemetry_report.py`` turns a recorded JSONL into the per-phase
summary table; ``make telemetry-smoke`` bounds the enabled-recorder
overhead against the disabled path; ``make obs-smoke`` gates the
observability plane end-to-end.
"""

from typing import Dict, Optional

#: The ONE registry of JSONL sidecar prefixes written under the
#: telemetry directory.  A "sidecar" is any structured side channel that
#: is NOT a flight-recorder event log (``server.jsonl`` /
#: ``worker-N.jsonl``): its rows have no recorder name/kind, so letting
#: one into the recorder-span merge corrupts the trace and the report.
#: Every observability PR used to patch the exclusion list in TWO
#: hand-maintained places (``tools/telemetry_report.py`` dir mode and
#: ``examples/train_async._export_telemetry``); both now route through
#: this map, and ``tools/psanalyze``'s ``sidecar-registry`` rule makes
#: an UNDECLARED prefix a lint failure instead of a live-run surprise.
#:
#: prefix → report route: the ``tools/telemetry_report.py`` section the
#: file feeds (``None`` = operator-facing raw log with no report
#: section — excluded from report collection entirely).
SIDECAR_PREFIXES: Dict[str, Optional[str]] = {
    "faults-": None,          # injected-fault event logs (resilience)
    "beacon-": None,          # worker health beacons (diagnosis tails)
    "numerics-": "numerics",  # grad-norm trajectories + fidelity probes
    "lineage-": "lineage",    # per-version push compositions + hop rows
    "anatomy-": "anatomy",    # round-anatomy critical-path rows
    "timeseries-": "history",  # retained metric history (TSDB)
    "slo-": "slo",            # SLO verdict events
    "control-": "actions",    # controller action rows
    "freshness-": "freshness",  # publish→edge propagation + delivery rows
    "hop-": "hop",            # leader hop sub-stage occupancy rows
}


def sidecar_prefix(path: str) -> Optional[str]:
    """The declared sidecar prefix of a telemetry-dir ``.jsonl`` file
    name/path, or None for recorder files (``server.jsonl``,
    ``worker-N.jsonl``) and anything else."""
    import os as _os

    base = _os.path.basename(path)
    if not base.endswith(".jsonl"):
        return None
    for p in SIDECAR_PREFIXES:
        if base.startswith(p):
            return p
    return None


def is_sidecar(path: str) -> bool:
    """True when the file must stay OUT of the recorder-span merge."""
    return sidecar_prefix(path) is not None


from pytorch_ps_mpi_tpu.telemetry.recorder import (
    FlightRecorder,
    configure,
    disable,
    get_recorder,
    install,
    load_jsonl,
    record_event,
    span,
)
from pytorch_ps_mpi_tpu.telemetry.registry import (
    Counter,
    Gauge,
    HEALTH_FLEET_ROLLUP_KEYS,
    Histogram,
    MetricsRegistry,
    PS_SERVER_METRIC_KEYS,
    PSServerTelemetry,
    ps_server_metrics,
    ps_server_registry,
    staleness_quantile,
)
from pytorch_ps_mpi_tpu.telemetry.http_server import MetricsHTTPServer
from pytorch_ps_mpi_tpu.telemetry.diagnosis import (
    BeaconWriter,
    HealthMonitor,
)
from pytorch_ps_mpi_tpu.telemetry.lineage import (
    LineageTracker,
    clock_offsets_from_rows,
    estimate_clock_offset,
    load_lineage_rows,
    trace_id,
)
from pytorch_ps_mpi_tpu.telemetry.numerics import (
    NumericsMonitor,
    ProbeWriter,
    tree_stats,
    update_weight_ratio,
)
from pytorch_ps_mpi_tpu.telemetry.trace_export import (
    export_chrome_trace,
    merged_trace_events,
)
from pytorch_ps_mpi_tpu.telemetry.timeseries import (
    MetricsHistory,
    history_from_rows,
    load_timeseries_rows,
)
from pytorch_ps_mpi_tpu.telemetry.profiler import (
    SamplingProfiler,
    load_profile,
    merge_profiles,
    top_frames,
)
from pytorch_ps_mpi_tpu.telemetry.slo import (
    SLOWatchdog,
    derive_targets,
)
from pytorch_ps_mpi_tpu.telemetry.fleet import (
    FleetMonitor,
    deregister_endpoint,
    parse_prometheus_text,
    register_endpoint,
)
from pytorch_ps_mpi_tpu.telemetry.anatomy import (
    RoundAnatomy,
    anatomy_from_round_rows,
    anatomy_from_rows,
    load_anatomy_rows,
)
from pytorch_ps_mpi_tpu.telemetry.freshness import (
    FreshnessTracker,
    freshness_flow_events,
    load_fresh_rows,
)
from pytorch_ps_mpi_tpu.telemetry.hop_anatomy import (
    HopAnatomy,
    hop_anatomy_from_rows,
    hop_trace_events,
    load_hop_rows,
)

__all__ = [
    "SIDECAR_PREFIXES",
    "sidecar_prefix",
    "is_sidecar",
    "RoundAnatomy",
    "anatomy_from_round_rows",
    "anatomy_from_rows",
    "load_anatomy_rows",
    "FlightRecorder",
    "configure",
    "disable",
    "get_recorder",
    "install",
    "load_jsonl",
    "record_event",
    "span",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "HEALTH_FLEET_ROLLUP_KEYS",
    "PS_SERVER_METRIC_KEYS",
    "PSServerTelemetry",
    "ps_server_metrics",
    "ps_server_registry",
    "staleness_quantile",
    "MetricsHTTPServer",
    "BeaconWriter",
    "HealthMonitor",
    "LineageTracker",
    "clock_offsets_from_rows",
    "estimate_clock_offset",
    "load_lineage_rows",
    "trace_id",
    "NumericsMonitor",
    "ProbeWriter",
    "tree_stats",
    "update_weight_ratio",
    "export_chrome_trace",
    "merged_trace_events",
    "MetricsHistory",
    "history_from_rows",
    "load_timeseries_rows",
    "SamplingProfiler",
    "load_profile",
    "merge_profiles",
    "top_frames",
    "SLOWatchdog",
    "derive_targets",
    "FleetMonitor",
    "deregister_endpoint",
    "parse_prometheus_text",
    "register_endpoint",
    "FreshnessTracker",
    "freshness_flow_events",
    "load_fresh_rows",
    "HopAnatomy",
    "hop_anatomy_from_rows",
    "hop_trace_events",
    "load_hop_rows",
]
