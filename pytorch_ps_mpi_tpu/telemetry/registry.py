"""MetricsRegistry: counters, gauges, bucketed histograms, Prometheus text.

One canonical metric schema for every PS server (shm and TCP emit
*identical* keys — enforced by ``tests/test_telemetry.py``), rendered in
the Prometheus text exposition format so a stock scraper reads the TCP
server's ``/metrics`` endpoint (:class:`.http_server.MetricsHTTPServer`)
and the shm server's :meth:`PSServerTelemetry.prometheus_text` scrape
method without translation.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_INF = float("inf")


def _fmt(v: float) -> str:
    """Prometheus float formatting: integers without the trailing .0,
    +Inf spelled the Prometheus way."""
    if v == _INF:
        return "+Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_text(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help, self.labels = name, help, dict(labels or {})
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease by {n}")
        with self._lock:
            self.value += n

    def set(self, v: float) -> None:
        """Mirror an externally-tracked monotonic count (the scrape-time
        collector path: servers keep their own counters, the registry
        reflects them)."""
        with self._lock:
            self.value = float(v)

    def render(self) -> List[str]:
        return [f"{self.name}{_labels_text(self.labels)} {_fmt(self.value)}"]


class Gauge:
    """Point-in-time value."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help, self.labels = name, help, dict(labels or {})
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def render(self) -> List[str]:
        return [f"{self.name}{_labels_text(self.labels)} {_fmt(self.value)}"]


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound; +Inf is implicit)."""

    kind = "histogram"

    def __init__(self, name: str, buckets: Sequence[float], help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.name, self.help, self.labels = name, help, dict(labels or {})
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            i = 0
            while i < len(self.bounds) and v > self.bounds[i]:
                i += 1
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def load(self, value_counts: Dict[Any, int]) -> None:
        """Mirror an externally-kept ``{value: count}`` histogram (e.g. a
        server's ``staleness_seen``) — replaces current contents. Built
        locally and swapped under ONE lock acquisition so concurrent
        scrapes (ThreadingHTTPServer runs collectors per request) can
        never interleave a reset with another scrape's adds. The source
        dict is snapshotted atomically first — it is typically the live
        ``staleness_seen`` the serve thread is inserting into."""
        counts = [0] * (len(self.bounds) + 1)
        total_sum, total_n = 0.0, 0
        for v, n in list(value_counts.items()):
            v, n = float(v), int(n)
            i = 0
            while i < len(self.bounds) and v > self.bounds[i]:
                i += 1
            counts[i] += n
            total_sum += v * n
            total_n += n
        with self._lock:
            self.counts = counts
            self.sum = total_sum
            self.count = total_n

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper bound of the bucket the
        q-quantile observation falls in) — good enough for the report
        table; exact values live in the flight recorder."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return math.nan
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target and c:
                return self.bounds[i] if i < len(self.bounds) else _INF
        return _INF

    def approx_quantile(self, q: float) -> float:
        """Interpolated quantile (Prometheus ``histogram_quantile``
        semantics): observations are assumed uniform within each bucket
        and the q-quantile position is linearly interpolated between the
        bucket's edges — so p95 of a histogram is a value, not just
        "somewhere ≤ bound". Observations in the +Inf overflow bucket
        degrade to the highest finite bound (same clamp Prometheus
        applies). NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            counts, total = list(self.counts), self.count
        if total == 0:
            return math.nan
        target = q * total
        cum = 0
        # the first bucket's lower edge: 0 for the nonneg histograms this
        # registry holds (latencies, staleness), else the bound itself
        lo = 0.0 if self.bounds[0] > 0 else float(self.bounds[0])
        for i, c in enumerate(counts):
            if i >= len(self.bounds):
                return float(self.bounds[-1])  # overflow bucket: clamp
            hi = float(self.bounds[i])
            if cum + c >= target and c:
                frac = (target - cum) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            cum += c
            lo = hi
        return float(self.bounds[-1])

    def render(self) -> List[str]:
        out = []
        cum = 0
        for b, c in zip(self.bounds + [_INF], self.counts):
            cum += c
            labels = dict(self.labels)
            labels["le"] = _fmt(b)
            out.append(f"{self.name}_bucket{_labels_text(labels)} {cum}")
        lt = _labels_text(self.labels)
        out.append(f"{self.name}_sum{lt} {_fmt(self.sum)}")
        out.append(f"{self.name}_count{lt} {self.count}")
        return out


class MetricsRegistry:
    """Named instruments + scrape-time collectors, rendered as Prometheus
    text. ``counter``/``gauge``/``histogram`` are get-or-create (same
    name returns the same instrument; a kind clash raises)."""

    def __init__(self) -> None:
        self._metrics: "Dict[str, Any]" = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, *args, **kw):
        # labeled instruments are distinct series under one metric name
        # (the Prometheus model: ``name{worker="1"}``); the registry key
        # carries the label set so per-worker counters coexist with the
        # unlabeled total
        key = name + _labels_text(kw.get("labels"))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, *args, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "", **kw) -> Counter:
        return self._get_or_create(Counter, name, help, **kw)

    def gauge(self, name: str, help: str = "", **kw) -> Gauge:
        return self._get_or_create(Gauge, name, help, **kw)

    def histogram(self, name: str, buckets: Sequence[float],
                  help: str = "", **kw) -> Histogram:
        return self._get_or_create(Histogram, name, buckets, help, **kw)

    def get(self, name: str):
        return self._metrics.get(name)

    def add_collector(
        self, fn: Callable[["MetricsRegistry"], None]
    ) -> None:
        """Register a scrape-time callback that refreshes instruments
        from external state (server counters) before each render."""
        self._collectors.append(fn)

    def collect(self) -> None:
        for fn in list(self._collectors):
            fn(self)

    def to_dict(self) -> Dict[str, float]:
        """Flat snapshot for tests/JSON: counters+gauges by name,
        histograms as ``name_sum``/``name_count``."""
        self.collect()
        out: Dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lt = _labels_text(m.labels)
            if isinstance(m, Histogram):
                out[f"{m.name}{lt}_sum"] = m.sum
                out[f"{m.name}{lt}_count"] = float(m.count)
            else:
                out[f"{m.name}{lt}"] = m.value
        return out

    def prometheus_text(self) -> str:
        self.collect()
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: (m.name, _labels_text(m.labels)))
        last_name = None
        for m in metrics:
            if m.name != last_name:  # HELP/TYPE once per metric name,
                # however many labeled series it has
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
                last_name = m.name
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Canonical PS-server schema (shm + TCP emit IDENTICAL keys/types)
# ---------------------------------------------------------------------------

#: The canonical ``metrics()`` dict keys every PS server emits, all float
#: (the reference's msg/packaged-bytes accounting, ``ps.py:135-136``,
#: plus the async protocol's staleness drop counter).
PS_SERVER_METRIC_KEYS: Tuple[str, ...] = (
    # sample ordering/aging for the fleet poller (telemetry.fleet): ts
    # is the wall clock at metrics() time, uptime_s the monotonic age of
    # this server PROCESS GENERATION (a supervisor restart resets it —
    # how the poller tells a respawned generation from a stale scrape)
    "ts",
    "uptime_s",
    "grads_received",
    "bytes_received",
    "raw_bytes_per_grad",
    "wire_bytes_per_grad",
    "compression_ratio",
    "stale_drops",
    # flat-bucket wire accounting (bucketing.BucketPlan on the CodecWire):
    # bucket_count == 0 means the per-leaf wire; wire_units_per_push is
    # the number of contiguous payload buffers one gradient push ships
    # (buckets when bucketing, leaves otherwise)
    "bucket_count",
    "wire_units_per_push",
    # self-verifying wire frames (resilience.frames): pushes whose frame
    # failed validation (corruption, config drift, size) — always 0 when
    # frame checking is off
    "frames_rejected",
    # staleness distribution summary (exact weighted quantiles over
    # ``staleness_seen``; the scrape registry mirrors them as the
    # ps_staleness_p* gauges via Histogram.approx_quantile) — the
    # headline numbers of the staleness/convergence tradeoff, 0.0 before
    # any gradient arrives
    "staleness_p50",
    "staleness_p95",
    "staleness_p99",
    # numerics observability (telemetry.numerics.NumericsMonitor): all
    # 0.0 when numerics is unarmed. nonfinite_total counts NaN/Inf
    # PUSHES (frames, not elements); grad_norm is the last healthy
    # consumed gradient's finite L2 norm; update_ratio is ||dp||/||p||
    # at the last probe; codec_rel_error / ef_residual_norm mirror the
    # latest worker-side codec-fidelity probe
    "nonfinite_total",
    "grad_norm",
    "update_ratio",
    "codec_rel_error",
    "ef_residual_norm",
    # gradient lineage (telemetry.lineage.LineageTracker): all 0.0 when
    # lineage is unarmed. lineage_pushes counts pushes billed to a
    # published version; push_e2e_p*_ms are EXACT per-push end-to-end
    # latencies (worker encode -> version published) measured from the
    # v2 frame headers' trace IDs — the measured numbers the PR 4
    # interarrival EWMAs only estimate
    "lineage_pushes",
    "push_e2e_p50_ms",
    "push_e2e_p95_ms",
    # round anatomy (telemetry.anatomy.RoundAnatomy): all 0.0 when
    # anatomy is unarmed. anatomy_rounds counts published versions
    # decomposed into exact stage-level critical paths;
    # anatomy_wire_share is the fraction of those rounds gated by the
    # wire stage (the controller's regime signal, measured not
    # estimated); anatomy_top_saving_frac is the advisor's best
    # projected round-time saving at a 20% Coz-style virtual speedup
    "anatomy_rounds",
    "anatomy_wire_share",
    "anatomy_top_saving_frac",
    # homomorphic aggregation (Codec.aggregate + the CodecWire
    # aggregator): agg_mode is 1.0 while the serve loop folds pushes
    # into a compressed accumulator (0.0 unarmed); decodes_per_publish
    # is decodes over gradient-composed publishes (== 1.0 in aggregation
    # mode, ~world-size on the sync decode-sum path, ALSO 1.0 on the
    # async path where every push publishes — read it WITH agg_mode,
    # 0.0 before any publish); agg_fallbacks counts pushes that took
    # the decode-sum
    # path while cfg["agg"] == "on" explicitly requested aggregation
    "agg_mode",
    "decodes_per_publish",
    "agg_fallbacks",
    # hierarchical aggregation (parallel.tree): worker pushes composed
    # through lineage trailers on every VALID tree-wire frame this
    # server validated (stale-dropped frames included — tree drivers
    # stop on this exact count); 0.0 on a non-tree server
    "tree_composed",
    # parameter-serving read tier (serving.ServingCore): all 0.0 when the
    # read tier is unarmed. reads_total counts read-tier requests served
    # (plus, on TCP, the transport's own native GET_PARAMS worker reads);
    # read_p50/p95_ms are read-tier service times; delta_bytes_saved is
    # payload bytes delta replies avoided vs full snapshots; reads_shed
    # counts admission-control rejections (explicit retry-after replies);
    # coalesce_hits counts delta reads served from an existing encode;
    # reads_not_modified counts version-conditional reads answered with
    # no payload (read tier + the native conditional GET_PARAMS path)
    "reads_total",
    "read_p50_ms",
    "read_p95_ms",
    "delta_bytes_saved",
    "reads_shed",
    "coalesce_hits",
    "reads_not_modified",
    # native read plane + follower tier (serving.native_read /
    # serving.follower): native_read_conns is the reader connections
    # currently open on the C++ epoll tier (0.0 on the Python loop);
    # replica_lag_versions is how many versions this replica trailed its
    # upstream at the last pull (0.0 standalone/current);
    # follower_bytes_relayed counts bytes pulled from upstream and
    # re-served by this follower (0.0 when not following)
    "native_read_conns",
    "replica_lag_versions",
    "follower_bytes_relayed",
    # self-driving control plane (control.Controller): all 0.0 when the
    # controller is unarmed. control_actions counts executed controller
    # actions (codec renegotiations, LR re-weights, evict/readmit,
    # read-tier tuning); control_epoch is the current wire epoch (codec
    # renegotiations since boot — the frame-fingerprint handshake's
    # generation counter); control_evicted is the number of workers
    # currently backoff-evicted from the sync barrier;
    # control_lr_scale_min is the smallest per-worker staleness LR
    # weight in force (1.0 = nobody de-weighted; 0.0 only when unarmed)
    "control_actions",
    "control_epoch",
    "control_evicted",
    "control_lr_scale_min",
    # structural control (the controller's topo rule): all 0.0 when
    # topo_actions is unarmed. topo_actions counts structural actions
    # (group replans/merges, replica scale, shard plans); replicas_live
    # is the read replicas the elastic tier currently runs;
    # group_replans is the tree splits currently in force (a merge
    # decrements — 0.0 means the boot topology)
    "topo_actions",
    "replicas_live",
    "group_replans",
    # read-path freshness plane (telemetry.freshness / serving.core):
    # all 0.0 until a publish stamps an FRS1 birth record.
    # read_fresh_p50_ms / read_fresh_p95_ms are publish→visible-here
    # latency quantiles over the last window of stamped versions (root
    # clock, skew-corrected per hop); serving_age_ms is the wall age of
    # the OLDEST tenant's currently-served version (the age-of-
    # information gauge — grows between publishes, snaps down on each);
    # fresh_hop_count is the deepest hop chain a served trailer carries
    # (0.0 at the root, N at an N-hop edge replica)
    "read_fresh_p50_ms",
    "read_fresh_p95_ms",
    "serving_age_ms",
    "fresh_hop_count",
    # leader hop anatomy (telemetry.hop_anatomy): the leader-pipeline
    # occupancy plane. All neutral (0.0; headroom 1.0) until hop rounds
    # land. hop_busy_frac is the median share of the hop window spent
    # WORKING (validate/fold/finalize/encode/push vs waiting);
    # hop_ingest_wait_ms the median per-round wait for group pushes;
    # hop_stream_headroom_ratio the median serial/overlapped projection
    # (≫1 = a streaming leader hop would pay, ≈1 = pipeline already
    # busy — split instead); hop_ring_drops counts native interval-ring
    # entries surrendered to overflow (bounded rings never block)
    "hop_rounds",
    "hop_busy_frac",
    "hop_ingest_wait_ms",
    "hop_stream_headroom_ratio",
    "hop_serial_ms",
    "hop_ring_drops",
)

#: The canonical-key subset the ``/health`` fleet rollup republishes
#: (``diagnosis.HealthMonitor.snapshot`` imports THIS — the rollup used
#: to hand-list keys inline, the drift class psanalyze's
#: metrics-surface rule now lints against). Must stay a subset of the
#: canonical schema; checked here so a bad edit fails at import, and
#: statically by ``tools/psanalyze``.
HEALTH_FLEET_ROLLUP_KEYS: Tuple[str, ...] = (
    "grads_received",
    "stale_drops",
    "staleness_p50",
    "staleness_p95",
    "staleness_p99",
    "agg_mode",
    "decodes_per_publish",
    "agg_fallbacks",
    "control_actions",
    "control_epoch",
    "native_read_conns",
    "replica_lag_versions",
    "follower_bytes_relayed",
    "hop_busy_frac",
    "hop_stream_headroom_ratio",
)
assert set(HEALTH_FLEET_ROLLUP_KEYS) <= set(PS_SERVER_METRIC_KEYS)


def staleness_quantile(seen: Dict[Any, int], q: float) -> float:
    """Exact weighted q-quantile of a ``{staleness_value: count}`` dict
    (the server's ``staleness_seen``); 0.0 when empty. Snapshots the
    dict in ONE C-level call first — scrapes run on the HTTP thread
    while the serve loop inserts, and a Python-level iteration over the
    live dict would intermittently raise 'changed size during
    iteration' into a 500."""
    items = sorted(seen.items())  # atomic under the GIL (no bytecode)
    total = sum(int(n) for _, n in items)
    if total == 0:
        return 0.0
    target = q * total
    cum = 0
    for v, n in items:
        cum += int(n)
        if cum >= target:
            return float(v)
    return float(items[-1][0])


def ps_server_metrics(server) -> Dict[str, float]:
    """The ONE implementation of the canonical server ``metrics()`` dict
    (both transports call this — the schema cannot fork again)."""
    if server.wire is not None:
        raw = float(server.wire.raw_bytes)
        wire = float(server.wire.wire_bytes)
        plan = getattr(server.wire, "plan", None)
        buckets = float(plan.num_buckets) if plan is not None else 0.0
        units = float(
            plan.num_buckets if plan is not None
            else len(server.wire.shapes)
        )
    else:
        import jax

        from pytorch_ps_mpi_tpu.parallel.dcn import _flat_size

        raw = wire = float(_flat_size(server.template) * 4)
        buckets = 0.0
        # the no-codec wire ships ONE concatenated f32 buffer per push
        units = 1.0 if jax.tree.leaves(server.template) else 0.0
    nm = getattr(server, "numerics_monitor", None)
    lt = getattr(server, "lineage_tracker", None)
    an = getattr(server, "anatomy", None)
    ha = getattr(server, "hop_anatomy", None)
    sc = getattr(server, "serving_core", None)
    cl = getattr(server, "controller", None)
    rm = sc.read_metrics() if (sc is not None and sc.armed) else {}
    # the transport's own worker-read path (TCP GET_PARAMS) counts too:
    # totals and cheap not-modified replies ride the same canonical keys
    nat_total, nat_nm = getattr(server, "_native_read_stats", (0, 0))
    t0_mono = getattr(server, "_t0_mono", None)
    if t0_mono is None:  # fake/test servers: anchor at first metrics()
        t0_mono = server.__dict__.setdefault("_t0_mono", time.monotonic())
    return {
        "ts": time.time(),
        "uptime_s": max(0.0, time.monotonic() - t0_mono),
        "grads_received": float(server.grads_received),
        "bytes_received": float(server.bytes_received),
        "raw_bytes_per_grad": raw,
        "wire_bytes_per_grad": wire,
        "compression_ratio": raw / wire,
        "stale_drops": float(server.stale_drops),
        "bucket_count": buckets,
        "wire_units_per_push": units,
        "frames_rejected": float(getattr(server, "frames_rejected_total", 0)),
        "staleness_p50": staleness_quantile(server.staleness_seen, 0.50),
        "staleness_p95": staleness_quantile(server.staleness_seen, 0.95),
        "staleness_p99": staleness_quantile(server.staleness_seen, 0.99),
        "nonfinite_total": float(
            nm.nonfinite_frames_total if nm is not None else 0.0),
        "grad_norm": float(nm.last_grad_norm if nm is not None else 0.0),
        "update_ratio": float(
            (nm.update_ratio or 0.0) if nm is not None else 0.0),
        "codec_rel_error": float(
            nm.codec_rel_error if nm is not None else 0.0),
        "ef_residual_norm": float(
            nm.ef_residual_norm if nm is not None else 0.0),
        "agg_mode": float(getattr(server, "agg_mode", 0.0)),
        "decodes_per_publish": (
            float(getattr(server, "decodes_done", 0))
            / max(1.0, float(getattr(server, "grad_publishes", 0)))
            if getattr(server, "grad_publishes", 0) else 0.0),
        "agg_fallbacks": float(getattr(server, "agg_fallbacks", 0)),
        "tree_composed": float(getattr(server, "tree_composed", 0)),
        "lineage_pushes": float(lt.composed if lt is not None else 0.0),
        "push_e2e_p50_ms": float(
            lt.e2e_ms_quantile(0.50) if lt is not None else 0.0),
        "push_e2e_p95_ms": float(
            lt.e2e_ms_quantile(0.95) if lt is not None else 0.0),
        "anatomy_rounds": float(an.rounds if an is not None else 0.0),
        "anatomy_wire_share": float(
            an.wire_share() if an is not None else 0.0),
        "anatomy_top_saving_frac": float(
            an.top_saving_frac() if an is not None else 0.0),
        "reads_total": rm.get("reads_total", 0.0) + float(nat_total),
        "read_p50_ms": rm.get("read_p50_ms", 0.0),
        "read_p95_ms": rm.get("read_p95_ms", 0.0),
        "delta_bytes_saved": rm.get("delta_bytes_saved", 0.0),
        "reads_shed": rm.get("reads_shed", 0.0),
        "coalesce_hits": rm.get("coalesce_hits", 0.0),
        "reads_not_modified": (rm.get("reads_not_modified", 0.0)
                               + float(nat_nm)),
        "native_read_conns": rm.get("native_read_conns", 0.0),
        "replica_lag_versions": rm.get("replica_lag_versions", 0.0),
        "follower_bytes_relayed": rm.get("follower_bytes_relayed", 0.0),
        "control_actions": float(
            cl.actions_total if cl is not None else 0.0),
        "control_epoch": float(cl.epoch if cl is not None else 0.0),
        "control_evicted": float(
            len(cl.evicted) if cl is not None else 0.0),
        "control_lr_scale_min": float(
            cl.lr_scale_min() if cl is not None else 0.0),
        "topo_actions": float(
            cl.topo_actions_total if cl is not None else 0.0),
        "replicas_live": float(
            cl.replicas_live if cl is not None else 0.0),
        "group_replans": float(
            cl.group_replans if cl is not None else 0.0),
        "read_fresh_p50_ms": rm.get("read_fresh_p50_ms", 0.0),
        "read_fresh_p95_ms": rm.get("read_fresh_p95_ms", 0.0),
        "serving_age_ms": rm.get("serving_age_ms", 0.0),
        "fresh_hop_count": rm.get("fresh_hop_count", 0.0),
        "hop_rounds": float(ha.rounds if ha is not None else 0.0),
        "hop_busy_frac": float(
            ha.busy_frac() if ha is not None else 0.0),
        "hop_ingest_wait_ms": float(
            ha.ingest_wait_ms() if ha is not None else 0.0),
        "hop_stream_headroom_ratio": float(
            ha.headroom_ratio() if ha is not None else 1.0),
        "hop_serial_ms": float(
            ha.serial_ms() if ha is not None else 0.0),
        "hop_ring_drops": float(
            ha.ring_drops if ha is not None else 0.0),
    }


def ps_server_registry(
    server, registry: Optional[MetricsRegistry] = None
) -> MetricsRegistry:
    """Attach a scrape-time collector mirroring ``server``'s state into
    Prometheus instruments (counters, gauges, and the staleness
    histogram). Scraping reads live server attributes — no hot-path
    bookkeeping is added to the serve loop."""
    reg = registry if registry is not None else MetricsRegistry()
    # per-unit buckets up to the bound, CAPPED: max_staleness can be huge
    # (tests use 10**9 to disable dropping) and bucket count must not
    # scale with it — beyond 32 the bound itself is the one extra edge
    ms = int(server.max_staleness)
    stale_buckets = sorted(set(list(range(min(ms, 32) + 2)) + [ms]))

    def collect(r: MetricsRegistry) -> None:
        m = ps_server_metrics(server)
        # sample ordering/aging for the fleet poller: every scrape is
        # stamped with its wall time + the server generation's uptime
        r.gauge("ps_scrape_ts_seconds",
                "wall-clock timestamp of this scrape").set(m["ts"])
        r.gauge("ps_uptime_seconds",
                "monotonic age of this server generation").set(
                    m["uptime_s"])
        r.counter("ps_grads_received_total",
                  "gradients consumed by the server").set(m["grads_received"])
        r.counter("ps_wire_bytes_received_total",
                  "payload bytes consumed").set(m["bytes_received"])
        r.counter("ps_stale_drops_total",
                  "gradients dropped for exceeding max_staleness").set(
                      m["stale_drops"])
        # per-worker labeled series ONLY (zero-filled for every
        # configured worker): an additional unlabeled total under the
        # same name would double PromQL aggregations like sum(...)
        rej_help = ("self-verifying frames rejected "
                    "(corruption / config drift / size mismatch)")
        rejected = getattr(server, "frames_rejected", {})
        for w in range(int(server.num_workers)):
            r.counter("ps_frames_rejected_total", rej_help,
                      labels={"worker": str(w)}).set(
                          float(rejected.get(w, 0)))
        r.gauge("ps_raw_bytes_per_grad",
                "dense f32 bytes of one gradient").set(m["raw_bytes_per_grad"])
        r.gauge("ps_wire_bytes_per_grad",
                "encoded payload bytes of one gradient").set(
                    m["wire_bytes_per_grad"])
        r.gauge("ps_compression_ratio",
                "raw/wire bytes").set(m["compression_ratio"])
        r.gauge("ps_bucket_count",
                "flat dtype-grouped buckets per gradient push "
                "(0 = per-leaf wire)").set(m["bucket_count"])
        r.gauge("ps_wire_units_per_push",
                "contiguous payload buffers one push ships "
                "(buckets when bucketing, leaves otherwise)").set(
                    m["wire_units_per_push"])
        r.gauge("ps_agg_mode",
                "1 while the serve loop aggregates pushes in the "
                "compressed domain (Codec.aggregate)").set(m["agg_mode"])
        r.gauge("ps_decodes_per_publish",
                "payload decodes per gradient-composed publish (~world "
                "on the sync decode-sum path; 1 under aggregation AND "
                "on the per-push async path — aggregation is armed only "
                "when ps_agg_mode is 1)").set(m["decodes_per_publish"])
        r.counter("ps_agg_fallbacks_total",
                  "pushes consumed via decode-sum while aggregation was "
                  "explicitly requested").set(m["agg_fallbacks"])
        r.counter("ps_tree_composed_total",
                  "worker pushes composed through hierarchical-tree "
                  "lineage trailers on valid frames").set(
                      m["tree_composed"])
        nat_total, nat_nm = getattr(server, "_native_read_stats", (0, 0))
        r.counter("ps_native_reads_total",
                  "transport-level worker snapshot reads (GET_PARAMS)"
                  ).set(float(nat_total))
        r.counter("ps_native_reads_not_modified_total",
                  "transport-level reads answered with the cheap "
                  "not-modified reply").set(float(nat_nm))
        r.gauge("ps_publish_version",
                "latest published snapshot version").set(float(server.version))
        r.gauge("ps_num_workers", "configured worker count").set(
            float(server.num_workers))
        hist = r.histogram("ps_staleness", stale_buckets,
                           "observed gradient staleness (versions)")
        hist.load(server.staleness_seen)
        # quantile GAUGES beside the bucketed histogram: alert rules and
        # the /health snapshot read a number, not a bucket dict
        # (Histogram.approx_quantile — NaN-free: 0.0 before any gradient)
        for q, name in ((0.50, "ps_staleness_p50"),
                        (0.95, "ps_staleness_p95"),
                        (0.99, "ps_staleness_p99")):
            v = hist.approx_quantile(q)
            r.gauge(name,
                    f"observed gradient staleness p{int(q * 100)} "
                    "(interpolated, versions)").set(
                        0.0 if math.isnan(v) else v)

    reg.add_collector(collect)
    return reg


class PSServerTelemetry:
    """Mixin giving a PS server the canonical telemetry surface:
    ``metrics()`` (the canonical dict), ``scrape_registry()`` (a
    :class:`MetricsRegistry` that reads live server state at scrape
    time), ``prometheus_text()`` (the scrape method), and
    :meth:`start_metrics_http` (the ``/metrics`` + ``/health`` HTTP
    endpoint — transport-independent: it renders live Python state on a
    daemon thread and never touches a native transport handle, so the
    shm server serves it as readily as the TCP one). Also the home of
    the frame-rejection accounting both transports share: one
    misconfigured or corrupting worker becomes a counted, per-worker
    rejection stream instead of a server crash."""

    _telemetry_registry: Optional[MetricsRegistry] = None
    #: total self-verifying frames rejected (all workers)
    frames_rejected_total: int = 0
    #: payload decodes performed (per consumed push on the decode-sum
    #: path, ONE per round under homomorphic aggregation) — incremented
    #: by the transports' ``_decode_payload`` and by the serve loop's
    #: round finalize; numerator of ``decodes_per_publish``
    decodes_done: int = 0
    #: gradient-composed publishes (the serve loop's ``_post_update``
    #: site; the initial parameter publish is excluded) — denominator of
    #: ``decodes_per_publish``
    grad_publishes: int = 0
    #: 1.0 while the serve loop's compressed-domain aggregation is armed
    agg_mode: float = 0.0
    #: pushes consumed via decode-sum while ``cfg["agg"] == "on"``
    #: explicitly requested aggregation (auto-fallback visibility)
    agg_fallbacks: int = 0
    #: the attached online-diagnosis monitor (``/health``'s source),
    #: set by ``serve()`` when health is armed — see :mod:`.diagnosis`
    health_monitor: Optional[Any] = None
    #: the attached numerics monitor (grad-norm/NaN/codec-fidelity
    #: source for the canonical schema and ``/health``'s ``numerics``
    #: section), set by ``serve()`` when numerics is armed — see
    #: :mod:`.numerics`
    numerics_monitor: Optional[Any] = None
    #: the attached gradient-lineage tracker (trace-ID consumer — the
    #: exact e2e-latency/staleness source for the canonical schema, fed
    #: by ``resilience.frames.framed_poll``), set by ``serve()`` when
    #: lineage is armed — see :mod:`.lineage`
    lineage_tracker: Optional[Any] = None
    #: the last consumed push's frame-carried lineage meta (worker,
    #: step, seq, staleness, send/recv walls, decode_s), refreshed by
    #: ``framed_poll`` on every successful pop
    last_push_meta: Optional[Dict[str, Any]] = None
    #: the attached round-anatomy engine (exact per-round critical
    #: paths + what-if advisor — the ``anatomy_*`` canonical keys'
    #: source), set by :class:`~pytorch_ps_mpi_tpu.telemetry.anatomy.
    #: RoundAnatomy` when lineage is armed — see :mod:`.anatomy`
    anatomy: Optional[Any] = None
    #: the attached parameter-serving core (snapshot ring + read tier +
    #: the canonical ``reads_*`` metrics source), set by
    #: :class:`~pytorch_ps_mpi_tpu.serving.ServingCore` on construction
    serving_core: Optional[Any] = None
    #: the attached self-driving controller (the ``control_*`` canonical
    #: keys' source and ``/health``'s ``control`` section), set by
    #: :class:`~pytorch_ps_mpi_tpu.control.Controller` — see
    #: :mod:`pytorch_ps_mpi_tpu.control`
    controller: Optional[Any] = None
    #: old-epoch frames consumed during codec-renegotiation transitions
    #: (``server.renegotiate_wire`` keeps the retiring wire accepted —
    #: these frames would have been ``"config"`` rejections without it)
    epoch_old_frames: int = 0
    #: the retained metrics history (``/history``'s source), set by
    #: :meth:`arm_observability` — see :mod:`.timeseries`
    timeseries_db: Optional[Any] = None
    #: the SLO burn-rate watchdog (``/health``'s ``slo`` section + the
    #: ``ps_slo_*`` instruments), set by :meth:`arm_observability`
    slo_watchdog: Optional[Any] = None
    #: the fleet poller (``/fleet``'s source), set by
    #: :meth:`arm_observability` — see :mod:`.fleet`
    fleet_monitor: Optional[Any] = None
    #: the continuous sampling profiler, set (and started) by
    #: :meth:`arm_observability` — see :mod:`.profiler`
    profiler: Optional[Any] = None
    #: the read-path freshness tracker (publish→edge propagation rows +
    #: the age-of-information plane), set by :meth:`arm_observability`
    #: — see :mod:`.freshness`
    freshness_tracker: Optional[Any] = None
    #: the attached leader-hop occupancy profiler (the ``hop_*``
    #: canonical keys' source: per-round sub-stage intervals + the
    #: streaming-headroom projection), set by :meth:`arm_observability`
    #: when ``cfg["hop_anatomy"]`` is armed — see :mod:`.hop_anatomy`
    hop_anatomy: Optional[Any] = None

    @property
    def frames_rejected(self) -> Dict[int, int]:
        """Per-worker rejected-frame counts (lazily created)."""
        return self.__dict__.setdefault("_frames_rejected", {})

    def _reject_frame(self, worker: int, reason: str) -> None:
        d = self.frames_rejected
        d[worker] = d.get(worker, 0) + 1
        self.frames_rejected_total += 1
        from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

        record_event("ps.frame_rejected", worker=worker, reason=reason)

    def metrics(self) -> Dict[str, float]:
        """Canonical wire-observability schema, identical across
        transports (see :data:`PS_SERVER_METRIC_KEYS`)."""
        return ps_server_metrics(self)

    def scrape_registry(self) -> MetricsRegistry:
        if self._telemetry_registry is None:
            self._telemetry_registry = ps_server_registry(self)
        return self._telemetry_registry

    def prometheus_text(self) -> str:
        return self.scrape_registry().prometheus_text()

    def health_json(self) -> str:
        """The ``/health`` body: the attached monitor's verdict snapshot,
        or an explicit not-armed marker — a scraper can always tell
        "diagnosis off" from "fleet empty"."""
        import json

        mon = self.health_monitor
        if mon is None:
            m = ps_server_metrics(self)
            # ts/uptime_s on the monitor-less document too: the fleet
            # poller orders and ages every member's samples uniformly
            doc: Dict[str, Any] = {"armed": False, "workers": [],
                                   "ts": m["ts"],
                                   "uptime_s": round(m["uptime_s"], 3)}
            sc = self.serving_core
            if sc is not None and sc.armed:
                # a read-only / monitor-less server still reports its
                # serving tier: ring occupancy, queue depth, read counts
                doc["serving"] = sc.serving_snapshot()
            if self.slo_watchdog is not None:
                doc["slo"] = self.slo_watchdog.snapshot()
            if self.controller is not None:
                # the monitor-less route still reports the controller:
                # action counts, eviction state, epoch — the pane a
                # fleet poller rolls up
                doc["control"] = self.controller.snapshot()
            if self.anatomy is not None:
                # the monitor-less route still reports the round
                # anatomy: critical-path shares + the what-if advisor
                doc["anatomy"] = self.anatomy.snapshot()
            if self.hop_anatomy is not None:
                # the monitor-less route still reports the hop anatomy:
                # sub-stage occupancy + the streaming-headroom board
                doc["hop"] = self.hop_anatomy.snapshot()
            if self.timeseries_db is not None:
                doc["history"] = self.timeseries_db.snapshot()
            return json.dumps(doc)
        return mon.render_json()

    def history_json(self, query: Optional[Dict[str, Any]] = None
                     ) -> "tuple[str, str]":
        """The ``/history`` body: the TSDB's query reply, or an explicit
        not-armed marker (same discipline as the unarmed ``/health``)."""
        import json

        db = self.timeseries_db
        if db is None:
            return (json.dumps({"armed": False, "key_names": []}),
                    "application/json")
        return db.render_http(query)

    def fleet_json(self, query: Optional[Dict[str, Any]] = None
                   ) -> "tuple[str, str]":
        """The ``/fleet`` body: the fleet poller's merged snapshot, or
        an explicit not-armed marker."""
        import json

        fm = self.fleet_monitor
        if fm is None:
            return (json.dumps({"armed": False, "members": {}}),
                    "application/json")
        return fm.render_http(query)

    def start_metrics_http(self, port: int = 0,
                           host: str = "0.0.0.0") -> int:
        """Serve ``prometheus_text()`` at ``http://host:port/metrics``
        and :meth:`health_json` at ``/health`` on a daemon thread
        (``port=0`` auto-assigns). Returns the bound port; idempotent —
        a second call returns the live endpoint's port. Torn down by
        :meth:`close_metrics_http` (every transport's ``close()`` calls
        it, so a supervisor restart can never leak the socket)."""
        if getattr(self, "_metrics_http", None) is None:
            from pytorch_ps_mpi_tpu.telemetry.http_server import (
                MetricsHTTPServer,
            )

            # the routes read their monitors at REQUEST time: a monitor
            # attached after the listener started is served immediately
            # (/history and /fleet render the explicit not-armed marker
            # until arm_observability attaches their sources)
            self._metrics_http = MetricsHTTPServer(
                self.prometheus_text, port=port, host=host,
                routes={"/health": lambda: (self.health_json(),
                                            "application/json"),
                        "/history": self.history_json,
                        "/fleet": self.fleet_json},
            )
        return self._metrics_http.port

    def close_metrics_http(self) -> None:
        http = getattr(self, "_metrics_http", None)
        self._metrics_http = None
        if http is not None:
            http.close()

    # -- fleet observability plane (timeseries / profiler / SLO / fleet) --
    def arm_observability(self, cfg: Dict[str, Any], *,
                          name: str = "server") -> None:
        """Attach the retained-history plane from the job ``cfg`` — the
        one wiring point every core-based server shares (``serve()``
        through the ServingCore, ``sharded.server_main`` directly):

        - ``cfg["timeseries"]`` / ``timeseries_kw`` — the in-process
          TSDB, sampled by :meth:`observability_tick` on the serve
          thread, persisted into ``timeseries_dir`` (falls back to
          ``telemetry_dir``), served at ``/history``;
        - ``cfg["slo"]`` / ``slo_kw`` — the burn-rate watchdog over that
          TSDB (auto-arms it), verdicts into ``slo-<name>.jsonl`` + the
          flight recorder + ``/health``'s ``slo`` section;
        - ``cfg["profile"]`` / ``profile_dir`` / ``profile_kw`` — the
          continuous sampling profiler, started here, written to
          ``profile-<name>.txt`` by :meth:`close_observability`;
        - ``cfg["fleet"]`` / ``fleet_dir`` / ``fleet_kw`` — the fleet
          poller behind ``/fleet``; with a ``fleet_dir`` and a live
          metrics endpoint this server also REGISTERS itself there
          (name ``cfg["fleet_name"]`` or ``name``), so a supervisor-
          respawned generation rejoins the pane under the same name.
        """
        out_dir = cfg.get("timeseries_dir") or cfg.get("telemetry_dir")
        if (cfg.get("timeseries") or cfg.get("timeseries_kw")
                or cfg.get("slo") or cfg.get("slo_kw")):
            from pytorch_ps_mpi_tpu.telemetry.timeseries import (
                MetricsHistory,
            )

            self.timeseries_db = MetricsHistory(
                dir=out_dir, name=name,
                **(cfg.get("timeseries_kw") or {}))
        if cfg.get("slo") or cfg.get("slo_kw"):
            from pytorch_ps_mpi_tpu.telemetry.slo import SLOWatchdog

            # attaches itself to self.slo_watchdog + scrape registry
            SLOWatchdog(self, cfg, history=self.timeseries_db,
                        name=name, dir=out_dir)
        if cfg.get("freshness") or cfg.get("freshness_kw"):
            from pytorch_ps_mpi_tpu.telemetry.freshness import (
                FreshnessTracker,
            )

            # attaches itself to self.freshness_tracker + scrape
            # registry; freshness_kw overrides come through the cfg
            FreshnessTracker(self, cfg, name=name, dir=out_dir)
        if cfg.get("hop_anatomy") or cfg.get("hop_anatomy_kw"):
            from pytorch_ps_mpi_tpu.telemetry.hop_anatomy import (
                HopAnatomy,
            )

            # attaches itself to self.hop_anatomy + scrape registry;
            # hop_anatomy_kw knob overrides come through the cfg. A
            # tree leader FEEDS it per-round (parallel.tree._hop_push);
            # the root arms it too and replays the leaders' tailed
            # hop-*.jsonl rows into it (the fleet scoreboard)
            HopAnatomy(self, cfg, name=name)
        if cfg.get("profile") or cfg.get("profile_dir"):
            from pytorch_ps_mpi_tpu.telemetry.profiler import (
                SamplingProfiler,
            )

            self.profiler = SamplingProfiler(
                name=name,
                dir=cfg.get("profile_dir") or cfg.get("telemetry_dir"),
                **(cfg.get("profile_kw") or {})).start()
        if cfg.get("fleet") or cfg.get("fleet_dir"):
            from pytorch_ps_mpi_tpu.telemetry import fleet as _fleet

            self.fleet_monitor = _fleet.FleetMonitor(
                endpoints=cfg.get("fleet_endpoints"),
                fleet_dir=cfg.get("fleet_dir"),
                **(cfg.get("fleet_kw") or {}))
            http = getattr(self, "_metrics_http", None)
            if cfg.get("fleet_dir") and http is not None:
                fname = str(cfg.get("fleet_name") or name)
                _fleet.register_endpoint(
                    cfg["fleet_dir"], fname, http.port,
                    role=cfg.get("fleet_role", "server"),
                    # extra card fields (e.g. a tree leader's group id +
                    # member worker ids) ride the registration verbatim
                    **(cfg.get("fleet_meta") or {}))
                self.__dict__["_fleet_registration"] = (
                    cfg["fleet_dir"], fname)

    def observability_tick(self) -> None:
        """Sample the TSDB + evaluate the SLO rules — called from the
        owning loop at tick cadence, same thread as the transport pumps
        (file appends and plain-dict folds only). One attr check when
        nothing is armed."""
        db = self.timeseries_db
        if db is not None:
            db.sample(self.metrics())
            wd = self.slo_watchdog
            if wd is not None:
                wd.evaluate()

    def finalize_observability(self) -> Dict[str, Any]:
        """Flush/stop the observability plane and return the final
        section snapshots + artifact paths. Idempotent (the serve loop
        calls it to collect its metrics sections; ``close()`` calls it
        again as a backstop). The sources — and the fleet registration —
        stay ATTACHED: ``/history`` and ``/fleet`` keep answering, and
        the member keeps its pane card, until the endpoint itself dies
        with ``server.close()``, same lifetime as ``/metrics`` and
        ``/health``."""
        out: Dict[str, Any] = {}
        first = not self.__dict__.get("_obs_closed", False)
        self.__dict__["_obs_closed"] = True
        prof = self.profiler
        if prof is not None:
            prof.stop()
            path = prof.write() if first else None
            out["profile"] = prof.snapshot()
            if path is not None:
                out["profile"]["file"] = path
        db = self.timeseries_db
        if db is not None:
            db.close()  # flush buffered rows; queries keep working
            out["history"] = db.snapshot()
        wd = self.slo_watchdog
        if wd is not None:
            wd.close()
            out["slo"] = wd.snapshot()
        ft = self.freshness_tracker
        if ft is not None:
            ft.close()
            out["freshness"] = ft.snapshot()
        ha = self.hop_anatomy
        if ha is not None:
            ha.close()
            out["hop"] = ha.snapshot()
        return out

    def close_observability(self) -> Dict[str, Any]:
        """:meth:`finalize_observability` + fleet deregistration — the
        transport ``close()`` teardown: the member leaves the pane only
        when the server generation really dies."""
        out = self.finalize_observability()
        reg = self.__dict__.pop("_fleet_registration", None)
        if reg is not None:
            from pytorch_ps_mpi_tpu.telemetry.fleet import (
                deregister_endpoint,
            )

            deregister_endpoint(*reg)
        return out
