"""FlightRecorder: bounded, thread-safe structured event/span log.

Every record is one flat dict (the JSONL row):

``name``       event/span name (``"ps.step"``, ``"worker.push_grad"``)
``kind``       ``"span"`` (has ``dur``) or ``"event"`` (a point)
``ts``         seconds, ``time.monotonic()`` — ordering/duration truth
               within one process
``wall``       seconds, ``time.time()`` — the cross-process alignment
               hint (monotonic epochs differ between processes)
``dur``        span duration in seconds (spans only)
``worker``     worker id (recorder default, overridable per record)
``step``       training/serve step the record belongs to
``staleness``  gradient staleness, when the record is about one gradient
``attrs``      everything else (free-form, JSON-serializable)

The buffer is a ``deque(maxlen=capacity)``: recording never blocks on
I/O and never grows without bound — old records are evicted and counted
in ``dropped`` (surfaced in the JSONL header row so a truncated recording
is never mistaken for a complete one).

A process-global recorder is installed with :func:`configure`; call
sites guard on :func:`get_recorder` returning ``None`` — the disabled
cost is one module attribute read, which is what lets the recorder ride
inside every training mode unconditionally.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

_HEADER_KIND = "recorder_meta"


class FlightRecorder:
    """Bounded thread-safe event/span log with JSONL export."""

    def __init__(self, capacity: int = 65536,
                 worker: Optional[Any] = None) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self.worker = worker
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dropped = 0
        self._t0_monotonic = time.monotonic()
        self._t0_wall = time.time()

    # -- recording --------------------------------------------------------
    def event(
        self,
        name: str,
        *,
        kind: str = "event",
        ts: Optional[float] = None,
        dur: Optional[float] = None,
        step: Optional[int] = None,
        worker: Optional[Any] = None,
        staleness: Optional[int] = None,
        **attrs: Any,
    ) -> None:
        """Append one record. ``ts`` defaults to now (monotonic); pass an
        explicit start time (also ``time.monotonic()``-based) when the
        duration was measured by the caller."""
        now_m = time.monotonic()
        rec: Dict[str, Any] = {
            "name": name,
            "kind": kind,
            "ts": now_m if ts is None else float(ts),
            # wall derived from the same instant so the two clocks in one
            # record always describe the same moment
            "wall": self._t0_wall + ((ts if ts is not None else now_m)
                                     - self._t0_monotonic),
        }
        if dur is not None:
            rec["dur"] = float(dur)
        if step is not None:
            rec["step"] = int(step)
        w = worker if worker is not None else self.worker
        if w is not None:
            rec["worker"] = w
        if staleness is not None:
            rec["staleness"] = int(staleness)
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(rec)

    @contextlib.contextmanager
    def span(self, name: str, *, step: Optional[int] = None,
             worker: Optional[Any] = None, **attrs: Any) -> Iterator[None]:
        """Context manager recording a ``kind="span"`` row on exit with
        the measured duration (exceptions still record the span)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.event(name, kind="span", ts=t0,
                       dur=time.monotonic() - t0, step=step, worker=worker,
                       **attrs)

    # -- reading ----------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    # -- JSONL ------------------------------------------------------------
    def dump_jsonl(self, path: str) -> str:
        """Write the buffer to ``path`` as JSONL: one meta header row
        (kind ``recorder_meta`` — capacity, dropped count, clock epochs)
        then one row per record. Returns ``path``."""
        rows = self.events()
        header = {
            "kind": _HEADER_KIND,
            "capacity": self.capacity,
            "dropped": self.dropped,
            "n_events": len(rows),
            "worker": self.worker,
            "t0_monotonic": self._t0_monotonic,
            "t0_wall": self._t0_wall,
        }
        with open(path, "w") as f:
            f.write(json.dumps(header) + "\n")
            for rec in rows:
                f.write(json.dumps(rec, default=_json_default) + "\n")
        return path


def _json_default(obj: Any) -> Any:
    """Last-resort serializer: numpy scalars/arrays and anything else a
    call site stuffed into attrs degrade to floats/strings, never crash
    the export."""
    try:
        import numpy as np

        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, np.generic):
            return obj.item()
    except Exception:
        pass
    return str(obj)


def load_jsonl(path: str):
    """Read a recorder JSONL back: returns ``(meta, events)`` where
    ``meta`` is the header row (``{}`` for a headerless file) and
    ``events`` the record list — the inverse of
    :meth:`FlightRecorder.dump_jsonl`."""
    meta: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("kind") == _HEADER_KIND and not events and not meta:
                meta = rec
            else:
                events.append(rec)
    return meta, events


# -- process-global recorder ------------------------------------------------

_recorder: Optional[FlightRecorder] = None


def configure(capacity: int = 65536,
              worker: Optional[Any] = None) -> FlightRecorder:
    """Install (and return) the process-global recorder. Call sites all
    over the codebase pick it up via :func:`get_recorder`."""
    global _recorder
    _recorder = FlightRecorder(capacity=capacity, worker=worker)
    return _recorder


def install(recorder: FlightRecorder) -> FlightRecorder:
    """Install an existing recorder as the process-global one — the
    re-enable path (``disable()`` then ``install(rec)`` pauses and
    resumes one buffer without discarding it, unlike ``configure``
    which starts fresh)."""
    global _recorder
    _recorder = recorder
    return recorder


def disable() -> None:
    """Remove the process-global recorder; instrumented paths return to
    their zero-cost guard."""
    global _recorder
    _recorder = None


def get_recorder() -> Optional[FlightRecorder]:
    """The process-global recorder, or None when telemetry is disabled —
    the one branch every instrumented hot path pays."""
    return _recorder


def record_event(name: str, **kw: Any) -> None:
    """Module-level convenience: record on the global recorder, no-op
    when disabled."""
    rec = _recorder
    if rec is not None:
        rec.event(name, **kw)


@contextlib.contextmanager
def span(name: str, **kw: Any) -> Iterator[None]:
    """Module-level span on the global recorder; a plain (cheap) yield
    when disabled."""
    rec = _recorder
    if rec is None:
        yield
    else:
        with rec.span(name, **kw):
            yield
