"""SLO watchdog: multi-window burn-rate rules over the metrics history.

The read-only half of the self-driving control plane (ROADMAP item 4):
every signal a future controller would act on first becomes a measured,
retained, *gated* verdict here. A rule names one canonical metric key,
a target, and how to read the series (``value`` — windowed mean of a
gauge like ``push_e2e_p95_ms`` — or ``rate`` — windowed per-second
delta of a counter like ``stale_drops``/``reads_shed``). Its **burn
rate** is measured/target; the SRE multi-window discipline applies: a
rule breaches only when BOTH the short window (fast detection) and the
long window (flap suppression) burn above the threshold, and the breach
is **latched** — one verdict event when it trips, one recovery event
when both windows drop back under ``recovery_factor``, nothing in
between. An injected straggler therefore trips *exactly one* burn
verdict, not one per tick (``tools/obs_smoke.py`` pins this).

Verdicts are recorded three ways, all replayable (PR 3 determinism
discipline — :meth:`SLOWatchdog.replay` re-derives the identical
verdict sequence from the persisted ``timeseries-*.jsonl`` rows):

- flight-recorder events (``slo.breach`` / ``slo.recover``);
- ``slo-<name>.jsonl`` rows beside the other telemetry side channels
  (routed away from the recorder-span merge like ``lineage-*``);
- the ``slo`` section in ``/health`` and ``/fleet``, plus the
  ``ps_slo_burn_rate{rule=...}`` gauge and ``ps_slo_breaches_total``
  scrape instruments.

Targets come from the committed perf trajectory when one exists:
:func:`derive_targets` reads ``bench_gate``-style
``benchmarks/results/*.jsonl`` rows and ``BENCH_r*.json`` round records
and sets each target at ``median × slack`` — the SLO is "don't regress
past what this repo has measured", the same contract ``bench_gate``
enforces offline, now evaluated live. Explicit
``cfg["slo_kw"]["targets"]`` always wins; :data:`DEFAULT_TARGETS` backs
everything else.
"""

from __future__ import annotations

import glob
import json
import math
import os
import time
from typing import Any, Dict, List, Optional

#: tuning knobs and their defaults (overridable via ``cfg["slo_kw"]``)
SLO_KNOBS: Dict[str, Any] = {
    "eval_every_s": 0.5,     # evaluation cadence (at the serve tick)
    "short_window_s": 5.0,   # fast-detection window
    "long_window_s": 30.0,   # flap-suppression window
    "burn_threshold": 1.0,   # burn > this on BOTH windows => breach
    "recovery_factor": 0.9,  # both windows under thr*this => recover
    "min_samples": 4,        # window warmup before a rule can breach
    "slack": 2.0,            # derive_targets: target = median * slack
    "targets": {},           # explicit {key: target} overrides
    "rules": None,           # full rule-list override
}

#: fallback targets when no measured trajectory covers a key — generous
#: by design: an SLO that false-positives on a healthy laptop run is
#: worse than one that only catches real regressions
DEFAULT_TARGETS: Dict[str, float] = {
    "push_e2e_p95_ms": 500.0,     # exact lineage e2e (worker -> publish)
    "read_p95_ms": 250.0,         # read-tier service time
    "stale_drops_per_s": 0.2,     # staleness-bound violations
    "reads_shed_per_s": 0.5,      # admission-control rejections
    "frames_rejected_per_s": 0.2,  # wire corruption / config drift
    "decodes_per_publish": 16.0,  # decode storm (agg regression)
    "codec_rel_error": 1.5,       # probe fidelity (unbiased codecs ~1)
    # age-of-information at the serving edge: generous because the age
    # grows between publishes by construction (a finished training run
    # serves a correctly-aging snapshot — that is not an incident);
    # smokes/tests that want a tight edge-staleness gate override this
    "serving_age_ms": 60000.0,
    # leader hop occupancy: a pipeline pinned near-saturation round
    # after round is paying a structural cost (split or stream it);
    # 0.95 leaves bursty rounds alone and catches the sustained burn
    "hop_busy_frac": 0.95,
}

#: map a measured artifact field -> the SLO target key it calibrates
_ARTIFACT_FIELDS: Dict[str, str] = {
    "e2e_ms_p95": "push_e2e_p95_ms",
    "push_e2e_p95_ms": "push_e2e_p95_ms",
    "read_p95_ms": "read_p95_ms",
}


def slo_path(slo_dir: str, name: str) -> str:
    return os.path.join(slo_dir, f"slo-{name}.jsonl")


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def derive_targets(results_dir: Optional[str] = None,
                   bench_glob: Optional[str] = None,
                   slack: float = 2.0) -> Dict[str, float]:
    """Targets from the committed perf trajectory: scan bench_gate-style
    JSONL rows (``benchmarks/results/*.jsonl``) and ``BENCH_r*.json``
    round records for the fields in :data:`_ARTIFACT_FIELDS`; each
    covered key's target is ``median(measured) × slack``. Keys with no
    measured history keep :data:`DEFAULT_TARGETS`. Unreadable files are
    skipped — a corrupt artifact must never unarm the watchdog."""
    seen: Dict[str, List[float]] = {}

    def _take(obj: Any) -> None:
        if not isinstance(obj, dict):
            return
        for field, key in _ARTIFACT_FIELDS.items():
            v = obj.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and math.isfinite(float(v)) and float(v) > 0:
                seen.setdefault(key, []).append(float(v))

    paths: List[str] = []
    if results_dir and os.path.isdir(results_dir):
        paths.extend(sorted(glob.glob(os.path.join(results_dir,
                                                   "*.jsonl"))))
    if bench_glob:
        paths.extend(sorted(glob.glob(bench_glob)))
    for p in paths:
        try:
            with open(p) as f:
                text = f.read()
        except OSError:
            continue
        if p.endswith(".jsonl"):
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    _take(json.loads(line))
                except ValueError:
                    continue
        else:
            try:
                doc = json.loads(text)
            except ValueError:
                continue
            _take(doc.get("parsed") if isinstance(doc, dict) else None)
            _take(doc)
    out = dict(DEFAULT_TARGETS)
    for key, vals in seen.items():
        out[key] = _median(vals) * float(slack)
    return out


def default_rules(targets: Dict[str, float]) -> List[Dict[str, Any]]:
    """The standing rule set over the canonical metric keys every server
    already emits. ``mode="value"`` reads the windowed mean of a gauge;
    ``mode="rate"`` reads the windowed per-second delta of a counter."""
    t = {**DEFAULT_TARGETS, **targets}
    return [
        {"name": "push_e2e_p95", "key": "push_e2e_p95_ms",
         "mode": "value", "target": t["push_e2e_p95_ms"],
         "help": "exact per-push e2e latency p95 (lineage-measured)"},
        {"name": "read_p95", "key": "read_p95_ms",
         "mode": "value", "target": t["read_p95_ms"],
         "help": "read-tier service time p95"},
        {"name": "stale_drops", "key": "stale_drops",
         "mode": "rate", "target": t["stale_drops_per_s"],
         "help": "staleness-bound violations per second"},
        {"name": "reads_shed", "key": "reads_shed",
         "mode": "rate", "target": t["reads_shed_per_s"],
         "help": "admission-control sheds per second"},
        {"name": "frames_rejected", "key": "frames_rejected",
         "mode": "rate", "target": t["frames_rejected_per_s"],
         "help": "wire-frame rejections per second"},
        {"name": "decodes_per_publish", "key": "decodes_per_publish",
         "mode": "value", "target": t["decodes_per_publish"],
         "help": "payload decodes per published version"},
        {"name": "codec_rel_error", "key": "codec_rel_error",
         "mode": "value", "target": t["codec_rel_error"],
         "help": "online codec-fidelity probe rel-error"},
        {"name": "serving_age", "key": "serving_age_ms",
         "mode": "value", "target": t["serving_age_ms"],
         "help": "age-of-information of the served version (freshness "
                 "plane; worst tenant)"},
        {"name": "hop_occupancy", "key": "hop_busy_frac",
         "mode": "value", "target": t["hop_busy_frac"],
         "help": "leader hop-pipeline occupancy (hop anatomy; "
                 "sustained saturation wants a split or a streaming "
                 "hop — read hop_stream_headroom_ratio for which)"},
    ]


class _RuleState:
    __slots__ = ("rule", "breached", "breaches", "burn_short", "burn_long")

    def __init__(self, rule: Dict[str, Any]):
        self.rule = rule
        self.breached = False
        self.breaches = 0
        self.burn_short: Optional[float] = None
        self.burn_long: Optional[float] = None


class SLOWatchdog:
    """Burn-rate rule engine over a :class:`~.timeseries.MetricsHistory`.

    ``server`` (optional) wires the scrape instruments and the
    ``/health`` section (the monitor-attachment pattern of
    HealthMonitor/NumericsMonitor/LineageTracker); ``history`` is the
    TSDB the rules read. :meth:`evaluate` runs at the serve loop's tick
    cadence on the serve thread; it self-throttles to
    ``eval_every_s``."""

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, history, name: str = "server",
                 dir: Optional[str] = None, **overrides: Any):
        cfg = cfg or {}
        self.knobs = dict(SLO_KNOBS)
        self.knobs.update(cfg.get("slo_kw") or {})
        self.knobs.update(overrides)
        self.history = history
        self.name = str(name)
        self.server = server
        targets = dict(self.knobs.get("targets") or {})
        rules = self.knobs.get("rules")
        if rules is None:
            rules = default_rules(targets)
        else:
            # explicit rule list: targets still override by key name
            rules = [dict(r) for r in rules]
            for r in rules:
                if r["key"] in targets:
                    r["target"] = targets[r["key"]]
        for r in rules:
            if float(r.get("target", 0.0)) <= 0:
                raise ValueError(
                    f"SLO rule {r.get('name')!r} needs a positive "
                    f"target, got {r.get('target')!r}")
        self._states = [_RuleState(r) for r in rules]
        self.breaches_total = 0
        self.evals = 0
        self.verdicts: List[Dict[str, Any]] = []  # bounded tail below
        self._last_eval = 0.0
        self.overhead_s = 0.0

        self.path: Optional[str] = None
        self._f = None
        if dir:
            os.makedirs(dir, exist_ok=True)
            self.path = slo_path(dir, self.name)
            self._f = open(self.path, "a")
        if server is not None:
            server.slo_watchdog = self
            reg = getattr(server, "scrape_registry", None)
            if reg is not None:
                self.register(reg())

    # -- evaluation -------------------------------------------------------
    def _burn(self, rule: Dict[str, Any], window_s: float,
              now: float) -> Optional[float]:
        stats = self.history.window_stats(rule["key"], window_s, now=now)
        if stats.get("n", 0) < int(self.knobs["min_samples"]):
            return None
        measured = (stats["rate_per_s"] if rule["mode"] == "rate"
                    else stats["mean"])
        return float(measured) / float(rule["target"])

    def evaluate(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One rule sweep; returns the NEW verdict events (usually
        empty). ``now`` overrides the wall clock for replay."""
        t_wall = time.time() if now is None else float(now)
        if t_wall - self._last_eval < float(self.knobs["eval_every_s"]):
            return []
        t0 = time.thread_time()  # CPU self-cost (see MetricsHistory)
        self._last_eval = t_wall
        self.evals += 1
        thr = float(self.knobs["burn_threshold"])
        rec_thr = thr * float(self.knobs["recovery_factor"])
        new: List[Dict[str, Any]] = []
        for st in self._states:
            bs = self._burn(st.rule, float(self.knobs["short_window_s"]),
                            t_wall)
            bl = self._burn(st.rule, float(self.knobs["long_window_s"]),
                            t_wall)
            st.burn_short, st.burn_long = bs, bl
            if bs is None or bl is None:
                continue
            if not st.breached and bs > thr and bl > thr:
                st.breached = True
                st.breaches += 1
                self.breaches_total += 1
                new.append(self._verdict("breach", st, t_wall))
            elif st.breached and bs < rec_thr and bl < rec_thr:
                st.breached = False
                new.append(self._verdict("recover", st, t_wall))
        self.overhead_s += time.thread_time() - t0
        return new

    def _verdict(self, kind: str, st: _RuleState,
                 t_wall: float) -> Dict[str, Any]:
        r = st.rule
        row = {
            "kind": kind,
            "rule": r["name"],
            "key": r["key"],
            "mode": r["mode"],
            "target": r["target"],
            "burn_short": round(st.burn_short, 4),
            "burn_long": round(st.burn_long, 4),
            "t": round(t_wall, 4),
            "name": self.name,
        }
        self.verdicts.append(row)
        if len(self.verdicts) > 256:
            del self.verdicts[:128]
        if self._f is not None:
            self._f.write(json.dumps(row) + "\n")
            self._f.flush()
        from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

        record_event(f"slo.{kind}", rule=r["name"], key=r["key"],
                     burn_short=row["burn_short"],
                     burn_long=row["burn_long"], target=r["target"])
        return row

    # -- surfaces ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {
            "armed": True,
            "name": self.name,
            "evals": self.evals,
            "breaches_total": self.breaches_total,
            "burning": [st.rule["name"] for st in self._states
                        if st.breached],
            "overhead_s": round(self.overhead_s, 6),
            "rules": [{
                "name": st.rule["name"],
                "key": st.rule["key"],
                "mode": st.rule["mode"],
                "target": st.rule["target"],
                "burn_short": st.burn_short,
                "burn_long": st.burn_long,
                "breached": st.breached,
                "breaches": st.breaches,
            } for st in self._states],
            "recent_verdicts": self.verdicts[-8:],
            "file": self.path,
        }

    def register(self, registry) -> None:
        """``ps_slo_burn_rate{rule=...}`` (long-window burn, the alert
        input) + ``ps_slo_breaches_total`` — per-rule labeled series
        beside one rollup counter, same discipline as the diagnosis
        instruments."""

        def collect(r) -> None:
            for st in self._states:
                lab = {"rule": st.rule["name"]}
                r.gauge("ps_slo_burn_rate",
                        "long-window SLO burn rate (measured/target; "
                        ">1 is budget-burning)", labels=lab).set(
                            float(st.burn_long or 0.0))
                r.counter("ps_slo_breaches_total",
                          "latched SLO breach verdicts",
                          labels=lab).set(float(st.breaches))
            r.counter("ps_slo_breaches_all_total",
                      "latched SLO breach verdicts (all rules)").set(
                          float(self.breaches_total))

        registry.add_collector(collect)

    def close(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            f.close()

    # -- replay -----------------------------------------------------------
    @classmethod
    def replay(cls, rows: List[Dict[str, Any]],
               rules: Optional[List[Dict[str, Any]]] = None,
               **overrides: Any) -> List[Dict[str, Any]]:
        """Re-derive the verdict sequence from persisted
        ``timeseries-*.jsonl`` rows — deterministic: the same rows and
        rules produce byte-identical verdicts (modulo the recorder,
        which replay leaves untouched). The offline half of the PR 3
        "every decision is a recorded, replayable event" discipline."""
        from pytorch_ps_mpi_tpu.telemetry.timeseries import (
            history_from_rows,
        )

        h = history_from_rows([], name="replay")
        kw = dict(overrides)
        if rules is not None:
            kw["rules"] = rules
        wd = cls(history=h, name="replay", **kw)
        out: List[Dict[str, Any]] = []
        for r in rows:
            h.sample(r["m"], now=float(r["t"]))
            out.extend(wd.evaluate(now=float(r["t"])))
        return out
