"""FleetMonitor: one merged observability pane over N PS endpoints.

Every process so far serves its OWN ``/metrics`` + ``/health`` — a
sharded run is S panes, a supervised run is a new pane per server
generation, and the read tier is another. This module folds them into
one: a :class:`FleetMonitor` polls every registered endpoint's
Prometheus text and ``/health`` JSON and merges them into a single
snapshot — summed counters, per-member labeled series, a worst-verdict
rollup, and per-shard skew detection — served at ``/fleet`` on any
armed server and rendered by ``tools/ps_top.py --fleet``.

Membership is a **registration directory**, not a static list: each
member writes ``endpoint-<name>.json`` (:func:`register_endpoint`) when
its metrics endpoint binds and removes it on clean close
(:func:`deregister_endpoint`). Registration is an atomic overwrite
keyed by name, so a supervisor-restarted server generation — whose
auto-assigned port changed — *rejoins* the pane under the same name
instead of orphaning a dead URL; ``sharded.server_main`` registers
``shard<i>`` the same way. Static ``endpoints=[...]`` URLs compose with
the directory for fixed fleets.

Polling runs wherever the monitor lives — the ``/fleet`` route fetches
on the HTTP thread (daemon, plain ``urllib`` to other ports, never a
native handle) with a min-interval cache, so scraping ``/fleet`` at any
rate costs the fleet one poll per ``min_poll_s``. The samples merged
are ordered/aged by the ``ts``/``uptime_s`` fields every ``/metrics``
and ``/health`` payload now carries (this PR's satellite — the poller
is why they exist).
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

#: tuning knobs and their defaults (overridable via ``cfg["fleet_kw"]``)
FLEET_KNOBS: Dict[str, Any] = {
    "timeout_s": 2.0,      # per-endpoint fetch timeout
    "min_poll_s": 0.5,     # snapshot cache TTL (poll coalescing)
    "skew_frac": 0.5,      # (max-min)/max past this flags skew
    "skew_min": 16.0,      # no skew verdicts below this absolute max
}

#: counters summed across members into the fleet rollup
_SUM_KEYS: Dict[str, str] = {
    "grads_received": "ps_grads_received_total",
    "bytes_received": "ps_wire_bytes_received_total",
    "stale_drops": "ps_stale_drops_total",
    "reads_total": "ps_reads_total",
    "reads_shed": "ps_reads_shed_total",
    # read plane: open native reader conns and follower relay volume sum
    # across the tree (the tree-wide serving capacity actually in use)
    "native_read_conns": "ps_native_read_conns",
    "follower_bytes_relayed": "ps_follower_bytes_relayed_total",
    "slo_breaches": "ps_slo_breaches_all_total",
    "tree_composed": "ps_tree_composed_total",
    "control_actions": "ps_control_actions_total",
    "anatomy_rounds": "ps_anatomy_rounds_total",
    # structural control: fleet-wide action volume, live replica count,
    # and splits currently in force — sums because each member's
    # controller only counts its OWN actuations
    "topo_actions": "ps_topo_actions_total",
    "replicas_live": "ps_replicas_live",
    "group_replans": "ps_group_replans_total",
    # hop anatomy: fleet-wide decomposed leader rounds (each leader
    # only counts its OWN hop rounds)
    "hop_rounds": "ps_hop_rounds_total",
}

#: gauges rolled up as the fleet max (worst member)
_MAX_KEYS: Dict[str, str] = {
    "staleness_p95": "ps_staleness_p95",
    "push_e2e_p95_ms": "ps_push_e2e_p95_ms",
    "read_p95_ms": "ps_read_p95_ms",
    "decodes_per_publish": "ps_decodes_per_publish",
    # the worst member's wire-gated critical-path share: a tree where
    # ONE pod's hop is wire-bound shows up here even when the fleet sum
    # looks healthy (per-hop cost attribution, DynamiQ's lesson)
    "anatomy_wire_share": "ps_anatomy_wire_share",
    "anatomy_top_saving_frac": "ps_anatomy_top_saving_frac",
    # the WORST replica's staleness: a distribution tree is only as
    # fresh as its laggiest hop, so the rollup takes the fleet max
    "replica_lag_versions": "ps_replica_lag_versions",
    # worst-edge-age: the wall age of the stalest served version across
    # the tree (the freshness plane's fleet rollup — what "how stale is
    # the model a reader at the edge sees" actually maxes out at)
    "serving_age_ms": "ps_serving_age_ms",
    # the HOTTEST leader pipeline: occupancy and streaming headroom are
    # per-leader verdict inputs, so the rollup takes the fleet max —
    # one saturated (or one serial) hop is where the next fix goes
    "hop_busy_frac": "ps_hop_busy_frac",
    "hop_stream_headroom_ratio": "ps_hop_stream_headroom_ratio",
}

#: per-member gauges the skew detector compares across shards
_SKEW_KEYS = ("grads_received", "publish_version")

_VERDICT_RANK = {"ok": 0, "slow": 1, "churning": 2, "missing": 3,
                 "quarantined": 4}

_line_re = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)\s*$")
_label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_prometheus_text(text: str) -> List[Dict[str, Any]]:
    """Prometheus exposition text → ``[{name, labels, value}]`` rows
    (``# HELP``/``# TYPE`` skipped; label values unescaped enough for
    the simple labels this stack emits). The one parser — the fleet
    poller and ``tools/telemetry_report.py`` share it."""
    series: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _line_re.match(line)
        if not m:
            continue
        name, labels_text, raw = m.groups()
        try:
            value = float(raw.replace("+Inf", "inf"))
        except ValueError:
            continue
        labels = dict(_label_re.findall(labels_text)) if labels_text else {}
        series.append({"name": name, "labels": labels, "value": value})
    return series


# ---------------------------------------------------------------------------
# endpoint registration (the cross-process membership mechanism)
# ---------------------------------------------------------------------------

def endpoint_path(fleet_dir: str, name: str) -> str:
    return os.path.join(fleet_dir, f"endpoint-{name}.json")


def register_endpoint(fleet_dir: str, name: str, port: int,
                      host: str = "127.0.0.1", role: str = "server",
                      **meta: Any) -> str:
    """Write (atomically, overwrite-by-name) this member's endpoint
    card. A re-registration under the same name — a respawned server
    generation, a shard restart — REPLACES the old card, so the pane
    follows the member across ports instead of polling a corpse."""
    os.makedirs(fleet_dir, exist_ok=True)
    path = endpoint_path(fleet_dir, name)
    doc = {"name": str(name), "url": f"http://{host}:{int(port)}",
           "role": str(role), "pid": os.getpid(),
           "registered_wall": time.time(), **meta}
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def deregister_endpoint(fleet_dir: str, name: str) -> None:
    try:
        os.remove(endpoint_path(fleet_dir, name))
    except OSError:
        pass


def list_endpoints(fleet_dir: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for p in sorted(glob.glob(os.path.join(fleet_dir, "endpoint-*.json"))):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # torn write mid-registration; next poll sees it
        if isinstance(doc, dict) and doc.get("url"):
            out.append(doc)
    return out


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

class FleetMonitor:
    """Poll + merge N endpoints into the ``/fleet`` document.

    ``endpoints`` is a list of base URLs (or ``{"name","url","role"}``
    dicts) for fixed members; ``fleet_dir`` adds the registration
    directory, rescanned per poll so members come and go without
    restarting the pane."""

    def __init__(self, endpoints: Optional[List[Any]] = None,
                 fleet_dir: Optional[str] = None, **overrides: Any):
        self.knobs = dict(FLEET_KNOBS)
        self.knobs.update(overrides)
        self.fleet_dir = fleet_dir
        self._static: List[Dict[str, Any]] = []
        for i, e in enumerate(endpoints or []):
            if isinstance(e, str):
                url = e if e.startswith("http") else f"http://{e}"
                self._static.append({"name": f"static-{i}", "url": url,
                                     "role": "server"})
            else:
                self._static.append(dict(e))
        self._lock = threading.Lock()
        self._poll_lock = threading.Lock()  # serializes the sweep itself
        self._cache: Optional[Dict[str, Any]] = None
        self._cache_t = 0.0
        self.polls = 0

    # -- membership -------------------------------------------------------
    def members(self) -> List[Dict[str, Any]]:
        out = {m["name"]: m for m in self._static}
        if self.fleet_dir:
            for doc in list_endpoints(self.fleet_dir):
                out[doc["name"]] = doc
        return [out[k] for k in sorted(out)]

    # -- polling ----------------------------------------------------------
    def _fetch(self, url: str, path: str) -> Optional[str]:
        try:
            with urllib.request.urlopen(
                    url.rstrip("/") + path,
                    timeout=float(self.knobs["timeout_s"])) as r:
                return r.read().decode()
        except Exception:
            return None

    def _poll_member(self, member: Dict[str, Any]) -> Dict[str, Any]:
        url = member["url"]
        row: Dict[str, Any] = {
            "name": member["name"], "url": url,
            "role": member.get("role", "server"), "ok": False,
            "error": None, "ts": None, "uptime_s": None, "age_s": None,
            "verdict": None, "metrics": {}, "labeled": [],
        }
        if member.get("group") is not None:
            # aggregation-tree cards carry their group id + leaf members
            row["group"] = member["group"]
            row["members"] = member.get("members")
        if member.get("upstream") is not None:
            # replica cards carry their tree edge: who they follow and
            # how many downstream replicas they are provisioned to feed
            row["upstream"] = member["upstream"]
            row["fanout"] = member.get("fanout")
        text = self._fetch(url, "/metrics")
        if text is None:
            row["error"] = "unreachable"
            return row
        flat: Dict[str, float] = {}
        for s in parse_prometheus_text(text):
            if s["labels"]:
                if "le" not in s["labels"]:  # histogram buckets are noise
                    row["labeled"].append(
                        {"name": s["name"], "labels": s["labels"],
                         "value": s["value"]})
            else:
                flat[s["name"]] = s["value"]
        row["ok"] = True
        row["ts"] = flat.get("ps_scrape_ts_seconds")
        row["uptime_s"] = flat.get("ps_uptime_seconds")
        if row["ts"] is not None:
            row["age_s"] = round(max(0.0, time.time() - row["ts"]), 3)
        m: Dict[str, float] = {}
        for k, prom in {**_SUM_KEYS, **_MAX_KEYS}.items():
            if prom in flat:
                m[k] = flat[prom]
        m["publish_version"] = flat.get("ps_publish_version", 0.0)
        row["metrics"] = m
        health = self._fetch(url, "/health")
        if health is not None:
            try:
                doc = json.loads(health)
            except ValueError:
                doc = {}
            worst = None
            for w in doc.get("workers") or []:
                v = w.get("verdict")
                if v is not None and (
                        worst is None
                        or _VERDICT_RANK.get(v, 0)
                        > _VERDICT_RANK.get(worst, 0)):
                    worst = v
            row["verdict"] = worst
            slo = doc.get("slo")
            if isinstance(slo, dict):
                row["slo"] = {"breaches_total": slo.get(
                    "breaches_total", 0), "burning": slo.get(
                        "burning", [])}
            serving = doc.get("serving")
            if isinstance(serving, dict):
                row["serving"] = {
                    "reads_per_s": serving.get("reads_per_s", 0.0),
                    "queue_depth": serving.get("queue_depth", 0),
                }
            control = doc.get("control")
            if isinstance(control, dict):
                # the member's controller card: what the pane's
                # controller rollup sums/maxes across the fleet
                row["control"] = {
                    "actions_total": control.get("actions_total", 0),
                    "flaps": control.get("flaps", 0),
                    "epoch": control.get("epoch", 0),
                    "evicted": control.get("evicted", []),
                    "lr_scale": control.get("lr_scale", {}),
                    "recent_actions": (control.get("recent_actions")
                                       or [])[-3:],
                }
        return row

    def _cache_fresh(self, now: float) -> Optional[Dict[str, Any]]:
        with self._lock:
            if (self._cache is not None
                    and now - self._cache_t
                    < float(self.knobs["min_poll_s"])):
                return self._cache
        return None

    def poll(self, force: bool = False) -> Dict[str, Any]:
        """The merged fleet snapshot, cached for ``min_poll_s`` so any
        number of concurrent ``/fleet`` scrapes cost one fleet sweep:
        the sweep itself is serialized, and a scrape that waited behind
        an in-flight sweep reuses its result instead of re-sweeping."""
        if not force:
            snap = self._cache_fresh(time.time())
            if snap is not None:
                return snap
        with self._poll_lock:
            now = time.time()
            if not force:
                # double-check: the sweep we waited behind just filled
                # the cache — N concurrent scrapes, one sweep
                snap = self._cache_fresh(now)
                if snap is not None:
                    return snap
            members = [self._poll_member(m) for m in self.members()]
            snap = self._merge(members, now)
            with self._lock:
                self._cache, self._cache_t = snap, now
                self.polls += 1
            return snap

    def _merge(self, members: List[Dict[str, Any]],
               now: float) -> Dict[str, Any]:
        ok = [m for m in members if m["ok"]]
        fleet: Dict[str, Any] = {}
        for k in _SUM_KEYS:
            fleet[k] = sum(m["metrics"].get(k, 0.0) for m in ok)
        for k in _MAX_KEYS:
            vals = [m["metrics"][k] for m in ok if k in m["metrics"]]
            fleet[f"{k}_max"] = max(vals) if vals else 0.0
        worst = None
        for m in ok:
            v = m["verdict"]
            if v is not None and (worst is None
                                  or _VERDICT_RANK.get(v, 0)
                                  > _VERDICT_RANK.get(worst, 0)):
                worst = v
        fleet["worst_verdict"] = worst
        # per-shard skew: a healthy sharded fleet advances together; one
        # shard falling behind on applied work or publish version is the
        # balance problem Li et al.'s partitioning can hide
        skew: Dict[str, Any] = {}
        shards = [m for m in ok if m.get("role") == "shard"] or ok
        if len(shards) > 1:
            for k in _SKEW_KEYS:
                vals = {m["name"]: m["metrics"].get(k, 0.0)
                        for m in shards if k in m["metrics"]}
                if len(vals) < 2:
                    continue
                mx, mn = max(vals.values()), min(vals.values())
                spread = (mx - mn) / mx if mx > 0 else 0.0
                skew[k] = {
                    "min": mn, "max": mx,
                    "spread_frac": round(spread, 4),
                    "flagged": bool(
                        mx >= float(self.knobs["skew_min"])
                        and spread > float(self.knobs["skew_frac"])),
                    "per_member": vals,
                }
        slo = {
            "breaches_total": sum(
                int((m.get("slo") or {}).get("breaches_total", 0))
                for m in ok),
            "burning": sorted({
                f"{m['name']}:{r}" for m in ok
                for r in (m.get("slo") or {}).get("burning", [])}),
        }
        # controller rollup: one line answers "is the fleet self-driving
        # and did anything flap" without opening every member's pane
        control = {
            "actions_total": sum(
                int((m.get("control") or {}).get("actions_total", 0))
                for m in ok),
            "flaps": sum(
                int((m.get("control") or {}).get("flaps", 0))
                for m in ok),
            "epoch_max": max(
                [int((m.get("control") or {}).get("epoch", 0))
                 for m in ok] or [0]),
            "evicted": sorted({
                f"{m['name']}:w{w}" for m in ok
                for w in (m.get("control") or {}).get("evicted", [])}),
            "members_armed": sum(
                1 for m in ok if m.get("control") is not None),
        }
        # per-group rollups: members whose registration card carries a
        # group id (aggregation-tree leaders) roll up side by side, so
        # one pane answers "which pod is behind" without PromQL
        groups: Dict[str, Any] = {}
        for m in members:
            g = m.get("group")
            if g is None:
                continue
            row = groups.setdefault(str(g), {
                "n_members": 0, "n_ok": 0, "grads_received": 0.0,
                "tree_composed": 0.0, "leaves": [], "worst_verdict": None,
            })
            row["n_members"] += 1
            row["leaves"] = sorted(set(row["leaves"])
                                   | set(m.get("members") or []))
            if not m["ok"]:
                continue
            row["n_ok"] += 1
            row["grads_received"] += m["metrics"].get("grads_received", 0.0)
            row["tree_composed"] += m["metrics"].get("tree_composed", 0.0)
            v = m.get("verdict")
            if v is not None and (
                    row["worst_verdict"] is None
                    or _VERDICT_RANK.get(v, 0)
                    > _VERDICT_RANK.get(row["worst_verdict"], 0)):
                row["worst_verdict"] = v
        # merged per-worker labeled series, member-tagged so one pane
        # shows e.g. every shard's rejection counters side by side
        labeled = [{"member": m["name"], **s}
                   for m in ok for s in m["labeled"]]
        return {
            "armed": True,
            "ts": round(now, 3),
            "n_members": len(members),
            "n_ok": len(ok),
            "members": {m["name"]: m for m in members},
            "fleet": fleet,
            "skew": skew,
            "groups": groups,
            "slo": slo,
            "control": control,
            "labeled": labeled,
        }

    # -- surfaces ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return self.poll()

    def render_http(self, query: Optional[Dict[str, Any]] = None
                    ) -> Tuple[str, str]:
        q = query or {}
        snap = self.poll(force=str(q.get("force", "")) in ("1", "true"))
        if str(q.get("labeled", "")) not in ("1", "true"):
            snap = {k: v for k, v in snap.items() if k != "labeled"}
            snap["members"] = {
                name: {k: v for k, v in m.items() if k != "labeled"}
                for name, m in snap["members"].items()
            }
        return json.dumps(snap), "application/json"
