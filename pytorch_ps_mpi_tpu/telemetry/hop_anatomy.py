"""Hop anatomy: leader-pipeline occupancy tracing and the
streaming-headroom scoreboard.

PR 15's round anatomy named ``leader_fold`` the tree's critical stage —
a 719–931 ms hop window at 64 workers — but nothing could see *inside*
that window: between a worker push's ``send_wall`` and the leader's one
upstream frame, the leader's time dissolves into an unattributed blur of
waiting, validating, folding, re-encoding and pushing.  This module is
the occupancy plane for that window.  Per leader, per round, the hop
timeline is reconstructed into sub-stage intervals:

``ingest_wait``
    waiting for group members' pushes to arrive (round start → fold
    start, minus measured validate time);
``validate``
    native PSF2 frame validation (magic/size/fingerprint/CRC), summed
    from the per-frame stamps ``tcpps.cpp``'s bounded ring captures;
``fold`` / ``finalize``
    the compressed-domain fold loop and its one-per-round finalize —
    the fold side is additionally attributable to native kernel time
    through ``wirecodec.cpp``'s per-fold-call span ring;
``encode`` / ``upstream_push``
    the EF re-encode and the one-frame upstream send;
``idle``
    whatever the stamps could not attribute (clamped ≥ 0).

Both native rings are bounded and drop-and-count on overflow — the hot
paths never block or reallocate for observability (the PR 15 overhead
contract, ≤ 5%).  They are armed and drained only from the pump-owning
thread, the same affinity rule ``tps_server_read_stats`` documents.

The **streaming-headroom projection** is the plane's headline: the tree
ROADMAP's #1 open item is the DynamiQ-style streaming leader hop
(ingest ⇄ fold ⇄ encode overlapped instead of serialized), and
:meth:`HopAnatomy.project` computes what that would buy — round time if
the three pipeline legs were perfectly overlapped (the max of the leg
sums plus a per-frame fill/drain tail) against the measured serial sum.
``headroom_ratio = serial / overlapped``: ≈ 1.0 means the pipeline is
already busy (splitting the group is the fix); ≫ 1 means the hop is
serial and streaming is the fix — the topo controller's upgraded
``leader_fold_hot`` verdict reads exactly this distinction.  The
projection is a pure function of the persisted row's (rounded) fields,
so an offline replay reproduces it byte-for-byte — the what-if smoke's
determinism contract, inherited from PR 15.

Rows land in ``hop-<name>.jsonl`` (a registered sidecar prefix, routed
away from the recorder-span merge like every other sidecar).
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: the hop timeline's sub-stage taxonomy, in pipeline order
HOP_STAGES = ("ingest_wait", "validate", "fold", "finalize", "encode",
              "upstream_push", "idle")

#: stages that are WORK (occupancy's numerator) — waiting and idle are not
BUSY_STAGES = ("validate", "fold", "finalize", "encode", "upstream_push")

#: the three pipeline legs a streaming leader would overlap
PIPE_LEGS = (("ingest_wait", "validate"), ("fold", "finalize"),
             ("encode", "upstream_push"))

#: engine tuning knobs and their defaults (``cfg["hop_anatomy_kw"]``)
HOP_KNOBS: Dict[str, Any] = {
    "window": 512,        # hop rounds retained for the scoreboard
    "stage_window": 1024,  # per-stage duration samples kept
    "flush_every": 32,    # JSONL rows buffered between flushes
    "min_rounds": 2,      # rounds before the scoreboard answers
    "ring_capacity": 4096,  # native interval-ring entries (spans/stamps)
}


def hop_path(out_dir: str, name) -> str:
    """``hop-<name>.jsonl`` — a registered sidecar prefix
    (:data:`pytorch_ps_mpi_tpu.telemetry.SIDECAR_PREFIXES`), routed away
    from the recorder-span merge like every other sidecar."""
    return os.path.join(out_dir, f"hop-{name}.jsonl")


def _med(vals) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _r6(v: float) -> float:
    # observe_round's ``round=`` kwarg shadows the builtin in its scope
    return round(float(v), 6)


def _r4(v: float) -> float:
    return round(float(v), 4)


def _p(vals, q: float) -> float:
    s = sorted(vals)
    if not s:
        return math.nan
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


class HopAnatomy:
    """The leader-pipeline occupancy profiler.  Live construction
    mirrors the other monitors (``HopAnatomy(server, cfg)`` attaches
    ``server.hop_anatomy`` and registers scrape instruments); tests and
    the offline loaders construct bare and drive :meth:`observe_row`.

    Two feed points:

    * :meth:`observe_round` — the measuring process (a tree leader)
      passes its per-round sub-stage walls; the engine builds the
      canonical row, ingests it, and persists it.
    * :meth:`observe_row` — a persisted (or relayed) row is replayed
      into the same windows; the root's hop tailer and the offline
      loaders use this, so live and replayed state cannot diverge.
    """

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, name: str = "server", **overrides: Any):
        cfg = cfg or {}
        self.knobs = dict(HOP_KNOBS)
        self.knobs.update(cfg.get("hop_anatomy_kw") or {})
        self.knobs.update(overrides)
        self.server = server
        self.name = str(name)
        self.dir = (cfg.get("lineage_dir") or cfg.get("telemetry_dir"))
        self.rounds = 0
        self.frames = 0
        #: native interval-ring entries surrendered to overflow
        self.ring_drops = 0
        self._rounds: deque = deque(maxlen=int(self.knobs["window"]))
        #: stage → bounded duration window (seconds)
        self._stage_win: Dict[str, deque] = {}
        #: per-round scoreboard windows
        self._busy: deque = deque(maxlen=int(self.knobs["window"]))
        self._headroom: deque = deque(maxlen=int(self.knobs["window"]))
        self._serial: deque = deque(maxlen=int(self.knobs["window"]))
        #: leader id → bounded per-leader windows (the fleet view on a
        #: root that tails several leaders' rows)
        self._leaders: Dict[int, Dict[str, Any]] = {}
        self.overhead_s = 0.0
        self._f = None
        self._rows_since_flush = 0
        if server is not None:
            server.hop_anatomy = self
            reg = getattr(server, "scrape_registry", None)
            if reg is not None:
                self.register(reg())

    # -- the projection -----------------------------------------------------
    @staticmethod
    def project(stages: Dict[str, Any], frames: int
                ) -> Tuple[float, float, float]:
        """Streaming-headroom projection from one round's sub-stage
        sums: ``(serial_s, overlap_s, headroom_ratio)``.

        ``serial_s`` is the measured serialized pipeline (every leg back
        to back — idle excluded, it is neither work nor overlappable).
        ``overlap_s`` is the projected round if the three legs ran
        perfectly overlapped: the bottleneck leg's sum plus a pipeline
        fill/drain tail — the non-bottleneck legs' cost for ONE frame,
        which no schedule can hide.  Pure arithmetic over the (rounded)
        row fields, so replays reproduce it byte-identically."""
        legs = [sum(float(stages.get(s) or 0.0) for s in leg)
                for leg in PIPE_LEGS]
        serial = sum(legs)
        bottleneck = max(legs)
        tail = (serial - bottleneck) / max(int(frames), 1)
        overlap = bottleneck + tail
        ratio = serial / overlap if overlap > 0 else 1.0
        return round(serial, 6), round(overlap, 6), round(ratio, 4)

    # -- feed points ----------------------------------------------------------
    def observe_round(self, *, leader: int, round: int, frames: int,
                      stages: Dict[str, float],
                      round_s: Optional[float] = None,
                      t: Optional[float] = None,
                      drops: int = 0, native: bool = False,
                      fold_calls: int = 0, fold_busy_s: float = 0.0,
                      ) -> Dict[str, Any]:
        """One measured leader round → the canonical ``hop_round`` row
        (ingested AND persisted).  ``stages`` carries the measured
        sub-stage walls (idle is derived here, never passed); ``drops``
        counts native ring entries lost to overflow this round."""
        t0 = time.perf_counter()
        st = {s: _r6(stages.get(s) or 0.0)
              for s in HOP_STAGES if s != "idle"}
        attributed = sum(st.values())
        wall = (float(round_s) if round_s is not None else attributed)
        st["idle"] = _r6(max(0.0, wall - attributed))
        serial, overlap, ratio = self.project(st, frames)
        busy = sum(st[s] for s in BUSY_STAGES)
        rec = {
            "kind": "hop_round", "version": 1,
            "t": float(t if t is not None else time.time()),
            "leader": int(leader), "round": int(round),
            "frames": int(frames), "round_s": _r6(wall),
            "stages": st,
            "serial_s": serial, "overlap_s": overlap,
            "headroom_ratio": ratio,
            "busy_frac": _r4(busy / wall) if wall > 0 else 0.0,
            "drops": int(drops), "native": bool(native),
            "fold_calls": int(fold_calls),
            "fold_busy_s": _r6(fold_busy_s),
        }
        self._ingest(rec)
        self._write_row(rec)
        self.overhead_s += time.perf_counter() - t0
        return rec

    def observe_row(self, row: Dict[str, Any]) -> None:
        """Replay one persisted ``hop_round`` row into the windows (the
        root's hop tailer, the offline loaders).  Never writes — the row
        already lives in its producer's sidecar."""
        if not isinstance(row, dict) or row.get("kind") != "hop_round":
            return
        t0 = time.perf_counter()
        self._ingest(row)
        self.overhead_s += time.perf_counter() - t0

    def _ingest(self, rec: Dict[str, Any]) -> None:
        self.rounds += 1
        self.frames += int(rec.get("frames") or 0)
        self.ring_drops += int(rec.get("drops") or 0)
        self._rounds.append(rec)
        cap = int(self.knobs["stage_window"])
        for s, v in (rec.get("stages") or {}).items():
            self._stage_win.setdefault(s, deque(maxlen=cap)).append(
                float(v))
        self._busy.append(float(rec.get("busy_frac") or 0.0))
        self._headroom.append(float(rec.get("headroom_ratio") or 1.0))
        self._serial.append(float(rec.get("serial_s") or 0.0))
        g = int(rec.get("leader", -1))
        lw = self._leaders.setdefault(g, {
            "rounds": 0,
            "busy": deque(maxlen=64), "headroom": deque(maxlen=64),
            "round_s": deque(maxlen=64),
        })
        lw["rounds"] += 1
        lw["busy"].append(float(rec.get("busy_frac") or 0.0))
        lw["headroom"].append(float(rec.get("headroom_ratio") or 1.0))
        lw["round_s"].append(float(rec.get("round_s") or 0.0))

    # -- scoreboard reads -----------------------------------------------------
    def _armed(self) -> bool:
        return self.rounds >= int(self.knobs["min_rounds"])

    def busy_frac(self) -> float:
        """Median per-round busy fraction: the share of the hop window
        the leader spent WORKING (validate/fold/finalize/encode/push)
        rather than waiting — 0.0 until ``min_rounds`` rounds landed."""
        return round(_med(list(self._busy)), 4) if self._armed() else 0.0

    def headroom_ratio(self) -> float:
        """Median streaming-headroom ratio (serial / overlapped): how
        much faster a perfectly pipelined hop would run this workload.
        1.0 = no headroom (or not enough rounds to answer)."""
        return (round(_med(list(self._headroom)), 4)
                if self._armed() else 1.0)

    def ingest_wait_ms(self) -> float:
        vals = list(self._stage_win.get("ingest_wait") or ())
        return round(1e3 * _med(vals), 3) if self._armed() and vals else 0.0

    def serial_ms(self) -> float:
        return (round(1e3 * _med(list(self._serial)), 3)
                if self._armed() else 0.0)

    def hot_leader(self) -> Optional[int]:
        """The leader with the highest median busy fraction — the topo
        controller's occupancy-based hot-group input.  None until two
        leaders report (a single leader has no 'hotter')."""
        meds = {g: _med(list(w["busy"]))
                for g, w in self._leaders.items() if w["busy"]}
        if len(meds) < 2:
            return None
        return max(meds, key=meds.get)

    def snapshot(self) -> Dict[str, Any]:
        """The hop-anatomy section of ``/health`` and the serve metrics
        — pure reads over the bounded windows."""
        return {
            "armed": True,
            "rounds": self.rounds,
            "frames": self.frames,
            "ring_drops": self.ring_drops,
            "busy_frac": self.busy_frac(),
            "ingest_wait_ms": self.ingest_wait_ms(),
            "headroom_ratio": self.headroom_ratio(),
            "serial_ms": self.serial_ms(),
            "stages": {
                s: {"p50_ms": round(1e3 * _med(vals), 3),
                    "p95_ms": round(1e3 * _p(vals, 0.95), 3)}
                for s, vals in ((s, list(self._stage_win.get(s) or ()))
                                for s in HOP_STAGES)
                if vals
            },
            "leaders": {
                int(g): {
                    "rounds": w["rounds"],
                    "busy_frac": round(_med(list(w["busy"])), 4),
                    "headroom_ratio": round(_med(list(w["headroom"])), 4),
                    "round_ms": round(1e3 * _med(list(w["round_s"])), 3),
                }
                for g, w in sorted(self._leaders.items())
            },
            "hot_leader": self.hot_leader(),
            "overhead_s": round(self.overhead_s, 6),
        }

    def register(self, registry) -> None:
        """Scrape instruments: the canonical-key twins plus per-stage
        labeled p50 gauges."""

        def collect(r) -> None:
            r.counter(
                "ps_hop_rounds_total",
                "leader hop rounds decomposed into sub-stage intervals",
            ).set(float(self.rounds))
            r.gauge(
                "ps_hop_busy_frac",
                "median share of the hop window the leader pipeline "
                "spent working (validate/fold/finalize/encode/push)",
            ).set(self.busy_frac())
            r.gauge(
                "ps_hop_ingest_wait_ms",
                "median per-round wait for group pushes to arrive (ms)",
            ).set(self.ingest_wait_ms())
            r.gauge(
                "ps_hop_stream_headroom_ratio",
                "median serial/overlapped round-time ratio — what a "
                "streaming (pipelined) leader hop would buy",
            ).set(self.headroom_ratio())
            r.gauge(
                "ps_hop_serial_ms",
                "median serialized hop pipeline time per round (ms)",
            ).set(self.serial_ms())
            r.counter(
                "ps_hop_ring_drops_total",
                "native interval-ring entries dropped to overflow "
                "(bounded rings never block the hot path)",
            ).set(float(self.ring_drops))
            for stage in HOP_STAGES:
                vals = list(self._stage_win.get(stage) or ())
                if vals:
                    r.gauge("ps_hop_stage_p50_ms",
                            "per-sub-stage duration p50 (ms)",
                            labels={"stage": stage}).set(
                                1e3 * _med(vals))

        registry.add_collector(collect)

    # -- disk -----------------------------------------------------------------
    def _write_row(self, row: Dict[str, Any]) -> None:
        if not self.dir:
            return
        if self._f is None:
            os.makedirs(self.dir, exist_ok=True)
            self._f = open(hop_path(self.dir, self.name), "a")
        self._f.write(json.dumps(row) + "\n")
        self._rows_since_flush += 1
        if self._rows_since_flush >= int(self.knobs["flush_every"]):
            self._f.flush()
            self._rows_since_flush = 0

    def flush(self) -> None:
        """Force the row buffer to disk — a leader calls this per round
        so the root's hop tailer (and the topo controller behind it)
        reads occupancy live, not ``flush_every`` rounds late."""
        if self._f is not None:
            self._f.flush()
            self._rows_since_flush = 0

    def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            f.flush()
            f.close()


# ---------------------------------------------------------------------------
# offline reconstruction (report sections, smokes, tests)
# ---------------------------------------------------------------------------

def load_hop_rows(path: str) -> List[Dict[str, Any]]:
    """``hop-*.jsonl`` → row list (torn trailing lines skipped)."""
    rows: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    return rows


def hop_anatomy_from_rows(rows: Iterable[Dict[str, Any]],
                          **overrides: Any) -> HopAnatomy:
    """Rebuild a :class:`HopAnatomy` from persisted ``hop_round`` rows.
    Rows are replayed in time order into the same windows the live
    engine fills, and the projection each row carries was computed from
    the row's own rounded fields — so the replayed scoreboard (and a
    re-projection of any row) is byte-identical to the live one."""
    ordered = sorted((r for r in rows if isinstance(r, dict)
                      and r.get("kind") == "hop_round"),
                     key=lambda r: (float(r.get("t", 0.0)),
                                    int(r.get("leader", -1)),
                                    int(r.get("round", 0))))
    eng = HopAnatomy(**overrides)
    for r in ordered:
        eng.observe_row(r)
    return eng


# ---------------------------------------------------------------------------
# Chrome-trace tracks
# ---------------------------------------------------------------------------

#: hop tracks sit above device pids so leader timelines group together
HOP_PID_BASE = 2000


def hop_trace_events(hop_rows: Iterable[Dict[str, Any]],
                     lineage_rows: Optional[Iterable[Dict[str, Any]]] = None,
                     *, t0_wall: float = 0.0) -> List[Dict[str, Any]]:
    """``hop_round`` rows → per-leader Chrome-trace tracks: one ``X``
    span per sub-stage (laid out back to back ending at the row's wall
    time, idle excluded) on pid ``HOP_PID_BASE + leader``.  When the
    leaders' lineage ``hop`` rows are also given, each composed push
    gets a flow STEP event (``ph: "t"``) anchored mid-fold-span with the
    push's canonical trace id — threading the existing worker-push →
    root-consume lineage arrows through the leader's hop track."""
    from pytorch_ps_mpi_tpu.telemetry.lineage import trace_id

    composed: Dict[Tuple[int, int], List[Tuple]] = {}
    for row in lineage_rows or ():
        if row.get("kind") != "hop":
            continue
        key = (int(row.get("leader", -1)), int(row.get("round", -1)))
        composed[key] = [
            (e.get("worker"), e.get("step"), e.get("seq"))
            for e in (row.get("composed") or ())
        ]
    out: List[Dict[str, Any]] = []
    pids: Dict[int, int] = {}
    order = [s for s in HOP_STAGES if s != "idle"]
    for row in hop_rows:
        if not isinstance(row, dict) or row.get("kind") != "hop_round":
            continue
        g = int(row.get("leader", -1))
        pid = pids.setdefault(g, HOP_PID_BASE + len(pids))
        st = row.get("stages") or {}
        total = sum(float(st.get(s) or 0.0) for s in order)
        cursor = (float(row.get("t", 0.0)) - t0_wall - total) * 1e6
        fold_mid = None
        for s in order:
            dur_us = float(st.get(s) or 0.0) * 1e6
            if dur_us <= 0.0:
                continue
            out.append({
                "ph": "X", "name": f"hop.{s}", "cat": "hop",
                "pid": pid, "tid": 1, "ts": cursor, "dur": dur_us,
                "args": {"leader": g, "round": row.get("round"),
                         "frames": row.get("frames")},
            })
            if s == "fold":
                fold_mid = cursor + dur_us * 0.5
            cursor += dur_us
        if fold_mid is None:
            fold_mid = cursor
        for key in composed.get((g, int(row.get("round", -1))), ()):
            out.append({
                "ph": "t", "cat": "lineage", "name": "grad push",
                "id": trace_id(*key), "pid": pid, "tid": 1,
                "ts": fold_mid,
            })
    for g, pid in pids.items():
        out.append({
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": f"leader {g} (hop anatomy)"},
        })
    return out
