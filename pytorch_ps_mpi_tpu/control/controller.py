"""Self-driving control plane: close the verdict→action loop.

Every robustness verdict the stack produces — quarantine offenses
(``telemetry.numerics``), exact per-push staleness (``telemetry.lineage``),
SLO burn rates over the TSDB (``telemetry.slo``), churn counters and
straggler attribution (``telemetry.diagnosis``) — used to feed only
dashboards. The :class:`Controller` turns them into recorded,
replayable, reversible **actions**, executed from inside the serve loop
(fed at the same ``on_tick``/consume sites as the monitors; no thread
ever touches a native transport handle):

1. **codec / ``bucket_mb`` / agg-mode renegotiation** from the measured
   wire-vs-compute balance ("On the Utility of Gradient Compression":
   compression only wins in specific wire-vs-compute regimes, so the
   regime is picked *online*).  The regime inputs come from the
   lineage-derived round-anatomy estimator
   (:meth:`telemetry.anatomy.RoundAnatomy.regime_estimate`) whenever
   lineage is armed — measured wire-stage times from frame
   timestamps, immune to a worker whose beacons are off or skewed —
   with the beacon-median fleet EWMAs as the fallback; the row's
   ``regime_src`` records which source fed it. A renegotiation is an **epoch bump**
   executed through the PR 3 frame handshake: the server installs the
   new :class:`~pytorch_ps_mpi_tpu.parallel.dcn.CodecWire` beside the
   old one and accepts BOTH fingerprints during the transition
   (in-flight old-epoch frames are consumed, never rejected), the new
   epoch is published to the workers via an atomically-replaced
   ``control-epoch.json`` they poll between steps, and the old epoch
   retires once every live worker has pushed on the new one (or the
   settle window lapses). Ladder entries must not exceed the boot
   wire's payload size — transport buffers are sized once at boot.
2. **staleness-aware per-worker LR scaling** from the exact lineage
   staleness distribution: PAPER.md's AsySG-InCon bound shrinks the
   stable LR as staleness grows, so a worker whose observed staleness
   runs above the fleet median gets its pushes de-weighted by
   ``((1 + fleet_p50) / (1 + worker_stale)) ** gamma`` — applied as a
   per-push weight in the serve loop, so no worker-side change is
   required, and restored to 1.0 when its staleness falls back.
3. **auto-evict / readmit**: numerics-quarantined workers get probation
   readmission after a clean probe window (the probation doubles on
   every re-offense); churn-verdict workers are backoff-evicted from
   the sync barrier (their queued pushes are held, the round completes
   degraded over the survivors) and rejoin through the existing
   degraded-round machinery when the backoff lapses.
4. **read-tier tuning**: admission depth follows the shed rate (raised
   under shed pressure while the read p95 holds its target, halved when
   the p95 burns), and the snapshot ring grows on ring-ageout pressure.
5. **structural actions** (rule ``topo``, armed by
   ``cfg["topo_actions"]``): the TOPOLOGY itself becomes an actuator.
   When the PR 15 anatomy advisor ranks ``leader_fold`` as the top
   debottleneck (or one tree leader churns past its respawn latch) the
   hot group is SPLIT — members migrate to a freshly promoted leader
   through ``run_tree``'s pinned-port respawn machinery, every
   in-flight push exactly accounted by the existing degraded-round
   fold (see :mod:`pytorch_ps_mpi_tpu.control.topo`). Shed-rate burn
   scales the PR 17 follower read tier OUT (spawn
   ``serve_readonly --follow-endpoint`` replicas); replica-lag burn or
   a sustained-idle tier scales it back IN. The PR 10 fleet skew
   verdict becomes a recorded shard split/merge PLAN
   (``control-topo.json``) applied at the next generation. Structural
   actions are latched, flap-counted, reversible rows like every other
   rule — ``group_replan`` has ``group_merge``, ``shard_split`` has
   ``shard_merge``, a scale-out has its scale-in.

Every decision is an event row in ``control-<name>.jsonl`` carrying the
**triggering verdict**, the old/new setting, and the worker (when
per-worker); every rule sits behind a cooldown+hysteresis latch
(SLOWatchdog-style) so the controller can never flap
(evict→readmit→evict of one worker inside a cooldown window is counted
in ``flaps`` and must stay 0 — ``tools/control_smoke.py`` pins it).

**Replayability.** The decision core (:class:`ControlEngine`) is a pure
function of its input rows: at every evaluation the live controller
flattens its inputs into one ``{key: float}`` row, persists it through
the PR 10 TSDB (``timeseries-control-<name>.jsonl``, full precision,
every evaluated row — the ingest throttle is bypassed), and feeds the
engine. :meth:`Controller.replay` over those persisted TSDB rows
re-derives the **identical action sequence** — the PR 3 "every decision
is a recorded, replayable event" discipline, now for actions instead of
verdicts. Setpoints come calibrated from the committed perf trajectory
via :func:`telemetry.slo.derive_targets`; explicit
``cfg["control_kw"]["read_p95_target_ms"]`` wins.

Every action row carries its **triggering verdict** with a
monotonically increasing ``id`` and the owning ``rule`` name injected
by the engine itself — the audit join key ``telemetry_report`` uses to
show actions next to the verdicts that caused them (and, being pure
engine state, byte-identical under replay).

Opt-outs: ``control_kw["pin"]`` lists rule names
(``codec``/``lr_scale``/``evict``/``read_tier``/``topo``) whose
settings are pinned — the controller observes but never acts on them.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: tuning knobs and their defaults (overridable via ``cfg["control_kw"]``)
CONTROL_KNOBS: Dict[str, Any] = {
    "eval_every_s": 0.5,       # evaluation cadence (at the serve tick)
    "warmup_s": 2.0,           # observe-only window after the first row
    "window_s": 5.0,           # rate window for counter-derived signals
    "cooldown_s": 10.0,        # default per-rule action cooldown
    "ewma_alpha": 0.25,        # per-worker staleness EWMA (lineage off)
    "pin": (),                 # rule names the controller must not touch
    # -- codec / bucket_mb / agg renegotiation (rule "codec") -----------
    "ladder": None,            # [{"codec","codec_kw","bucket_mb"}, ...];
    #                            entry 0 MUST be the boot wire config;
    #                            None disables the rule entirely
    "wire_hi": 0.65,           # wire fraction above => downshift (idx+1)
    "wire_lo": 0.25,           # wire fraction below => upshift (idx-1)
    "settle_s": 5.0,           # max transition age before forced retire
    "settle_min_s": 1.0,       # min transition age before ANY retire —
    #                            in-flight old-epoch frames get at least
    #                            this grace even when the seen fleet has
    #                            already switched (or, after a server
    #                            restart, is still empty)
    # -- staleness-aware per-worker LR scaling (rule "lr_scale") --------
    "lr_gamma": 1.0,           # weight = ((1+p50)/(1+stale))**gamma
    "lr_min_scale": 0.25,      # weight floor (never mute a worker)
    "lr_step": 0.1,            # min |delta| before a scale action fires
    "lr_stale_margin": 1.0,    # only de-weight past p50 + margin
    # -- auto-evict / readmit (rule "evict") ----------------------------
    "churn_evict": 6.0,        # churn delta per window => barrier evict
    "evict_backoff_s": 5.0,    # eviction span; doubles per repeat
    "evict_backoff_max_s": 120.0,
    "max_evict_frac": 0.5,     # never evict past this fraction of fleet
    "probation_s": 4.0,        # clean window before quarantine readmit
    "probation_factor": 2.0,   # probation doubles per re-offense
    "probation_max_s": 300.0,
    # -- read-tier tuning (rule "read_tier") ----------------------------
    "shed_hi_per_s": 1.0,      # sheds/s above => raise admission depth
    "depth_min": 4,
    "depth_max": 1024,
    "ring_grow_per_s": 0.5,    # ring ageouts/s above => grow the ring
    "ring_max": 64,
    "read_p95_target_ms": None,  # None => slo.derive_targets()
    # -- structural actions (rule "topo"; cfg["topo_actions"] arms) -----
    "topo_actions": False,       # master switch (mirrors cfg key)
    "replan_max": 1,             # group splits per run (spare wid slots)
    "replan_cooldown_s": 20.0,   # min gap between structural replans
    "leader_fold_hot_frac": 0.2,  # advisor saving_frac flagging a hop hot
    "hop_streaming_headroom": 1.2,  # serial/overlap ratio => fix:streaming
    "leader_churn_replan": 2.0,  # leader respawns before a churn replan
    "replica_min": 0,            # read-tier floor (scale-out bootstraps)
    "replica_max": 4,            # read-tier ceiling
    "replica_cooldown_s": 10.0,  # min gap between replica scale steps
    "replica_shed_per_s": 2.0,   # root sheds/s that scale the tier OUT
    "replica_lag_hi": 8.0,       # worst replica lag (versions) => IN
    # freshness-burn scale-out: the fleet's worst-edge age (the
    # freshness plane's serving_age_ms_max rollup) past this wall bound
    # means readers somewhere see a stale model — add serving capacity
    "replica_age_hi_ms": 5000.0,
    "shard_cooldown_s": 30.0,    # min gap between shard plan changes
    "shard_split_skew": 0.5,     # fleet skew spread_frac that splits
    "shard_merge_skew": 0.1,     # spread below which a split merges back
}

#: rule names ``control_kw["pin"]`` accepts
RULES = ("codec", "lr_scale", "evict", "read_tier", "topo")


def epoch_path(control_dir: str) -> str:
    return os.path.join(control_dir, "control-epoch.json")


def actions_path(control_dir: str, name: str) -> str:
    return os.path.join(control_dir, f"control-{name}.jsonl")


def write_epoch(control_dir: str, doc: Dict[str, Any]) -> str:
    """Atomically publish the current wire epoch for the worker fleet
    (write-to-temp + rename — a worker's poll can never read a torn
    document)."""
    os.makedirs(control_dir, exist_ok=True)
    path = epoch_path(control_dir)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def poll_epoch(control_dir: str, state: Dict[str, Any]
               ) -> Optional[Dict[str, Any]]:
    """Worker-side epoch poll: one ``os.stat`` per call (cheap enough
    for every step); parses the document only when the file changed and
    returns it only when it names a NEWER epoch than ``state`` has seen.
    ``state`` is the caller's mutable ``{"epoch": int, "mtime": int}``."""
    path = epoch_path(control_dir)
    try:
        st = os.stat(path)
    except OSError:
        return None
    if st.st_mtime_ns == state.get("mtime"):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        # transient read failure (EMFILE, rename race): do NOT latch
        # the mtime — the next poll must retry, or this worker would
        # silently miss the epoch and be config-rejected after retire
        return None
    state["mtime"] = st.st_mtime_ns
    if not isinstance(doc, dict):
        return None
    if int(doc.get("epoch", 0)) <= int(state.get("epoch", 0)):
        return None
    state["epoch"] = int(doc["epoch"])
    return doc


def ladder_agg_ok(ladder, agg_req: str = "auto") -> List[bool]:
    """Per-rung compressed-domain capability, derived from the codec
    registry under the same exactness policy serve() applies (an
    approximate algebra needs the explicit ``agg == "on"``).
    Deterministic from cfg alone, so live and replayed engines agree on
    whether a retire re-arms aggregation. The serve loop still
    re-validates the REAL wire (per-unit shapes) before folding."""
    out: List[bool] = []
    for e in ladder or ():
        try:
            from pytorch_ps_mpi_tpu.codecs import get_codec

            c = get_codec(e["codec"], **(e.get("codec_kw") or {}))
            ok = bool(getattr(c, "supports_aggregate", False)) and (
                str(agg_req) == "on"
                or getattr(c, "agg_exact", True))
        except Exception:
            ok = False
        out.append(ok)
    return out


def apply_epoch(worker, doc: Dict[str, Any]) -> bool:
    """Apply a polled epoch document to a transport worker: build the
    codec and renegotiate the wire. Returns False when the worker's
    transport declines (tree leaf conns, unframed wires) — the worker
    keeps pushing its old epoch and the server keeps consuming it until
    the old epoch retires."""
    reneg = getattr(worker, "renegotiate", None)
    if reneg is None:
        return False
    from pytorch_ps_mpi_tpu.codecs import get_codec

    code = get_codec(doc["codec"], **(doc.get("codec_kw") or {}))
    return bool(reneg(code, bucket_mb=float(doc.get("bucket_mb", 0.0))))


def _r(v: float, nd: int = 6) -> float:
    """One rounding discipline for every number that lands in an action
    row — replay must reproduce rows byte-identically."""
    return round(float(v), nd)


class _RateWindow:
    """Windowed per-second delta of a monotonic counter fed as (t, v)
    samples — reset-clamped like the TSDB's rate (a counter that resets
    across a server restart reads as 0, not negative)."""

    __slots__ = ("win",)

    def __init__(self, maxlen: int = 64):
        self.win: deque = deque(maxlen=maxlen)

    def rate(self, t: float, v: float, window_s: float) -> float:
        self.win.append((t, v))
        t0 = t - window_s
        pts = [(tt, vv) for tt, vv in self.win if tt >= t0]
        if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
            return 0.0
        return max(0.0, (pts[-1][1] - pts[0][1])
                   / (pts[-1][0] - pts[0][0]))


class ControlEngine:
    """The pure decision core: ``step(row) -> [action rows]``.

    Deterministic by construction — no wall clock, no randomness, no
    live-state reads; everything a rule consults arrives in the input
    row (which is why live and replayed runs derive identical action
    sequences). All mutation is internal latch state.
    """

    def __init__(self, knobs: Dict[str, Any], num_workers: int,
                 *, agg_capable: bool = False,
                 depth: int = 64, ring: int = 8,
                 ladder_idx: int = 0, epoch: int = 0,
                 agg_ok: Optional[List[bool]] = None,
                 seed_transition: bool = False,
                 read_p95_target_ms: Optional[float] = None):
        self.knobs = dict(CONTROL_KNOBS)
        self.knobs.update(knobs or {})
        self.num_workers = int(num_workers)
        self.pin = set(self.knobs.get("pin") or ())
        bad = self.pin - set(RULES)
        if bad:
            raise ValueError(f"unknown pinned rule(s) {sorted(bad)}; "
                             f"rules are {RULES}")
        ladder = self.knobs.get("ladder")
        self.ladder: List[Dict[str, Any]] = (
            [dict(e) for e in ladder] if ladder else [])
        # per-rung compressed-domain capability: agg_on is only emitted
        # at a retire whose rung can actually fold (see ladder_agg_ok)
        self.agg_ok: List[bool] = (
            list(agg_ok) if agg_ok is not None
            else [True] * len(self.ladder))
        self.ladder_idx = int(ladder_idx)
        self.agg_capable = bool(agg_capable)
        self.agg_suspended = False
        self._agg_was_on = False  # re-arm after the transition retires
        self.epoch = int(epoch)
        self.transition_since: Optional[float] = None
        # a restored generation (ladder_idx/epoch from the epoch file)
        # anchors its retiring-transition grace window at the FIRST
        # evaluation's timestamp — engine-side, so replay with the same
        # init reproduces the retire row
        self._seed_transition = bool(seed_transition)
        self.lr_scale: Dict[int, float] = {}
        self.evicted: Dict[int, float] = {}        # worker -> until_t
        self._evict_backoff: Dict[int, float] = {}
        self._evict_span: Dict[int, float] = {}    # span of the CURRENT
        self._evict_guard: Dict[int, float] = {}   # no re-evict before t
        self.probation: Dict[int, Dict[str, float]] = {}
        self._probation_span: Dict[int, float] = {}
        self.depth = int(depth)
        self.ring = int(ring)
        if read_p95_target_ms is not None:
            self.read_p95_target_ms = float(read_p95_target_ms)
        elif self.knobs["read_p95_target_ms"] is not None:
            self.read_p95_target_ms = float(
                self.knobs["read_p95_target_ms"])
        else:
            from pytorch_ps_mpi_tpu.telemetry.slo import derive_targets

            self.read_p95_target_ms = float(
                derive_targets("benchmarks/results",
                               "BENCH_r*.json")["read_p95_ms"])
        # structural-action state (rule "topo"): the engine's intended
        # shape — the executors chase it, never the other way round
        self.replans = 0           # tree group splits in force
        self.replicas = 0          # intended read-tier replica count
        self.shard_extra = 0       # planned shard-count delta (+1/0)
        self._replica_idle_since: Optional[float] = None
        self.topo_actions = 0      # structural action rows emitted
        self.actions: List[Dict[str, Any]] = []
        self.flaps = 0
        self.t0: Optional[float] = None
        self._last_action: Dict[Any, float] = {}
        # flap detection memory: last few (t, old, new) per (rule,worker)
        self._act_hist: Dict[Any, deque] = {}
        self._rates: Dict[str, _RateWindow] = {}

    # -- bookkeeping ------------------------------------------------------
    def _rate(self, key: str, t: float, v: float) -> float:
        rw = self._rates.get(key)
        if rw is None:
            rw = self._rates[key] = _RateWindow()
        return rw.rate(t, v, float(self.knobs["window_s"]))

    def _cooled(self, key: Any, t: float,
                span: Optional[float] = None) -> bool:
        last = self._last_action.get(key)
        span = float(self.knobs["cooldown_s"]) if span is None else span
        return last is None or t - last >= span

    def _act(self, t: float, rule: str, action: str, old: Any, new: Any,
             verdict: Dict[str, Any], worker: Optional[int] = None,
             latch: Any = None) -> Dict[str, Any]:
        # the latch key must be the SAME one the rule's _cooled() check
        # reads, or the cooldown never engages (default: per rule+worker)
        key = (rule, worker) if latch is None else latch
        # flap detection: a DOUBLE reversal on one (rule, worker) inside
        # one cooldown window — e.g. evict→readmit→evict — is a flap. A
        # single reversal (de-weight then restore, evict then backoff
        # readmit) is the reversible-actions contract working, not a
        # flap. The latches are tuned so this never fires; the counter
        # exists so chaos runs can assert it stayed 0.
        hist = self._act_hist.setdefault(key, deque(maxlen=4))
        if (len(hist) >= 2
                and t - hist[-2][0] < float(self.knobs["cooldown_s"])
                and new == hist[-1][1] and hist[-1][2] == hist[-2][1]):
            self.flaps += 1
        hist.append((t, old, new))
        # audit join key: every verdict carries a monotone id + the
        # owning rule — engine state, so replay reproduces both
        verdict = {"id": len(self.actions), "rule": rule, **verdict}
        row: Dict[str, Any] = {
            "t": _r(t, 4), "rule": rule, "action": action,
            "old": old, "new": new, "verdict": verdict,
        }
        if worker is not None:
            row["worker"] = int(worker)
        if rule == "topo":
            self.topo_actions += 1
        self.actions.append(row)
        self._last_action[key] = t
        return row

    # -- the sweep --------------------------------------------------------
    def step(self, row: Dict[str, Any]) -> List[Dict[str, Any]]:
        """One evaluation over a flat input row. Returns the NEW action
        rows (usually empty)."""
        t = float(row["ts"])
        if self.t0 is None:
            self.t0 = t
        if self._seed_transition:
            self.transition_since = t
            self._seed_transition = False
        n0 = len(self.actions)
        warm = t - self.t0 >= float(self.knobs["warmup_s"])
        self._step_codec(row, t, warm)
        if warm:
            self._step_lr(row, t)
            self._step_evict(row, t)
            self._step_read_tier(row, t)
            self._step_topo(row, t)
        return self.actions[n0:]

    # -- rule: codec / bucket_mb / agg renegotiation ----------------------
    def _step_codec(self, row: Dict[str, Any], t: float,
                    warm: bool) -> None:
        if not self.ladder or "codec" in self.pin:
            return
        # transition retire runs even during warmup (a transition only
        # exists because an action already fired)
        if self.transition_since is not None:
            pending = row.get("epoch_pending", 0.0)
            age = t - self.transition_since
            aged = age >= float(self.knobs["settle_s"])
            settled = (pending <= 0
                       and age >= float(self.knobs["settle_min_s"]))
            if settled or aged:
                self._act(t, "codec", "epoch_retire",
                          self.epoch - 1, self.epoch,
                          {"kind": "transition_done",
                           "epoch_pending": _r(pending),
                           "settled": bool(pending <= 0)})
                self.transition_since = None
                if self._agg_was_on:
                    if (0 <= self.ladder_idx < len(self.agg_ok)
                            and self.agg_ok[self.ladder_idx]):
                        self._agg_was_on = False
                        self.agg_suspended = False
                        self._act(t, "codec", "agg_on", 0.0, 1.0,
                                  {"kind": "transition_done",
                                   "epoch": self.epoch})
                    # else: this rung cannot fold — aggregation STAYS
                    # suspended (truthfully: no agg_on row, agg_mode 0)
                    # until a later transition lands on a capable rung
            return
        if not warm:
            return
        wire_s = float(row.get("wire_s", 0.0))
        compute_s = float(row.get("compute_s", 0.0))
        total = wire_s + compute_s
        frac = wire_s / total if total > 0 else None
        down = (frac is not None and frac > float(self.knobs["wire_hi"])
                and self.ladder_idx + 1 < len(self.ladder))
        up = (frac is not None and frac < float(self.knobs["wire_lo"])
              and self.ladder_idx > 0)
        if not (down or up):
            if (self.agg_suspended and self._agg_was_on
                    and 0 <= self.ladder_idx < len(self.agg_ok)
                    and self.agg_ok[self.ladder_idx]
                    and self._cooled(("codec", None), t)):
                # an agg_off whose renegotiation never materialized (the
                # balance fell back in band before the cooled re-check):
                # abandon it and re-arm, or the run pays decode-sum cost
                # forever on one noisy evaluation
                self.agg_suspended = False
                self._agg_was_on = False
                self._act(t, "codec", "agg_on", 0.0, 1.0,
                          {"kind": "renegotiation_abandoned",
                           "wire_frac": (None if frac is None
                                         else _r(frac))})
            return
        if not self._cooled(("codec", None), t):
            return
        verdict = {"kind": "wire_bound" if down else "compute_bound",
                   "wire_frac": _r(frac), "wire_s": _r(wire_s),
                   "compute_s": _r(compute_s)}
        if self.agg_capable and not self.agg_suspended:
            # step 1 of a renegotiation under armed aggregation: suspend
            # the compressed-domain fold first (the serve loop drains
            # its raw round queues on the decode path), bump the epoch
            # at the NEXT cooled evaluation
            self.agg_suspended = True
            self._agg_was_on = True
            self._act(t, "codec", "agg_off", 1.0, 0.0, verdict)
            return
        old_i, new_i = self.ladder_idx, (
            self.ladder_idx + 1 if down else self.ladder_idx - 1)
        self.ladder_idx = new_i
        self.epoch += 1
        self.transition_since = t
        self._act(t, "codec", "renegotiate",
                  self._ladder_name(old_i), self._ladder_name(new_i),
                  {**verdict, "epoch": self.epoch})

    def _ladder_name(self, i: int) -> str:
        e = self.ladder[i]
        name = str(e.get("codec"))
        if e.get("bucket_mb"):
            name += f"@{e['bucket_mb']}mb"
        return name

    # -- rule: staleness-aware per-worker LR scaling ----------------------
    def _step_lr(self, row: Dict[str, Any], t: float) -> None:
        if "lr_scale" in self.pin:
            return
        p50 = float(row.get("stale_p50", 0.0))
        gamma = float(self.knobs["lr_gamma"])
        lo = float(self.knobs["lr_min_scale"])
        step = float(self.knobs["lr_step"])
        margin = float(self.knobs["lr_stale_margin"])
        for w in range(self.num_workers):
            stale = float(row.get(f"w{w}_stale", 0.0))
            if stale > p50 + margin:
                target = max(lo, min(
                    1.0, ((1.0 + p50) / (1.0 + stale)) ** gamma))
            else:
                target = 1.0  # staleness back in band: restore full LR
            target = _r(target, 3)
            cur = self.lr_scale.get(w, 1.0)
            if abs(target - cur) < step or not self._cooled(
                    ("lr_scale", w), t):
                continue
            self.lr_scale[w] = target
            self._act(t, "lr_scale", "scale", cur, target,
                      {"kind": "stale", "worker_stale": _r(stale),
                       "fleet_p50": _r(p50), "gamma": gamma}, worker=w)

    # -- rule: auto-evict / readmit ---------------------------------------
    def _step_evict(self, row: Dict[str, Any], t: float) -> None:
        if "evict" in self.pin:
            return
        k = self.knobs
        # quarantine probation readmission
        for w in range(self.num_workers):
            quar = row.get(f"w{w}_quar", 0.0) > 0
            if not quar:
                self.probation.pop(w, None)
                continue
            pr = self.probation.get(w)
            if pr is None:
                span = self._probation_span.get(
                    w, float(k["probation_s"]))
                self.probation[w] = {"since": t, "span": span,
                                     "nonf": row.get(f"w{w}_nonfinite",
                                                     0.0)}
                continue
            if row.get(f"w{w}_nonfinite", 0.0) > pr["nonf"]:
                # new offense while quarantined: restart the clean
                # window (and lengthen the next one)
                pr["since"] = t
                pr["nonf"] = row.get(f"w{w}_nonfinite", 0.0)
                continue
            if t - pr["since"] >= pr["span"]:
                self._probation_span[w] = min(
                    float(k["probation_max_s"]),
                    pr["span"] * float(k["probation_factor"]))
                self.probation.pop(w, None)
                self._act(t, "evict", "readmit_quarantine", 1.0, 0.0,
                          {"kind": "probation_clean",
                           "clean_s": _r(t - pr["since"]),
                           "nonfinite": _r(pr["nonf"]),
                           "next_probation_s": _r(
                               self._probation_span[w])}, worker=w)
        # churn-verdict barrier eviction / backoff readmission
        max_evicted = max(1, int(self.num_workers
                                 * float(k["max_evict_frac"])))
        for w in range(self.num_workers):
            until = self.evicted.get(w)
            if until is not None:
                if t >= until:
                    span = self._evict_span.get(
                        w, float(k["evict_backoff_s"]))
                    del self.evicted[w]
                    # re-evict guard: the flap window — churn must
                    # re-accumulate for a full backoff before this
                    # worker can be evicted again
                    self._evict_guard[w] = t + span
                    self._act(t, "evict", "readmit", 1.0, 0.0,
                              {"kind": "backoff_elapsed",
                               "evicted_s": _r(span)}, worker=w)
                continue
            churn_rate = self._rate(f"w{w}_churn", t,
                                    float(row.get(f"w{w}_churn", 0.0)))
            churn_delta = churn_rate * float(k["window_s"])
            if (churn_delta >= float(k["churn_evict"])
                    and len(self.evicted) < max_evicted
                    and t >= self._evict_guard.get(w, -1e18)
                    and self._cooled(("evict", w), t)):
                backoff = self._evict_backoff.get(
                    w, float(k["evict_backoff_s"]))
                self.evicted[w] = t + backoff
                self._evict_span[w] = backoff
                self._evict_backoff[w] = min(
                    float(k["evict_backoff_max_s"]), backoff * 2.0)
                self._act(t, "evict", "evict", 0.0, 1.0,
                          {"kind": "churning",
                           "churn_per_window": _r(churn_delta),
                           "backoff_s": _r(backoff)}, worker=w)

    # -- rule: read-tier tuning -------------------------------------------
    def _step_read_tier(self, row: Dict[str, Any], t: float) -> None:
        if "read_tier" in self.pin or row.get("serving", 0.0) <= 0:
            return
        k = self.knobs
        shed_rate = self._rate("reads_shed", t,
                               float(row.get("reads_shed", 0.0)))
        p95 = float(row.get("read_p95_ms", 0.0))
        target = self.read_p95_target_ms
        if (p95 > target and self.depth > int(k["depth_min"])
                and self._cooled(("read_tier", "depth"), t)):
            old = self.depth
            self.depth = max(int(k["depth_min"]), self.depth // 2)
            self._act(t, "read_tier", "depth", old, self.depth,
                      {"kind": "read_p95_burn", "read_p95_ms": _r(p95),
                       "target_ms": _r(target)},
                      latch=("read_tier", "depth"))
        elif (shed_rate > float(k["shed_hi_per_s"])
              and p95 < 0.8 * target
              and self.depth < int(k["depth_max"])
              and self._cooled(("read_tier", "depth"), t)):
            old = self.depth
            self.depth = min(int(k["depth_max"]), self.depth * 2)
            self._act(t, "read_tier", "depth", old, self.depth,
                      {"kind": "shed_pressure",
                       "sheds_per_s": _r(shed_rate),
                       "read_p95_ms": _r(p95), "target_ms": _r(target)},
                      latch=("read_tier", "depth"))
        ageout_rate = self._rate("ring_ageouts", t,
                                 float(row.get("ring_ageouts", 0.0)))
        if (ageout_rate > float(k["ring_grow_per_s"])
                and self.ring < int(k["ring_max"])
                and self._cooled(("read_tier", "ring"), t)):
            old = self.ring
            self.ring = min(int(k["ring_max"]), self.ring * 2)
            self._act(t, "read_tier", "ring", old, self.ring,
                      {"kind": "ring_thrash",
                       "ageouts_per_s": _r(ageout_rate)},
                      latch=("read_tier", "ring"))

    # -- rule: structural actions (topology as an actuator) ---------------
    def _step_topo(self, row: Dict[str, Any], t: float) -> None:
        if not self.knobs.get("topo_actions") or "topo" in self.pin:
            return
        k = self.knobs
        # (a) tree re-plan: the advisor's ranked debottleneck decides —
        # a replan only fires when leader_fold is the TOP stage and its
        # projected saving clears the hot threshold (or a leader churns
        # past the respawn latch: respawn loops are structural too)
        if row.get("tree_groups", 0.0) > 0:
            hot = int(row.get("hot_group", -1.0))
            churn_grp = int(row.get("hot_churn_group", -1.0))
            saving = float(row.get("lf_saving_frac", 0.0))
            fold_hot = (row.get("lf_top", 0.0) > 0 and hot >= 0
                        and saving >= float(k["leader_fold_hot_frac"]))
            churn_hot = (churn_grp >= 0
                         and float(row.get("leader_respawns", 0.0))
                         >= float(k["leader_churn_replan"]))
            if (self.replans < int(k["replan_max"])
                    and (fold_hot or churn_hot)
                    and self._cooled(("topo", "replan"), t,
                                     float(k["replan_cooldown_s"]))):
                self.replans += 1
                if fold_hot:
                    verdict = {"kind": "leader_fold_hot", "group": hot,
                               "saving_frac": _r(saving)}
                    if row.get("hop_rounds", 0.0) > 0:
                        # hop anatomy refines the verdict: a serial
                        # pipeline with real streaming headroom wants
                        # an overlapped hop, not more leaders; a busy
                        # pipeline with no headroom wants the split
                        headroom = float(
                            row.get("hop_headroom_ratio", 1.0))
                        verdict["fix"] = (
                            "streaming"
                            if headroom
                            >= float(k["hop_streaming_headroom"])
                            else "split")
                        verdict["hop_busy_frac"] = _r(
                            row.get("hop_busy_frac", 0.0))
                        verdict["hop_headroom_ratio"] = _r(headroom)
                else:
                    verdict = {"kind": "leader_churn",
                               "group": churn_grp,
                               "respawns": _r(row.get(
                                   "leader_respawns", 0.0))}
                self._act(t, "topo", "group_replan",
                          self.replans - 1, self.replans, verdict,
                          latch=("topo", "replan"))
            elif (self.replans > 0 and not fold_hot and not churn_hot
                  # merge hysteresis: the hop must be COLD (saving well
                  # under the split threshold) for a doubled cooldown —
                  # a split that merges back on one quiet window would
                  # be the replan-storm failure mode
                  and saving < 0.5 * float(k["leader_fold_hot_frac"])
                  and self._cooled(("topo", "replan"), t,
                                   2.0 * float(k["replan_cooldown_s"]))):
                self.replans -= 1
                self._act(t, "topo", "group_merge",
                          self.replans + 1, self.replans,
                          {"kind": "hotspot_cleared",
                           "saving_frac": _r(saving)},
                          latch=("topo", "replan"))
        # (b) elastic read tier: shed burn scales OUT, replica-lag burn
        # or a sustained-idle tier scales IN — replicas are actuators,
        # not hand-sized cfg
        if row.get("serving", 0.0) > 0 and int(k["replica_max"]) > 0:
            shed_rate = self._rate("topo_reads_shed", t,
                                   float(row.get("reads_shed", 0.0)))
            lag = float(row.get("replica_lag_max", 0.0))
            # freshness burn: the worst edge's age-of-information (the
            # fleet serving_age_ms_max rollup, persisted in THIS row)
            edge_age = float(row.get("edge_age_ms", 0.0))
            age_hot = edge_age >= float(k["replica_age_hi_ms"])
            if (shed_rate > 0 or age_hot
                    or self.replicas <= int(k["replica_min"])):
                self._replica_idle_since = None
            elif self._replica_idle_since is None:
                self._replica_idle_since = t
            idle = (self._replica_idle_since is not None
                    and t - self._replica_idle_since
                    >= 2.0 * float(k["replica_cooldown_s"]))
            if (self.replicas < int(k["replica_max"])
                    and (shed_rate >= float(k["replica_shed_per_s"])
                         or age_hot
                         or self.replicas < int(k["replica_min"]))
                    and self._cooled(("topo", "replica"), t,
                                     float(k["replica_cooldown_s"]))):
                old = self.replicas
                self.replicas += 1
                if shed_rate >= float(k["replica_shed_per_s"]):
                    verdict = {"kind": "shed_pressure",
                               "sheds_per_s": _r(shed_rate)}
                elif age_hot:
                    verdict = {"kind": "edge_age_burn",
                               "edge_age_ms": _r(edge_age)}
                else:
                    verdict = {"kind": "tier_floor",
                               "replica_min": int(k["replica_min"])}
                self._act(t, "topo", "replica", old, self.replicas,
                          verdict, latch=("topo", "replica"))
            elif (self.replicas > int(k["replica_min"])
                  and (lag >= float(k["replica_lag_hi"]) or idle)
                  and self._cooled(("topo", "replica"), t,
                                   float(k["replica_cooldown_s"]))):
                old = self.replicas
                self.replicas -= 1
                if lag >= float(k["replica_lag_hi"]):
                    verdict = {"kind": "replica_lag_burn",
                               "lag_versions": _r(lag)}
                else:
                    verdict = {"kind": "tier_idle",
                               "idle_s": _r(t - self._replica_idle_since)}
                self._act(t, "topo", "replica", old, self.replicas,
                          verdict, latch=("topo", "replica"))
        # (c) shard split/merge: the PR 10 fleet skew verdict becomes a
        # recorded PLAN (control-topo.json; applied at the next
        # generation through sharded.planned_shards) — never a live
        # migration
        shards = int(row.get("shards_n", 0.0))
        if shards >= 2:
            skew = float(row.get("shard_skew", 0.0))
            if (self.shard_extra == 0
                    and row.get("shard_skew_hot", 0.0) > 0
                    and skew >= float(k["shard_split_skew"])
                    and self._cooled(("topo", "shard"), t,
                                     float(k["shard_cooldown_s"]))):
                self.shard_extra = 1
                self._act(t, "topo", "shard_split", shards, shards + 1,
                          {"kind": "shard_skew",
                           "spread_frac": _r(skew)},
                          latch=("topo", "shard"))
            elif (self.shard_extra > 0
                  and skew <= float(k["shard_merge_skew"])
                  and self._cooled(("topo", "shard"), t,
                                   2.0 * float(k["shard_cooldown_s"]))):
                self.shard_extra = 0
                self._act(t, "topo", "shard_merge", shards + 1, shards,
                          {"kind": "skew_cleared",
                           "spread_frac": _r(skew)},
                          latch=("topo", "shard"))

    # -- surfaces ---------------------------------------------------------
    def lr_scale_min(self) -> float:
        return min(self.lr_scale.values()) if self.lr_scale else 1.0

    def snapshot(self) -> Dict[str, Any]:
        return {
            "actions_total": len(self.actions),
            "flaps": self.flaps,
            "epoch": self.epoch,
            "ladder": [self._ladder_name(i)
                       for i in range(len(self.ladder))],
            "ladder_idx": self.ladder_idx,
            "transition_active": self.transition_since is not None,
            "agg_suspended": self.agg_suspended,
            "lr_scale": {int(w): v for w, v in sorted(
                self.lr_scale.items())},
            "evicted": sorted(self.evicted),
            "probation": sorted(self.probation),
            "admission_depth": self.depth,
            "ring": self.ring,
            "read_p95_target_ms": _r(self.read_p95_target_ms, 3),
            "pinned": sorted(self.pin),
            "topo_armed": bool(self.knobs.get("topo_actions")),
            "topo_actions": self.topo_actions,
            "group_replans": self.replans,
            "replicas": self.replicas,
            "shard_extra": self.shard_extra,
            "recent_actions": self.actions[-8:],
        }


class Controller:
    """The live half: builds input rows from the attached server +
    monitors, persists them through the TSDB, feeds the
    :class:`ControlEngine`, and EXECUTES the actions it emits.

    Construction mirrors the monitors (``Controller(server, cfg)``
    attaches ``server.controller`` and registers scrape instruments);
    feed points are :meth:`observe_push` at the serve loop's consume
    site and :meth:`tick` at its tick cadence — both same-thread with
    the transport pumps.
    """

    def __init__(self, server, cfg: Optional[Dict[str, Any]] = None,
                 *, core=None, name: str = "server", **overrides: Any):
        cfg = cfg or {}
        self.knobs = dict(CONTROL_KNOBS)
        self.knobs.update(cfg.get("control_kw") or {})
        self.knobs.update(overrides)
        # the structural-action switch is a TOP-LEVEL cfg key (callers
        # arm it like cfg["control"]); the knob mirrors it so the pure
        # engine sees one boolean — replay() derives it the same way
        if cfg.get("topo_actions"):
            self.knobs["topo_actions"] = True
        self.server = server
        self.core = core if core is not None else getattr(
            server, "serving_core", None)
        self.name = str(name)
        self.num_workers = int(server.num_workers)
        self.cfg = cfg
        self.dir = (cfg.get("control_dir") or cfg.get("telemetry_dir"))
        ladder = self.knobs.get("ladder")
        if ladder:
            self._check_ladder(ladder)
            if (not getattr(server, "frame", False)
                    or getattr(server, "wire", None) is None
                    or getattr(server, "tree_slots", 0)):
                # a wire that cannot renegotiate (unframed, codec-less,
                # or a tree trailer wire whose hop codec is the tree's
                # own agreement) must not run the codec rule at all —
                # the engine's epoch would drift fictitiously while
                # every execution failed
                print("control: codec ladder disabled — this wire "
                      "cannot renegotiate (needs frame_check + a codec "
                      "wire, non-tree)", flush=True)
                ladder = None
                self.knobs["ladder"] = None
        if ladder:
            if not self.dir:
                # without the epoch file the workers can never learn a
                # new epoch: the forced settle-window retire would then
                # config-reject the whole fleet forever — fail at
                # construction, not mid-run
                raise ValueError(
                    "a codec ladder needs cfg['control_dir'] (or "
                    "telemetry_dir): workers poll control-epoch.json "
                    "there to follow renegotiations")
            # every rung must fit the boot wire's frame size NOW: a rung
            # that only failed inside the action executor would leave
            # the engine's ladder_idx/epoch permanently diverged from
            # the real wire (the executor swallows exceptions by design)
            self._check_ladder_sizes(server, ladder)
        depth = (self.core.admission_depth if self.core is not None
                 else int(CONTROL_KNOBS["depth_min"]))
        ring = (int(self.core.knobs["ring"]) if self.core is not None
                else 8)
        self.engine = ControlEngine(
            self.knobs, self.num_workers,
            agg_capable=False,  # serve() calls set_agg before the loop
            depth=depth, ring=ring,
            agg_ok=ladder_agg_ok(self.knobs.get("ladder"),
                                 str(cfg.get("agg", "auto"))))
        # elastic read tier: the replica scaler is built lazily at the
        # first scale action (the core's read listener may bind after
        # construction) — see _replica_scaler()
        self._replicas = None
        # per-worker staleness EWMAs — the lineage-off fallback input
        # (exact per-push staleness windows win when lineage is armed)
        self._stale_ewma: Dict[int, Optional[float]] = {}
        self._last_eval = 0.0
        self.exec_errors = 0
        self.overhead_s = 0.0

        self.history = None
        self._actions_f = None
        self.actions_file: Optional[str] = None
        if self.dir:
            from pytorch_ps_mpi_tpu.telemetry.timeseries import (
                MetricsHistory,
            )

            self.history = MetricsHistory(
                dir=self.dir, name=f"control-{self.name}")
            self.actions_file = actions_path(self.dir, self.name)
            os.makedirs(self.dir, exist_ok=True)
            self._actions_f = open(self.actions_file, "a")
        server.controller = self
        reg = getattr(server, "scrape_registry", None)
        if reg is not None:
            self.register(reg())
        # a supervisor-restarted server generation rejoins the fleet's
        # current wire epoch: the epoch file outlives the generation
        self._restore_epoch()

    @staticmethod
    def _check_ladder(ladder) -> None:
        for i, e in enumerate(ladder):
            if not isinstance(e, dict) or not e.get("codec"):
                raise ValueError(
                    f"control ladder entry {i} must be a dict with a "
                    f"'codec' name, got {e!r}")

    @staticmethod
    def _check_ladder_sizes(server, ladder) -> None:
        """Build each rung's wire against the server template and check
        it fits the boot frame (the same cap ``renegotiate_wire``
        enforces) — one eval_shape pass per rung, at construction."""
        from pytorch_ps_mpi_tpu.codecs import get_codec
        from pytorch_ps_mpi_tpu.parallel.dcn import CodecWire

        boot = int(server._expected_payload)
        for i, e in enumerate(ladder):
            code = get_codec(e["codec"], **(e.get("codec_kw") or {}))
            w = CodecWire(code, server.template,
                          bucket_mb=float(e.get("bucket_mb", 0.0)))
            if w.wire_bytes > boot:
                raise ValueError(
                    f"control ladder entry {i} ({e['codec']!r}) needs "
                    f"{w.wire_bytes} B payloads but the boot wire (and "
                    f"every transport buffer) was sized for {boot} B — "
                    "ladder entries must not exceed the boot wire")

    # -- properties the serve loop reads ----------------------------------
    @property
    def agg_suspended(self) -> bool:
        return self.engine.agg_suspended

    @property
    def epoch(self) -> int:
        return self.engine.epoch

    @property
    def actions_total(self) -> int:
        return len(self.engine.actions)

    @property
    def flaps(self) -> int:
        return self.engine.flaps

    @property
    def evicted(self):
        return self.engine.evicted

    @property
    def topo_actions_total(self) -> int:
        return self.engine.topo_actions

    @property
    def group_replans(self) -> int:
        return self.engine.replans

    @property
    def replicas_live(self) -> int:
        """REAL live replica processes (the scaler's truth), not the
        engine's intent — a failed spawn shows up as the gap."""
        return self._replicas.live if self._replicas is not None else 0

    def lr_scale_min(self) -> float:
        return self.engine.lr_scale_min()

    def push_weight(self, worker: int) -> float:
        """The per-push LR weight the serve loop applies — 1.0 unless
        the lr_scale rule has de-weighted this worker."""
        return self.engine.lr_scale.get(int(worker), 1.0)

    def is_evicted(self, worker: int) -> bool:
        return int(worker) in self.engine.evicted

    def set_agg(self, armed: bool) -> None:
        """serve() reports whether compressed-domain aggregation is
        armed — a codec renegotiation then sequences agg_off → epoch
        bump → retire → agg_on."""
        self.engine.agg_capable = bool(armed)

    # -- feed points ------------------------------------------------------
    def observe_push(self, worker: int, staleness: int) -> None:
        """O(1) per consumed push (the same consume site that feeds the
        HealthMonitor): per-worker staleness EWMA — the lr_scale input
        when lineage's exact windows are not armed."""
        w = int(worker)
        a = float(self.knobs["ewma_alpha"])
        v = self._stale_ewma.get(w)
        self._stale_ewma[w] = (float(staleness) if v is None
                               else v + a * (staleness - v))

    def tick(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation (self-throttled to ``eval_every_s``): build
        the input row, persist it, run the engine, execute the new
        actions. Returns the new action rows."""
        t = time.time() if now is None else float(now)
        if t - self._last_eval < float(self.knobs["eval_every_s"]):
            return []
        t0 = time.thread_time()
        self._last_eval = t
        row = self._input_row(t)
        if self.history is not None:
            # force: every engine-evaluated row must persist, or replay
            # would see fewer rows than the live engine did. The one
            # case force cannot bypass — a non-monotone wall clock
            # (NTP step) — must then skip the evaluation too: an
            # unpersisted row feeding the engine would break the
            # byte-identical replay contract.
            if not self.history.sample(row, now=t, force=True):
                self.overhead_s += time.thread_time() - t0
                return []
        actions = self.engine.step(row)
        for a in actions:
            self._record(a)
            self._execute(a)
        self.overhead_s += time.thread_time() - t0
        return actions

    # -- input row --------------------------------------------------------
    def _input_row(self, t: float) -> Dict[str, float]:
        server = self.server
        m = server.metrics()
        row: Dict[str, float] = {
            "ts": t,
            "stale_p50": m["staleness_p50"],
            "stale_p95": m["staleness_p95"],
            "stale_drops": m["stale_drops"],
            "grads_received": m["grads_received"],
            "frames_rejected": m["frames_rejected"],
            "push_e2e_p95_ms": m["push_e2e_p95_ms"],
            "reads_shed": m["reads_shed"],
            "read_p95_ms": m["read_p95_ms"],
            "decodes_per_publish": m["decodes_per_publish"],
            "serving": 1.0 if (self.core is not None
                               and self.core.armed) else 0.0,
            "ring_ageouts": float(self.core.ring_ageouts
                                  if self.core is not None else 0.0),
            "epoch_pending": float(self._epoch_pending()),
        }
        hm = getattr(server, "health_monitor", None)
        nm = getattr(server, "numerics_monitor", None)
        lt = getattr(server, "lineage_tracker", None)
        an = getattr(server, "anatomy", None)
        # wire-vs-compute regime: the lineage-derived round-anatomy
        # estimator wins when armed and warmed — it measures the wire
        # stage from frame timestamps (clock-corrected), so a worker
        # whose BEACONS are off or skewed cannot hide a wire-bound
        # fleet.  Beacon medians are the fallback.  Either way the
        # numbers land in THIS persisted row, so replay consumes the
        # estimator's output byte-identically without knowing which
        # source produced it.
        est = an.regime_estimate() if an is not None else None
        if est is not None:
            row["compute_s"] = float(est["compute_s"])
            row["wire_s"] = float(est["wire_s"])
            row["regime_src"] = 1.0  # 1 = lineage anatomy, 0 = beacons
        else:
            compute, wire = [], []
            if hm is not None:
                for h in hm._w:
                    if h.compute_ewma.value is not None:
                        compute.append(h.compute_ewma.value)
                    if h.wire_ewma.value is not None:
                        wire.append(h.wire_ewma.value)

            def _med(xs):
                # fleet MEDIAN, not mean: one compute-bound straggler
                # must not mask a wire-bound fleet (the same robustness
                # argument as the diagnosis layer's median+MAD gates) —
                # the codec rule picks the regime for the FLEET
                s = sorted(xs)
                n = len(s)
                return (s[n // 2] if n % 2
                        else 0.5 * (s[n // 2 - 1] + s[n // 2])) if s else 0.0

            row["compute_s"] = _med(compute)
            row["wire_s"] = _med(wire)
            row["regime_src"] = 0.0
        respawns = getattr(server, "_supervisor_respawns", None) or {}
        for w in range(self.num_workers):
            if lt is not None and lt._w[w].stale_win:
                win = sorted(lt._w[w].stale_win)
                stale = float(win[min(len(win) - 1,
                                      int(round(0.95 * (len(win) - 1))))])
            else:
                stale = float(self._stale_ewma.get(w) or 0.0)
            row[f"w{w}_stale"] = stale
            row[f"w{w}_quar"] = (1.0 if nm is not None
                                 and nm.is_quarantined(w) else 0.0)
            row[f"w{w}_nonfinite"] = float(
                nm._w[w].nonfinite if nm is not None else 0.0)
            churn = float(server.frames_rejected.get(w, 0))
            churn += 2.0 * float(respawns.get(w, 0))
            if hm is not None:
                churn += float(hm._w[w].retries + hm._w[w].reconnects)
            row[f"w{w}_churn"] = churn
            row[f"w{w}_grads"] = float(
                hm._w[w].grads if hm is not None else 0.0)
        if self.knobs.get("topo_actions"):
            row.update(self._topo_inputs(an))
        return row

    def _topo_inputs(self, an) -> Dict[str, float]:
        """Structural-rule inputs, flattened into the persisted row —
        the topo rule replays from THESE numbers, never from live state.
        ``topo_state`` is the run_tree supervisor's shape bulletin
        (groups in force, leader respawn churn); the advisor supplies
        the ranked leader_fold saving; the fleet poller supplies shard
        skew and the worst replica's lag."""
        server = self.server
        ts = getattr(server, "topo_state", None) or {}
        out: Dict[str, float] = {
            "tree_groups": float(ts.get("groups", 0.0)),
            "leader_respawns": float(ts.get("leader_respawns", 0.0)),
            "hot_churn_group": float(ts.get("hot_churn_group", -1.0)),
        }
        lf_top, lf_saving, hot_group = 0.0, 0.0, -1.0
        if an is not None:
            adv = an.advisor()
            if adv and adv[0].get("stage") == "leader_fold":
                lf_top = 1.0
            lf = next((a for a in adv
                       if a.get("stage") == "leader_fold"), None)
            if lf is not None:
                lf_saving = float((lf.get("debottleneck") or {}).get(
                    "saving_frac", 0.0))
            hot = an.hot_hop()
            if hot is not None:
                hot_group = float(hot)
        out["lf_top"] = lf_top
        out["lf_saving_frac"] = lf_saving
        out["hot_group"] = hot_group
        # hop-anatomy occupancy plane (0.0 / 1.0 neutral when unarmed —
        # hop_rounds==0 keeps the topo rule byte-identical to a run
        # without hop tracing)
        ha = getattr(server, "hop_anatomy", None)
        hop_rounds = hop_busy = 0.0
        hop_headroom = 1.0
        if ha is not None and ha.rounds:
            hop_rounds = float(ha.rounds)
            hop_busy = float(ha.busy_frac())
            hop_headroom = float(ha.headroom_ratio())
        out["hop_rounds"] = hop_rounds
        out["hop_busy_frac"] = hop_busy
        out["hop_headroom_ratio"] = hop_headroom
        out["replicas_live"] = float(self.replicas_live)
        lag = skew = skew_hot = shards = edge_age = 0.0
        fm = getattr(server, "fleet_monitor", None)
        if fm is not None:
            try:
                snap = fm.poll()
            except Exception:
                snap = None
            if snap and snap.get("armed"):
                fleet = snap.get("fleet") or {}
                lag = float(fleet.get("replica_lag_versions_max", 0.0))
                # worst-edge age-of-information: the freshness plane's
                # fleet rollup — the evidence behind edge_age_burn
                edge_age = float(fleet.get("serving_age_ms_max", 0.0))
                shards = float(sum(
                    1 for m in (snap.get("members") or {}).values()
                    if m.get("ok") and m.get("role") == "shard"))
                for v in (snap.get("skew") or {}).values():
                    skew = max(skew, float(v.get("spread_frac", 0.0)))
                    if v.get("flagged"):
                        skew_hot = 1.0
        out["replica_lag_max"] = lag
        out["edge_age_ms"] = edge_age
        out["shard_skew"] = skew
        out["shard_skew_hot"] = skew_hot
        out["shards_n"] = shards
        return out

    def _epoch_pending(self) -> int:
        """Live workers still pushing an older epoch (0 outside a
        transition) — the retire signal."""
        table = getattr(self.server, "_epoch_table", None)
        if not table:
            return 0
        seen = getattr(self.server, "_epoch_seen", {})
        cur = getattr(self.server, "_epoch", 0)
        pending = 0
        for w in range(self.num_workers):
            if w not in self.server.last_seen:
                continue  # never-seen workers are the supervisor's story
            if seen.get(w, 0) < cur:
                pending += 1
        return pending

    # -- action recording + execution -------------------------------------
    def _record(self, action: Dict[str, Any]) -> None:
        if self._actions_f is not None:
            self._actions_f.write(json.dumps(action) + "\n")
            self._actions_f.flush()
        from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

        record_event("control.action", rule=action["rule"],
                     action=action["action"],
                     worker=action.get("worker"),
                     old=str(action["old"]), new=str(action["new"]))

    def _execute(self, action: Dict[str, Any]) -> None:
        """Apply one engine action to the live system. Failures are
        counted, never propagated — a broken actuator must not take the
        serve loop down (and the recorded row stays the engine's
        deterministic decision, not the execution outcome)."""
        try:
            self._execute_inner(action)
        except Exception as e:  # pragma: no cover - defensive
            self.exec_errors += 1
            from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

            record_event("control.exec_error", rule=action["rule"],
                         action=action["action"], error=str(e))

    def _execute_inner(self, action: Dict[str, Any]) -> None:
        rule, act = action["rule"], action["action"]
        if rule == "codec":
            if act == "renegotiate":
                entry = self.engine.ladder[self.engine.ladder_idx]
                from pytorch_ps_mpi_tpu.codecs import get_codec

                code = get_codec(entry["codec"],
                                 **(entry.get("codec_kw") or {}))
                self.server.renegotiate_wire(
                    code, bucket_mb=float(entry.get("bucket_mb", 0.0)))
                if self.dir:
                    write_epoch(self.dir, {
                        "epoch": self.engine.epoch,
                        "codec": entry["codec"],
                        "codec_kw": entry.get("codec_kw") or {},
                        "bucket_mb": float(entry.get("bucket_mb", 0.0)),
                    })
            elif act == "epoch_retire":
                fin = getattr(self.server, "finish_renegotiation", None)
                if fin is not None:
                    fin()
            # agg_off / agg_on: pure engine state; the serve loop reads
            # ctl.agg_suspended at its round sites
        elif rule == "evict":
            if act == "readmit_quarantine":
                nm = getattr(self.server, "numerics_monitor", None)
                if nm is not None:
                    nm.readmit(int(action["worker"]))
            # evict / readmit: engine state read by the sync barrier
        elif rule == "read_tier":
            if self.core is None:
                return
            if act == "depth":
                self.core.set_admission_depth(int(action["new"]))
            elif act == "ring":
                self.core.set_ring(int(action["new"]))
        elif rule == "topo":
            if act in ("group_replan", "group_merge"):
                # the run_tree supervisor installed the actuator: it
                # owns the leader processes and the pinned ports
                ta = getattr(self.server, "topo_actuator", None)
                if ta is not None:
                    if act == "group_replan":
                        ta.request_replan(action["verdict"])
                    else:
                        ta.request_merge(action["verdict"])
            elif act == "replica":
                sc = self._replica_scaler()
                if sc is not None:
                    sc.scale_to(int(action["new"]), action["verdict"])
            elif act in ("shard_split", "shard_merge"):
                if self.dir:
                    from pytorch_ps_mpi_tpu.control.topo import (
                        write_shard_plan,
                    )

                    write_shard_plan(self.dir, int(action["new"]),
                                     action["verdict"])

    def _replica_scaler(self):
        """Build the replica scaler on first use: the read tier must be
        live (core with a bound read listener) and a control/telemetry
        dir armed — else replica actions record but cannot execute
        (counted in ``exec_errors`` by the caller's raise)."""
        if self._replicas is not None:
            return self._replicas
        rp = getattr(self.core, "read_port", None)
        if not rp or not self.dir:
            raise RuntimeError(
                "replica scale action needs a live read tier "
                "(cfg['read_port']) and a control/telemetry dir")
        from pytorch_ps_mpi_tpu.control.topo import ReplicaScaler

        self._replicas = ReplicaScaler(
            "127.0.0.1", int(rp), dir=self.dir,
            fleet_dir=self.cfg.get("fleet_dir"))
        return self._replicas

    def _restore_epoch(self) -> None:
        """A restarted server generation must rejoin the fleet's current
        wire epoch BEFORE consuming: workers renegotiated by a previous
        generation keep pushing the bumped fingerprint, which a
        boot-wire server would config-reject forever."""
        if not self.dir or not self.engine.ladder:
            return
        state: Dict[str, Any] = {"epoch": 0, "mtime": 0}
        doc = poll_epoch(self.dir, state)
        if doc is None:
            return
        idx = next((i for i, e in enumerate(self.engine.ladder)
                    if e.get("codec") == doc.get("codec")
                    and float(e.get("bucket_mb", 0.0))
                    == float(doc.get("bucket_mb", 0.0))), None)
        if idx is None or idx == self.engine.ladder_idx:
            return
        from pytorch_ps_mpi_tpu.codecs import get_codec

        entry = self.engine.ladder[idx]
        code = get_codec(entry["codec"], **(entry.get("codec_kw") or {}))
        try:
            self.server.renegotiate_wire(
                code, bucket_mb=float(entry.get("bucket_mb", 0.0)))
        except Exception as e:
            # a failed restore must never crash Controller construction
            # — a supervisor would respawn-loop the generation forever.
            # Skipping leaves new-epoch pushes config-rejected (visible
            # churn) instead of a dead server.
            self.exec_errors += 1
            from pytorch_ps_mpi_tpu.telemetry.recorder import (
                record_event,
            )

            record_event("control.exec_error", rule="codec",
                         action="restore_epoch", error=str(e))
            return
        # the old (boot) epoch stays accepted for a real grace window
        # (settle_min_s .. settle_s, anchored at the FIRST evaluation):
        # workers that pushed boot-fingerprint frames just before this
        # generation came up are consumed, not rejected
        self.engine.ladder_idx = idx
        self.engine.epoch = int(doc["epoch"])
        self.engine._seed_transition = True
        setattr(self.server, "_epoch", int(doc["epoch"]))

    # -- surfaces ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        out = self.engine.snapshot()
        out.update({
            "armed": True,
            "name": self.name,
            "exec_errors": self.exec_errors,
            "overhead_s": _r(self.overhead_s),
            "actions_file": self.actions_file,
            "input_file": (self.history.path
                           if self.history is not None else None),
        })
        return out

    def register(self, registry) -> None:
        def collect(r) -> None:
            r.counter("ps_control_actions_total",
                      "controller actions executed (all rules)").set(
                          float(self.actions_total))
            r.counter("ps_control_flaps_total",
                      "action reversals inside one cooldown window "
                      "(should stay 0)").set(float(self.flaps))
            r.gauge("ps_control_epoch",
                    "current wire epoch (codec renegotiations since "
                    "boot)").set(float(self.epoch))
            r.gauge("ps_control_evicted",
                    "workers currently backoff-evicted from the sync "
                    "barrier").set(float(len(self.engine.evicted)))
            r.gauge("ps_control_lr_scale_min",
                    "smallest per-worker staleness LR weight in force "
                    "(1 = no de-weighting)").set(
                        float(self.lr_scale_min()))
            r.counter("ps_topo_actions_total",
                      "structural (topology) actions: group replans, "
                      "replica scale steps, shard plan changes").set(
                          float(self.topo_actions_total))
            r.gauge("ps_replicas_live",
                    "read-tier replica processes currently live "
                    "(controller-spawned)").set(float(self.replicas_live))
            r.counter("ps_group_replans_total",
                      "tree group splits currently in force (a merge "
                      "reverts one)").set(float(self.group_replans))

        registry.add_collector(collect)

    def close(self) -> None:
        if self.history is not None:
            self.history.close()
        sc, self._replicas = self._replicas, None
        if sc is not None:
            sc.close()
        f, self._actions_f = self._actions_f, None
        if f is not None:
            f.close()

    # -- replay -----------------------------------------------------------
    @classmethod
    def replay(cls, rows: List[Dict[str, Any]], *,
               num_workers: int, cfg: Optional[Dict[str, Any]] = None,
               agg_capable: bool = False, depth: int = 64, ring: int = 8,
               ladder_idx: int = 0, epoch: int = 0,
               seed_transition: bool = False,
               **overrides: Any) -> List[Dict[str, Any]]:
        """Re-derive the action sequence from persisted TSDB rows
        (``timeseries-control-<name>.jsonl`` via
        :func:`~pytorch_ps_mpi_tpu.telemetry.timeseries.load_timeseries_rows`).
        Deterministic: the same rows, knobs and INITIAL setpoints
        produce byte-identical action rows — the controller twin of
        ``SLOWatchdog.replay``. Pass the live run's boot
        ``depth``/``ring`` (the serving knobs); for a supervisor-
        restarted generation that restored a wire epoch from
        ``control-epoch.json``, additionally pass its restored
        ``ladder_idx``/``epoch`` and ``seed_transition=True``."""
        knobs = dict((cfg or {}).get("control_kw") or {})
        knobs.update(overrides)
        # same derivation as the live __init__: the top-level cfg switch
        # arms the topo rule — replay must see the identical knob
        if (cfg or {}).get("topo_actions"):
            knobs["topo_actions"] = True
        eng = ControlEngine(
            knobs, num_workers, agg_capable=agg_capable,
            depth=depth, ring=ring, ladder_idx=ladder_idx, epoch=epoch,
            seed_transition=seed_transition,
            agg_ok=ladder_agg_ok(knobs.get("ladder"),
                                 str((cfg or {}).get("agg", "auto"))))
        out: List[Dict[str, Any]] = []
        for r in rows:
            out.extend(eng.step(r["m"]))
        return out
