"""Structural actuators — topology as a control action.

The :class:`~pytorch_ps_mpi_tpu.control.controller.ControlEngine`'s
``topo`` rule (PR 18) decides *that* the fleet should reshape; this
module is *how*.  Three actuators, one shared publication document:

``control-topo.json``
    The structural counterpart of ``control-epoch.json`` — an atomic
    (write-temp + rename), monotone-``seq`` document the worker fleet
    polls once per step (:func:`poll_topo`, one ``os.stat``).  It
    carries the leader re-assignment map (``assign: {wid: addr}``,
    consumed by ``TreeWorkerConn.repoint``) and the planned shard count
    (``shards``, consumed by :func:`planned_shards` at the next
    sharded-server generation — shard moves are never live migrations).

:class:`TreeTopoActuator`
    Lives inside ``run_tree`` (the only process holding the leader
    supervision lists).  ``request_replan`` moves HALF the hot group's
    members behind a freshly spawned leader; the spawn is asynchronous
    (``pump()`` on the serve loop's tick reaps the hello) so the serve
    thread never blocks on a child boot.  The new leader lands in the
    same ``leaders``/``leader_ports`` lists the existing respawn loop
    supervises, so from the moment of its hello it is pinned-port
    respawned like any boot-time leader.  Migrated members repoint on
    their next topo poll; the old leader's degrade/flush machinery
    folds their already-queued pushes (exact composed accounting — no
    push is lost or double-counted across the transition).
    ``request_merge`` reassigns the members back and lets the split
    leader idle-exit clean (rc 0 is never respawned); its group slot is
    recycled by the next split so the root's spare-wid headroom stays
    bounded by ``replan_max``.

:class:`ReplicaScaler`
    The elastic read tier: spawns/retires ``examples/serve_readonly.py
    --follow-endpoint`` replica processes.  Replicas self-register
    fleet cards (``replica-<pid>``) and re-parent by subscribing to the
    endpoint the scaler hands them; retirement removes the card first
    so the pane never shows a corpse, then terminates the process.

:class:`HopTailer`
    Live feed for the anatomy advisor: tails the leaders'
    ``lineage-leader<g>.jsonl`` sidecars (offset-tracked, torn-line
    safe) and replays each hop row into ``RoundAnatomy.observe_hop`` —
    the same rows the offline profiler reads, so the live advisor's
    ``leader_fold`` ranking (and the engine's ``hot_group`` input)
    match the post-hoc one.

Everything here is a *live* actuator: determinism lives in the engine
(every action row already carries its verdict and replays
byte-identical from TSDB rows); these classes only carry actions out
and are free to fail — the controller counts failures in
``exec_errors`` without perturbing the action log.
"""

from __future__ import annotations

import glob
import json
import os
import select
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# the topology document (control-topo.json)
# ---------------------------------------------------------------------------


def topo_path(control_dir: str) -> str:
    return os.path.join(control_dir, "control-topo.json")


def read_topo(control_dir: str) -> Optional[Dict[str, Any]]:
    """Best-effort read of the current topology document (None when
    absent or torn — the atomic rename makes torn reads transient)."""
    try:
        with open(topo_path(control_dir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def update_topo(control_dir: str, **fields: Any) -> Dict[str, Any]:
    """Merge ``fields`` into ``control-topo.json`` and publish it
    atomically with a bumped monotone ``seq`` (the worker poll's
    freshness gate).  ``assign`` maps MERGE key-wise — a shard-plan
    update must not clobber a standing leader re-assignment."""
    os.makedirs(control_dir, exist_ok=True)
    doc = read_topo(control_dir) or {}
    assign = dict(doc.get("assign") or {})
    if "assign" in fields:
        assign.update(fields.pop("assign") or {})
    doc.update(fields)
    doc["assign"] = assign
    doc["seq"] = int(doc.get("seq", 0)) + 1
    path = topo_path(control_dir)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc


def poll_topo(control_dir: str, state: Dict[str, Any]
              ) -> Optional[Dict[str, Any]]:
    """Worker-side topology poll, modeled on ``poll_epoch``: one
    ``os.stat`` per call, parse only on change, return only documents
    with a NEWER ``seq`` than ``state`` has seen.  ``state`` is the
    caller's mutable ``{"seq": int, "mtime": int}``."""
    path = topo_path(control_dir)
    try:
        st = os.stat(path)
    except OSError:
        return None
    if st.st_mtime_ns == state.get("mtime"):
        return None
    doc = read_topo(control_dir)
    if doc is None:
        # transient read failure: do NOT latch the mtime — the next
        # poll must retry or this worker would miss the re-assignment
        return None
    state["mtime"] = st.st_mtime_ns
    if int(doc.get("seq", 0)) <= int(state.get("seq", 0)):
        return None
    state["seq"] = int(doc.get("seq", 0))
    return doc


def write_shard_plan(control_dir: str, n_shards: int,
                     verdict: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Record the engine's shard split/merge decision as a PLAN: the
    next sharded-server generation reads it through
    :func:`planned_shards`.  Shard moves rehash the whole key space, so
    they are never applied to a live generation."""
    return update_topo(control_dir, shards=int(n_shards),
                       shard_verdict=verdict or {})


def planned_shards(control_dir: Optional[str], default: int) -> int:
    """The shard count the next server generation should boot with:
    the planned value when a topo document carries one, else
    ``default`` (the cfg value).  Clamped to >= 1."""
    if control_dir:
        doc = read_topo(control_dir)
        if doc is not None and "shards" in doc:
            try:
                return max(1, int(doc["shards"]))
            except (TypeError, ValueError):
                pass
    return max(1, int(default))


# ---------------------------------------------------------------------------
# live hop feed (leaders' lineage sidecars -> anatomy advisor)
# ---------------------------------------------------------------------------


class HopTailer:
    """Offset-tracked tailer for the leaders' ``lineage-leader*.jsonl``
    sidecars: each ``poll()`` reads only the bytes appended since the
    last one, parses complete lines (a torn tail line is left for the
    next poll), and hands every row to ``sink`` — normally
    ``RoundAnatomy.observe_hop``, which itself filters for hop rows."""

    def __init__(self, dir: str, sink: Callable[[Dict[str, Any]], Any],
                 pattern: str = "lineage-leader*.jsonl"):
        self.dir = dir
        self.sink = sink
        self.pattern = pattern
        self._offsets: Dict[str, int] = {}
        self.rows = 0

    def poll(self) -> int:
        """Drain new complete rows from every matching sidecar; returns
        the number of rows fed this call. Sink exceptions are swallowed
        (a malformed row must not take down the serve loop's tick)."""
        fed = 0
        for path in sorted(glob.glob(os.path.join(self.dir,
                                                  self.pattern))):
            off = self._offsets.get(path, 0)
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(1 << 20)
            except OSError:
                continue
            if not chunk:
                continue
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                continue  # torn tail only; re-read next poll
            self._offsets[path] = off + last_nl + 1
            for line in chunk[:last_nl].splitlines():
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(row, dict):
                    continue
                try:
                    self.sink(row)
                except Exception:
                    pass
                fed += 1
        self.rows += fed
        return fed


# ---------------------------------------------------------------------------
# tree re-planning (group split / merge)
# ---------------------------------------------------------------------------


class TreeTopoActuator:
    """Carries the engine's ``group_replan``/``group_merge`` actions
    out inside ``run_tree``.  Owns no policy: which group is hot, the
    cooldowns, and the latch all live in the engine; this class only
    splits/merges membership through the supervisor's own lists.

    Split protocol (all asynchronous — nothing here blocks the serve
    thread):

    1. ``request_replan(verdict)`` spawns a new leader for the LATER
       half of the hot group's members (port 0 — the pin happens at
       first respawn like any boot leader) and parks it as pending.
    2. ``pump()`` (called from the supervisor's ``on_tick``) reaps the
       hello without blocking.  On hello: the new leader joins the
       ``leaders``/``leader_ports``/``respawns`` lists (so the existing
       rc!=0 respawn loop supervises it), the group lists are updated,
       and the re-assignment map is published through
       ``control-topo.json`` for the moved members' next topo poll.
    3. Moved members ``repoint`` to the new leader; the old leader's
       degrade/flush machinery folds their queued pushes, then marks
       them dead — every acked push is composed exactly once.

    Merge reassigns the members back and empties the split group; the
    split leader idle-exits rc 0 (never respawned) and its group slot
    is recycled by the next split, keeping the root's spare-wid
    headroom bounded by ``replan_max``.
    """

    def __init__(self, *, cfg: Dict[str, Any], groups: List[List[int]],
                 leaders: List[Any], leader_ports: List[int],
                 leader_addrs: List[str], respawns: List[int],
                 root_addr: str, control_dir: Optional[str] = None,
                 leader_env: Optional[Dict[str, str]] = None,
                 spawn_fn: Optional[Callable[..., Any]] = None):
        self.cfg = cfg
        self.groups = groups
        self.leaders = leaders
        self.leader_ports = leader_ports
        self.leader_addrs = leader_addrs
        self.respawns = respawns
        self.root_addr = root_addr
        self.control_dir = control_dir or cfg.get("control_dir") \
            or cfg.get("telemetry_dir")
        self.leader_env = leader_env
        self._spawn = spawn_fn
        self._pending: Optional[Dict[str, Any]] = None
        self._split: Optional[Dict[str, Any]] = None
        self._free_gids: List[int] = []
        self.events: List[Dict[str, Any]] = []

    # -- requests (called from the controller's execute path) -------------
    def request_replan(self, verdict: Dict[str, Any]) -> bool:
        """Begin splitting the group the verdict names. Returns False
        (and records why) when the request cannot be honored — a split
        already pending/active, an unknown group, or one too small to
        split; the engine's action row stands either way (replay sees
        the decision, ``exec`` truth lives in the event rows)."""
        if self._pending is not None or self._split is not None:
            self._event("replan_skipped", reason="split_active")
            return False
        gid = int(verdict.get("group", -1))
        if not (0 <= gid < len(self.groups)) or len(self.groups[gid]) < 2:
            self._event("replan_skipped", reason="bad_group", group=gid)
            return False
        members = list(self.groups[gid])
        stay, moved = members[:len(members) // 2], \
            members[len(members) // 2:]
        new_gid = self._free_gids.pop() if self._free_gids \
            else len(self.groups)
        if self._spawn is None:
            from pytorch_ps_mpi_tpu.parallel.tree import spawn_leader
            self._spawn = spawn_leader
        try:
            proc = self._spawn([self.root_addr], new_gid, moved, self.cfg,
                               env=self.leader_env)
        except Exception as e:
            if new_gid < len(self.groups):
                self._free_gids.append(new_gid)
            self._event("replan_failed", reason=f"spawn: {e}", group=gid)
            return False
        self._pending = {"proc": proc, "gid": new_gid, "from": gid,
                         "stay": stay, "moved": moved,
                         "verdict": verdict, "t0": time.time()}
        self._event("replan_spawned", group=gid, new_group=new_gid,
                    moved=list(moved), verdict=verdict)
        return True

    def request_merge(self, verdict: Dict[str, Any]) -> bool:
        """Reverse the active split: moved members repoint back to
        their original leader; the split leader idle-exits clean."""
        sp = self._split
        if sp is None:
            self._event("merge_skipped", reason="no_split")
            return False
        src = int(sp["from"])
        self.groups[src] = list(sp["stay"]) + list(sp["moved"])
        self.groups[sp["gid"]] = []
        self._free_gids.append(int(sp["gid"]))
        if self.control_dir:
            update_topo(self.control_dir,
                        assign={str(w): self.leader_addrs[src]
                                for w in sp["moved"]})
        self._event("merged", group=src, from_group=sp["gid"],
                    moved=list(sp["moved"]), verdict=verdict)
        self._split = None
        return True

    # -- supervisor tick ---------------------------------------------------
    def pump(self) -> None:
        """Non-blocking: reap a pending split leader's hello and, once
        it arrives, commit the membership change. Safe to call every
        serve-loop tick."""
        p = self._pending
        if p is None:
            return
        proc = p["proc"]
        if proc.poll() is not None:
            self._pending = None
            if int(p["gid"]) < len(self.groups):
                self._free_gids.append(int(p["gid"]))
            self._event("replan_failed", reason=f"rc={proc.returncode}",
                        group=p["from"])
            return
        if proc.stdout is None:
            return
        try:
            r, _, _ = select.select([proc.stdout], [], [], 0)
        except (OSError, ValueError):
            return
        if not r:
            if time.time() - p["t0"] > 120.0:
                self._pending = None
                try:
                    proc.terminate()
                except Exception:
                    pass
                self._event("replan_failed", reason="hello_timeout",
                            group=p["from"])
            return
        line = proc.stdout.readline()
        if not line:
            return
        try:
            hello = json.loads(line)
        except ValueError:
            return
        addr = hello["addr"]
        port = 0 if addr.startswith("shm:") \
            else int(addr.rsplit(":", 1)[1])
        gid, src = int(p["gid"]), int(p["from"])
        if gid == len(self.groups):  # fresh slot
            self.groups.append(list(p["moved"]))
            self.leaders.append(proc)
            self.leader_addrs.append(addr)
            self.leader_ports.append(port)
            self.respawns.append(0)
        else:  # recycled slot from an earlier merge
            self.groups[gid] = list(p["moved"])
            self.leaders[gid] = proc
            self.leader_addrs[gid] = addr
            self.leader_ports[gid] = port
            self.respawns[gid] = 0
        self.groups[src] = list(p["stay"])
        if self.control_dir:
            update_topo(self.control_dir,
                        assign={str(w): addr for w in p["moved"]})
        self._split = {"gid": gid, "from": src, "stay": p["stay"],
                       "moved": p["moved"], "addr": addr}
        self._pending = None
        self._event("replanned", group=src, new_group=gid, addr=addr,
                    moved=list(p["moved"]), verdict=p["verdict"])

    # -- surfaces ----------------------------------------------------------
    @property
    def active_groups(self) -> int:
        return sum(1 for g in self.groups if g)

    @property
    def split_active(self) -> bool:
        return self._split is not None or self._pending is not None

    def _event(self, act: str, **fields: Any) -> None:
        row = {"t": time.time(), "act": act, **fields}
        self.events.append(row)
        if self.control_dir:
            try:
                from pytorch_ps_mpi_tpu.control.controller import (
                    actions_path,
                )

                with open(actions_path(self.control_dir, "topo"),
                          "a") as f:
                    f.write(json.dumps(row, sort_keys=True) + "\n")
            except OSError:
                pass


# ---------------------------------------------------------------------------
# elastic read tier (replica scale-out / scale-in)
# ---------------------------------------------------------------------------

_SERVE_READONLY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "examples", "serve_readonly.py")


class ReplicaScaler:
    """Spawns and retires ``serve_readonly --follow-endpoint`` replica
    processes to track the engine's replica target.  A replica
    self-registers its fleet card (``replica-<pid>``) and subscribes to
    the upstream endpoint it is handed; retirement is LIFO — newest
    replica first — and removes the fleet card *before* terminating the
    process so the pane never polls a corpse."""

    def __init__(self, host: str, port: int, *, dir: Optional[str] = None,
                 fleet_dir: Optional[str] = None,
                 extra_args: Optional[List[str]] = None):
        self.host = host
        self.port = int(port)
        self.dir = dir
        self.fleet_dir = fleet_dir
        self.extra_args = list(extra_args or ())
        self.procs: List[Any] = []
        self.events: List[Dict[str, Any]] = []

    # split out so tests can fake the process boundary
    def _spawn_replica(self) -> Any:
        cmd = [sys.executable, _SERVE_READONLY,
               "--follow-endpoint", f"{self.host}:{self.port}",
               "--read-port", "0"]
        if self.fleet_dir:
            # the fleet card rides the replica's own /metrics endpoint —
            # without an HTTP port the card is never registered
            cmd += ["--fleet-dir", self.fleet_dir, "--metrics-port", "0"]
        if self.dir:
            cmd += ["--control-dir", self.dir]
        cmd += self.extra_args
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)

    def _retire_replica(self, proc: Any) -> None:
        if self.fleet_dir is not None and proc.pid is not None:
            try:
                from pytorch_ps_mpi_tpu.telemetry.fleet import (
                    deregister_endpoint,
                )

                deregister_endpoint(self.fleet_dir,
                                    f"replica-{proc.pid}")
            except Exception:
                pass
        try:
            proc.terminate()
        except Exception:
            pass

    def _prune(self) -> None:
        self.procs = [p for p in self.procs if p.poll() is None]

    @property
    def live(self) -> int:
        self._prune()
        return len(self.procs)

    def scale_to(self, n: int, verdict: Optional[Dict[str, Any]] = None
                 ) -> int:
        """Spawn/retire until ``live == n`` (clamped >= 0). Returns the
        resulting live count; each transition appends one event row."""
        n = max(0, int(n))
        self._prune()
        while len(self.procs) < n:
            proc = self._spawn_replica()
            self.procs.append(proc)
            self.events.append({"t": time.time(), "act": "spawn",
                                "pid": proc.pid, "n": len(self.procs),
                                "verdict": verdict or {}})
        while len(self.procs) > n:
            proc = self.procs.pop()
            self._retire_replica(proc)
            self.events.append({"t": time.time(), "act": "retire",
                                "pid": proc.pid, "n": len(self.procs),
                                "verdict": verdict or {}})
        return len(self.procs)

    def hellos(self, timeout: float = 30.0) -> List[Dict[str, Any]]:
        """Block (bounded) until every live replica has printed its
        hello; returns the parsed hello docs. A smoke/test convenience
        — the controller itself never waits on replica boot."""
        out = []
        deadline = time.time() + timeout
        for p in list(self.procs):
            if getattr(p, "_hello", None) is not None:
                out.append(p._hello)
                continue
            if p.stdout is None:
                continue
            while time.time() < deadline:
                r, _, _ = select.select([p.stdout], [], [], 0.25)
                if r:
                    line = p.stdout.readline()
                    if line:
                        try:
                            p._hello = json.loads(line)
                            out.append(p._hello)
                        except ValueError:
                            continue
                        break
                if p.poll() is not None:
                    break
        return out

    def close(self) -> None:
        while self.procs:
            self._retire_replica(self.procs.pop())
