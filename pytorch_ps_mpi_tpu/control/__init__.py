"""Self-driving control plane — the verdict→action loop.

See :mod:`pytorch_ps_mpi_tpu.control.controller` for the full design:
the :class:`Controller` runs inside the serve loop, turns latched
monitor verdicts into recorded/replayable/reversible actions (codec
renegotiation via wire-epoch bumps, staleness-aware per-push LR
weights, barrier evict/readmit, read-tier tuning), and
:meth:`Controller.replay` re-derives the identical action sequence from
the persisted TSDB input rows.
"""

from pytorch_ps_mpi_tpu.control.controller import (  # noqa: F401
    CONTROL_KNOBS,
    RULES,
    ControlEngine,
    Controller,
    actions_path,
    apply_epoch,
    epoch_path,
    poll_epoch,
    write_epoch,
)
