"""Train any BASELINE config end-to-end from the command line.

The driving script the reference kept in a sibling research repo
(SURVEY: "the driving train script ... imports this package"), made part
of the framework. Synthetic data (zero-egress environment); every knob of
the optimizer surface is exposed.

Examples:
  python examples/train.py --config mlp_mnist --steps 50
  python examples/train.py --config resnet18_cifar10 --codec topk --codec-arg fraction=0.01
  python examples/train.py --config bert_mlm --optim adam --lr 1e-3 --mode leader
  python examples/train.py --config resnet50_imagenet --steps 10 --batch 32
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from pytorch_ps_mpi_tpu.utils.backend_guard import (
    enable_compilation_cache,
    ensure_live_backend,
)

# probe the accelerator BEFORE jax initializes a backend: the axon TPU
# tunnel can hang indefinitely on the first device op when it is down,
# and this CLI should fall back to the host CPU instead of freezing
ensure_live_backend()
enable_compilation_cache()

import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu import MPI_PS
from pytorch_ps_mpi_tpu.codecs import get_codec
from pytorch_ps_mpi_tpu.data import cross_entropy_loss, synthetic_images, synthetic_mlm
from pytorch_ps_mpi_tpu.models import MLP, BertConfig, BertMLM, ResNet18, ResNet50
from pytorch_ps_mpi_tpu.models.bert import mlm_loss
from pytorch_ps_mpi_tpu.trainer import Trainer

CONFIGS = ["mlp_mnist", "resnet18_cifar10", "resnet50_imagenet", "bert_mlm",
           "switch_mlm", "gpt_lm"]


def build(config: str, batch: int, seed: int = 0, remat: bool = False,
          scan_layers: bool = False):
    """Returns (params, loss_fn, batch_iterator)."""
    key = jax.random.key(seed)
    if config == "switch_mlm":
        from pytorch_ps_mpi_tpu.models import SwitchConfig, SwitchMLM

        scfg = SwitchConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                            num_heads=8, intermediate_size=512, n_experts=8,
                            max_position=128)
        model = SwitchMLM(scfg)
        data = synthetic_mlm(batch, seq_len=128, vocab_size=scfg.vocab_size)
        b0 = next(data)
        params = model.init(key, b0["tokens"])
        def loss_fn(p, b):
            return mlm_loss(model.apply(p, b["tokens"]), b["targets"], b["mask"])
        return params, loss_fn, data
    if config == "gpt_lm":
        from pytorch_ps_mpi_tpu.data import synthetic_lm
        from pytorch_ps_mpi_tpu.models import GPTLM, causal_lm_loss, gpt_config

        gcfg = gpt_config(vocab_size=8192, hidden_size=256, num_layers=4,
                          num_heads=8, intermediate_size=1024,
                          max_position=256, remat=remat,
                          scan_layers=scan_layers)
        model = GPTLM(gcfg)
        data = synthetic_lm(batch, seq_len=128, vocab_size=gcfg.vocab_size)
        b0 = next(data)
        params = model.init(key, b0["tokens"])
        def loss_fn(p, b):
            return causal_lm_loss(model.apply(p, b["tokens"]), b["tokens"])
        return params, loss_fn, data
    if config == "mlp_mnist":
        model = MLP(features=(128, 10))
        data = synthetic_images("mnist", batch)
        x0, _ = next(data)
        params = model.init(key, x0)
        def loss_fn(p, b):
            x, y = b
            return cross_entropy_loss(model.apply(p, x), y)
        return params, loss_fn, data
    if config == "resnet18_cifar10":
        model = ResNet18(num_classes=10, small_inputs=True)
    elif config == "resnet50_imagenet":
        model = ResNet50(num_classes=1000)
    else:
        cfg = dataclasses.replace(BertConfig.base(), remat=remat,
                                  scan_layers=scan_layers)
        model = BertMLM(cfg)
        data = synthetic_mlm(batch, seq_len=128, vocab_size=cfg.vocab_size)
        b0 = next(data)
        params = model.init(key, b0["tokens"])
        def loss_fn(p, b):
            return mlm_loss(model.apply(p, b["tokens"]), b["targets"], b["mask"])
        return params, loss_fn, data
    name = "cifar10" if config == "resnet18_cifar10" else "imagenet"
    data = synthetic_images(name, batch)
    x0, _ = next(data)
    params = model.init(key, x0)
    def loss_fn(p, b):
        x, y = b
        return cross_entropy_loss(model.apply(p, x), y)
    return params, loss_fn, data


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", choices=CONFIGS, default="mlp_mnist")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--optim", choices=["sgd", "adam", "adafactor"],
                    default="sgd")
    # default=None is the explicit-lr sentinel: sniffing sys.argv for the
    # literal "--lr" missed --lr=0.05 and argparse prefix forms and
    # silently discarded the user's rate on the adafactor path
    ap.add_argument("--lr", type=float, default=None,
                    help="learning rate (default 0.01; adafactor with no "
                         "explicit --lr and no schedule uses the paper's "
                         "relative step size)")
    ap.add_argument("--lr-schedule", choices=["constant", "warmup_cosine",
                                              "step_decay"], default=None,
                    help="in-program lr schedule over --lr (evaluated on "
                         "the traced step counter; no recompiles)")
    ap.add_argument("--warmup-steps", type=int, default=0)
    ap.add_argument("--decay-boundaries", default="",
                    help="comma ints for step_decay, e.g. 100,200")
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--clip-norm", type=float, default=0.0,
                    help="clip the aggregated gradient to this global "
                         "L2 norm (0 = off)")
    ap.add_argument("--weight-decay", type=float, default=0.0)
    ap.add_argument("--adamw", action="store_true",
                    help="decoupled weight decay (AdamW) instead of "
                         "torch-style coupled L2 (adam only)")
    ap.add_argument("--mode", choices=["allgather", "leader"], default="allgather")
    ap.add_argument("--codec", default=None,
                    help="identity|bf16|f16|topk|randomk|int8|qsgd|sign|terngrad|"
                         "powersgd|threshold|ef")
    ap.add_argument("--codec-arg", action="append", default=[],
                    help="k=v passed to the codec (repeatable)")
    ap.add_argument("--bucket-mb", type=float, default=0.0,
                    help="fuse per-leaf collectives into ~N MB "
                         "dtype-grouped flat buckets (0 = per-leaf; see "
                         "docs/OPERATIONS.md 'Gradient bucketing')")
    ap.add_argument("--bf16-comm", action="store_true",
                    help="bfloat16 gradient collectives")
    ap.add_argument("--donate", action="store_true",
                    help="donate params/state buffers to XLA (in-place "
                         "device update; ~one params+state copy less HBM)")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize transformer layers in backward "
                         "(bert_mlm / gpt_lm configs)")
    ap.add_argument("--scan-layers", action="store_true",
                    help="lax.scan over a stacked layer body: one "
                         "layer's HLO to compile instead of L copies "
                         "(bert_mlm / gpt_lm configs)")
    ap.add_argument("--scan-chunk", type=int, default=1,
                    help=">1 fuses N steps per XLA program")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--instrument", action="store_true",
                    help="per-stage timing metrics")
    ap.add_argument("--log-every", type=int, default=5)
    ap.add_argument("--telemetry-dir", default=None,
                    help="enable the run-wide FlightRecorder; dumps "
                         "train.jsonl (tools/telemetry_report.py reads it) "
                         "into this directory at exit")
    args = ap.parse_args(argv)
    explicit_lr = args.lr is not None
    if args.lr is None:
        args.lr = 0.01
    if args.telemetry_dir:
        import os

        from pytorch_ps_mpi_tpu import telemetry

        os.makedirs(args.telemetry_dir, exist_ok=True)
        telemetry.configure(worker="trainer")
    if args.adamw:
        if args.optim != "adam":
            ap.error("--adamw requires --optim adam")
        if not args.weight_decay:
            # decoupled decay with wd=0 would be a silent no-op; pick
            # the conventional AdamW default instead of surprising the
            # user with unregularized plain Adam
            args.weight_decay = 0.01
            print("note: --adamw without --weight-decay: using 0.01")

    code = None
    if args.codec:
        kw = {}
        for kv in args.codec_arg:
            k, v = kv.split("=", 1)
            try:
                v = json.loads(v)
            except json.JSONDecodeError:
                pass
            kw[k] = v
        code = get_codec(args.codec, **kw)

    if args.remat and args.config not in ("bert_mlm", "gpt_lm"):
        print(f"note: --remat has no effect on {args.config} "
              "(transformer configs only)")
    if args.scan_layers and args.config not in ("bert_mlm", "gpt_lm"):
        print(f"note: --scan-layers has no effect on {args.config} "
              "(transformer configs only)")
    params, loss_fn, data = build(args.config, args.batch, remat=args.remat,
                                  scan_layers=args.scan_layers)
    from pytorch_ps_mpi_tpu.data import prefetch

    data = prefetch(data)  # overlap host batch construction with the step
    lr = args.lr
    if args.lr_schedule == "warmup_cosine":
        from pytorch_ps_mpi_tpu.optim import warmup_cosine

        lr = warmup_cosine(args.lr, total_steps=args.steps,
                           warmup_steps=args.warmup_steps)
    elif args.lr_schedule == "step_decay":
        from pytorch_ps_mpi_tpu.optim import step_decay

        bounds = tuple(int(b) for b in args.decay_boundaries.split(",") if b)
        lr = step_decay(args.lr, boundaries=bounds or (args.steps // 2,))
    hyper = {"lr": lr}
    if args.optim == "sgd":
        hyper["momentum"] = args.momentum
    if args.weight_decay:
        hyper["weight_decay"] = args.weight_decay
    if args.adamw:
        hyper["decoupled_weight_decay"] = True
    if args.optim == "adafactor" and args.lr_schedule is None \
            and not explicit_lr:
        # no explicit lr and no schedule: the paper's relative step size
        hyper["lr"] = None
    opt = MPI_PS(
        params, optim=args.optim, code=code, mode=args.mode,
        average=True, instrument=args.instrument,
        comm_dtype=jnp.bfloat16 if args.bf16_comm else None,
        donate_buffers=args.donate, clip_norm=args.clip_norm,
        bucket_mb=args.bucket_mb, **hyper,
    )
    print(f"config={args.config} devices={jax.device_count()} "
          f"world={opt.size} codec={args.codec or 'identity'}")
    trainer = Trainer(
        opt, loss_fn, checkpoint_dir=args.checkpoint_dir,
        scan_chunk=args.scan_chunk,
    )
    resumed = trainer.maybe_restore()
    if resumed:
        print(f"resumed from step {trainer.step_count}")
    summary = trainer.fit(data, args.steps, log_every=args.log_every)
    if args.telemetry_dir:
        import os

        from pytorch_ps_mpi_tpu import telemetry

        path = telemetry.get_recorder().dump_jsonl(
            os.path.join(args.telemetry_dir, "train.jsonl")
        )
        print(f"telemetry: {path} (summarize with "
              "tools/telemetry_report.py)")
    print(json.dumps({k: round(float(v), 6) for k, v in summary.items()}))


if __name__ == "__main__":
    main()
