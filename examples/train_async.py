"""Async (AsySG-InCon) training CLI — the reference's README pseudo-code
(``/root/reference/README.md:61-81``: workers compute gradients against
whatever parameters they last read; a parameter server applies them in
arrival order) as an actual runnable, with real jitted compute in every
process (``parallel/async_train.py``).

The server runs in this process; each worker is its own OS process with
its own JAX runtime (pinned to the host backend so fleets never contend
for a single tunneled TPU chip). Gradients travel as codec-encoded
payload bytes through the native shared-memory transport
(``native/psqueue.cpp``).

Examples:
  python examples/train_async.py --model mlp --workers 4 --steps 50
  python examples/train_async.py --model resnet18 --codec sign \
      --workers 4 --steps 10 --straggler-ms 500 --max-staleness 3
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # server process: host backend

from pytorch_ps_mpi_tpu.parallel import dcn
from pytorch_ps_mpi_tpu.parallel.async_train import (
    make_problem,
    serve,
    spawn_worker,
)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["mlp", "resnet18", "resnet50"],
                    default="mlp")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--steps", type=int, default=50,
                    help="gradient pushes per worker")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--optim", choices=["sgd", "adam"], default="sgd")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--codec", default=None,
                    help="codec registry name (e.g. sign, int8, threshold)")
    ap.add_argument("--max-staleness", type=int, default=4)
    ap.add_argument("--straggler-ms", type=float, default=0.0,
                    help="inject this delay into the last worker's loop")
    ap.add_argument("--sync-barrier", action="store_true",
                    help="synchronous-PS oracle mode (for comparison runs)")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--transport", default="shm", choices=["shm", "tcp"],
                    help="PS wire: shm (co-hosted processes) or tcp (the "
                         "cross-host DCN-role transport)")
    ap.add_argument("--port", type=int, default=0,
                    help="tcp transport: listen port (0 = auto)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="checkpoint the PS state every --checkpoint-every "
                         "applied gradients")
    ap.add_argument("--checkpoint-every", type=int, default=50)
    ap.add_argument("--resume", action="store_true",
                    help="restore the latest PS checkpoint before serving")
    args = ap.parse_args(argv)
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir")

    in_shape = (8,) if args.model == "mlp" else (32, 32, 3)
    cfg = {
        "model": args.model,
        "model_kw": {"num_classes": 10} if args.model != "mlp" else
                    {"features": (64, 8)},
        "in_shape": list(in_shape),
        "batch": args.batch,
        "seed": 0,
        "optim": args.optim,
        "hyper": {"lr": args.lr},
        "steps": args.steps,
        "open_timeout": args.timeout,
        "push_timeout": args.timeout,
    }
    if args.codec:
        cfg["codec"] = args.codec
    if args.straggler_ms:
        cfg["slow_ms"] = {str(args.workers - 1): args.straggler_ms}

    code = None
    if args.codec:
        from pytorch_ps_mpi_tpu.codecs import get_codec

        code = get_codec(args.codec)

    _, params0, _, _ = make_problem(cfg)
    if args.transport == "tcp":
        from pytorch_ps_mpi_tpu.parallel import tcp

        cfg["transport"] = "tcp"
        server = tcp.TcpPSServer(
            args.port, num_workers=args.workers, template=params0,
            max_staleness=args.max_staleness, code=code,
        )
        name = f"127.0.0.1:{server.port}"
        print(f"tcp PS listening on {name}")
    else:
        name = f"/psq_train_{os.getpid()}"
        server = dcn.ShmPSServer(
            name, num_workers=args.workers, template=params0,
            max_staleness=args.max_staleness, code=code,
        )
    total = args.workers * args.steps
    procs = []
    try:
        procs = [spawn_worker(name, i, cfg) for i in range(args.workers)]
        params, metrics = serve(
            server, cfg, total_grads=0, total_received=total,
            sync_barrier=args.sync_barrier, timeout=args.timeout,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every, resume=args.resume,
        )
        for p in procs:
            rc = p.wait(timeout=args.timeout)
            if rc != 0:
                raise SystemExit(f"worker exited {rc}")
    finally:
        server.close()
        for p in procs:  # never leave orphan workers if serve() raised
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)

    print(json.dumps(metrics, default=str))
    return metrics


if __name__ == "__main__":
    main()
