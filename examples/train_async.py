"""Async (AsySG-InCon) training CLI — the reference's README pseudo-code
(``/root/reference/README.md:61-81``: workers compute gradients against
whatever parameters they last read; a parameter server applies them in
arrival order) as an actual runnable, with real jitted compute in every
process (``parallel/async_train.py``).

The server runs in this process; each worker is its own OS process with
its own JAX runtime (pinned to the host backend so fleets never contend
for a single tunneled TPU chip). Gradients travel as codec-encoded
payload bytes through the native shared-memory transport
(``native/psqueue.cpp``).

Examples:
  python examples/train_async.py --model mlp --workers 4 --steps 50
  python examples/train_async.py --model resnet18 --codec sign \
      --workers 4 --steps 10 --straggler-ms 500 --max-staleness 3
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # server process: host backend

from pytorch_ps_mpi_tpu.parallel import dcn
from pytorch_ps_mpi_tpu.parallel.async_train import (
    join_workers,
    make_problem,
    serve,
    spawn_worker,
)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["mlp", "resnet18", "resnet50"],
                    default="mlp")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--steps", type=int, default=50,
                    help="gradient pushes per worker")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--optim", choices=["sgd", "adam"], default="sgd")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--codec", default=None,
                    help="codec registry name (e.g. sign, int8, threshold)")
    ap.add_argument("--bucket-mb", type=float, default=0.0,
                    help="with --codec (a bucketable one): ship dtype-"
                         "grouped ~N MB flat bucket payloads per push "
                         "instead of per-leaf fragments; one flag "
                         "configures server AND workers (the wire "
                         "agreement has a single source)")
    ap.add_argument("--max-staleness", type=int, default=4)
    ap.add_argument("--straggler-ms", type=float, default=0.0,
                    help="inject this delay into the last worker's loop")
    ap.add_argument("--sync-barrier", action="store_true",
                    help="synchronous-PS oracle mode (for comparison runs)")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--transport", default="shm", choices=["shm", "tcp"],
                    help="PS wire: shm (co-hosted processes) or tcp (the "
                         "cross-host DCN-role transport)")
    ap.add_argument("--port", type=int, default=0,
                    help="tcp transport: listen port (0 = auto)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="checkpoint the PS state every --checkpoint-every "
                         "applied gradients")
    ap.add_argument("--checkpoint-every", type=int, default=50)
    ap.add_argument("--resume", action="store_true",
                    help="restore the latest PS checkpoint before serving")
    ap.add_argument("--telemetry-dir", default=None,
                    help="ONE flag, full telemetry: FlightRecorder JSONL "
                         "from the server and every worker, a Prometheus "
                         "/metrics endpoint (tcp transport; port in the "
                         "final metrics line), a merged host+device "
                         "Perfetto trace (trace.json), and a per-phase "
                         "report — all dropped in this directory")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics (+ /health) on this "
                         "port — both transports (0 = auto; implied =0 "
                         "by --telemetry-dir)")
    ap.add_argument("--health-port", type=int, default=None,
                    help="arm the online HealthMonitor and serve its "
                         "/health JSON (per-worker verdicts, straggler "
                         "attribution, anomaly flags) beside /metrics "
                         "on this port (0 = auto). Worker beacon files "
                         "land in --telemetry-dir when set, else a temp "
                         "dir")
    ap.add_argument("--ps-top", action="store_true",
                    help="run the tools/ps_top.py live dashboard against "
                         "the /health endpoint for the duration of the "
                         "run (implies --health-port 0; with --supervise "
                         "pass an explicit --health-port so the pinned "
                         "port survives server restarts)")
    ap.add_argument("--trace", action="store_true", default=None,
                    help="arm end-to-end gradient lineage tracing: every "
                         "framed push carries a causal trace ID (worker, "
                         "step, seq) + encode timestamp, every published "
                         "version gets a lineage-server.jsonl row naming "
                         "its composing pushes, exact per-push e2e/"
                         "staleness land in /metrics, and the merged "
                         "trace.json gains cross-process flow arrows "
                         "(worker push span -> server consume span, "
                         "clock-skew corrected). Needs --telemetry-dir "
                         "(artifacts land there) and frame checking "
                         "(the trace ID rides the v2 frame header)")
    ap.add_argument("--no-trace", dest="trace", action="store_false",
                    help="disable lineage tracing (it is otherwise "
                         "implied by --telemetry-dir)")
    ap.add_argument("--numerics", action="store_true",
                    help="arm the NumericsMonitor: every consumed push "
                         "is validated (NaN/Inf counted per worker, the "
                         "worker quarantined), grad-norm/update-ratio "
                         "stats flow into /metrics + /health, workers "
                         "probe codec fidelity online, and a NaN or "
                         "norm spike writes a postmortem-*.json into "
                         "the numerics dir (--telemetry-dir when set)")
    ap.add_argument("--numerics-policy", choices=["skip", "zero", "abort"],
                    default="skip",
                    help="what happens to a non-finite push: skip it "
                         "(default), zero its bad elements and apply "
                         "the rest, or abort the run with a postmortem")
    ap.add_argument("--numerics-probe-every", type=int, default=25,
                    help="codec-fidelity probe / trajectory-row cadence "
                         "(steps)")
    ap.add_argument("--read-port", type=int, default=None,
                    help="arm the parameter-serving read tier on this "
                         "port (0 = auto; bound port in the final "
                         "metrics line as read_port): versioned "
                         "snapshot ring, version-conditional reads "
                         "(not-modified / delta / full), request "
                         "coalescing, admission-control load shedding. "
                         "Readers: pytorch_ps_mpi_tpu.serving."
                         "ServingReader")
    ap.add_argument("--snapshot-ring", type=int, default=None,
                    help="with --read-port: versions kept for delta "
                         "reads (default 8)")
    ap.add_argument("--history", action="store_true",
                    help="arm the in-process metrics TSDB: every "
                         "canonical metric key retained as ring-"
                         "buffered history (raw + 1s/10s/60s tiers), "
                         "persisted as timeseries-server.jsonl in "
                         "--telemetry-dir and served at /history")
    ap.add_argument("--profile", action="store_true",
                    help="arm the continuous sampling profiler (~100 Hz "
                         "collapsed-stack flamegraph text with a hard "
                         "self-overhead budget) in the server AND every "
                         "worker; profile-*.txt land in --telemetry-dir "
                         "and merge in the report")
    ap.add_argument("--slo", action="store_true",
                    help="arm the SLO burn-rate watchdog over the "
                         "metrics history (implies --history): latched "
                         "breach/recover verdicts into slo-server.jsonl "
                         "+ the flight recorder, an 'slo' section in "
                         "/health, and ps_slo_* scrape instruments")
    ap.add_argument("--slo-target", action="append", default=[],
                    help="override one SLO target, KEY=VALUE "
                         "(repeatable; e.g. push_e2e_p95_ms=250)")
    ap.add_argument("--freshness", action="store_true",
                    help="arm the read-path freshness tracker: every "
                         "published version's FRS1 birth record becomes "
                         "publish->visible latency distributions, the "
                         "serving_age_ms age-of-information gauge, and "
                         "freshness-server.jsonl propagation rows in "
                         "--telemetry-dir")
    ap.add_argument("--hop-anatomy", action="store_true",
                    help="arm leader-hop occupancy tracing (tree "
                         "topology): per-round sub-stage timelines "
                         "(ingest_wait/validate/fold/finalize/encode/"
                         "push) from bounded native interval rings, "
                         "hop-leaderN.jsonl rows, the hop_busy_frac / "
                         "hop_stream_headroom_ratio scoreboard")
    ap.add_argument("--control", action="store_true",
                    help="arm the self-driving controller (requires "
                         "--telemetry-dir for its action/replay rows): "
                         "verdicts become recorded reversible actions — "
                         "staleness LR de-weighting, evict/readmit, "
                         "read-tier tuning, and (with a ladder via "
                         "cfg['control_kw']) codec renegotiation")
    ap.add_argument("--fleet-dir", default=None,
                    help="fleet registration directory: this server "
                         "registers its endpoint there (re-registering "
                         "across supervisor restarts) and serves the "
                         "merged /fleet snapshot; watch the pane with "
                         "tools/ps_top.py --fleet DIR")
    ap.add_argument("--no-frame-check", action="store_true",
                    help="disable the self-verifying wire frames (CRC + "
                         "config fingerprint on every push; on by default "
                         "— one cfg configures both ends, so the frame "
                         "header is part of the wire agreement)")
    ap.add_argument("--resilient", action="store_true",
                    help="workers retry/backoff on timeouts and reconnect "
                         "on EOF instead of dying (survives a server "
                         "restart-from-checkpoint)")
    ap.add_argument("--supervise", action="store_true",
                    help="run under the resilience Supervisor: dead "
                         "workers are respawned, a crashed server is "
                         "restarted with --resume from --checkpoint-dir; "
                         "implies --resilient")
    ap.add_argument("--fault-plan", default=None,
                    help="deterministic chaos: a JSON fault-plan list, or "
                         "@path/to/plan.json (entries "
                         "{at_step, worker, kind}; kinds drop/delay/"
                         "duplicate/corrupt/crash_worker/crash_server)")
    ap.add_argument("--fault-seed", type=int, default=0,
                    help="seed for fault randomness (corrupt byte "
                         "positions, backoff jitter): same plan + seed = "
                         "same injected-event log")
    ap.add_argument("--fault-log-dir", default=None,
                    help="directory for per-process injected-fault JSONLs "
                         "(defaults to --telemetry-dir when set)")
    args = ap.parse_args(argv)
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir")
    if args.supervise:
        args.resilient = True
    fault_plan = None
    if args.fault_plan:
        try:  # parse ONCE; validation and cfg use the same object
            fault_plan = _parse_fault_plan(args.fault_plan)
        except (ValueError, OSError) as e:
            ap.error(f"--fault-plan is not valid JSON (or @file): {e}")
        if not args.supervise:
            # the plain serve path stops on a FIXED received count, which
            # drop/corrupt faults make unreachable (600 s hang) and
            # crash_worker turns into a dead fleet member nobody respawns
            # — only the supervisor's workers-done stop condition
            # tolerates a fault plan
            ap.error("--fault-plan requires --supervise")
        if any(f.get("kind") == "crash_server" for f in fault_plan
               ) and not args.checkpoint_dir:
            ap.error("a crash_server fault needs --checkpoint-dir to be "
                     "survivable")

    in_shape = (8,) if args.model == "mlp" else (32, 32, 3)
    cfg = {
        "model": args.model,
        "model_kw": {"num_classes": 10} if args.model != "mlp" else
                    {"features": (64, 8)},
        "in_shape": list(in_shape),
        "batch": args.batch,
        "seed": 0,
        "optim": args.optim,
        "hyper": {"lr": args.lr},
        "steps": args.steps,
        "open_timeout": args.timeout,
        "push_timeout": args.timeout,
    }
    if args.codec:
        cfg["codec"] = args.codec
        if args.bucket_mb:
            cfg["bucket_mb"] = args.bucket_mb
    if args.straggler_ms:
        cfg["slow_ms"] = {str(args.workers - 1): args.straggler_ms}
    # one flag, both ends: the frame header joins the wire agreement the
    # way the codec config and bucket_mb already do
    cfg["frame_check"] = not args.no_frame_check
    if args.resilient:
        cfg["resilient"] = True
        # resilient workers need SHORT op timeouts — the retry/backoff
        # loop supplies the patience, and a failover is only detected
        # when a push times out (a push into a dead server's orphaned
        # mailbox blocks the full timeout before the reconnect fires)
        cfg["push_timeout"] = min(float(args.timeout), 10.0)
    if fault_plan is not None:
        cfg["fault_plan"] = fault_plan
        cfg["fault_seed"] = args.fault_seed
        fault_log = args.fault_log_dir or args.telemetry_dir
        if fault_log:
            import glob

            os.makedirs(fault_log, exist_ok=True)
            # fault logs APPEND (respawned workers must extend, not
            # clobber, their generation-0 rows) — so a reused dir must
            # be cleared at RUN start or the identical-replay comparison
            # sees the previous run's rows too
            for stale in glob.glob(os.path.join(fault_log,
                                                "faults-*.jsonl")):
                os.remove(stale)
            cfg["fault_log_dir"] = fault_log
    if args.telemetry_dir:
        import glob

        os.makedirs(args.telemetry_dir, exist_ok=True)
        # a reused dir must not leak a previous run's files into this
        # run's merged trace/report (worker counts can differ) —
        # numerics trajectories and postmortems included
        for stale in glob.glob(os.path.join(args.telemetry_dir, "*.jsonl")) \
                + glob.glob(os.path.join(args.telemetry_dir, "trace.json")) \
                + glob.glob(os.path.join(args.telemetry_dir,
                                         "postmortem-*.json")) \
                + glob.glob(os.path.join(args.telemetry_dir,
                                         "profile-*.txt")):
            os.remove(stale)
        cfg["telemetry_dir"] = args.telemetry_dir
        if args.metrics_port is None:
            args.metrics_port = 0
    if (args.history or args.slo or args.profile) \
            and not args.telemetry_dir:
        ap.error("--history/--slo/--profile need --telemetry-dir (their "
                 "timeseries-/slo-/profile- artifacts land there)")
    if args.slo_target and not args.slo:
        ap.error("--slo-target needs --slo")
    if args.history or args.slo:
        cfg["timeseries"] = True
    if args.slo:
        cfg["slo"] = True
        if args.slo_target:
            targets = {}
            for kv in args.slo_target:
                k, _, v = kv.partition("=")
                try:
                    targets[k] = float(v)
                except ValueError:
                    ap.error(f"--slo-target {kv!r} is not KEY=FLOAT")
            cfg["slo_kw"] = {"targets": targets}
    if args.profile:
        cfg["profile"] = True
    if args.freshness:
        cfg["freshness"] = True
    if args.hop_anatomy:
        cfg["hop_anatomy"] = True
    if args.control:
        if not args.telemetry_dir:
            ap.error("--control needs --telemetry-dir (action rows, "
                     "replay input rows and control-epoch.json land "
                     "there)")
        cfg["control"] = True
        cfg["control_dir"] = args.telemetry_dir
    if args.fleet_dir:
        cfg["fleet_dir"] = args.fleet_dir
        if args.metrics_port is None:
            args.metrics_port = 0  # registration needs a live endpoint
    # lineage tracing: explicit --trace demands its prerequisites; the
    # default (no flag) arms it whenever they are already met — one
    # --telemetry-dir flag keeps meaning "full telemetry"
    if args.trace:
        if not args.telemetry_dir:
            ap.error("--trace needs --telemetry-dir (lineage rows and "
                     "the flow-event trace land there)")
        if not cfg["frame_check"]:
            ap.error("--trace needs frame checking (the trace ID rides "
                     "the v2 frame header); drop --no-frame-check")
    if (args.trace or (args.trace is None and args.telemetry_dir
                       and cfg["frame_check"])):
        cfg["lineage"] = True
        cfg["lineage_dir"] = args.telemetry_dir
    if args.numerics:
        import tempfile

        cfg["numerics"] = True
        # one dir, both ends: workers append probe rows here, the server
        # tails them and drops postmortems beside them
        cfg["numerics_dir"] = (args.telemetry_dir
                               or tempfile.mkdtemp(prefix="ps_numerics_"))
        cfg["numerics_kw"] = {"policy": args.numerics_policy,
                              "probe_every": args.numerics_probe_every}
    if args.metrics_port is not None:
        cfg["metrics_port"] = args.metrics_port
    if args.read_port is not None:
        cfg["read_port"] = args.read_port
        if args.snapshot_ring is not None:
            cfg["serving_kw"] = {"ring": args.snapshot_ring}
    elif args.snapshot_ring is not None:
        ap.error("--snapshot-ring needs --read-port (it sizes the read "
                 "tier's snapshot ring)")
    if args.ps_top and args.health_port is None:
        if args.supervise:
            ap.error("--ps-top with --supervise needs an explicit "
                     "--health-port (the dashboard must re-find the "
                     "endpoint across server restarts)")
        args.health_port = 0
    if args.health_port is not None:
        cfg["health_port"] = args.health_port
        if "health_dir" not in cfg:
            import tempfile

            cfg["health_dir"] = (args.telemetry_dir
                                 or tempfile.mkdtemp(prefix="ps_health_"))

    if args.supervise:
        from pytorch_ps_mpi_tpu.resilience import Supervisor

        if args.transport == "tcp":
            cfg["transport"] = "tcp"
        cfg["max_staleness"] = args.max_staleness
        if args.resume:
            cfg["resume"] = True
        sup = Supervisor(
            cfg, args.workers, port=args.port,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            sync_barrier=args.sync_barrier, timeout=args.timeout,
        )
        top = _spawn_ps_top(args.health_port) if args.ps_top else None
        try:
            params, metrics = sup.run()
        finally:
            _stop_ps_top(top)
        if args.telemetry_dir:
            # merged trace + report from the per-process JSONLs (no
            # device trace on the supervised path: the server process
            # restarts across phases, so there is no single profiler
            # session to capture)
            metrics.update(_export_telemetry(args.telemetry_dir,
                                             None, None))
        print(json.dumps(metrics, default=str))
        return metrics

    code = None
    if args.codec:
        from pytorch_ps_mpi_tpu.codecs import get_codec

        code = get_codec(args.codec)

    _, params0, _, _ = make_problem(cfg)
    if args.transport == "tcp":
        from pytorch_ps_mpi_tpu.parallel import tcp

        cfg["transport"] = "tcp"
        server = tcp.TcpPSServer(
            args.port, num_workers=args.workers, template=params0,
            max_staleness=args.max_staleness, code=code,
            bucket_mb=cfg.get("bucket_mb", 0.0),
            frame=cfg["frame_check"],
        )
        name = f"127.0.0.1:{server.port}"
        print(f"tcp PS listening on {name}")
    else:
        name = f"/psq_train_{os.getpid()}"
        server = dcn.ShmPSServer(
            name, num_workers=args.workers, template=params0,
            max_staleness=args.max_staleness, code=code,
            bucket_mb=cfg.get("bucket_mb", 0.0),
            frame=cfg["frame_check"],
        )
    total = args.workers * args.steps
    procs = []
    top = None
    if args.ps_top:
        # bind the /metrics + /health endpoint NOW (serve()'s own call is
        # idempotent and returns this same port) so the dashboard can
        # attach before the first gradient flows — on the SAME port
        # serve() would pick (metrics_port wins over health_port there),
        # so an explicit --metrics-port is honored, never shadowed
        bound = server.start_metrics_http(
            args.metrics_port if args.metrics_port is not None
            else args.health_port)
        print(f"/health live on port {bound}")
        top = _spawn_ps_top(bound)
    device_trace_dir = device_t0_wall = None
    if args.telemetry_dir:
        # device-side half of the merged timeline: trace the server
        # process's XLA programs (the jitted decode+update+publish path)
        # while serve() runs; workers are separate processes — their
        # host-side story arrives through their JSONLs
        import time as _time

        device_trace_dir = os.path.join(args.telemetry_dir, "device-trace")
        jax.profiler.start_trace(device_trace_dir)
        device_t0_wall = _time.time()
    try:
        procs = [spawn_worker(name, i, cfg) for i in range(args.workers)]
        params, metrics = serve(
            server, cfg, total_grads=0, total_received=total,
            sync_barrier=args.sync_barrier, timeout=args.timeout,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every, resume=args.resume,
        )
        for rc in join_workers(procs, timeout=args.timeout):
            if rc != 0:
                raise SystemExit(f"worker exited {rc}")
    finally:
        if device_trace_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # a profiler write error must never
                # skip the server close / orphan-worker kill below
                print(f"device trace capture failed: {e}", file=sys.stderr)
                device_trace_dir = None
        _stop_ps_top(top)
        # server.close() also tears down the /metrics + /health endpoint
        # (PSServerTelemetry.close_metrics_http) — no leaked sockets
        server.close()
        # never leave orphan workers if serve() raised: terminate + reap
        join_workers(procs, timeout=5.0)

    if args.telemetry_dir:
        metrics.update(_export_telemetry(
            args.telemetry_dir, device_trace_dir, device_t0_wall
        ))
    print(json.dumps(metrics, default=str))
    return metrics


def _spawn_ps_top(port):
    """Launch the live dashboard against the local /health endpoint."""
    import subprocess

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "ps_top.py",
    )
    return subprocess.Popen([sys.executable, script, str(int(port))])


def _stop_ps_top(proc) -> None:
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=5)
    except Exception:
        proc.kill()


def _parse_fault_plan(spec: str):
    """A fault plan from the CLI: inline JSON, or ``@file.json``."""
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            return json.load(f)
    return json.loads(spec)


def _export_telemetry(tdir: str, device_trace_dir, device_t0_wall) -> dict:
    """Merge every process's JSONL (+ the server's device trace) into
    trace.json, print the per-phase report, return artifact paths.

    When lineage files are present (``--trace``), the worker JSONLs are
    first shifted onto the server's clock by the per-worker offsets
    fitted from the frame send/recv timestamp pairs, and the trace gains
    cross-process flow events (arrows) linking each worker push span to
    its server consume span."""
    import glob

    from pytorch_ps_mpi_tpu.telemetry import (
        clock_offsets_from_rows,
        export_chrome_trace,
        is_sidecar,
        load_jsonl,
        load_lineage_rows,
    )
    from tools.telemetry_report import format_table, summarize

    # sidecar JSONLs (fault logs, beacons, numerics trajectories,
    # lineage compositions, anatomy rounds, retained histories, SLO
    # verdicts, controller actions) are not flight-recorder files: the
    # shared SIDECAR_PREFIXES registry (pytorch_ps_mpi_tpu.telemetry)
    # routes them away from the merged trace here AND from
    # telemetry_report's dir-mode span merge — one list, enforced by
    # psanalyze's sidecar-registry rule, instead of the two
    # hand-patched copies every observability PR used to edit
    files = sorted(f for f in glob.glob(os.path.join(tdir, "*.jsonl"))
                   if not is_sidecar(f))
    events = []
    for f in files:
        events.extend(load_jsonl(f)[1])
    lineage_files = sorted(glob.glob(os.path.join(tdir, "lineage-*.jsonl")))
    lineage_rows = []
    for f in lineage_files:
        lineage_rows.extend(load_lineage_rows(f))
    offsets = clock_offsets_from_rows(lineage_rows) if lineage_rows else None
    # hop-anatomy rows add one trace track per tree leader (sub-stage
    # spans the composed lineage arrows thread through)
    from pytorch_ps_mpi_tpu.telemetry import load_hop_rows

    hop_rows = []
    for f in sorted(glob.glob(os.path.join(tdir, "hop-*.jsonl"))):
        hop_rows.extend(load_hop_rows(f))
    trace_path, counts = export_chrome_trace(
        os.path.join(tdir, "trace.json"), events,
        device_trace_dir=device_trace_dir, device_t0_wall=device_t0_wall,
        lineage_rows=lineage_rows or None, clock_offsets=offsets,
        hop_rows=hop_rows or None,
    )
    # every sidecar with a report route joins the printed report through
    # its own section (numerics/lineage/anatomy/history/slo/actions),
    # never the span merge — the same registry decides both directions
    from pytorch_ps_mpi_tpu.telemetry import (
        SIDECAR_PREFIXES,
        sidecar_prefix,
    )

    section_files = sorted(
        f for f in glob.glob(os.path.join(tdir, "*.jsonl"))
        if SIDECAR_PREFIXES.get(sidecar_prefix(f) or "") is not None)
    obs_files = sorted(glob.glob(os.path.join(tdir, "profile-*.txt")))
    print(format_table(summarize(files + section_files + obs_files,
                                 by_worker=False)))
    out = {
        "telemetry_trace": trace_path,
        "telemetry_trace_host_events": counts["host"],
        "telemetry_trace_device_events": counts["device"],
        "telemetry_files": files,
    }
    if lineage_rows:
        out["telemetry_trace_flow_events"] = counts["flow"]
        out["clock_offsets"] = offsets
    if hop_rows:
        out["telemetry_trace_hop_events"] = counts["hop"]
    return out


if __name__ == "__main__":
    main()
