"""Read-only parameter-serving tier from a checkpoint directory.

The :class:`~pytorch_ps_mpi_tpu.serving.ServingCore` without a trainer
loop, without workers, without a transport server: restore the latest PS
checkpoint (the ``_PSCheckpointCadence`` snapshots ``serve()`` /
``Supervisor`` write), publish it into the snapshot ring, and serve
version-conditional reads (not-modified / delta / full, with coalescing
and admission control) plus ``/metrics`` + ``/health`` — the deployment
shape where inference replicas read a trained model without ever
touching the training fleet.

With ``--follow`` the tier keeps polling the checkpoint directory and
republishes whenever the trainer lands a newer step, so readers track a
LIVE training run through cheap delta reads.

Examples::

  # train with checkpoints, then serve them read-only
  python examples/train_async.py --model mlp --workers 2 --steps 50 \\
      --checkpoint-dir /tmp/ps_ckpt
  python examples/serve_readonly.py --checkpoint-dir /tmp/ps_ckpt \\
      --model mlp --read-port 7070 --metrics-port 9100

  # a reader
  python - <<'PY'
  from pytorch_ps_mpi_tpu.serving import ServingReader
  from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
  cfg = {"model": "mlp", "model_kw": {"features": (64, 8)},
         "in_shape": [8], "batch": 1, "seed": 0}
  _, tmpl, _, _ = make_problem(cfg)
  r = ServingReader("127.0.0.1", 7070, tmpl)
  params, version = r.read_params()
  print("got version", version)
  PY
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def restore_latest(checkpoint_dir: str, cfg: dict):
    """(params, version, step) from the newest PS checkpoint."""
    from pytorch_ps_mpi_tpu.optim import OPTIMIZERS
    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
    from pytorch_ps_mpi_tpu.utils.checkpoint import CheckpointManager

    _, params0, _, _ = make_problem(cfg)
    _, init_state, _ = OPTIMIZERS[cfg.get("optim", "sgd")]
    template = {"params": params0, "opt_state": init_state(params0),
                "version": 0, "applied_total": 0, "checkpoint_every": 0}
    ckpt = CheckpointManager(checkpoint_dir)
    step = ckpt.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoints in {checkpoint_dir}")
    restored = ckpt.restore(template, step=step)
    return restored["params"], int(restored["version"]), int(step), params0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--checkpoint-dir", required=True,
                    help="directory of _PSCheckpointCadence snapshots")
    ap.add_argument("--model", choices=["mlp", "resnet18", "resnet50"],
                    default="mlp",
                    help="model the checkpoint was trained with (defines "
                         "the parameter template — must match training)")
    ap.add_argument("--read-port", type=int, default=0,
                    help="read-tier port (0 = auto; printed on stdout)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="/metrics + /health port (0 = auto)")
    ap.add_argument("--tenant", default="default",
                    help="tenant namespace this checkpoint serves under")
    ap.add_argument("--ring", type=int, default=8,
                    help="snapshot ring depth (versions kept for deltas)")
    ap.add_argument("--admission-depth", type=int, default=64)
    ap.add_argument("--follow", type=float, default=0.0,
                    help="poll the checkpoint dir every N seconds and "
                         "republish newer steps (0 = serve one snapshot)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="exit after this many seconds (0 = forever)")
    args = ap.parse_args(argv)

    cfg = {
        "model": args.model,
        "model_kw": {"num_classes": 10} if args.model != "mlp" else
                    {"features": (64, 8)},
        "in_shape": [8] if args.model == "mlp" else [32, 32, 3],
        "batch": 1,
        "seed": 0,
    }
    params, version, step, template = restore_latest(
        args.checkpoint_dir, cfg)

    from pytorch_ps_mpi_tpu.serving import ServingCore

    serve_cfg = {
        "read_port": args.read_port,
        "metrics_port": args.metrics_port,
        "serving_kw": {"ring": args.ring,
                       "admission_depth": args.admission_depth},
    }
    core = ServingCore(None, serve_cfg, template=template,
                       tenant=args.tenant)
    core.publish(params, version=max(version, 1), tenant=args.tenant)
    hello = {"read_port": core.read_port, "tenant": args.tenant,
             "version": max(version, 1), "checkpoint_step": step}
    if core.metrics_http_port is not None:
        hello["metrics_port"] = core.metrics_http_port
    print(json.dumps(hello), flush=True)

    deadline = time.time() + args.duration if args.duration else None
    last_step = step
    try:
        while deadline is None or time.time() < deadline:
            time.sleep(min(args.follow, 1.0) if args.follow else 0.25)
            if args.follow:
                try:
                    params, version, step, _ = restore_latest(
                        args.checkpoint_dir, cfg)
                except (FileNotFoundError, ValueError, OSError):
                    continue  # trainer mid-write; next poll gets it
                if step > last_step:
                    v = core.publish(params, version=max(version, 1),
                                     tenant=args.tenant)
                    last_step = step
                    print(json.dumps({"republished": v,
                                      "checkpoint_step": step}),
                          flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        snap = core.serving_snapshot()
        core.close()
        print(json.dumps({"final_serving": {
            k: snap[k] for k in ("reads_total", "reads_delta",
                                 "reads_not_modified", "reads_shed",
                                 "coalesce_hits")}}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
