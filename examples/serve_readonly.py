"""Read-only parameter-serving tier from a checkpoint directory.

The :class:`~pytorch_ps_mpi_tpu.serving.ServingCore` without a trainer
loop, without workers, without a transport server: restore the latest PS
checkpoint (the ``_PSCheckpointCadence`` snapshots ``serve()`` /
``Supervisor`` write), publish it into the snapshot ring, and serve
version-conditional reads (not-modified / delta / full, with coalescing
and admission control) plus ``/metrics`` + ``/health`` — the deployment
shape where inference replicas read a trained model without ever
touching the training fleet.

With ``--follow`` the tier keeps polling the checkpoint directory and
republishes whenever the trainer lands a newer step, so readers track a
LIVE training run through cheap delta reads; the poll backs off
exponentially while no newer checkpoint appears (tpu_watch-style), so
an idle follower stops burning a core.

With ``--follow-endpoint HOST:PORT`` the process is a REPLICA instead:
it subscribes to an upstream read tier's delta stream
(:class:`~pytorch_ps_mpi_tpu.serving.FollowerLoop`) and re-serves it
from its own ring — chain replicas to build the distribution tree that
lets one trainer-side core serve N replicas rather than N×10⁴ readers.
Replicas register fleet cards with ``role="replica"`` (upstream +
fanout in the card), export ``replica_lag_versions`` /
``follower_bytes_relayed``, and survive a root restart by reconnecting
with backoff while serving their last version.

Examples::

  # train with checkpoints, then serve them read-only
  python examples/train_async.py --model mlp --workers 2 --steps 50 \\
      --checkpoint-dir /tmp/ps_ckpt
  python examples/serve_readonly.py --checkpoint-dir /tmp/ps_ckpt \\
      --model mlp --read-port 7070 --metrics-port 9100

  # a reader
  python - <<'PY'
  from pytorch_ps_mpi_tpu.serving import ServingReader
  from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
  cfg = {"model": "mlp", "model_kw": {"features": (64, 8)},
         "in_shape": [8], "batch": 1, "seed": 0}
  _, tmpl, _, _ = make_problem(cfg)
  r = ServingReader("127.0.0.1", 7070, tmpl)
  params, version = r.read_params()
  print("got version", version)
  PY
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def restore_latest(checkpoint_dir: str, cfg: dict):
    """(params, version, step) from the newest PS checkpoint."""
    from pytorch_ps_mpi_tpu.optim import OPTIMIZERS
    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
    from pytorch_ps_mpi_tpu.utils.checkpoint import CheckpointManager

    _, params0, _, _ = make_problem(cfg)
    _, init_state, _ = OPTIMIZERS[cfg.get("optim", "sgd")]
    template = {"params": params0, "opt_state": init_state(params0),
                "version": 0, "applied_total": 0, "checkpoint_every": 0}
    ckpt = CheckpointManager(checkpoint_dir)
    step = ckpt.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoints in {checkpoint_dir}")
    restored = ckpt.restore(template, step=step)
    return restored["params"], int(restored["version"]), int(step), params0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--checkpoint-dir", default=None,
                    help="directory of _PSCheckpointCadence snapshots "
                         "(required unless --follow-endpoint)")
    ap.add_argument("--model", choices=["mlp", "resnet18", "resnet50"],
                    default="mlp",
                    help="model the checkpoint was trained with (defines "
                         "the parameter template — must match training)")
    ap.add_argument("--read-port", type=int, default=0,
                    help="read-tier port (0 = auto; printed on stdout)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="/metrics + /health port (0 = auto)")
    ap.add_argument("--tenant", default="default",
                    help="tenant namespace this checkpoint serves under")
    ap.add_argument("--ring", type=int, default=8,
                    help="snapshot ring depth (versions kept for deltas)")
    ap.add_argument("--admission-depth", type=int, default=64)
    ap.add_argument("--follow", type=float, default=0.0,
                    help="poll the checkpoint dir every N seconds and "
                         "republish newer steps (0 = serve one snapshot; "
                         "idle polls back off exponentially to "
                         "max(8s, 4x this))")
    ap.add_argument("--follow-endpoint", default=None, metavar="HOST:PORT",
                    help="replica mode: subscribe to this upstream read "
                         "tier and re-serve its delta stream (no "
                         "checkpoint dir needed)")
    ap.add_argument("--fanout", type=int, default=2,
                    help="replica mode: downstream replicas this node is "
                         "provisioned to feed (advertised on the fleet "
                         "card for tree planning)")
    ap.add_argument("--serving-kw", default=None,
                    help="JSON dict merged into serving_kw (delta codec "
                         "knobs etc. — must match the upstream's codec "
                         "in replica mode)")
    ap.add_argument("--read-native", default="auto",
                    help="native C++ read tier: auto (default; falls "
                         "back to the Python loop), off")
    ap.add_argument("--fleet-dir", default=None,
                    help="register this tier's endpoint card here "
                         "(role=replica when following an endpoint)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="replica mode: write reader_round anatomy rows "
                         "(anatomy-<fleet name>.jsonl) here")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="exit after this many seconds (0 = forever)")
    ap.add_argument("--control-dir", default=None,
                    help="replica mode: poll control-topo.json here and "
                         "re-parent the subscription when its "
                         "replica_upstream map names this replica "
                         "(structural control's elastic read tier)")
    args = ap.parse_args(argv)
    if not args.checkpoint_dir and not args.follow_endpoint:
        ap.error("--checkpoint-dir is required unless --follow-endpoint")

    serving_kw = {"ring": args.ring,
                  "admission_depth": args.admission_depth}
    serving_kw.update(json.loads(args.serving_kw) if args.serving_kw
                      else {})
    cfg = {
        "model": args.model,
        "model_kw": {"num_classes": 10} if args.model != "mlp" else
                    {"features": (64, 8)},
        "in_shape": [8] if args.model == "mlp" else [32, 32, 3],
        "batch": 1,
        "seed": 0,
        "read_port": args.read_port,
        "read_native": args.read_native,
        "metrics_port": args.metrics_port,
        "serving_kw": serving_kw,
        "follow_endpoint": args.follow_endpoint,
        "follow_fanout": args.fanout,
    }
    if args.fleet_dir:
        cfg["fleet_dir"] = args.fleet_dir
        cfg["fleet_name"] = (f"replica-{os.getpid()}"
                             if args.follow_endpoint else "read-tier")
        if args.follow_endpoint:
            cfg["fleet_role"] = "replica"
            cfg["fleet_meta"] = {"upstream": args.follow_endpoint,
                                 "fanout": cfg.get("follow_fanout")}

    if args.checkpoint_dir:
        params, version, step, template = restore_latest(
            args.checkpoint_dir, cfg)
    else:
        from pytorch_ps_mpi_tpu.parallel.async_train import make_problem

        _, template, _, _ = make_problem(cfg)
        params, version, step = None, 0, -1

    from pytorch_ps_mpi_tpu.serving import FollowerLoop, ServingCore

    core = ServingCore(None, cfg, template=template, tenant=args.tenant)
    if params is not None:
        core.publish(params, version=max(version, 1), tenant=args.tenant)
    follower = None
    if cfg.get("follow_endpoint"):
        up_host, _, up_port = str(cfg["follow_endpoint"]).rpartition(":")
        anatomy = None
        if args.telemetry_dir:
            from pytorch_ps_mpi_tpu.telemetry.anatomy import RoundAnatomy

            anatomy = RoundAnatomy(
                None, {"telemetry_dir": args.telemetry_dir},
                num_workers=1,
                name=str(cfg.get("fleet_name") or "replica"))
        follower = FollowerLoop(
            core, up_host or "127.0.0.1", int(up_port),
            template=template, tenant=args.tenant,
            poll_s=args.follow or 0.25, serving_kw=serving_kw,
            anatomy=anatomy).start()
    hello = {"read_port": core.read_port, "tenant": args.tenant,
             "version": max(version, 1) if params is not None else 0,
             "checkpoint_step": step, "native": core.read_native}
    if follower is not None:
        hello["upstream"] = cfg["follow_endpoint"]
        hello["fanout"] = cfg.get("follow_fanout")
    if core.metrics_http_port is not None:
        hello["metrics_port"] = core.metrics_http_port
    print(json.dumps(hello), flush=True)

    deadline = time.time() + args.duration if args.duration else None
    topo_state = {"seq": 0, "mtime": 0}
    replica_name = str(cfg.get("fleet_name") or f"replica-{os.getpid()}")

    def _poll_reparent():
        # structural control: a scale event can rebuild the replica
        # tree — control-topo.json's replica_upstream map names each
        # replica's (possibly new) parent; repoint is idempotent
        if not (args.control_dir and follower is not None):
            return
        from pytorch_ps_mpi_tpu.control.topo import poll_topo

        doc = poll_topo(args.control_dir, topo_state)
        if doc is None:
            return
        up = (doc.get("replica_upstream") or {}).get(replica_name)
        if not up:
            return
        host, _, port = str(up).rpartition(":")
        try:
            if follower.repoint(host or "127.0.0.1", int(port)):
                print(json.dumps({"reparented": up}), flush=True)
        except (TypeError, ValueError):
            pass

    last_step = step
    # idle-backoff pacing (tpu_watch-style): a fresh checkpoint snaps the
    # poll back to the base cadence; every empty poll doubles it
    base_sleep = min(args.follow, 1.0) if args.follow else 0.25
    max_sleep = max(8.0, 4.0 * base_sleep) if args.follow else base_sleep
    sleep_s = base_sleep
    try:
        while deadline is None or time.time() < deadline:
            time.sleep(sleep_s if deadline is None
                       else min(sleep_s, max(deadline - time.time(), 0)))
            _poll_reparent()
            if args.follow and args.checkpoint_dir:
                try:
                    params, version, step, _ = restore_latest(
                        args.checkpoint_dir, cfg)
                except (FileNotFoundError, ValueError, OSError):
                    sleep_s = min(sleep_s * 2.0, max_sleep)
                    continue  # trainer mid-write; next poll gets it
                if step > last_step:
                    v = core.publish(params, version=max(version, 1),
                                     tenant=args.tenant)
                    last_step = step
                    sleep_s = base_sleep
                    print(json.dumps({"republished": v,
                                      "checkpoint_step": step}),
                          flush=True)
                else:
                    sleep_s = min(sleep_s * 2.0, max_sleep)
    except KeyboardInterrupt:
        pass
    finally:
        if follower is not None:
            follower.close()
        snap = core.serving_snapshot()
        core.close()
        final = {k: snap[k] for k in ("reads_total", "reads_delta",
                                      "reads_not_modified", "reads_shed",
                                      "coalesce_hits")}
        if follower is not None:
            final["republished"] = follower.republished
            final["replica_lag_versions"] = snap["replica_lag_versions"]
            final["follower_bytes_relayed"] = snap[
                "follower_bytes_relayed"]
        print(json.dumps({"final_serving": final}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
