"""Train a TP-sharded transformer block with the drop-in optimizer.

The user-facing CLI for round 5's headline composition: a (data, model)
— optionally (data, seq, model) — mesh where Megatron column/row-
parallel attention + MLP keep their weights sharded over 'model', ring
attention (with --sp) shards the sequence, and ``MPI_PS(param_specs=…)``
drives the whole thing: per-device local gradients flow through the
codec pipeline, aggregate over the data axes only, and the optimizer
state (leader/ZeRO-1 included) stays sharded alongside the weights.
The numerics behind every path are pinned in
``tests/test_ps_model_parallel.py``.

The reference scaled workers only (`README.md:6` "models fit on one
device"); this script is the model axis as a one-command surface.

Examples:
  # 2-way data x 4-way tensor parallelism (virtual CPU mesh ok):
  python examples/train_tp.py --dp 2 --tp 4 --steps 3

  # the full 3-D mesh with a bf16 wire and ZeRO-1 sharded optimizer:
  python examples/train_tp.py --dp 2 --sp 2 --tp 2 --codec bf16 \
      --mode leader --steps 3
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2, help="data-parallel ways")
    ap.add_argument("--sp", type=int, default=1,
                    help="sequence-parallel ways (ring attention)")
    ap.add_argument("--tp", type=int, default=4,
                    help="tensor-parallel ways (devices = dp * sp * tp)")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=4,
                    help="global batch (must divide by --dp)")
    ap.add_argument("--seq", type=int, default=32,
                    help="sequence length (must divide by --sp)")
    ap.add_argument("--optim", choices=["sgd", "adam"], default="sgd")
    ap.add_argument("--mode", choices=["allgather", "leader"],
                    default="allgather",
                    help="leader = ZeRO-1 sharded optimizer state")
    ap.add_argument("--codec", default=None,
                    help="gradient codec (e.g. bf16, powersgd, topk)")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--ffn", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=512)
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    n_need = args.dp * args.sp * args.tp

    # fail fast on pure-CLI mistakes BEFORE the backend probe
    if args.batch % args.dp:
        print(f"--batch {args.batch} must divide by --dp {args.dp}",
              file=sys.stderr)
        sys.exit(2)
    if args.seq % args.sp:
        print(f"--seq {args.seq} must divide by --sp {args.sp}",
              file=sys.stderr)
        sys.exit(2)
    if args.heads % args.tp:
        print(f"--heads {args.heads} must divide by --tp {args.tp}",
              file=sys.stderr)
        sys.exit(2)
    if args.hidden % args.heads:
        print(f"--hidden {args.hidden} must divide by --heads {args.heads}",
              file=sys.stderr)
        sys.exit(2)
    if args.ffn % args.tp:
        print(f"--ffn {args.ffn} must divide by --tp {args.tp}",
              file=sys.stderr)
        sys.exit(2)

    from pytorch_ps_mpi_tpu.utils.backend_guard import (
        enable_compilation_cache,
        ensure_live_backend,
        size_virtual_cpu_mesh,
    )

    live = ensure_live_backend()
    enable_compilation_cache()

    import jax

    if not live:
        # the guard already pinned the platform to the host CPU; size
        # the virtual mesh before anything initializes the backend
        size_virtual_cpu_mesh(n_need)
    if len(jax.devices()) < n_need:
        print(
            f"backend {jax.default_backend()!r} has {len(jax.devices())} "
            f"device(s) < dp*sp*tp={n_need}; re-run under a larger slice "
            "or use the virtual CPU mesh (JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_need})",
            file=sys.stderr,
        )
        sys.exit(2)

    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pytorch_ps_mpi_tpu.codecs import get_codec
    from pytorch_ps_mpi_tpu.mesh import make_mesh
    from pytorch_ps_mpi_tpu.parallel import tp as tpmod
    from pytorch_ps_mpi_tpu.ps import MPI_PS

    mesh = make_mesh(shape=(args.dp, args.sp, args.tp),
                     axis_names=("data", "seq", "model"),
                     devices=jax.devices()[:n_need])

    d, heads, ffn, vocab = args.hidden, args.heads, args.ffn, args.vocab
    seq, batch = args.seq, args.batch
    l_local = seq // args.sp

    k = jax.random.key(0)
    k_emb, k_pos, k_attn, k_mlp, k_head, k_tok = jax.random.split(k, 6)
    params = {
        "emb": 0.02 * jax.random.normal(k_emb, (vocab, d)),
        "pos": 0.02 * jax.random.normal(k_pos, (seq, d)),
        "attn": tpmod.init_tp_attention(k_attn, d, heads, args.tp),
        "mlp": tpmod.init_tp_mlp(k_mlp, d, ffn, args.tp),
        "head": 0.02 * jax.random.normal(k_head, (d, vocab)),
    }
    specs = {
        "emb": P(), "pos": P(),
        "attn": tpmod.tp_param_spec(params["attn"], "model"),
        "mlp": tpmod.tp_param_spec(params["mlp"], "model"),
        "head": P(),
    }
    tokens = jax.random.randint(k_tok, (batch, seq), 1, vocab)

    def loss_fn(p, toks):
        offset = lax.axis_index("seq") * l_local
        x = p["emb"][toks] + p["pos"][offset + jnp.arange(l_local)][None]
        x = x + tpmod.tp_self_attention(
            x, p["attn"], "model",
            seq_axis="seq" if args.sp > 1 else None,
            causal=False, local_grads=True,
        )
        x = x + tpmod.tp_mlp(x, p["mlp"], "model", local_grads=True)
        logits = x @ p["head"]
        ll = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(ll, toks[..., None], axis=-1)[..., 0]
        # local loss, STATIC global normalizer (the param_specs contract)
        return -ll.sum() / jnp.asarray(batch * seq, jnp.float32)

    agg = ("data", "seq") if args.sp > 1 else "data"
    batch_spec = P("data", "seq") if args.sp > 1 else P("data")
    opt = MPI_PS(
        params, optim=args.optim, lr=args.lr, mode=args.mode,
        code=get_codec(args.codec) if args.codec else None,
        mesh=mesh, axis_name=agg, param_specs=specs, batch_spec=batch_spec,
    )

    for step in range(args.steps):
        t0 = time.perf_counter()
        loss, data = opt.step(loss_fn=loss_fn, batch=tokens)
        print(json.dumps({
            "step": step,
            "loss": round(float(loss), 4),
            "step_s": round(time.perf_counter() - t0, 3),
            "mesh": f"{args.dp}x{args.sp}x{args.tp}",
            "mode": args.mode,
            "codec": args.codec or "identity",
            "wire_lowering": data["wire_lowering"],
            "wire_bytes_per_worker": data["wire_bytes_per_worker"],
        }), flush=True)

    w1 = opt.params["mlp"]["w1"]
    assert "model" in str(w1.sharding.spec), w1.sharding
    print(json.dumps({"done": True,
                      "tp_leaves_sharded_over": str(w1.sharding.spec)}),
          flush=True)


if __name__ == "__main__":
    main()
