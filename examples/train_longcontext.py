"""Train a causal LM at long sequence length with sequence parallelism.

The user-facing CLI for the context-parallel paths (`parallel/ring.py`,
`parallel/ulysses.py`): a GPT over a (data, seq) mesh where every device
holds one sequence shard, ring hops (or Ulysses all_to_alls) exchange
the K/V context, per-layer remat keeps activation memory flat, and the
data-parallel gradient psum rides the same fused step — the composition
`tests/test_longcontext.py` proves at seq 2048.

The reference scaled workers, never sequence (`README.md:6` "models fit
on one device" — SURVEY §5.7); this script is that missing axis as a
one-command surface.

Examples:
  # 8 sequence shards, seq 2048, ring attention (virtual CPU mesh ok):
  python examples/train_longcontext.py --seq 2048 --sp 8 --steps 3

  # 4-way data x 2-way sequence, Ulysses:
  python examples/train_longcontext.py --dp 4 --sp 2 --batch 4 \
      --attention ulysses
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--dp", type=int, default=1, help="data-parallel ways")
    ap.add_argument("--sp", type=int, default=8,
                    help="sequence-parallel ways (devices = dp * sp)")
    ap.add_argument("--attention", choices=["ring", "ulysses"],
                    default="ring")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=1,
                    help="global batch (must divide by --dp)")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--no-remat", action="store_true",
                    help="disable per-layer rematerialization")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=512)
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    n_need = args.dp * args.sp

    # fail fast on pure-CLI mistakes BEFORE the backend probe (a dead
    # tunnel costs minutes of probing; a typo'd --seq should not)
    if args.batch % args.dp:
        print(f"--batch {args.batch} must divide by --dp {args.dp}",
              file=sys.stderr)
        sys.exit(2)
    if args.seq % args.sp:
        print(f"--seq {args.seq} must divide by --sp {args.sp}",
              file=sys.stderr)
        sys.exit(2)
    if args.attention == "ulysses" and args.heads % args.sp:
        # ulysses shards HEADS over the seq axis after its all_to_all
        print(f"--attention ulysses needs --heads {args.heads} divisible "
              f"by --sp {args.sp}", file=sys.stderr)
        sys.exit(2)

    from pytorch_ps_mpi_tpu.utils.backend_guard import (
        enable_compilation_cache,
        ensure_live_backend,
    )

    live = ensure_live_backend()
    enable_compilation_cache()

    import jax

    if not live:
        # the guard already pinned the platform to the host CPU; size the
        # virtual mesh BEFORE anything initializes the backend (the knob
        # is ignored once jax.devices() has run)
        from pytorch_ps_mpi_tpu.utils.backend_guard import (
            size_virtual_cpu_mesh,
        )

        size_virtual_cpu_mesh(n_need)
    if len(jax.devices()) < n_need:
        print(
            f"backend {jax.default_backend()!r} has {len(jax.devices())} "
            f"device(s) < dp*sp={n_need}; re-run under a larger slice or "
            "use the virtual CPU mesh (JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_need})",
            file=sys.stderr,
        )
        sys.exit(2)

    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pytorch_ps_mpi_tpu.mesh import make_mesh
    from pytorch_ps_mpi_tpu.models import GPTLM, gpt_config
    from pytorch_ps_mpi_tpu.optim import (
        SGDHyper,
        init_sgd_state,
        sgd_update,
    )

    mesh = make_mesh(shape=(args.dp, args.sp), axis_names=("data", "seq"),
                     devices=jax.devices()[:n_need])
    l_local = args.seq // args.sp

    kw = dict(vocab_size=args.vocab, hidden_size=args.hidden,
              num_layers=args.layers, num_heads=args.heads,
              intermediate_size=2 * args.hidden, max_position=args.seq,
              remat=not args.no_remat)
    cfg = gpt_config(attention=args.attention, **kw)
    cfg_init = gpt_config(**kw)  # full-attention twin: same param tree,
    #                              init needs no bound mesh axis

    tokens = jax.random.randint(jax.random.key(1),
                                (args.batch, args.seq), 0, args.vocab)
    # init on a SHORT slice: parameter shapes depend only on the config
    # (vocab/max_position/hidden), and a full-length dense init forward
    # would materialize O(seq^2) scores on one device — the exact wall
    # this script exists to avoid
    init_toks = tokens[:1, : min(16, args.seq)]
    params = jax.jit(GPTLM(cfg_init).init)(jax.random.key(0), init_toks)
    opt_state = init_sgd_state(params)
    h = SGDHyper(lr=args.lr, momentum=args.momentum)
    model = GPTLM(cfg)

    def spmd(params, opt_state, toks):
        offset = lax.axis_index("seq") * l_local

        # the denominator is a compile-time constant (same local target
        # count on every shard): batch * (seq - sp) total targets
        den = float(args.batch * (args.seq - args.sp))

        def loss_fn(p):
            logits = model.apply(p, toks, position_offset=offset)
            # globally-normalized next-token CE. Targets are sliced PER
            # SHARD (position t predicts t+1 within the shard), so the
            # sp-1 cross-shard boundary predictions are excluded from
            # the objective — a deliberate simplification worth ~sp/seq
            # of the tokens (8/2048 = 0.4% at the defaults); loss values
            # are comparable across --sp only up to that. The MODEL
            # attends across shards fully (ring/ulysses); only the loss
            # slicing is shard-local.
            logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            ll = jnp.take_along_axis(logp, toks[:, 1:, None],
                                     axis=-1)[..., 0]
            num = lax.psum(ll.sum(), ("seq", "data"))
            return -num / den

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # one fused all-reduce over both mesh axes per leaf
        grads = jax.tree.map(lambda g: lax.psum(g, ("seq", "data")), grads)
        new_p, new_s = sgd_update(params, grads, opt_state, h)
        return new_p, new_s, loss

    step = jax.jit(
        jax.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), P("data", "seq")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )

    print(f"mesh=({args.dp}x{args.sp}) attention={args.attention} "
          f"seq={args.seq} (l_local={l_local}) remat={not args.no_remat} "
          f"backend={jax.default_backend()}", flush=True)
    t0 = time.time()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        loss = float(loss)
        print(json.dumps({"step": i, "loss": round(loss, 4),
                          "wall_s": round(time.time() - t0, 2)}),
              flush=True)
        assert loss == loss, "loss is NaN"


if __name__ == "__main__":
    main()
