"""Train against SHARDED parameter servers from the command line.

Spawns S shard-server processes (each owning a contiguous slice of the
flat parameter vector, Li et al. OSDI'14 — ``parallel/sharded.py``) and
W worker processes (jitted ``value_and_grad``, per-shard push/read over
the TCP wire), waits for completion, reassembles the final model from
the shard snapshots, and prints a metrics JSON line. On one machine the
shards are processes; across hosts the same worker code connects to
remote ``host:port`` addresses.

Examples:
  python examples/train_sharded.py --shards 2 --workers 3 --steps 40
  python examples/train_sharded.py --codec sign --slow-shard-ms 8
  python examples/train_sharded.py --checkpoint-dir /tmp/ck --resume
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

# abspath, not __file__.rsplit: a relative invocation like
# `python examples/train_sharded.py` must still find the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", choices=["mlp", "resnet18", "resnet50"],
                    default="mlp")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--steps", type=int, default=40,
                    help="gradient pushes per worker (per shard)")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--optim", choices=["sgd", "adam"], default="sgd")
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--codec", default=None,
                    help="payload codec on every shard wire (e.g. sign)")
    ap.add_argument("--max-staleness", type=int, default=4)
    ap.add_argument("--slow-shard-ms", type=float, default=0.0,
                    help="per-update sleep injected into the LAST shard "
                         "(forces observable cross-shard version spread)")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=50)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args(argv)
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir")

    import jax

    jax.config.update("jax_platforms", "cpu")  # coordinator does no compute

    import numpy as np

    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
    from pytorch_ps_mpi_tpu.parallel.sharded import (
        assemble,
        read_server_port,
        spawn_shard_server,
        spawn_sharded_worker,
    )

    in_shape = (8,) if args.model == "mlp" else (32, 32, 3)
    cfg = {
        "model": args.model,
        "model_kw": {"num_classes": 10} if args.model != "mlp" else
                    {"features": (64, 8)},
        "in_shape": list(in_shape),
        "batch": args.batch,
        "seed": 0,
        "optim": args.optim,
        "hyper": {"lr": args.lr},
        "n_workers": args.workers,
        "steps": args.steps,
        "max_staleness": args.max_staleness,
        "server_timeout": args.timeout,
        "open_timeout": args.timeout,
        "push_timeout": args.timeout,
    }
    if args.codec:
        cfg["codec"] = args.codec
    if args.slow_shard_ms:
        cfg["server_slow_ms"] = {str(args.shards - 1): args.slow_shard_ms}
    if args.checkpoint_dir:
        cfg["checkpoint_dir"] = args.checkpoint_dir
        cfg["checkpoint_every"] = args.checkpoint_every
        cfg["resume"] = args.resume

    _, params0, batch_fn, loss_fn = make_problem(cfg)

    tmp = tempfile.mkdtemp(prefix="sharded_")
    servers, shard_paths, workers, worker_paths = [], [], [], []
    try:
        return _run(args, cfg, tmp, servers, shard_paths, workers,
                    worker_paths, params0, batch_fn, loss_fn)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)  # snapshots already read


def _run(args, cfg, tmp, servers, shard_paths, workers, worker_paths,
         params0, batch_fn, loss_fn):
    import numpy as np

    from pytorch_ps_mpi_tpu.parallel.sharded import (
        assemble,
        read_server_port,
        spawn_shard_server,
        spawn_sharded_worker,
    )

    try:
        for s in range(args.shards):
            out = f"{tmp}/shard{s}.npz"
            shard_paths.append(out)
            servers.append(spawn_shard_server(s, args.shards, cfg, out))
        ports = [read_server_port(p, timeout=args.timeout) for p in servers]
        addrs = [f"127.0.0.1:{p}" for p in ports]
        print(f"shard servers: {addrs}")
        for w in range(args.workers):
            out = f"{tmp}/worker{w}.json"
            worker_paths.append(out)
            workers.append(spawn_sharded_worker(addrs, w, cfg, out))
        for p in workers:
            rc = p.wait(timeout=args.timeout)
            if rc != 0:
                raise SystemExit(f"worker exited {rc}")
        for p in servers:
            rc = p.wait(timeout=args.timeout)
            if rc != 0:
                raise SystemExit(f"shard server exited {rc}")
    finally:
        for p in servers + workers:
            if p.poll() is None:
                p.kill()

    params = assemble(shard_paths, params0)
    eval_batch = batch_fn(10**6, 10**6)
    shards_meta = []
    for path in shard_paths:
        z = np.load(path, allow_pickle=False)
        shards_meta.append({
            "applied_total": int(z["applied_total"]),
            "version": int(z["version"]),
            "stale_drops": int(z["stale_drops"]),
            "compression_ratio": round(float(z["compression_ratio"]), 2),
        })
    spreads = []
    for path in worker_paths:
        with open(path) as f:
            spreads.append(json.load(f)["max_version_spread"])
    metrics = {
        "loss_initial": float(loss_fn(params0, eval_batch)),
        "loss_final": float(loss_fn(params, eval_batch)),
        "shards": shards_meta,
        "max_version_spread_seen": max(spreads) if spreads else 0,
    }
    print(json.dumps(metrics))
    return metrics


if __name__ == "__main__":
    main()
