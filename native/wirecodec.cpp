// wirecodec: host-side wire compression + homomorphic fold kernels.
//
// The TPU-native framework's answer to the reference's c-blosc dependency
// (reference mpi_comms.py:18-30 reached blosc through python bindings; this
// repo ships the native code itself). Two classic filters:
//
//   * byte shuffle  — transpose the bytes of fixed-width elements so that
//     high-order bytes (mostly equal for floats of similar magnitude) become
//     long runs; blosc's core trick.
//   * RLE0          — run-length encode zero bytes, which dominate shuffled
//     float data and sparse/top-k gradient payloads.
//
// On-device gradients never touch this path (ICI outruns any host codec —
// SURVEY §2.4); this is for host I/O: checkpoints, cross-process metadata,
// DCN-side buffers.
//
// Format of rle0: repeated [zero_run varint][lit_len varint][lit bytes].
// Varints are LEB128. Worst case output = input + 16.
//
// -- wc_fold_*: fused decode+accumulate (the serve loop's hot path) --------
//
// One kernel per compressed-domain algebra family (codecs/base.py): each
// folds ONE worker's payload into the round accumulator in a single pass
// over the payload — dequantize-multiply-add fused, so the f32
// "decoded tensor" intermediate the numpy fallback materializes
// (multiply into tmp, then add) never exists. Auto-vectorized by -O3;
// compiled with -ffp-contract=off (utils/native.py passes it) so the
// separate multiply and add match the numpy fallback BIT-EXACTLY — an
// FMA-contracted fold would be more accurate but would break the
// native==numpy parity contract the tests pin.

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>

// -- fold cycle counters (continuous profiling, telemetry/profiler.py) ----
// A Python stack sampler cannot see inside one opaque ctypes call, so the
// fold hot path keeps its own process-global counters: calls, elements
// folded, and wall nanoseconds. One clock_gettime pair per fold call
// (~40 ns) against payload-sized loops — negligible, and relaxed atomics
// keep the counters safe if folds ever run off the serve thread.
static std::atomic<uint64_t> g_fold_calls{0};
static std::atomic<uint64_t> g_fold_elems{0};
static std::atomic<uint64_t> g_fold_ns{0};

// -- per-fold-call interval ring (hop anatomy) ------------------------------
// The counters above answer "how much fold work happened"; the hop-anatomy
// plane (telemetry/hop_anatomy.py) also needs WHEN each fold ran, so armed
// processes additionally record one (start_ns, end_ns, elems) span per
// wc_fold_* call into a bounded ring. Overflow drops the span and counts
// the drop — the ring never blocks or reallocates on the fold hot path.
// Single-writer discipline: arm/drain only from the fold-calling thread
// (the leader loop), same affinity rule as tps_server_read_stats.
struct FoldSpan {
  uint64_t start_ns;  // CLOCK_MONOTONIC at fold entry
  uint64_t end_ns;    // CLOCK_MONOTONIC at fold return
  uint64_t elems;     // elements folded by this call
};
static_assert(sizeof(FoldSpan) == 24, "FoldSpan must be 24 bytes");

static FoldSpan* g_span_ring = nullptr;
static uint32_t g_span_cap = 0;
static std::atomic<uint32_t> g_span_len{0};
static std::atomic<uint64_t> g_span_dropped{0};

namespace {
struct FoldProf {
  timespec t0;
  explicit FoldProf() { clock_gettime(CLOCK_MONOTONIC, &t0); }
  void done(size_t n) {
    timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    uint64_t ns = (uint64_t)(t1.tv_sec - t0.tv_sec) * 1000000000ull +
                  (uint64_t)(t1.tv_nsec - t0.tv_nsec);
    g_fold_calls.fetch_add(1, std::memory_order_relaxed);
    g_fold_elems.fetch_add((uint64_t)n, std::memory_order_relaxed);
    g_fold_ns.fetch_add(ns, std::memory_order_relaxed);
    if (g_span_ring != nullptr) {
      uint32_t len = g_span_len.load(std::memory_order_relaxed);
      if (len < g_span_cap) {
        FoldSpan& s = g_span_ring[len];
        s.start_ns = (uint64_t)t0.tv_sec * 1000000000ull +
                     (uint64_t)t0.tv_nsec;
        s.end_ns = s.start_ns + ns;
        s.elems = (uint64_t)n;
        g_span_len.store(len + 1, std::memory_order_release);
      } else {
        g_span_dropped.fetch_add(1, std::memory_order_relaxed);
      }
    }
  }
};
}  // namespace

extern "C" {

// Read (and optionally reset) the fold cycle counters — the
// "_native_read_stats-style refresh": Python copies three plain ints,
// never holds a native pointer across calls.
void wc_profile_stats(uint64_t* calls, uint64_t* elems, uint64_t* ns) {
  *calls = g_fold_calls.load(std::memory_order_relaxed);
  *elems = g_fold_elems.load(std::memory_order_relaxed);
  *ns = g_fold_ns.load(std::memory_order_relaxed);
}

void wc_profile_reset() {
  g_fold_calls.store(0, std::memory_order_relaxed);
  g_fold_elems.store(0, std::memory_order_relaxed);
  g_fold_ns.store(0, std::memory_order_relaxed);
}

// ABI self-description for the load-time size check (the ctypes twin in
// utils/native.py asserts its sizeof against this before first use).
uint32_t wc_abi_fold_span_bytes() { return (uint32_t)sizeof(FoldSpan); }

// Arm (or resize/disarm with capacity 0) the fold-span capture ring.
// Returns 0 on success, -1 on allocation failure. Arming resets length
// and the drop counter; call only from the fold thread.
int wc_fold_spans_arm(uint32_t capacity) {
  delete[] g_span_ring;
  g_span_ring = nullptr;
  g_span_cap = 0;
  g_span_len.store(0, std::memory_order_relaxed);
  g_span_dropped.store(0, std::memory_order_relaxed);
  if (capacity == 0) return 0;
  g_span_ring = new (std::nothrow) FoldSpan[capacity];
  if (g_span_ring == nullptr) return -1;
  g_span_cap = capacity;
  return 0;
}

// Copy out up to max recorded spans (oldest first), reset the ring, and
// report (then reset) the spans dropped to overflow since the previous
// drain. Returns the number of spans written to out. Fold thread only.
uint32_t wc_fold_spans_drain(FoldSpan* out, uint32_t max, uint64_t* dropped) {
  uint32_t len = g_span_len.load(std::memory_order_acquire);
  uint32_t n = len < max ? len : max;
  if (g_span_ring != nullptr && n > 0)
    std::memcpy(out, g_span_ring, (size_t)n * sizeof(FoldSpan));
  // entries beyond max are surrendered as drops, never silently lost
  if (len > n)
    g_span_dropped.fetch_add(len - n, std::memory_order_relaxed);
  g_span_len.store(0, std::memory_order_relaxed);
  if (dropped != nullptr)
    *dropped = g_span_dropped.exchange(0, std::memory_order_relaxed);
  return n;
}

// acc[i] += scale * q[i] — int8/qsgd scale-folded integer family.
void wc_fold_scaled_i8(float* acc, const int8_t* q, float scale, size_t n) {
  FoldProf prof;
  for (size_t i = 0; i < n; ++i) {
    float v = (float)q[i] * scale;
    acc[i] += v;
  }
  prof.done(n);
}

// acc[i] += scale * (digit_i - 1) — terngrad base-4 2-bit unpack + MA.
// packed holds 4 ternary digits {0,1,2} per byte, weights 1/4/16/64.
void wc_fold_tern(float* acc, const uint8_t* packed, float scale, size_t n) {
  FoldProf prof;
  size_t full = n / 4;
  for (size_t b = 0; b < full; ++b) {
    uint8_t p = packed[b];
    float* a = acc + b * 4;
    // digits decoded branch-free; separate mul+add per element (see
    // the -ffp-contract note above)
    float d0 = (float)((p & 3) - 1);
    float d1 = (float)(((p >> 2) & 3) - 1);
    float d2 = (float)(((p >> 4) & 3) - 1);
    float d3 = (float)(((p >> 6) & 3) - 1);
    a[0] += d0 * scale;
    a[1] += d1 * scale;
    a[2] += d2 * scale;
    a[3] += d3 * scale;
  }
  for (size_t i = full * 4; i < n; ++i) {
    int digit = (packed[i / 4] >> (2 * (i % 4))) & 3;
    acc[i] += (float)(digit - 1) * scale;
  }
  prof.done(n);
}

// votes[i] += bit_i — sign popcount vote counts (bitorder 'little',
// matching np.unpackbits(bitorder='little') and the jnp pack weights).
void wc_fold_sign(int32_t* votes, const uint8_t* packed, size_t n) {
  FoldProf prof;
  size_t full = n / 8;
  for (size_t b = 0; b < full; ++b) {
    uint8_t p = packed[b];
    int32_t* v = votes + b * 8;
    for (int j = 0; j < 8; ++j) v[j] += (p >> j) & 1;
  }
  for (size_t i = full * 8; i < n; ++i)
    votes[i] += (packed[i / 8] >> (i % 8)) & 1;
  prof.done(n);
}

// acc[idx[j]] += val[j] — sparse (idx, val) merge-fold straight into the
// dense f32 accumulator. Out-of-range indices (blocktopk's >= n pad-slot
// picks, mode='drop' semantics) are skipped. Element order preserved, so
// the accumulation order matches the numpy np.add.at finalize exactly.
void wc_fold_sparse(float* acc, const float* val, const int32_t* idx,
                    size_t k, size_t n) {
  FoldProf prof;
  for (size_t j = 0; j < k; ++j) {
    int32_t i = idx[j];
    if (i >= 0 && (size_t)i < n) acc[i] += val[j];
  }
  prof.done(k);
}

// Scatter-zero for the pooled sparse accumulator: re-zero exactly the
// entries a previous round's folds touched (same in-range drop rule as
// wc_fold_sparse), so buffer recycling costs O(touched), not O(n).
void wc_zero_sparse(float* acc, const int32_t* idx, size_t k, size_t n) {
  for (size_t j = 0; j < k; ++j) {
    int32_t i = idx[j];
    if (i >= 0 && (size_t)i < n) acc[i] = 0.0f;
  }
}

// blocktopk8: int8-quantized sparse values with one f32 scale per block
// of kb survivors — dequantize (q * scale) and scatter-add in one pass.
void wc_fold_sparse_q8(float* acc, const int8_t* q, const float* scales,
                       const int32_t* idx, size_t nb, size_t kb, size_t n) {
  FoldProf prof;
  for (size_t b = 0; b < nb; ++b) {
    float s = scales[b];
    const int8_t* qb = q + b * kb;
    const int32_t* ib = idx + b * kb;
    for (size_t j = 0; j < kb; ++j) {
      int32_t i = ib[j];
      float v = (float)qb[j] * s;
      if (i >= 0 && (size_t)i < n) acc[i] += v;
    }
  }
  prof.done(nb * kb);
}

// acc[i] += x[i] — identity/f32 dense fold.
void wc_fold_dense_f32(float* acc, const float* x, size_t n) {
  FoldProf prof;
  for (size_t i = 0; i < n; ++i) acc[i] += x[i];
  prof.done(n);
}

// acc[i] += (float)bf16[i] — bf16 payload cast-up fold (a bf16 is the
// top 16 bits of the equal-valued f32; the cast is exact).
void wc_fold_dense_bf16(float* acc, const uint16_t* x, size_t n) {
  FoldProf prof;
  for (size_t i = 0; i < n; ++i) {
    uint32_t bits = (uint32_t)x[i] << 16;
    float v;
    std::memcpy(&v, &bits, 4);
    acc[i] += v;
  }
  prof.done(n);
}

void wc_shuffle(const uint8_t* src, uint8_t* dst, size_t n_elems, size_t elem) {
  for (size_t i = 0; i < n_elems; ++i)
    for (size_t j = 0; j < elem; ++j)
      dst[j * n_elems + i] = src[i * elem + j];
}

void wc_unshuffle(const uint8_t* src, uint8_t* dst, size_t n_elems, size_t elem) {
  for (size_t i = 0; i < n_elems; ++i)
    for (size_t j = 0; j < elem; ++j)
      dst[i * elem + j] = src[j * n_elems + i];
}

static inline size_t put_varint(uint8_t* dst, uint64_t v) {
  size_t k = 0;
  while (v >= 0x80) {
    dst[k++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  dst[k++] = (uint8_t)v;
  return k;
}

static inline size_t get_varint(const uint8_t* src, size_t avail, uint64_t* v) {
  uint64_t out = 0;
  int shift = 0;
  for (size_t k = 0; k < avail && k < 10; ++k) {
    out |= (uint64_t)(src[k] & 0x7F) << shift;
    if (!(src[k] & 0x80)) {
      *v = out;
      return k + 1;
    }
    shift += 7;
  }
  return 0;  // malformed
}

size_t wc_rle0_max_out(size_t n) { return n + n / 64 + 32; }

// Returns compressed size, or 0 on insufficient dst capacity.
size_t wc_rle0_encode(const uint8_t* src, size_t n, uint8_t* dst, size_t cap) {
  size_t i = 0, o = 0;
  while (i < n) {
    size_t zrun = 0;
    while (i + zrun < n && src[i + zrun] == 0) ++zrun;
    size_t lit_start = i + zrun, lit = 0;
    // literal run extends until the next "worthwhile" zero run (>= 2) or end
    while (lit_start + lit < n) {
      if (src[lit_start + lit] == 0) {
        size_t z = 0;
        while (lit_start + lit + z < n && src[lit_start + lit + z] == 0) ++z;
        if (z >= 2) break;
      }
      ++lit;
    }
    if (o + 20 + lit > cap) return 0;
    o += put_varint(dst + o, zrun);
    o += put_varint(dst + o, lit);
    std::memcpy(dst + o, src + lit_start, lit);
    o += lit;
    i = lit_start + lit;
  }
  return o;
}

// Returns decompressed size, or 0 on malformed input / capacity overflow.
size_t wc_rle0_decode(const uint8_t* src, size_t n, uint8_t* dst, size_t cap) {
  size_t i = 0, o = 0;
  while (i < n) {
    uint64_t zrun, lit;
    size_t k = get_varint(src + i, n - i, &zrun);
    if (!k) return 0;
    i += k;
    k = get_varint(src + i, n - i, &lit);
    if (!k) return 0;
    i += k;
    if (o + zrun + lit > cap || i + lit > n) return 0;
    std::memset(dst + o, 0, zrun);
    o += zrun;
    std::memcpy(dst + o, src + i, lit);
    o += lit;
    i += lit;
  }
  return o;
}

}  // extern "C"
