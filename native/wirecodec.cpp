// wirecodec: host-side wire compression for checkpoint/metadata buffers.
//
// The TPU-native framework's answer to the reference's c-blosc dependency
// (reference mpi_comms.py:18-30 reached blosc through python bindings; this
// repo ships the native code itself). Two classic filters:
//
//   * byte shuffle  — transpose the bytes of fixed-width elements so that
//     high-order bytes (mostly equal for floats of similar magnitude) become
//     long runs; blosc's core trick.
//   * RLE0          — run-length encode zero bytes, which dominate shuffled
//     float data and sparse/top-k gradient payloads.
//
// On-device gradients never touch this path (ICI outruns any host codec —
// SURVEY §2.4); this is for host I/O: checkpoints, cross-process metadata,
// DCN-side buffers.
//
// Format of rle0: repeated [zero_run varint][lit_len varint][lit bytes].
// Varints are LEB128. Worst case output = input + 16.

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

void wc_shuffle(const uint8_t* src, uint8_t* dst, size_t n_elems, size_t elem) {
  for (size_t i = 0; i < n_elems; ++i)
    for (size_t j = 0; j < elem; ++j)
      dst[j * n_elems + i] = src[i * elem + j];
}

void wc_unshuffle(const uint8_t* src, uint8_t* dst, size_t n_elems, size_t elem) {
  for (size_t i = 0; i < n_elems; ++i)
    for (size_t j = 0; j < elem; ++j)
      dst[i * elem + j] = src[j * n_elems + i];
}

static inline size_t put_varint(uint8_t* dst, uint64_t v) {
  size_t k = 0;
  while (v >= 0x80) {
    dst[k++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  dst[k++] = (uint8_t)v;
  return k;
}

static inline size_t get_varint(const uint8_t* src, size_t avail, uint64_t* v) {
  uint64_t out = 0;
  int shift = 0;
  for (size_t k = 0; k < avail && k < 10; ++k) {
    out |= (uint64_t)(src[k] & 0x7F) << shift;
    if (!(src[k] & 0x80)) {
      *v = out;
      return k + 1;
    }
    shift += 7;
  }
  return 0;  // malformed
}

size_t wc_rle0_max_out(size_t n) { return n + n / 64 + 32; }

// Returns compressed size, or 0 on insufficient dst capacity.
size_t wc_rle0_encode(const uint8_t* src, size_t n, uint8_t* dst, size_t cap) {
  size_t i = 0, o = 0;
  while (i < n) {
    size_t zrun = 0;
    while (i + zrun < n && src[i + zrun] == 0) ++zrun;
    size_t lit_start = i + zrun, lit = 0;
    // literal run extends until the next "worthwhile" zero run (>= 2) or end
    while (lit_start + lit < n) {
      if (src[lit_start + lit] == 0) {
        size_t z = 0;
        while (lit_start + lit + z < n && src[lit_start + lit + z] == 0) ++z;
        if (z >= 2) break;
      }
      ++lit;
    }
    if (o + 20 + lit > cap) return 0;
    o += put_varint(dst + o, zrun);
    o += put_varint(dst + o, lit);
    std::memcpy(dst + o, src + lit_start, lit);
    o += lit;
    i = lit_start + lit;
  }
  return o;
}

// Returns decompressed size, or 0 on malformed input / capacity overflow.
size_t wc_rle0_decode(const uint8_t* src, size_t n, uint8_t* dst, size_t cap) {
  size_t i = 0, o = 0;
  while (i < n) {
    uint64_t zrun, lit;
    size_t k = get_varint(src + i, n - i, &zrun);
    if (!k) return 0;
    i += k;
    k = get_varint(src + i, n - i, &lit);
    if (!k) return 0;
    i += k;
    if (o + zrun + lit > cap || i + lit > n) return 0;
    std::memset(dst + o, 0, zrun);
    o += zrun;
    std::memcpy(dst + o, src + i, lit);
    o += lit;
    i += lit;
  }
  return o;
}

}  // extern "C"
