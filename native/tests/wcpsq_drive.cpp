// Sanitizer driver for wirecodec + psqueue (tools/native_sanitize.py):
// the filter/fold kernels over adversarial sizes and the full shm
// segment lifecycle (create/open/publish/seqlock-read/push/pop/reset/
// close), compiled as one executable per sanitizer mode (ASan leak
// check, UBSan, or TSan on the seqlock paths). See tcpps_drive.cpp for
// why the precise leak check lives in native drivers rather than the
// LD_PRELOADed pytest leg.

#include "../wirecodec.cpp"
#include "../psqueue.cpp"

#include <cassert>
#include <cstdio>
#include <thread>
#include <vector>

int main() {
  // ---- wirecodec: shuffle/rle0 roundtrip + every fold kernel --------
  for (size_t n : {0ul, 1ul, 3ul, 63ul, 64ul, 1000ul, 4096ul}) {
    std::vector<uint8_t> raw(n * 4);
    for (size_t i = 0; i < raw.size(); ++i)
      raw[i] = (uint8_t)((i % 7 == 0) ? 0 : i * 13);  // zero runs + noise
    std::vector<uint8_t> shuf(raw.size()), unshuf(raw.size());
    if (n) {
      wc_shuffle(raw.data(), shuf.data(), n, 4);
      wc_unshuffle(shuf.data(), unshuf.data(), n, 4);
      assert(unshuf == raw && "shuffle roundtrip");
    }
    size_t cap = wc_rle0_max_out(raw.size());
    std::vector<uint8_t> enc(cap), dec(raw.size());
    size_t esz = wc_rle0_encode(raw.data(), raw.size(), enc.data(), cap);
    size_t dsz = wc_rle0_decode(enc.data(), esz, dec.data(), raw.size());
    assert(dsz == raw.size() && dec == raw && "rle0 roundtrip");
  }
  {
    constexpr size_t n = 1027;  // off the 4-lane alignment on purpose
    std::vector<float> acc(n, 0.0f);
    std::vector<int8_t> q(n);
    for (size_t i = 0; i < n; ++i) q[i] = (int8_t)(i % 251 - 125);
    wc_fold_scaled_i8(acc.data(), q.data(), 0.5f, n);
    std::vector<uint8_t> packed((n + 3) / 4, 0b10010011);
    wc_fold_tern(acc.data(), packed.data(), 0.25f, n);
    std::vector<int32_t> votes(n, 0);
    std::vector<uint8_t> bits((n + 7) / 8, 0xA5);
    wc_fold_sign(votes.data(), bits.data(), n);
    std::vector<float> val = {1.f, 2.f, 3.f};
    std::vector<int32_t> idx = {0, (int32_t)n - 1, (int32_t)n + 5};
    wc_fold_sparse(acc.data(), val.data(), idx.data(), val.size(), n);
    wc_zero_sparse(acc.data(), idx.data(), idx.size(), n);
    std::vector<int8_t> q8(8, 42);
    std::vector<float> scales = {0.1f, 0.2f};
    std::vector<int32_t> sidx = {1, 2, 3, 4, 5, 6, 7, 8};
    wc_fold_sparse_q8(acc.data(), q8.data(), scales.data(), sidx.data(),
                      2, 4, n);
    std::vector<float> x(n, 1.5f);
    wc_fold_dense_f32(acc.data(), x.data(), n);
    std::vector<uint16_t> bf(n, 0x3FC0);  // 1.5 in bf16
    wc_fold_dense_bf16(acc.data(), bf.data(), n);
    uint64_t calls, elems, ns;
    wc_profile_stats(&calls, &elems, &ns);
    assert(calls >= 7 && "fold profile counters should have advanced");
    wc_profile_reset();
  }

  // ---- psqueue: segment lifecycle under a concurrent worker ---------
  const char* seg = "/psanalyze-wcpsq-drive";
  constexpr uint64_t kParamCap = 1 << 16;
  constexpr uint64_t kGradCap = 1 << 14;
  constexpr int kPushes = 200;
  void* sv = psq_create(seg, 2, kParamCap, kGradCap);
  assert(sv && "psq_create failed");
  assert(psq_n_workers(sv) == 2);
  std::vector<uint8_t> params(kParamCap, 0x5A);
  assert(psq_publish_params(sv, params.data(), params.size(), 1) == 0);

  std::thread worker([&] {
    void* wv = psq_open(seg);
    assert(wv && "psq_open failed");
    std::vector<uint8_t> buf(kParamCap);
    uint64_t ver = 0;
    int64_t n = psq_read_params(wv, buf.data(), buf.size(), &ver);
    assert(n == (int64_t)kParamCap && ver >= 1);
    (void)psq_params_version(wv);
    std::vector<uint8_t> grad(kGradCap, 0x33);
    for (int i = 0; i < kPushes;) {
      if (psq_push_grad(wv, 0, grad.data(), grad.size(), ver) == 1)
        ++i;  // 0 = mailbox still full, retry
    }
    psq_close(wv);
  });

  std::vector<uint8_t> gbuf(kGradCap);
  uint32_t wid = 0, cursor = 0;
  uint64_t gver = 0;
  int got = 0;
  while (got < kPushes) {
    // keep republishing while draining: the seqlock writer vs the
    // worker's reader is the cross-thread pair TSan watches
    assert(psq_publish_params(sv, params.data(), params.size(),
                              2 + got) == 0);
    int64_t n = psq_pop_grad(sv, gbuf.data(), gbuf.size(), &wid, &gver,
                             &cursor);
    if (n > 0) {
      assert(n == (int64_t)kGradCap && wid == 0);
      ++got;
    }
    (void)psq_grad_pending(sv, 0);
  }
  worker.join();
  assert(psq_reset_slot(sv, 1) == 0);
  psq_close(sv);
  std::printf("wcpsq_drive: folds + rle0 ok, %d shm pushes drained\n",
              got);
  return 0;
}
