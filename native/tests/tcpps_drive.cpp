// Sanitizer driver for the tcpps pump (tools/native_sanitize.py):
// server pump + batched pop on the main thread, a worker pushing
// framed gradients from a second thread, the profile-stats atomics
// polled from a third — the full create/connect/publish/push/pop/close
// lifecycle.
//
// Compiled as an EXECUTABLE including tcpps.cpp directly, once per
// sanitizer mode:
// - -fsanitize=thread (make native-tsan): TSan wants the whole program
//   instrumented — LD_PRELOADing libtsan under an uninstrumented
//   CPython reports false races in the interpreter itself. The
//   Python-facing contract ("one thread owns the handle") is what
//   psanalyze's thread-affinity rule checks statically; this checks
//   the native side's actual shared state (the socket and the g_*
//   profile atomics) between pump, worker, and stats reader.
// - -fsanitize=address / undefined (make native-asan / native-ubsan):
//   the PRECISE leak/overflow check on the handle lifecycle. The
//   pytest leg's leak check must suppress everything allocated under
//   libpython frames (LSan matches any frame, and ctypes calls bottom
//   out there), so leaks in the libraries themselves are proven here,
//   where there is no interpreter to suppress around.

#include "../tcpps.cpp"

#include <cassert>
#include <cstdio>
#include <thread>
#include <vector>

namespace {

std::vector<uint8_t> make_psf2_frame(uint64_t fingerprint,
                                     uint32_t payload_len) {
  std::vector<uint8_t> payload(payload_len);
  for (uint32_t i = 0; i < payload_len; ++i)
    payload[i] = (uint8_t)(i * 31 + 7);
  PsfHeader h{};
  h.magic = kPsfMagicV2;
  h.payload_len = payload_len;
  h.crc = crc32_of(payload.data(), payload.size());
  h.fingerprint = fingerprint;
  h.step = 3;
  h.seq = 11;
  h.send_wall = 1234.5;
  std::vector<uint8_t> frame(sizeof(h) + payload.size());
  std::memcpy(frame.data(), &h, sizeof(h));
  std::memcpy(frame.data() + sizeof(h), payload.data(), payload.size());
  return frame;
}

}  // namespace

int main() {
  constexpr uint64_t kFingerprint = 0x5053414e414c59ULL;  // arbitrary
  constexpr uint32_t kPayload = 4096;
  constexpr int kPushes = 64;

  void* sv = tps_server_create(0, 1, 1 << 20);
  assert(sv && "server create failed");
  uint16_t port = tps_server_port(sv);
  tps_server_set_frame_check(sv, kFingerprint, kPayload);
  std::vector<uint8_t> params(kPayload, 0xAB);
  assert(tps_server_publish(sv, params.data(), params.size(), 1) == 0);

  std::thread worker([&] {
    void* wv = tps_worker_connect("127.0.0.1", port, 0, 10000);
    assert(wv && "worker connect failed");
    std::vector<uint8_t> buf(1 << 20);
    uint64_t version = 0;
    int64_t n = tps_worker_read_params(wv, buf.data(), buf.size(),
                                       &version, 10000, 0);
    assert(n == (int64_t)kPayload && version == 1);
    std::vector<uint8_t> frame = make_psf2_frame(kFingerprint, kPayload);
    for (int i = 0; i < kPushes; ++i) {
      int rc = tps_worker_push_grad(wv, frame.data(), frame.size(),
                                    version, 10000);
      assert(rc == 1 && "push failed");
    }
    tps_worker_close(wv);
  });

  std::atomic<bool> done{false};
  std::thread stats([&] {
    // the cross-thread surface Python's profiler actually touches:
    // plain atomics, read while the pump is hot
    while (!done.load(std::memory_order_relaxed)) {
      uint64_t calls, events, ns, frames;
      tps_profile_stats(&calls, &events, &ns, &frames);
    }
  });

  std::vector<uint8_t> batch(1 << 20);
  std::vector<BatchMeta> metas(16);
  int got = 0;
  while (got < kPushes) {
    tps_server_pump(sv);
    int n = tps_server_pop_grad_batch(sv, batch.data(), batch.size(),
                                      metas.data(), (int)metas.size());
    for (int i = 0; i < n; ++i) {
      assert(metas[i].status == FRAME_OK && "frame rejected");
      assert(metas[i].len == kPayload);
      assert(metas[i].step == 3 && metas[i].seq == 11);
    }
    got += n;
  }
  worker.join();
  done.store(true, std::memory_order_relaxed);
  stats.join();

  uint64_t calls, events, ns, frames;
  tps_profile_stats(&calls, &events, &ns, &frames);
  assert(frames == (uint64_t)kPushes && "validated-frame count drifted");
  tps_server_close(sv);
  std::printf("tcpps_drive: %d framed pushes pumped, %llu validated\n",
              got, (unsigned long long)frames);
  return 0;
}
