// psqueue: shared-memory parameter-server transport for host processes.
//
// The native runtime piece of the async (AsySG-InCon) path: where the
// reference moved pickled gradient buffers between ranks with MPI
// (Igatherv/Ibcast, reference mpi_comms.py:88,132) and got asynchrony from
// nonblocking requests, this provides the same roles for co-hosted
// processes (one per pod-slice controller in the DCN picture):
//
//   * a versioned parameter board the server publishes and workers read at
//     any time — the "inconsistent read" of AsySG-InCon: no barrier, a
//     worker may read version v while another reads v-2; a seqlock keeps
//     each read internally consistent without blocking the writer.
//   * one single-slot gradient mailbox per worker (EMPTY/WRITING/FULL
//     atomic state), tagged with the parameter version the gradient was
//     computed at, so the server can measure/bound staleness.
//
// Layout in one shm segment:
//   Header | param area (2 KiB aligned) | n_workers * (SlotHeader | grad area)

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x50535155455545ULL;  // "PSQUEUE"
constexpr size_t kAlign = 2048;

inline size_t align_up(size_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

struct Header {
  uint64_t magic;
  uint32_t n_workers;
  uint32_t reserved;
  uint64_t param_cap;
  uint64_t grad_cap;
  std::atomic<uint64_t> param_seq;   // seqlock: odd = write in progress
  std::atomic<uint64_t> param_version;
  std::atomic<uint64_t> param_len;
};

enum SlotState : uint32_t { EMPTY = 0, WRITING = 1, FULL = 2 };

struct SlotHeader {
  std::atomic<uint32_t> state;
  uint32_t reserved;
  std::atomic<uint64_t> version;  // param version the grad was computed at
  std::atomic<uint64_t> len;
};

struct Handle {
  int fd;
  size_t total;
  uint8_t* base;
  bool owner;
  char name[256];
};

inline Header* hdr(Handle* h) { return reinterpret_cast<Header*>(h->base); }
inline uint8_t* param_area(Handle* h) {
  return h->base + align_up(sizeof(Header));
}
inline SlotHeader* slot(Handle* h, uint32_t w) {
  Header* H = hdr(h);
  uint8_t* p = param_area(h) + align_up(H->param_cap);
  size_t slot_stride = align_up(sizeof(SlotHeader)) + align_up(H->grad_cap);
  return reinterpret_cast<SlotHeader*>(p + w * slot_stride);
}
inline uint8_t* slot_data(Handle* h, uint32_t w) {
  return reinterpret_cast<uint8_t*>(slot(h, w)) + align_up(sizeof(SlotHeader));
}

size_t total_size(uint32_t n_workers, uint64_t param_cap, uint64_t grad_cap) {
  return align_up(sizeof(Header)) + align_up(param_cap) +
         n_workers * (align_up(sizeof(SlotHeader)) + align_up(grad_cap));
}

}  // namespace

extern "C" {

// Server: create + initialize the segment. Returns NULL on failure.
void* psq_create(const char* name, uint32_t n_workers, uint64_t param_cap,
                 uint64_t grad_cap) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t total = total_size(n_workers, param_cap, grad_cap);
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  std::memset(base, 0, total);
  Handle* h = new Handle{fd, total, (uint8_t*)base, true, {0}};
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  Header* H = hdr(h);
  H->n_workers = n_workers;
  H->param_cap = param_cap;
  H->grad_cap = grad_cap;
  H->param_seq.store(0);
  H->param_version.store(0);
  H->param_len.store(0);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  H->magic = kMagic;
  return h;
}

// Worker: attach to an existing segment. Returns NULL on failure.
void* psq_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle{fd, (size_t)st.st_size, (uint8_t*)base, false, {0}};
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  if (hdr(h)->magic != kMagic) {
    munmap(base, h->total);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

void psq_close(void* hv) {
  Handle* h = (Handle*)hv;
  if (!h) return;
  munmap(h->base, h->total);
  close(h->fd);
  if (h->owner) shm_unlink(h->name);
  delete h;
}

uint32_t psq_n_workers(void* hv) { return hdr((Handle*)hv)->n_workers; }

// Server: publish a new parameter snapshot; bumps version. Seqlock write.
int psq_publish_params(void* hv, const uint8_t* buf, uint64_t len,
                       uint64_t version) {
  Handle* h = (Handle*)hv;
  Header* H = hdr(h);
  if (len > H->param_cap) return -1;
  uint64_t seq = H->param_seq.load(std::memory_order_relaxed);
  H->param_seq.store(seq + 1, std::memory_order_release);  // odd: writing
  std::atomic_thread_fence(std::memory_order_seq_cst);
  std::memcpy(param_area(h), buf, len);
  H->param_len.store(len, std::memory_order_relaxed);
  H->param_version.store(version, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  H->param_seq.store(seq + 2, std::memory_order_release);  // even: done
  return 0;
}

// Worker: consistent read of the latest params. Returns byte length,
// stores the snapshot's version. Retries while the seqlock is odd/moved.
// Backs off (sched_yield, then short sleeps) between retries: on an
// oversubscribed host a server republishing at full rate can otherwise
// livelock a starved reader, which would see the seq move on every
// attempt (observed as spurious -2 with 4 ResNet-50 workers on 1 core).
int64_t psq_read_params(void* hv, uint8_t* buf, uint64_t cap,
                        uint64_t* version_out) {
  Handle* h = (Handle*)hv;
  Header* H = hdr(h);
  for (int attempt = 0; attempt < 100000; ++attempt) {
    if (attempt > 16) {
      if (attempt < 1024) {
        sched_yield();
      } else {  // ~50 us: lets the writer finish even on one core
        struct timespec ts = {0, 50000};
        nanosleep(&ts, nullptr);
      }
    }
    uint64_t s1 = H->param_seq.load(std::memory_order_acquire);
    if (s1 & 1) continue;  // write in progress
    uint64_t len = H->param_len.load(std::memory_order_relaxed);
    uint64_t ver = H->param_version.load(std::memory_order_relaxed);
    if (len > cap) return -1;
    std::atomic_thread_fence(std::memory_order_seq_cst);
    std::memcpy(buf, param_area(h), len);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    uint64_t s2 = H->param_seq.load(std::memory_order_acquire);
    if (s1 == s2) {
      if (version_out) *version_out = ver;
      return (int64_t)len;
    }
  }
  return -2;  // writer wedged
}

// Cheap version peek (one atomic load, no snapshot copy): lets a reader
// holding version v skip the full seqlock read when nothing was
// published since — the shm analog of the TCP not-modified reply. The
// value may be mid-publish stale by one version; the follow-up full
// read resolves it, so a reader can never act on a torn snapshot.
uint64_t psq_params_version(void* hv) {
  return hdr((Handle*)hv)->param_version.load(std::memory_order_acquire);
}

// Worker: push a gradient into this worker's mailbox. Returns 0 if the
// slot still holds an unconsumed gradient (caller retries/backs off).
int psq_push_grad(void* hv, uint32_t worker, const uint8_t* buf, uint64_t len,
                  uint64_t version) {
  Handle* h = (Handle*)hv;
  Header* H = hdr(h);
  if (worker >= H->n_workers || len > H->grad_cap) return -1;
  SlotHeader* S = slot(h, worker);
  uint32_t expected = EMPTY;
  if (!S->state.compare_exchange_strong(expected, WRITING,
                                        std::memory_order_acquire))
    return 0;
  std::memcpy(slot_data(h, worker), buf, len);
  S->len.store(len, std::memory_order_relaxed);
  S->version.store(version, std::memory_order_relaxed);
  S->state.store(FULL, std::memory_order_release);
  return 1;
}

// Server/controller: forcibly return a worker's mailbox to EMPTY. For
// elastic replacement of a CRASHED worker: a process killed inside its
// WRITING window leaves the slot wedged (every replacement push would
// see state!=EMPTY forever). Caller guarantees the previous owner is
// dead before resetting; any half-written payload is discarded.
int psq_reset_slot(void* hv, uint32_t worker) {
  Handle* h = (Handle*)hv;
  if (worker >= hdr(h)->n_workers) return -1;
  slot(h, worker)->state.store(EMPTY, std::memory_order_release);
  return 0;
}

// Anyone: is worker w's mailbox currently FULL (pushed, unconsumed)?
// Lets liveness checks distinguish "server hasn't polled" from "worker
// hasn't pushed".
int psq_grad_pending(void* hv, uint32_t worker) {
  Handle* h = (Handle*)hv;
  if (worker >= hdr(h)->n_workers) return -1;
  return slot(h, worker)->state.load(std::memory_order_acquire) == FULL ? 1 : 0;
}

// Server: take one FULL gradient, scanning round-robin from *cursor.
// Returns byte length (>0) and fills worker/version; 0 if none pending.
int64_t psq_pop_grad(void* hv, uint8_t* buf, uint64_t cap,
                     uint32_t* worker_out, uint64_t* version_out,
                     uint32_t* cursor) {
  Handle* h = (Handle*)hv;
  Header* H = hdr(h);
  uint32_t n = H->n_workers;
  for (uint32_t k = 0; k < n; ++k) {
    uint32_t w = (*cursor + k) % n;
    SlotHeader* S = slot(h, w);
    if (S->state.load(std::memory_order_acquire) != FULL) continue;
    uint64_t len = S->len.load(std::memory_order_relaxed);
    if (len > cap) return -1;
    std::memcpy(buf, slot_data(h, w), len);
    if (worker_out) *worker_out = w;
    if (version_out) *version_out = S->version.load(std::memory_order_relaxed);
    S->state.store(EMPTY, std::memory_order_release);
    *cursor = (w + 1) % n;
    return (int64_t)len;
  }
  return 0;
}

}  // extern "C"
