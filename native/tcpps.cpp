// tcpps: TCP parameter-server transport for cross-host async training.
//
// The cross-HOST face of the AsySG-InCon wire: psqueue.cpp covers
// co-hosted processes over shared memory; this covers workers on other
// hosts — the role the reference's MPI-over-Ethernet/IB deployment played
// (reference README.md:19-23 "run on a cluster", mpi_comms.py:88,132) —
// over plain TCP, the transport a TPU pod's DCN exposes to host code.
// Same protocol semantics as psqueue:
//
//   * a versioned parameter snapshot the server owns; workers request the
//     latest at any time (inconsistent reads — no barrier; two workers
//     may receive different versions concurrently).
//   * version-tagged gradient pushes, acknowledged by the server on
//     receipt, so a worker has at most one unacknowledged push in flight
//     (the back-pressure psqueue gets from its single-slot mailbox).
//
// Server side is single-threaded and non-blocking: the Python serve loop
// calls tps_server_pump() (accept + progress all connections + parse
// frames) then tps_server_pop_grad(). Worker side is blocking with
// timeouts — workers spend their time in jitted compute, not in the
// transport. No threads anywhere; ctypes calls release the GIL so a
// blocked worker never stalls a pumping server in the same process.
//
// Wire frame (little-endian, 28-byte header then payload):
//   u32 magic 'TPS1' | u8 op | u8 pad[3] | u32 worker | u64 version | u64 len
//   ops: 1 HELLO (worker->server, announces worker id)
//        2 GET_PARAMS (worker->server)
//        3 PARAMS (server->worker; version+payload, len 0 until first publish)
//        4 PUSH_GRAD (worker->server; version = params version used)
//        5 ACK (server->worker; confirms one PUSH_GRAD was queued)

//
// WAN emulation (test mode): the kernel here has no netem qdisc, so
// cross-host latency is emulated in the WORKER-side calls — env
// TPS_WAN_RTT_MS adds rtt/2 before each request is sent and rtt/2
// before its reply is returned (both propagation directions);
// TPS_WAN_JITTER_MS adds uniform [0, J) per direction. The server
// stays delay-free: it is single-threaded and non-blocking, and a
// server-side sleep would serialize every connection (over-modeling a
// shared medium). Zero/unset env = zero overhead (checked once).

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x31535054;  // "TPS1"

enum Op : uint8_t {
  HELLO = 1,
  GET_PARAMS = 2,
  PARAMS = 3,
  PUSH_GRAD = 4,
  ACK = 5,
};

#pragma pack(push, 1)
struct FrameHdr {
  uint32_t magic;
  uint8_t op;
  uint8_t pad[3];
  uint32_t worker;
  uint64_t version;
  uint64_t len;
};
#pragma pack(pop)
static_assert(sizeof(FrameHdr) == 28, "frame header must be 28 bytes");

struct GradMsg {
  uint32_t worker;
  uint64_t version;
  std::vector<uint8_t> bytes;
};

struct Conn {
  int fd = -1;
  int32_t worker = -1;  // -1 until HELLO
  bool dead = false;    // EOF/error seen in the read phase
  std::vector<uint8_t> rx;
  std::vector<uint8_t> tx;
};

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  uint32_t n_workers = 0;
  uint64_t max_msg = 0;
  std::vector<Conn*> conns;
  std::deque<GradMsg> grads;
  std::vector<uint8_t> params;
  uint64_t param_version = 0;
  // read-path accounting (served by the pump thread, mirrored into the
  // Python server's scrape registry via tps_server_read_stats)
  uint64_t reads_total = 0;
  uint64_t reads_not_modified = 0;
  // epoll-batched ingest: readiness-driven accept + recv so an idle
  // fleet costs zero syscalls per pump beyond one epoll_wait. -1 =
  // epoll unavailable, fall back to the full-sweep recv loop.
  int epfd = -1;
  // inner PSF2 frame validation (tps_server_set_frame_check): CRC32 +
  // config fingerprint checked in C++ by the batched pop, so the serve
  // loop receives only validated payload views
  int frame_check = 0;
  uint64_t fingerprint = 0;
  uint64_t expected_payload = 0;
};

struct Worker {
  int fd = -1;
  uint32_t id = 0;
  std::vector<uint8_t> rx;
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// ---- WAN-emulation delay shim (see file header) ---------------------------

double wan_env_ms(const char* name) {
  const char* v = getenv(name);
  if (!v || !*v) return 0.0;
  double ms = atof(v);
  return ms > 0.0 ? ms : 0.0;
}

double wan_oneway_ms() {
  static double ms = wan_env_ms("TPS_WAN_RTT_MS") / 2.0;
  return ms;
}

double wan_jitter_ms() {
  static double ms = wan_env_ms("TPS_WAN_JITTER_MS");
  return ms;
}

// xorshift64: cheap per-process jitter stream, seeded once from pid+time
uint64_t wan_rand() {
  static uint64_t s = [] {
    struct timespec t;
    clock_gettime(CLOCK_MONOTONIC, &t);
    uint64_t x = (uint64_t)t.tv_nsec ^ ((uint64_t)getpid() << 32) ^ 0x9e3779b9ULL;
    return x ? x : 1ULL;
  }();
  s ^= s << 13;
  s ^= s >> 7;
  s ^= s << 17;
  return s;
}

// one direction's propagation delay; no-op when the env is unset
void wan_delay_oneway() {
  double ms = wan_oneway_ms();
  double j = wan_jitter_ms();
  if (ms <= 0.0 && j <= 0.0) return;
  if (j > 0.0) ms += (double)(wan_rand() % 10000) / 10000.0 * j;
  struct timespec ts;
  ts.tv_sec = (time_t)(ms / 1000.0);
  ts.tv_nsec = (long)((ms - ts.tv_sec * 1000.0) * 1e6);
  nanosleep(&ts, nullptr);
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void append_frame(std::vector<uint8_t>& tx, uint8_t op, uint32_t worker,
                  uint64_t version, const uint8_t* payload, uint64_t len) {
  FrameHdr h{};
  h.magic = kMagic;
  h.op = op;
  h.worker = worker;
  h.version = version;
  h.len = len;
  const uint8_t* hp = reinterpret_cast<const uint8_t*>(&h);
  tx.insert(tx.end(), hp, hp + sizeof(h));
  if (len) tx.insert(tx.end(), payload, payload + len);
}

// Queue bound: with push-ACK back-pressure each connected worker has at
// most one unacknowledged push, but a server that pumps without popping
// could still accumulate. When the queue is at cap, PUSH_GRAD frames stay
// unparsed in the connection's rx buffer (no ACK sent), so the worker
// blocks awaiting its ack and TCP back-pressure does the rest — a queued
// gradient is NEVER silently dropped once acknowledged, which the
// consumed-count stop conditions (serve's total_received, server_main's
// expected) and the sync-barrier "every gradient enters exactly one
// round" oracle all rely on.
size_t queue_cap(const Server* s) { return 4 * (size_t)s->n_workers + 16; }

void close_conn(Server* s, size_t i) {
  Conn* c = s->conns[i];
  if (c->fd >= 0) {
    if (s->epfd >= 0) epoll_ctl(s->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
  }
  delete c;
  s->conns.erase(s->conns.begin() + i);
}

// Drain one connection's socket into its rx buffer (up to the per-conn
// memory bound); sets c->dead on EOF/error. Returns progress events.
int read_conn(Server* s, Conn* c) {
  int events = 0;
  // per-conn memory bound: once a full max-size frame is buffered
  // (possible only while the grad queue back-pressures), stop reading
  // until handle_frames consumes it
  while (c->rx.size() <= sizeof(FrameHdr) + s->max_msg) {
    uint8_t buf[65536];
    ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
    if (r > 0) {
      c->rx.insert(c->rx.end(), buf, buf + r);
      ++events;
      continue;
    }
    if (r == 0) c->dead = true;  // EOF
    else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
      c->dead = true;
    break;
  }
  return events;
}

// Accept every pending connection; registers with epoll when armed.
int accept_all(Server* s) {
  int events = 0;
  for (;;) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    set_nonblock(fd);
    set_nodelay(fd);
    Conn* c = new Conn();
    c->fd = fd;
    s->conns.push_back(c);
    if (s->epfd >= 0) {
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.ptr = c;
      epoll_ctl(s->epfd, EPOLL_CTL_ADD, fd, &ev);
    }
    ++events;
  }
  return events;
}

// ---- CRC32 (zlib-compatible: poly 0xEDB88320, init/xorout 0xFFFFFFFF),
// for the in-C++ PSF2 inner-frame validation of the batched pop --------

const uint32_t* crc32_table() {
  static uint32_t table[256];
  static bool init = [] {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    return true;
  }();
  (void)init;
  return table;
}

uint32_t crc32_of(const uint8_t* p, size_t n) {
  const uint32_t* t = crc32_table();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---- PSF2 inner frame (resilience/frames.py, v2 36-byte header) -----------

constexpr uint32_t kPsfMagicV2 = 0x32465350;  // "PSF2"
constexpr uint32_t kPsfMagicV1 = 0x31465350;  // "PSF1" — rejected "version"
constexpr size_t kPsfHeader = 36;

// Rejection reason codes shared with the Python side (tcp.py maps them
// back to frames.open_frame's reason strings).
enum FrameStatus : uint32_t {
  FRAME_OK = 0,
  FRAME_SHORT = 1,
  FRAME_VERSION = 2,
  FRAME_MAGIC = 3,
  FRAME_SIZE = 4,
  FRAME_CONFIG = 5,
  FRAME_CORRUPT = 6,
};

#pragma pack(push, 1)
struct PsfHeader {
  uint32_t magic;
  uint32_t payload_len;
  uint32_t crc;
  uint64_t fingerprint;
  uint32_t step;
  uint32_t seq;
  double send_wall;
};
#pragma pack(pop)
static_assert(sizeof(PsfHeader) == kPsfHeader, "PSF2 header must be 36 B");

// Validate one queued message against the armed wire agreement —
// EXACTLY frames.open_frame's checks in the same order. On FRAME_OK,
// *payload/*plen point into the message.
uint32_t validate_frame(const Server* s, const GradMsg& m,
                        const uint8_t** payload, uint64_t* plen,
                        PsfHeader* hdr_out) {
  const uint8_t* b = m.bytes.data();
  size_t n = m.bytes.size();
  if (n < 4) return FRAME_SHORT;
  uint32_t magic;
  std::memcpy(&magic, b, 4);
  if (magic == kPsfMagicV1) return FRAME_VERSION;
  if (magic != kPsfMagicV2) return FRAME_MAGIC;
  if (n < kPsfHeader) return FRAME_SHORT;
  PsfHeader h;
  std::memcpy(&h, b, sizeof(h));
  if (h.payload_len != n - kPsfHeader ||
      (s->expected_payload && h.payload_len != s->expected_payload))
    return FRAME_SIZE;
  if (h.fingerprint != s->fingerprint) return FRAME_CONFIG;
  if (crc32_of(b + kPsfHeader, h.payload_len) != h.crc) return FRAME_CORRUPT;
  *payload = b + kPsfHeader;
  *plen = h.payload_len;
  if (hdr_out) *hdr_out = h;
  return FRAME_OK;
}

// Parse every complete frame in c->rx; returns false on protocol error
// (caller closes the connection).
bool handle_frames(Server* s, Conn* c) {
  size_t off = 0;
  while (c->rx.size() - off >= sizeof(FrameHdr)) {
    FrameHdr h;
    std::memcpy(&h, c->rx.data() + off, sizeof(h));
    if (h.magic != kMagic || h.len > s->max_msg) return false;
    if (c->rx.size() - off < sizeof(h) + h.len) break;  // partial payload
    const uint8_t* payload = c->rx.data() + off + sizeof(h);
    switch (h.op) {
      case HELLO:
        c->worker = (int32_t)h.worker;
        break;
      case GET_PARAMS:
        // version-conditional read: the request's version field carries
        // the worker's "I have v" (0 = unconditional, the legacy form).
        // An unchanged snapshot gets a cheap zero-payload PARAMS reply
        // echoing the version instead of re-shipping the full snapshot
        // — distinguishable from "nothing published yet" because a
        // published version is never 0.
        ++s->reads_total;
        if (h.version != 0 && h.version == s->param_version) {
          ++s->reads_not_modified;
          append_frame(c->tx, PARAMS, 0, s->param_version, nullptr, 0);
        } else {
          append_frame(c->tx, PARAMS, 0, s->param_version, s->params.data(),
                       s->params.size());
        }
        break;
      case PUSH_GRAD: {
        if (s->grads.size() >= queue_cap(s)) {
          // keep the frame buffered, send no ACK: the pushing worker
          // stalls until pop_grad frees a slot (processed next pump)
          if (off) c->rx.erase(c->rx.begin(), c->rx.begin() + off);
          return true;
        }
        GradMsg m;
        m.worker = h.worker;
        m.version = h.version;
        m.bytes.assign(payload, payload + h.len);
        s->grads.push_back(std::move(m));
        append_frame(c->tx, ACK, h.worker, h.version, nullptr, 0);
        break;
      }
      default:
        return false;
    }
    off += sizeof(h) + h.len;
  }
  if (off) c->rx.erase(c->rx.begin(), c->rx.begin() + off);
  return true;
}

// Blocking read of exactly n bytes with a deadline; 0 ok, -1 error/EOF,
// -2 timeout.
int read_full(int fd, uint8_t* buf, size_t n, int timeout_ms) {
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  size_t got = 0;
  while (got < n) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - t0.tv_sec) * 1000 +
                   (now.tv_nsec - t0.tv_nsec) / 1000000;
    long left = timeout_ms - elapsed;
    if (left <= 0) return -2;
    struct pollfd p{fd, POLLIN, 0};
    int pr = poll(&p, 1, (int)left);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) return -2;
    ssize_t r = recv(fd, buf + got, n - got, 0);
    if (r == 0) return -1;  // peer closed
    if (r < 0) {
      if (errno == EINTR || errno == EAGAIN) continue;
      return -1;
    }
    got += (size_t)r;
  }
  return 0;
}

int write_full(int fd, const uint8_t* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd p{fd, POLLOUT, 0};
        poll(&p, 1, 100);
        continue;
      }
      return -1;
    }
    sent += (size_t)r;
  }
  return 0;
}

}  // namespace

extern "C" {

// ---- server ---------------------------------------------------------------

// Listen on 0.0.0.0:port (0 = auto-assign; read back with
// tps_server_port). max_msg bounds any single frame payload (params or
// gradient bytes). Returns NULL on failure.
void* tps_server_create(uint16_t port, uint32_t n_workers, uint64_t max_msg) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);
  set_nonblock(fd);
  Server* s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->n_workers = n_workers;
  s->max_msg = max_msg;
  // epoll instance for readiness-batched ingest; a failed create means
  // the pump falls back to the original full sweep (same semantics)
  s->epfd = epoll_create1(0);
  if (s->epfd >= 0) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;  // nullptr marks the listener
    if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, fd, &ev) != 0) {
      close(s->epfd);
      s->epfd = -1;
    }
  }
  return s;
}

// Arm C++-side validation of the inner PSF2 frame for the batched pop:
// the Python server passes its wire fingerprint + expected payload size
// once at construction, and tps_server_pop_grad_batch then rejects bad
// frames (reason-coded) without the bytes ever crossing into Python.
void tps_server_set_frame_check(void* sv, uint64_t fingerprint,
                                uint64_t expected_payload) {
  Server* s = (Server*)sv;
  s->frame_check = 1;
  s->fingerprint = fingerprint;
  s->expected_payload = expected_payload;
}

uint16_t tps_server_port(void* sv) { return ((Server*)sv)->port; }

// Store the new snapshot; served to every subsequent GET_PARAMS.
int tps_server_publish(void* sv, const uint8_t* buf, uint64_t len,
                       uint64_t version) {
  Server* s = (Server*)sv;
  if (len > s->max_msg) return -1;
  s->params.assign(buf, buf + len);
  s->param_version = version;
  return 0;
}

// One non-blocking sweep: accept, read, parse, reply, flush. Returns the
// number of complete frames/connection events progressed (0 = idle).
//
// -- pump cycle counters (continuous profiling, telemetry/profiler.py) ---
// The Python stack sampler sees one opaque ctypes call for the whole
// epoll pump; these process-global counters (calls / events / wall ns)
// are its native-side ledger, read by tps_profile_stats the same
// plain-ints-only way as tps_server_read_stats.
static std::atomic<uint64_t> g_pump_calls{0};
static std::atomic<uint64_t> g_pump_events{0};
static std::atomic<uint64_t> g_pump_ns{0};
static std::atomic<uint64_t> g_frames_validated{0};

namespace {
struct PumpProf {
  timespec t0;
  PumpProf() { clock_gettime(CLOCK_MONOTONIC, &t0); }
  void done(int events) {
    timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    uint64_t ns = (uint64_t)(t1.tv_sec - t0.tv_sec) * 1000000000ull +
                  (uint64_t)(t1.tv_nsec - t0.tv_nsec);
    g_pump_calls.fetch_add(1, std::memory_order_relaxed);
    if (events > 0)
      g_pump_events.fetch_add((uint64_t)events, std::memory_order_relaxed);
    g_pump_ns.fetch_add(ns, std::memory_order_relaxed);
  }
};
}  // namespace

void tps_profile_stats(uint64_t* pump_calls, uint64_t* pump_events,
                       uint64_t* pump_ns, uint64_t* frames_validated) {
  *pump_calls = g_pump_calls.load(std::memory_order_relaxed);
  *pump_events = g_pump_events.load(std::memory_order_relaxed);
  *pump_ns = g_pump_ns.load(std::memory_order_relaxed);
  *frames_validated = g_frames_validated.load(std::memory_order_relaxed);
}

void tps_profile_reset() {
  g_pump_calls.store(0, std::memory_order_relaxed);
  g_pump_events.store(0, std::memory_order_relaxed);
  g_pump_ns.store(0, std::memory_order_relaxed);
  g_frames_validated.store(0, std::memory_order_relaxed);
}

// With epoll armed (the default) the accept+recv phase is readiness-
// driven: ONE epoll_wait(0) names exactly the sockets with pending
// bytes, and only those pay a recv() syscall — an idle fleet member
// costs nothing per pump, where the old full sweep paid one EAGAIN
// recv per connection per call. The parse/flush phase still walks all
// connections (pure memory ops unless a reply is owed): a conn whose
// buffered frame was deferred by grad-queue back-pressure has no
// kernel event to re-announce it, so readiness alone must never gate
// handle_frames.
int tps_server_pump(void* sv) {
  PumpProf prof;
  Server* s = (Server*)sv;
  int events = 0;
  if (s->epfd >= 0) {
    epoll_event evs[64];
    for (;;) {
      int ne = epoll_wait(s->epfd, evs, 64, 0);
      if (ne <= 0) break;
      int pass_events = 0;
      for (int e = 0; e < ne; ++e) {
        if (evs[e].data.ptr == nullptr) {
          pass_events += accept_all(s);
        } else {
          Conn* c = (Conn*)evs[e].data.ptr;
          pass_events += read_conn(s, c);
        }
      }
      events += pass_events;
      // exit on a short pass (every ready fd seen) OR a no-progress
      // pass: level-triggered epoll re-reports conns parked at the
      // per-conn rx memory bound, and with 64+ of those the event
      // count alone would never drop below the batch size — only the
      // parse phase below can free their buffers, so spinning here
      // would hang the server at 100% CPU
      if (ne < 64 || pass_events == 0) break;
    }
  } else {
    events += accept_all(s);
    for (Conn* c : s->conns)
      if (!c->dead) events += read_conn(s, c);
  }
  for (size_t i = 0; i < s->conns.size();) {
    Conn* c = s->conns[i];
    bool dead = c->dead;
    if (!dead && !handle_frames(s, c)) dead = true;  // protocol error
    if (!dead && !c->tx.empty()) {                   // flush replies
      ssize_t w = send(c->fd, c->tx.data(), c->tx.size(), MSG_NOSIGNAL);
      if (w > 0) {
        c->tx.erase(c->tx.begin(), c->tx.begin() + w);
        ++events;
      } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                 errno != EINTR) {
        dead = true;
      }
    }
    if (dead) close_conn(s, i);
    else ++i;
  }
  prof.done(events);
  return events;
}

// Per-frame record of the batched pop (mirrored by ctypes in tcp.py).
#pragma pack(push, 1)
struct BatchMeta {
  uint32_t worker;
  uint32_t status;   // FrameStatus: 0 ok, else the rejection reason
  uint64_t version;
  uint64_t off;      // payload offset into the batch buffer (ok only)
  uint64_t len;      // payload byte length (0 when rejected)
  uint32_t step;     // PSF2 lineage fields (0 unless frame_check hit ok)
  uint32_t seq;
  double send_wall;
};
#pragma pack(pop)
static_assert(sizeof(BatchMeta) == 48, "BatchMeta must be 48 bytes");

// Batched pop: drain up to max_frames queued gradients in ONE call,
// validating each inner PSF2 frame in C++ when armed
// (tps_server_set_frame_check) — magic/version, declared vs expected
// size, config fingerprint, CRC32 — and packing only the VALIDATED
// payload bytes contiguously into buf. Rejected frames produce a
// reason-coded meta and no bytes; Python turns them into the same
// counted per-worker rejections frames.framed_poll produces. Returns
// the number of metas filled (0 = nothing queued); stops early when
// the next payload would not fit in cap (that frame stays queued).
int tps_server_pop_grad_batch(void* sv, uint8_t* buf, uint64_t cap,
                              BatchMeta* metas, int max_frames) {
  Server* s = (Server*)sv;
  int n = 0;
  uint64_t off = 0;
  while (n < max_frames && !s->grads.empty()) {
    GradMsg& m = s->grads.front();
    BatchMeta& meta = metas[n];
    meta.worker = m.worker;
    meta.version = m.version;
    meta.step = 0;
    meta.seq = 0;
    meta.send_wall = 0.0;
    const uint8_t* payload = m.bytes.data();
    uint64_t plen = m.bytes.size();
    uint32_t status = FRAME_OK;
    if (s->frame_check) {
      PsfHeader h{};
      status = validate_frame(s, m, &payload, &plen, &h);
      if (status == FRAME_OK) {
        g_frames_validated.fetch_add(1, std::memory_order_relaxed);
        meta.step = h.step;
        meta.seq = h.seq;
        meta.send_wall = h.send_wall;
      }
    }
    if (status != FRAME_OK) {
      meta.status = status;
      meta.off = 0;
      meta.len = 0;
      s->grads.pop_front();
      ++n;
      continue;
    }
    if (off + plen > cap) break;  // no room: frame stays queued
    std::memcpy(buf + off, payload, plen);
    meta.status = FRAME_OK;
    meta.off = off;
    meta.len = plen;
    off += plen;
    s->grads.pop_front();
    ++n;
  }
  return n;
}

// Pop one queued gradient (FIFO arrival order). Returns byte length >0
// and fills worker/version; 0 if none; -1 if the payload exceeds cap.
int64_t tps_server_pop_grad(void* sv, uint8_t* buf, uint64_t cap,
                            uint32_t* worker_out, uint64_t* version_out) {
  Server* s = (Server*)sv;
  if (s->grads.empty()) return 0;
  GradMsg& m = s->grads.front();
  if (m.bytes.size() > cap) return -1;
  std::memcpy(buf, m.bytes.data(), m.bytes.size());
  if (worker_out) *worker_out = m.worker;
  if (version_out) *version_out = m.version;
  int64_t n = (int64_t)m.bytes.size();
  s->grads.pop_front();
  return n;
}

// Gradients currently queued from this worker (liveness signal: pushed
// but not yet consumed counts as alive, mirroring psq_grad_pending).
int tps_server_pending(void* sv, uint32_t worker) {
  Server* s = (Server*)sv;
  int n = 0;
  for (const GradMsg& m : s->grads)
    if (m.worker == worker) ++n;
  return n;
}

// Is a connection claiming this worker id currently open? A crashed
// worker's socket closes (RST/EOF) and this flips to 0 — the transport-
// level failure signal shm cannot give; a replacement just reconnects.
int tps_server_connected(void* sv, uint32_t worker) {
  Server* s = (Server*)sv;
  for (const Conn* c : s->conns)
    if (c->worker == (int32_t)worker) return 1;
  return 0;
}

// Read-path counters: total GET_PARAMS served and how many were answered
// with the cheap not-modified reply. Written only by the pump (the serve
// thread); callers read them from that same thread and mirror into
// Python-side state for scrape threads.
void tps_server_read_stats(void* sv, uint64_t* total, uint64_t* not_modified) {
  Server* s = (Server*)sv;
  if (total) *total = s->reads_total;
  if (not_modified) *not_modified = s->reads_not_modified;
}

void tps_server_close(void* sv) {
  Server* s = (Server*)sv;
  if (!s) return;
  for (size_t i = s->conns.size(); i-- > 0;) close_conn(s, i);
  if (s->listen_fd >= 0) close(s->listen_fd);
  if (s->epfd >= 0) close(s->epfd);
  delete s;
}

// ---- worker ---------------------------------------------------------------

// Connect (retrying until timeout_ms — the server may not be up yet) and
// send HELLO. Returns NULL on failure.
void* tps_worker_connect(const char* host, uint16_t port, uint32_t worker_id,
                         int timeout_ms) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  int fd = -1;
  for (;;) {
    fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) break;
    close(fd);
    fd = -1;
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - t0.tv_sec) * 1000 +
                   (now.tv_nsec - t0.tv_nsec) / 1000000;
    if (elapsed >= timeout_ms) return nullptr;
    struct timespec ts = {0, 50 * 1000 * 1000};  // 50 ms between attempts
    nanosleep(&ts, nullptr);
  }
  set_nodelay(fd);
  Worker* w = new Worker();
  w->fd = fd;
  w->id = worker_id;
  std::vector<uint8_t> tx;
  append_frame(tx, HELLO, worker_id, 0, nullptr, 0);
  if (write_full(fd, tx.data(), tx.size()) != 0) {
    close(fd);
    delete w;
    return nullptr;
  }
  return w;
}

// Request + receive the latest snapshot. ``have_version`` is the
// version-conditional "I have v" (0 = unconditional): when the server's
// snapshot still IS that version it replies without the payload and this
// returns -4 ("not modified" — the caller's cached copy is current).
// Otherwise returns byte length (0 until the server's first publish) and
// fills version; -1 error, -2 timeout, -3 if the reply exceeds cap.
int64_t tps_worker_read_params(void* wv, uint8_t* buf, uint64_t cap,
                               uint64_t* version_out, int timeout_ms,
                               uint64_t have_version) {
  Worker* w = (Worker*)wv;
  // one deadline for the whole call: header + payload reads share the
  // caller's budget instead of each getting timeout_ms (which made the
  // worst-case block 2x what the caller asked for)
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  wan_delay_oneway();  // request propagation (WAN emulation; usually 0)
  std::vector<uint8_t> tx;
  append_frame(tx, GET_PARAMS, w->id, have_version, nullptr, 0);
  if (write_full(w->fd, tx.data(), tx.size()) != 0) return -1;
  FrameHdr h;
  // header read gets the REMAINING budget (the emulated request delay
  // above counted against the deadline like any network time would);
  // the reply-direction delay after the reads is additive latency by
  // design — it models propagation the caller cannot see into, so only
  // the emulated-WAN latency itself, never an extra timeout window,
  // extends the call
  struct timespec nowh;
  clock_gettime(CLOCK_MONOTONIC, &nowh);
  long spent = (nowh.tv_sec - t0.tv_sec) * 1000 +
               (nowh.tv_nsec - t0.tv_nsec) / 1000000;
  long hleft = timeout_ms - spent;
  if (hleft <= 0) return -2;
  int rc = read_full(w->fd, reinterpret_cast<uint8_t*>(&h), sizeof(h),
                     (int)hleft);
  if (rc != 0) return rc;
  if (h.magic != kMagic || h.op != PARAMS) return -1;
  if (h.len == 0 && have_version != 0 && h.version == have_version) {
    // not modified: the server confirmed our cached version is current
    wan_delay_oneway();  // reply propagation
    if (version_out) *version_out = h.version;
    return -4;
  }
  if (h.len > cap) return -3;
  if (h.len) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - t0.tv_sec) * 1000 +
                   (now.tv_nsec - t0.tv_nsec) / 1000000;
    long left = timeout_ms - elapsed;
    if (left <= 0) return -2;
    rc = read_full(w->fd, buf, h.len, (int)left);
    if (rc != 0) return rc;
  }
  wan_delay_oneway();  // reply propagation
  if (version_out) *version_out = h.version;
  return (int64_t)h.len;
}

// Push one gradient and wait for the server's ACK (back-pressure: at most
// one unacknowledged push in flight, like psqueue's single-slot mailbox).
// Returns 1 on ack; -1 error, -2 timeout.
int tps_worker_push_grad(void* wv, const uint8_t* buf, uint64_t len,
                         uint64_t version, int timeout_ms) {
  Worker* w = (Worker*)wv;
  wan_delay_oneway();  // push propagation (WAN emulation; usually 0)
  FrameHdr h{};
  h.magic = kMagic;
  h.op = PUSH_GRAD;
  h.worker = w->id;
  h.version = version;
  h.len = len;
  if (write_full(w->fd, reinterpret_cast<uint8_t*>(&h), sizeof(h)) != 0)
    return -1;
  if (len && write_full(w->fd, buf, len) != 0) return -1;
  FrameHdr ack;
  int rc = read_full(w->fd, reinterpret_cast<uint8_t*>(&ack), sizeof(ack),
                     timeout_ms);
  if (rc != 0) return rc;
  if (ack.magic != kMagic || ack.op != ACK || ack.len != 0) return -1;
  wan_delay_oneway();  // ack propagation
  return 1;
}

void tps_worker_close(void* wv) {
  Worker* w = (Worker*)wv;
  if (!w) return;
  if (w->fd >= 0) close(w->fd);
  delete w;
}

// ---- ABI self-description -------------------------------------------------
// The runtime twin of psanalyze's abi-drift rule: tcp.py re-reads the
// wire constants from the LOADED library at bind time and refuses the
// library on any mismatch with resilience/frames.py — so a stale or
// hand-copied .so whose header layout or reason codes drifted fails at
// load, not as a silent mis-decode mid-training.

uint32_t tps_abi_psf_magic(void) { return kPsfMagicV2; }

uint32_t tps_abi_psf_magic_v1(void) { return kPsfMagicV1; }

uint32_t tps_abi_psf_header_bytes(void) { return (uint32_t)kPsfHeader; }

uint32_t tps_abi_batch_meta_bytes(void) {
  return (uint32_t)sizeof(BatchMeta);
}

// Reason string for a FrameStatus code (NULL for unknown/OK) — the
// enum's names are the protocol, not just labels: Python counts
// rejections under these exact strings.
const char* tps_abi_frame_status_name(uint32_t code) {
  switch (code) {
    case FRAME_SHORT: return "short";
    case FRAME_VERSION: return "version";
    case FRAME_MAGIC: return "magic";
    case FRAME_SIZE: return "size";
    case FRAME_CONFIG: return "config";
    case FRAME_CORRUPT: return "corrupt";
    default: return nullptr;
  }
}

}  // extern "C"
