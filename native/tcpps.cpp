// tcpps: TCP parameter-server transport for cross-host async training.
//
// The cross-HOST face of the AsySG-InCon wire: psqueue.cpp covers
// co-hosted processes over shared memory; this covers workers on other
// hosts — the role the reference's MPI-over-Ethernet/IB deployment played
// (reference README.md:19-23 "run on a cluster", mpi_comms.py:88,132) —
// over plain TCP, the transport a TPU pod's DCN exposes to host code.
// Same protocol semantics as psqueue:
//
//   * a versioned parameter snapshot the server owns; workers request the
//     latest at any time (inconsistent reads — no barrier; two workers
//     may receive different versions concurrently).
//   * version-tagged gradient pushes, acknowledged by the server on
//     receipt, so a worker has at most one unacknowledged push in flight
//     (the back-pressure psqueue gets from its single-slot mailbox).
//
// Server side is single-threaded and non-blocking: the Python serve loop
// calls tps_server_pump() (accept + progress all connections + parse
// frames) then tps_server_pop_grad(). Worker side is blocking with
// timeouts — workers spend their time in jitted compute, not in the
// transport. No threads anywhere; ctypes calls release the GIL so a
// blocked worker never stalls a pumping server in the same process.
//
// Wire frame (little-endian, 28-byte header then payload):
//   u32 magic 'TPS1' | u8 op | u8 pad[3] | u32 worker | u64 version | u64 len
//   ops: 1 HELLO (worker->server, announces worker id)
//        2 GET_PARAMS (worker->server)
//        3 PARAMS (server->worker; version+payload, len 0 until first publish)
//        4 PUSH_GRAD (worker->server; version = params version used)
//        5 ACK (server->worker; confirms one PUSH_GRAD was queued)

//
// WAN emulation (test mode): the kernel here has no netem qdisc, so
// cross-host latency is emulated in the WORKER-side calls — env
// TPS_WAN_RTT_MS adds rtt/2 before each request is sent and rtt/2
// before its reply is returned (both propagation directions);
// TPS_WAN_JITTER_MS adds uniform [0, J) per direction. The server
// stays delay-free: it is single-threaded and non-blocking, and a
// server-side sleep would serialize every connection (over-modeling a
// shared medium). Zero/unset env = zero overhead (checked once).

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <new>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x31535054;  // "TPS1"

enum Op : uint8_t {
  HELLO = 1,
  GET_PARAMS = 2,
  PARAMS = 3,
  PUSH_GRAD = 4,
  ACK = 5,
};

#pragma pack(push, 1)
struct FrameHdr {
  uint32_t magic;
  uint8_t op;
  uint8_t pad[3];
  uint32_t worker;
  uint64_t version;
  uint64_t len;
};
#pragma pack(pop)
static_assert(sizeof(FrameHdr) == 28, "frame header must be 28 bytes");

struct GradMsg {
  uint32_t worker;
  uint64_t version;
  std::vector<uint8_t> bytes;
};

struct Conn {
  int fd = -1;
  int32_t worker = -1;  // -1 until HELLO
  bool dead = false;    // EOF/error seen in the read phase
  std::vector<uint8_t> rx;
  std::vector<uint8_t> tx;
};

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  uint32_t n_workers = 0;
  uint64_t max_msg = 0;
  std::vector<Conn*> conns;
  std::deque<GradMsg> grads;
  std::vector<uint8_t> params;
  uint64_t param_version = 0;
  // read-path accounting (served by the pump thread, mirrored into the
  // Python server's scrape registry via tps_server_read_stats)
  uint64_t reads_total = 0;
  uint64_t reads_not_modified = 0;
  // epoll-batched ingest: readiness-driven accept + recv so an idle
  // fleet costs zero syscalls per pump beyond one epoll_wait. -1 =
  // epoll unavailable, fall back to the full-sweep recv loop.
  int epfd = -1;
  // inner PSF2 frame validation (tps_server_set_frame_check): CRC32 +
  // config fingerprint checked in C++ by the batched pop, so the serve
  // loop receives only validated payload views
  int frame_check = 0;
  uint64_t fingerprint = 0;
  uint64_t expected_payload = 0;
};

struct Worker {
  int fd = -1;
  uint32_t id = 0;
  std::vector<uint8_t> rx;
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// ---- WAN-emulation delay shim (see file header) ---------------------------

double wan_env_ms(const char* name) {
  const char* v = getenv(name);
  if (!v || !*v) return 0.0;
  double ms = atof(v);
  return ms > 0.0 ? ms : 0.0;
}

double wan_oneway_ms() {
  static double ms = wan_env_ms("TPS_WAN_RTT_MS") / 2.0;
  return ms;
}

double wan_jitter_ms() {
  static double ms = wan_env_ms("TPS_WAN_JITTER_MS");
  return ms;
}

// xorshift64: cheap per-process jitter stream, seeded once from pid+time
uint64_t wan_rand() {
  static uint64_t s = [] {
    struct timespec t;
    clock_gettime(CLOCK_MONOTONIC, &t);
    uint64_t x = (uint64_t)t.tv_nsec ^ ((uint64_t)getpid() << 32) ^ 0x9e3779b9ULL;
    return x ? x : 1ULL;
  }();
  s ^= s << 13;
  s ^= s >> 7;
  s ^= s << 17;
  return s;
}

// one direction's propagation delay; no-op when the env is unset
void wan_delay_oneway() {
  double ms = wan_oneway_ms();
  double j = wan_jitter_ms();
  if (ms <= 0.0 && j <= 0.0) return;
  if (j > 0.0) ms += (double)(wan_rand() % 10000) / 10000.0 * j;
  struct timespec ts;
  ts.tv_sec = (time_t)(ms / 1000.0);
  ts.tv_nsec = (long)((ms - ts.tv_sec * 1000.0) * 1e6);
  nanosleep(&ts, nullptr);
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void append_frame(std::vector<uint8_t>& tx, uint8_t op, uint32_t worker,
                  uint64_t version, const uint8_t* payload, uint64_t len) {
  FrameHdr h{};
  h.magic = kMagic;
  h.op = op;
  h.worker = worker;
  h.version = version;
  h.len = len;
  const uint8_t* hp = reinterpret_cast<const uint8_t*>(&h);
  tx.insert(tx.end(), hp, hp + sizeof(h));
  if (len) tx.insert(tx.end(), payload, payload + len);
}

// Queue bound: with push-ACK back-pressure each connected worker has at
// most one unacknowledged push, but a server that pumps without popping
// could still accumulate. When the queue is at cap, PUSH_GRAD frames stay
// unparsed in the connection's rx buffer (no ACK sent), so the worker
// blocks awaiting its ack and TCP back-pressure does the rest — a queued
// gradient is NEVER silently dropped once acknowledged, which the
// consumed-count stop conditions (serve's total_received, server_main's
// expected) and the sync-barrier "every gradient enters exactly one
// round" oracle all rely on.
size_t queue_cap(const Server* s) { return 4 * (size_t)s->n_workers + 16; }

void close_conn(Server* s, size_t i) {
  Conn* c = s->conns[i];
  if (c->fd >= 0) {
    if (s->epfd >= 0) epoll_ctl(s->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
  }
  delete c;
  s->conns.erase(s->conns.begin() + i);
}

// Drain one connection's socket into its rx buffer (up to the per-conn
// memory bound); sets c->dead on EOF/error. Returns progress events.
int read_conn(Server* s, Conn* c) {
  int events = 0;
  // per-conn memory bound: once a full max-size frame is buffered
  // (possible only while the grad queue back-pressures), stop reading
  // until handle_frames consumes it
  while (c->rx.size() <= sizeof(FrameHdr) + s->max_msg) {
    uint8_t buf[65536];
    ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
    if (r > 0) {
      c->rx.insert(c->rx.end(), buf, buf + r);
      ++events;
      continue;
    }
    if (r == 0) c->dead = true;  // EOF
    else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
      c->dead = true;
    break;
  }
  return events;
}

// Accept every pending connection; registers with epoll when armed.
int accept_all(Server* s) {
  int events = 0;
  for (;;) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    set_nonblock(fd);
    set_nodelay(fd);
    Conn* c = new Conn();
    c->fd = fd;
    s->conns.push_back(c);
    if (s->epfd >= 0) {
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.ptr = c;
      epoll_ctl(s->epfd, EPOLL_CTL_ADD, fd, &ev);
    }
    ++events;
  }
  return events;
}

// ---- CRC32 (zlib-compatible: poly 0xEDB88320, init/xorout 0xFFFFFFFF),
// for the in-C++ PSF2 inner-frame validation of the batched pop --------

const uint32_t* crc32_table() {
  static uint32_t table[256];
  static bool init = [] {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    return true;
  }();
  (void)init;
  return table;
}

uint32_t crc32_of(const uint8_t* p, size_t n) {
  const uint32_t* t = crc32_table();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---- PSF2 inner frame (resilience/frames.py, v2 36-byte header) -----------

constexpr uint32_t kPsfMagicV2 = 0x32465350;  // "PSF2"
constexpr uint32_t kPsfMagicV1 = 0x31465350;  // "PSF1" — rejected "version"
constexpr size_t kPsfHeader = 36;

// Rejection reason codes shared with the Python side (tcp.py maps them
// back to frames.open_frame's reason strings).
enum FrameStatus : uint32_t {
  FRAME_OK = 0,
  FRAME_SHORT = 1,
  FRAME_VERSION = 2,
  FRAME_MAGIC = 3,
  FRAME_SIZE = 4,
  FRAME_CONFIG = 5,
  FRAME_CORRUPT = 6,
};

#pragma pack(push, 1)
struct PsfHeader {
  uint32_t magic;
  uint32_t payload_len;
  uint32_t crc;
  uint64_t fingerprint;
  uint32_t step;
  uint32_t seq;
  double send_wall;
};
#pragma pack(pop)
static_assert(sizeof(PsfHeader) == kPsfHeader, "PSF2 header must be 36 B");

// Validate one queued message against the armed wire agreement —
// EXACTLY frames.open_frame's checks in the same order. On FRAME_OK,
// *payload/*plen point into the message.
uint32_t validate_frame(const Server* s, const GradMsg& m,
                        const uint8_t** payload, uint64_t* plen,
                        PsfHeader* hdr_out) {
  const uint8_t* b = m.bytes.data();
  size_t n = m.bytes.size();
  if (n < 4) return FRAME_SHORT;
  uint32_t magic;
  std::memcpy(&magic, b, 4);
  if (magic == kPsfMagicV1) return FRAME_VERSION;
  if (magic != kPsfMagicV2) return FRAME_MAGIC;
  if (n < kPsfHeader) return FRAME_SHORT;
  PsfHeader h;
  std::memcpy(&h, b, sizeof(h));
  if (h.payload_len != n - kPsfHeader ||
      (s->expected_payload && h.payload_len != s->expected_payload))
    return FRAME_SIZE;
  if (h.fingerprint != s->fingerprint) return FRAME_CONFIG;
  if (crc32_of(b + kPsfHeader, h.payload_len) != h.crc) return FRAME_CORRUPT;
  *payload = b + kPsfHeader;
  *plen = h.payload_len;
  if (hdr_out) *hdr_out = h;
  return FRAME_OK;
}

// Parse every complete frame in c->rx; returns false on protocol error
// (caller closes the connection).
bool handle_frames(Server* s, Conn* c) {
  size_t off = 0;
  while (c->rx.size() - off >= sizeof(FrameHdr)) {
    FrameHdr h;
    std::memcpy(&h, c->rx.data() + off, sizeof(h));
    if (h.magic != kMagic || h.len > s->max_msg) return false;
    if (c->rx.size() - off < sizeof(h) + h.len) break;  // partial payload
    const uint8_t* payload = c->rx.data() + off + sizeof(h);
    switch (h.op) {
      case HELLO:
        c->worker = (int32_t)h.worker;
        break;
      case GET_PARAMS:
        // version-conditional read: the request's version field carries
        // the worker's "I have v" (0 = unconditional, the legacy form).
        // An unchanged snapshot gets a cheap zero-payload PARAMS reply
        // echoing the version instead of re-shipping the full snapshot
        // — distinguishable from "nothing published yet" because a
        // published version is never 0.
        ++s->reads_total;
        if (h.version != 0 && h.version == s->param_version) {
          ++s->reads_not_modified;
          append_frame(c->tx, PARAMS, 0, s->param_version, nullptr, 0);
        } else {
          append_frame(c->tx, PARAMS, 0, s->param_version, s->params.data(),
                       s->params.size());
        }
        break;
      case PUSH_GRAD: {
        if (s->grads.size() >= queue_cap(s)) {
          // keep the frame buffered, send no ACK: the pushing worker
          // stalls until pop_grad frees a slot (processed next pump)
          if (off) c->rx.erase(c->rx.begin(), c->rx.begin() + off);
          return true;
        }
        GradMsg m;
        m.worker = h.worker;
        m.version = h.version;
        m.bytes.assign(payload, payload + h.len);
        s->grads.push_back(std::move(m));
        append_frame(c->tx, ACK, h.worker, h.version, nullptr, 0);
        break;
      }
      default:
        return false;
    }
    off += sizeof(h) + h.len;
  }
  if (off) c->rx.erase(c->rx.begin(), c->rx.begin() + off);
  return true;
}

// Blocking read of exactly n bytes with a deadline; 0 ok, -1 error/EOF,
// -2 timeout.
int read_full(int fd, uint8_t* buf, size_t n, int timeout_ms) {
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  size_t got = 0;
  while (got < n) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - t0.tv_sec) * 1000 +
                   (now.tv_nsec - t0.tv_nsec) / 1000000;
    long left = timeout_ms - elapsed;
    if (left <= 0) return -2;
    struct pollfd p{fd, POLLIN, 0};
    int pr = poll(&p, 1, (int)left);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) return -2;
    ssize_t r = recv(fd, buf + got, n - got, 0);
    if (r == 0) return -1;  // peer closed
    if (r < 0) {
      if (errno == EINTR || errno == EAGAIN) continue;
      return -1;
    }
    got += (size_t)r;
  }
  return 0;
}

int write_full(int fd, const uint8_t* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd p{fd, POLLOUT, 0};
        poll(&p, 1, 100);
        continue;
      }
      return -1;
    }
    sent += (size_t)r;
  }
  return 0;
}

}  // namespace

extern "C" {

// ---- server ---------------------------------------------------------------

// Listen on 0.0.0.0:port (0 = auto-assign; read back with
// tps_server_port). max_msg bounds any single frame payload (params or
// gradient bytes). Returns NULL on failure.
void* tps_server_create(uint16_t port, uint32_t n_workers, uint64_t max_msg) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);
  set_nonblock(fd);
  Server* s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->n_workers = n_workers;
  s->max_msg = max_msg;
  // epoll instance for readiness-batched ingest; a failed create means
  // the pump falls back to the original full sweep (same semantics)
  s->epfd = epoll_create1(0);
  if (s->epfd >= 0) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;  // nullptr marks the listener
    if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, fd, &ev) != 0) {
      close(s->epfd);
      s->epfd = -1;
    }
  }
  return s;
}

// Arm C++-side validation of the inner PSF2 frame for the batched pop:
// the Python server passes its wire fingerprint + expected payload size
// once at construction, and tps_server_pop_grad_batch then rejects bad
// frames (reason-coded) without the bytes ever crossing into Python.
void tps_server_set_frame_check(void* sv, uint64_t fingerprint,
                                uint64_t expected_payload) {
  Server* s = (Server*)sv;
  s->frame_check = 1;
  s->fingerprint = fingerprint;
  s->expected_payload = expected_payload;
}

uint16_t tps_server_port(void* sv) { return ((Server*)sv)->port; }

// Store the new snapshot; served to every subsequent GET_PARAMS.
int tps_server_publish(void* sv, const uint8_t* buf, uint64_t len,
                       uint64_t version) {
  Server* s = (Server*)sv;
  if (len > s->max_msg) return -1;
  s->params.assign(buf, buf + len);
  s->param_version = version;
  return 0;
}

// One non-blocking sweep: accept, read, parse, reply, flush. Returns the
// number of complete frames/connection events progressed (0 = idle).
//
// -- pump cycle counters (continuous profiling, telemetry/profiler.py) ---
// The Python stack sampler sees one opaque ctypes call for the whole
// epoll pump; these process-global counters (calls / events / wall ns)
// are its native-side ledger, read by tps_profile_stats the same
// plain-ints-only way as tps_server_read_stats.
static std::atomic<uint64_t> g_pump_calls{0};
static std::atomic<uint64_t> g_pump_events{0};
static std::atomic<uint64_t> g_pump_ns{0};
static std::atomic<uint64_t> g_frames_validated{0};

namespace {
struct PumpProf {
  timespec t0;
  PumpProf() { clock_gettime(CLOCK_MONOTONIC, &t0); }
  void done(int events) {
    timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    uint64_t ns = (uint64_t)(t1.tv_sec - t0.tv_sec) * 1000000000ull +
                  (uint64_t)(t1.tv_nsec - t0.tv_nsec);
    g_pump_calls.fetch_add(1, std::memory_order_relaxed);
    if (events > 0)
      g_pump_events.fetch_add((uint64_t)events, std::memory_order_relaxed);
    g_pump_ns.fetch_add(ns, std::memory_order_relaxed);
  }
};
}  // namespace

void tps_profile_stats(uint64_t* pump_calls, uint64_t* pump_events,
                       uint64_t* pump_ns, uint64_t* frames_validated) {
  *pump_calls = g_pump_calls.load(std::memory_order_relaxed);
  *pump_events = g_pump_events.load(std::memory_order_relaxed);
  *pump_ns = g_pump_ns.load(std::memory_order_relaxed);
  *frames_validated = g_frames_validated.load(std::memory_order_relaxed);
}

void tps_profile_reset() {
  g_pump_calls.store(0, std::memory_order_relaxed);
  g_pump_events.store(0, std::memory_order_relaxed);
  g_pump_ns.store(0, std::memory_order_relaxed);
  g_frames_validated.store(0, std::memory_order_relaxed);
}

// With epoll armed (the default) the accept+recv phase is readiness-
// driven: ONE epoll_wait(0) names exactly the sockets with pending
// bytes, and only those pay a recv() syscall — an idle fleet member
// costs nothing per pump, where the old full sweep paid one EAGAIN
// recv per connection per call. The parse/flush phase still walks all
// connections (pure memory ops unless a reply is owed): a conn whose
// buffered frame was deferred by grad-queue back-pressure has no
// kernel event to re-announce it, so readiness alone must never gate
// handle_frames.
int tps_server_pump(void* sv) {
  PumpProf prof;
  Server* s = (Server*)sv;
  int events = 0;
  if (s->epfd >= 0) {
    epoll_event evs[64];
    for (;;) {
      int ne = epoll_wait(s->epfd, evs, 64, 0);
      if (ne <= 0) break;
      int pass_events = 0;
      for (int e = 0; e < ne; ++e) {
        if (evs[e].data.ptr == nullptr) {
          pass_events += accept_all(s);
        } else {
          Conn* c = (Conn*)evs[e].data.ptr;
          pass_events += read_conn(s, c);
        }
      }
      events += pass_events;
      // exit on a short pass (every ready fd seen) OR a no-progress
      // pass: level-triggered epoll re-reports conns parked at the
      // per-conn rx memory bound, and with 64+ of those the event
      // count alone would never drop below the batch size — only the
      // parse phase below can free their buffers, so spinning here
      // would hang the server at 100% CPU
      if (ne < 64 || pass_events == 0) break;
    }
  } else {
    events += accept_all(s);
    for (Conn* c : s->conns)
      if (!c->dead) events += read_conn(s, c);
  }
  for (size_t i = 0; i < s->conns.size();) {
    Conn* c = s->conns[i];
    bool dead = c->dead;
    if (!dead && !handle_frames(s, c)) dead = true;  // protocol error
    if (!dead && !c->tx.empty()) {                   // flush replies
      ssize_t w = send(c->fd, c->tx.data(), c->tx.size(), MSG_NOSIGNAL);
      if (w > 0) {
        c->tx.erase(c->tx.begin(), c->tx.begin() + w);
        ++events;
      } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                 errno != EINTR) {
        dead = true;
      }
    }
    if (dead) close_conn(s, i);
    else ++i;
  }
  prof.done(events);
  return events;
}

// Per-frame record of the batched pop (mirrored by ctypes in tcp.py).
#pragma pack(push, 1)
struct BatchMeta {
  uint32_t worker;
  uint32_t status;   // FrameStatus: 0 ok, else the rejection reason
  uint64_t version;
  uint64_t off;      // payload offset into the batch buffer (ok only)
  uint64_t len;      // payload byte length (0 when rejected)
  uint32_t step;     // PSF2 lineage fields (0 unless frame_check hit ok)
  uint32_t seq;
  double send_wall;
};
#pragma pack(pop)
static_assert(sizeof(BatchMeta) == 48, "BatchMeta must be 48 bytes");

// -- per-frame ingest stamp ring (hop anatomy) ------------------------------
// The pump counters above aggregate; the hop-anatomy plane
// (telemetry/hop_anatomy.py) needs per-frame timing, so an armed process
// records one stamp per frame popped by tps_server_pop_grad_batch:
// when it left the queue, how long its PSF2 validation took, its payload
// size and verdict. Bounded ring, overflow drops-and-counts — the pop
// hot path never blocks or reallocates. Thread affinity matches
// tps_server_read_stats: arm/drain ONLY from the pump-owning thread.
#pragma pack(push, 1)
struct HopStamp {
  uint64_t t_ns;         // CLOCK_MONOTONIC when the frame was popped
  uint64_t validate_ns;  // ns inside validate_frame (0: check unarmed)
  uint64_t bytes;        // validated payload byte length (0 on reject)
  uint32_t worker;
  uint32_t status;       // FrameStatus: 0 ok, else rejection reason
};
#pragma pack(pop)
static_assert(sizeof(HopStamp) == 32, "HopStamp must be 32 bytes");

static HopStamp* g_stamp_ring = nullptr;
static uint32_t g_stamp_cap = 0;
static std::atomic<uint32_t> g_stamp_len{0};
static std::atomic<uint64_t> g_stamp_dropped{0};

static inline uint64_t hop_now_ns() {
  timespec t;
  clock_gettime(CLOCK_MONOTONIC, &t);
  return (uint64_t)t.tv_sec * 1000000000ull + (uint64_t)t.tv_nsec;
}

static void hop_stamp_record(uint32_t worker, uint32_t status,
                             uint64_t bytes, uint64_t validate_ns) {
  if (g_stamp_ring == nullptr) return;
  uint32_t len = g_stamp_len.load(std::memory_order_relaxed);
  if (len >= g_stamp_cap) {
    g_stamp_dropped.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  HopStamp& st = g_stamp_ring[len];
  st.t_ns = hop_now_ns();
  st.validate_ns = validate_ns;
  st.bytes = bytes;
  st.worker = worker;
  st.status = status;
  g_stamp_len.store(len + 1, std::memory_order_release);
}

// ABI self-description for the load-time size check (ctypes twin in
// parallel/tcp.py asserts its sizeof against this before first use).
uint32_t tps_abi_hop_stamp_bytes() { return (uint32_t)sizeof(HopStamp); }

// Arm (capacity > 0) or disarm (capacity 0) the stamp ring. Returns 0 on
// success, -1 on allocation failure. Resets length + drop counter.
int tps_hop_stamps_arm(uint32_t capacity) {
  delete[] g_stamp_ring;
  g_stamp_ring = nullptr;
  g_stamp_cap = 0;
  g_stamp_len.store(0, std::memory_order_relaxed);
  g_stamp_dropped.store(0, std::memory_order_relaxed);
  if (capacity == 0) return 0;
  g_stamp_ring = new (std::nothrow) HopStamp[capacity];
  if (g_stamp_ring == nullptr) return -1;
  g_stamp_cap = capacity;
  return 0;
}

// Batched drain: copy out up to max stamps (oldest first), reset the
// ring, report (and reset) the overflow-drop count since the previous
// drain. Returns stamps written. Pump-owning thread only.
uint32_t tps_hop_stamps_drain(HopStamp* out, uint32_t max,
                              uint64_t* dropped) {
  uint32_t len = g_stamp_len.load(std::memory_order_acquire);
  uint32_t n = len < max ? len : max;
  if (g_stamp_ring != nullptr && n > 0)
    std::memcpy(out, g_stamp_ring, (size_t)n * sizeof(HopStamp));
  if (len > n)
    g_stamp_dropped.fetch_add(len - n, std::memory_order_relaxed);
  g_stamp_len.store(0, std::memory_order_relaxed);
  if (dropped != nullptr)
    *dropped = g_stamp_dropped.exchange(0, std::memory_order_relaxed);
  return n;
}

// Batched pop: drain up to max_frames queued gradients in ONE call,
// validating each inner PSF2 frame in C++ when armed
// (tps_server_set_frame_check) — magic/version, declared vs expected
// size, config fingerprint, CRC32 — and packing only the VALIDATED
// payload bytes contiguously into buf. Rejected frames produce a
// reason-coded meta and no bytes; Python turns them into the same
// counted per-worker rejections frames.framed_poll produces. Returns
// the number of metas filled (0 = nothing queued); stops early when
// the next payload would not fit in cap (that frame stays queued).
int tps_server_pop_grad_batch(void* sv, uint8_t* buf, uint64_t cap,
                              BatchMeta* metas, int max_frames) {
  Server* s = (Server*)sv;
  int n = 0;
  uint64_t off = 0;
  while (n < max_frames && !s->grads.empty()) {
    GradMsg& m = s->grads.front();
    BatchMeta& meta = metas[n];
    meta.worker = m.worker;
    meta.version = m.version;
    meta.step = 0;
    meta.seq = 0;
    meta.send_wall = 0.0;
    const uint8_t* payload = m.bytes.data();
    uint64_t plen = m.bytes.size();
    uint32_t status = FRAME_OK;
    uint64_t v_ns = 0;
    if (s->frame_check) {
      PsfHeader h{};
      uint64_t v_t0 = g_stamp_ring != nullptr ? hop_now_ns() : 0;
      status = validate_frame(s, m, &payload, &plen, &h);
      if (g_stamp_ring != nullptr) v_ns = hop_now_ns() - v_t0;
      if (status == FRAME_OK) {
        g_frames_validated.fetch_add(1, std::memory_order_relaxed);
        meta.step = h.step;
        meta.seq = h.seq;
        meta.send_wall = h.send_wall;
      }
    }
    if (status != FRAME_OK) {
      meta.status = status;
      meta.off = 0;
      meta.len = 0;
      hop_stamp_record(m.worker, status, 0, v_ns);
      s->grads.pop_front();
      ++n;
      continue;
    }
    if (off + plen > cap) break;  // no room: frame stays queued
    std::memcpy(buf + off, payload, plen);
    meta.status = FRAME_OK;
    meta.off = off;
    meta.len = plen;
    hop_stamp_record(m.worker, FRAME_OK, plen, v_ns);
    off += plen;
    s->grads.pop_front();
    ++n;
  }
  return n;
}

// Pop one queued gradient (FIFO arrival order). Returns byte length >0
// and fills worker/version; 0 if none; -1 if the payload exceeds cap.
int64_t tps_server_pop_grad(void* sv, uint8_t* buf, uint64_t cap,
                            uint32_t* worker_out, uint64_t* version_out) {
  Server* s = (Server*)sv;
  if (s->grads.empty()) return 0;
  GradMsg& m = s->grads.front();
  if (m.bytes.size() > cap) return -1;
  std::memcpy(buf, m.bytes.data(), m.bytes.size());
  if (worker_out) *worker_out = m.worker;
  if (version_out) *version_out = m.version;
  int64_t n = (int64_t)m.bytes.size();
  s->grads.pop_front();
  return n;
}

// Gradients currently queued from this worker (liveness signal: pushed
// but not yet consumed counts as alive, mirroring psq_grad_pending).
int tps_server_pending(void* sv, uint32_t worker) {
  Server* s = (Server*)sv;
  int n = 0;
  for (const GradMsg& m : s->grads)
    if (m.worker == worker) ++n;
  return n;
}

// Is a connection claiming this worker id currently open? A crashed
// worker's socket closes (RST/EOF) and this flips to 0 — the transport-
// level failure signal shm cannot give; a replacement just reconnects.
int tps_server_connected(void* sv, uint32_t worker) {
  Server* s = (Server*)sv;
  for (const Conn* c : s->conns)
    if (c->worker == (int32_t)worker) return 1;
  return 0;
}

// Read-path counters: total GET_PARAMS served and how many were answered
// with the cheap not-modified reply. Written only by the pump (the serve
// thread); callers read them from that same thread and mirror into
// Python-side state for scrape threads.
void tps_server_read_stats(void* sv, uint64_t* total, uint64_t* not_modified) {
  Server* s = (Server*)sv;
  if (total) *total = s->reads_total;
  if (not_modified) *not_modified = s->reads_not_modified;
}

void tps_server_close(void* sv) {
  Server* s = (Server*)sv;
  if (!s) return;
  for (size_t i = s->conns.size(); i-- > 0;) close_conn(s, i);
  if (s->listen_fd >= 0) close(s->listen_fd);
  if (s->epfd >= 0) close(s->epfd);
  delete s;
}

// ---- worker ---------------------------------------------------------------

// Connect (retrying until timeout_ms — the server may not be up yet) and
// send HELLO. Returns NULL on failure.
void* tps_worker_connect(const char* host, uint16_t port, uint32_t worker_id,
                         int timeout_ms) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  int fd = -1;
  for (;;) {
    fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) break;
    close(fd);
    fd = -1;
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - t0.tv_sec) * 1000 +
                   (now.tv_nsec - t0.tv_nsec) / 1000000;
    if (elapsed >= timeout_ms) return nullptr;
    struct timespec ts = {0, 50 * 1000 * 1000};  // 50 ms between attempts
    nanosleep(&ts, nullptr);
  }
  set_nodelay(fd);
  Worker* w = new Worker();
  w->fd = fd;
  w->id = worker_id;
  std::vector<uint8_t> tx;
  append_frame(tx, HELLO, worker_id, 0, nullptr, 0);
  if (write_full(fd, tx.data(), tx.size()) != 0) {
    close(fd);
    delete w;
    return nullptr;
  }
  return w;
}

// Request + receive the latest snapshot. ``have_version`` is the
// version-conditional "I have v" (0 = unconditional): when the server's
// snapshot still IS that version it replies without the payload and this
// returns -4 ("not modified" — the caller's cached copy is current).
// Otherwise returns byte length (0 until the server's first publish) and
// fills version; -1 error, -2 timeout, -3 if the reply exceeds cap.
int64_t tps_worker_read_params(void* wv, uint8_t* buf, uint64_t cap,
                               uint64_t* version_out, int timeout_ms,
                               uint64_t have_version) {
  Worker* w = (Worker*)wv;
  // one deadline for the whole call: header + payload reads share the
  // caller's budget instead of each getting timeout_ms (which made the
  // worst-case block 2x what the caller asked for)
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  wan_delay_oneway();  // request propagation (WAN emulation; usually 0)
  std::vector<uint8_t> tx;
  append_frame(tx, GET_PARAMS, w->id, have_version, nullptr, 0);
  if (write_full(w->fd, tx.data(), tx.size()) != 0) return -1;
  FrameHdr h;
  // header read gets the REMAINING budget (the emulated request delay
  // above counted against the deadline like any network time would);
  // the reply-direction delay after the reads is additive latency by
  // design — it models propagation the caller cannot see into, so only
  // the emulated-WAN latency itself, never an extra timeout window,
  // extends the call
  struct timespec nowh;
  clock_gettime(CLOCK_MONOTONIC, &nowh);
  long spent = (nowh.tv_sec - t0.tv_sec) * 1000 +
               (nowh.tv_nsec - t0.tv_nsec) / 1000000;
  long hleft = timeout_ms - spent;
  if (hleft <= 0) return -2;
  int rc = read_full(w->fd, reinterpret_cast<uint8_t*>(&h), sizeof(h),
                     (int)hleft);
  if (rc != 0) return rc;
  if (h.magic != kMagic || h.op != PARAMS) return -1;
  if (h.len == 0 && have_version != 0 && h.version == have_version) {
    // not modified: the server confirmed our cached version is current
    wan_delay_oneway();  // reply propagation
    if (version_out) *version_out = h.version;
    return -4;
  }
  if (h.len > cap) return -3;
  if (h.len) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed = (now.tv_sec - t0.tv_sec) * 1000 +
                   (now.tv_nsec - t0.tv_nsec) / 1000000;
    long left = timeout_ms - elapsed;
    if (left <= 0) return -2;
    rc = read_full(w->fd, buf, h.len, (int)left);
    if (rc != 0) return rc;
  }
  wan_delay_oneway();  // reply propagation
  if (version_out) *version_out = h.version;
  return (int64_t)h.len;
}

// Push one gradient and wait for the server's ACK (back-pressure: at most
// one unacknowledged push in flight, like psqueue's single-slot mailbox).
// Returns 1 on ack; -1 error, -2 timeout.
int tps_worker_push_grad(void* wv, const uint8_t* buf, uint64_t len,
                         uint64_t version, int timeout_ms) {
  Worker* w = (Worker*)wv;
  wan_delay_oneway();  // push propagation (WAN emulation; usually 0)
  FrameHdr h{};
  h.magic = kMagic;
  h.op = PUSH_GRAD;
  h.worker = w->id;
  h.version = version;
  h.len = len;
  if (write_full(w->fd, reinterpret_cast<uint8_t*>(&h), sizeof(h)) != 0)
    return -1;
  if (len && write_full(w->fd, buf, len) != 0) return -1;
  FrameHdr ack;
  int rc = read_full(w->fd, reinterpret_cast<uint8_t*>(&ack), sizeof(ack),
                     timeout_ms);
  if (rc != 0) return rc;
  if (ack.magic != kMagic || ack.op != ACK || ack.len != 0) return -1;
  wan_delay_oneway();  // ack propagation
  return 1;
}

void tps_worker_close(void* wv) {
  Worker* w = (Worker*)wv;
  if (!w) return;
  if (w->fd >= 0) close(w->fd);
  delete w;
}

// ---- ABI self-description -------------------------------------------------
// The runtime twin of psanalyze's abi-drift rule: tcp.py re-reads the
// wire constants from the LOADED library at bind time and refuses the
// library on any mismatch with resilience/frames.py — so a stale or
// hand-copied .so whose header layout or reason codes drifted fails at
// load, not as a silent mis-decode mid-training.

uint32_t tps_abi_psf_magic(void) { return kPsfMagicV2; }

uint32_t tps_abi_psf_magic_v1(void) { return kPsfMagicV1; }

uint32_t tps_abi_psf_header_bytes(void) { return (uint32_t)kPsfHeader; }

uint32_t tps_abi_batch_meta_bytes(void) {
  return (uint32_t)sizeof(BatchMeta);
}

// Reason string for a FrameStatus code (NULL for unknown/OK) — the
// enum's names are the protocol, not just labels: Python counts
// rejections under these exact strings.
const char* tps_abi_frame_status_name(uint32_t code) {
  switch (code) {
    case FRAME_SHORT: return "short";
    case FRAME_VERSION: return "version";
    case FRAME_MAGIC: return "magic";
    case FRAME_SIZE: return "size";
    case FRAME_CONFIG: return "config";
    case FRAME_CORRUPT: return "corrupt";
    default: return nullptr;
  }
}

}  // extern "C"

// ===========================================================================
// Native PSR1 read tier: the serving/net.py event loop rebuilt on this
// file's epoll machinery. Accept / validate / reply run entirely here —
// readiness-driven, so an idle reader costs zero syscalls per pump — and
// payloads are ZERO-COPY: the server never owns snapshot or delta bytes.
// Python publishes (ptr, len, token) triples pointing at frozen
// SnapshotStore arrays / cached DeltaCodec encodes; replies writev the
// header + that view straight to the socket, and when the last byte of
// the last in-flight send drains, the token surfaces through
// tps_read_released so Python can fire the refcount release hook —
// exactly the net.py `done()` contract, at the wire's speed.
//
// Threading: unlike the TPS1 server above (single-threaded by contract),
// the read tier is SHARED between Python's pump thread (tps_read_pump)
// and the publish/metrics threads (tps_read_publish / tps_read_stats /
// tps_read_set_admission). One mutex guards all state; epoll_wait runs
// OUTSIDE the lock (publishes never stall behind a poll), and an eventfd
// wakes a blocked pump so close/retire are prompt.
//
// Reply semantics mirror serving/net.py BYTE for byte (the parity tests
// compare raw reply streams): same 40-byte little-endian header, same
// kind selection (not-modified / delta / full / retry / error), same
// shed-at-parse admission check, same "unknown tenant" / "bad request
// magic/op" error payloads. Version-window boundaries (publish + delta
// encodes) stay in Python; everything per-request lives here.

#include <map>
#include <mutex>
#include <string>
#include <sys/eventfd.h>
#include <sys/uio.h>
#include <unordered_map>

namespace {

constexpr uint32_t kPsrMagic = 0x31525350;  // "PSR1"
constexpr uint8_t kPsrOpRead = 1;
constexpr uint8_t kPsrFlagWantDelta = 1;
constexpr uint8_t kPsrFlagWantFresh = 2;

enum PsrKind : uint8_t {
  PSR_FULL = 0,
  PSR_DELTA = 1,
  PSR_NOT_MODIFIED = 2,
  PSR_RETRY = 3,
  PSR_ERROR = 4,
};

#pragma pack(push, 1)
struct PsrReq {  // serving/net.py _REQ = "<IBBHQ"
  uint32_t magic;
  uint8_t op;
  uint8_t flags;
  uint16_t tenant_len;
  uint64_t have_version;
};
struct PsrRep {  // serving/net.py _REP = "<IBBHQQdQ"
  uint32_t magic;
  uint8_t kind;
  uint8_t pad1;
  uint16_t pad2;
  uint64_t version;
  uint64_t base_version;
  double retry_after_s;
  uint64_t payload_len;
};
#pragma pack(pop)
static_assert(sizeof(PsrReq) == 16, "PSR1 request must be 16 bytes");
static_assert(sizeof(PsrRep) == 40, "PSR1 reply must be 40 bytes");

// A published payload view: Python-owned bytes (frozen snapshot / cached
// delta encode) identified by an opaque token. The server only ever
// reads through `data`; when the buffer is both retired (no longer the
// serveable latest) and drained (no in-flight send references it), the
// token joins the released queue for Python to unpin.
struct RBuf {
  uint64_t token = 0;
  const uint8_t* data = nullptr;
  uint64_t len = 0;
  uint32_t inflight = 0;  // queued tx items referencing this buffer
  bool retired = false;   // superseded by a newer publish
  uint64_t served = 0;    // replies that rode this buffer (coalescing)
};

struct RTenant {
  uint64_t latest = 0;            // latest published version (0 = none)
  RBuf* full = nullptr;           // latest full snapshot view
  std::map<uint64_t, RBuf*> deltas;  // base version -> delta view
  // FRS1 freshness trailer for `latest` (copied, owned here; cleared on
  // every publish so a stale birth record can never ride a new version)
  std::vector<uint8_t> fresh;
  double publish_wall = 0.0;      // last tps_read_set_fresh wall clock
  uint64_t fresh_replies = 0;     // replies that carried the trailer
  uint64_t min_have = 0;          // oldest nonzero have_version answered
};

// One queued reply: header (+ any inline error text) in `head`, then an
// optional zero-copy payload view.
struct TxItem {
  std::vector<uint8_t> head;
  size_t head_off = 0;
  RBuf* view = nullptr;
  uint64_t view_off = 0;
  std::vector<uint8_t> tail;  // FRS1 trailer after the payload view
  size_t tail_off = 0;
  bool counted_pending = false;  // admitted reply (sheds don't count)
};

struct RConn {
  int fd = -1;
  std::vector<uint8_t> rx;
  std::deque<TxItem> tx;
  bool closing = false;     // drop after the tx queue drains (bad magic)
  bool want_write = false;  // EPOLLOUT armed
};

// Per-read-server stats block, packed for the ctypes mirror in
// serving/native_read.py (same BatchMeta discipline: one static_assert
// here, one sizeof assert there, one ABI twin below).
#pragma pack(push, 1)
struct ReadStats {
  uint64_t conns;            // currently open reader connections
  uint64_t accepted_total;   // connections accepted over the lifetime
  uint64_t pending;          // admitted replies not yet fully drained
  uint64_t reads_total;      // answered reads (full+delta+not_modified)
  uint64_t reads_full;
  uint64_t reads_delta;
  uint64_t reads_not_modified;
  uint64_t reads_shed;       // admission-control retry replies
  uint64_t reads_error;      // error replies (unknown tenant, bad magic)
  uint64_t rejected_frames;  // bad-magic/op requests (connection closed)
  uint64_t eof_mid_request;  // peer EOF with a torn request buffered
  uint64_t coalesce_hits;    // delta replies riding an already-served encode
  uint64_t delta_bytes_saved;
  uint64_t bytes_sent;
  uint64_t pump_calls;
  uint64_t pump_ns;
};
#pragma pack(pop)
static_assert(sizeof(ReadStats) == 128, "ReadStats must be 128 bytes");

// Per-tenant freshness export, packed for the ctypes mirror in
// serving/native_read.py (same discipline as ReadStats: static_assert
// here, sizeof assert there, ABI twin below). Folded into core counters
// at teardown like the conn/shed counters.
#pragma pack(push, 1)
struct ReadFreshStats {
  uint64_t latest_version;    // latest published version for the tenant
  double last_publish_wall;   // wall clock of the last set_fresh
  uint64_t fresh_replies;     // replies that carried the FRS1 trailer
  uint64_t min_have_version;  // oldest nonzero have_version answered
};
#pragma pack(pop)
static_assert(sizeof(ReadFreshStats) == 32,
              "ReadFreshStats must be 32 bytes");

// epoll data.ptr sentinel for the wake eventfd (nullptr = listener,
// like the TPS1 server above; any other value = an RConn*).
static char g_wake_sentinel;

struct ReadServer {
  int listen_fd = -1;
  uint16_t port = 0;
  int epfd = -1;
  int wake_fd = -1;
  std::mutex mu;
  std::vector<RConn*> conns;
  std::unordered_map<std::string, RTenant*> tenants;
  std::deque<uint64_t> released;  // drained+retired tokens for Python
  uint64_t admission_depth = 64;
  double retry_after_s = 0.05;
  uint64_t pending = 0;  // admitted replies queued/in flight (backlog)
  // an empty request tenant maps here (net.py: `tenant or default`)
  std::string default_tenant;
  ReadStats st{};
};

// Buffer fully drained AND superseded: surface the token. Buffers still
// installed in a tenant map outlive any number of drains.
void rbuf_unref(ReadServer* s, RBuf* b) {
  if (b == nullptr) return;
  --b->inflight;
  if (b->retired && b->inflight == 0) {
    s->released.push_back(b->token);
    delete b;
  }
}

void rbuf_retire(ReadServer* s, RBuf* b) {
  if (b == nullptr) return;
  b->retired = true;
  if (b->inflight == 0) {
    s->released.push_back(b->token);
    delete b;
  }
}

void rconn_close(ReadServer* s, RConn* c) {
  // un-reference every queued payload view: pinned snapshots must be
  // released even when the reader disappeared mid-send (net.py _drop)
  while (!c->tx.empty()) {
    TxItem& it = c->tx.front();
    if (it.counted_pending && s->pending > 0) --s->pending;
    rbuf_unref(s, it.view);
    c->tx.pop_front();
  }
  if (c->fd >= 0) {
    if (s->epfd >= 0) epoll_ctl(s->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
  }
  for (size_t i = 0; i < s->conns.size(); ++i) {
    if (s->conns[i] == c) {
      s->conns.erase(s->conns.begin() + i);
      break;
    }
  }
  delete c;
}

void rconn_interest(ReadServer* s, RConn* c, bool want_write) {
  if (c->want_write == want_write) return;
  c->want_write = want_write;
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
  ev.data.ptr = c;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

// Flush the conn's tx queue with writev (header + zero-copy payload view
// in one syscall). Returns false when the connection died.
bool rconn_flush(ReadServer* s, RConn* c) {
  while (!c->tx.empty()) {
    TxItem& it = c->tx.front();
    iovec iov[3];
    int niov = 0;
    if (it.head_off < it.head.size()) {
      iov[niov].iov_base = it.head.data() + it.head_off;
      iov[niov].iov_len = it.head.size() - it.head_off;
      ++niov;
    }
    if (it.view != nullptr && it.view_off < it.view->len) {
      iov[niov].iov_base = const_cast<uint8_t*>(it.view->data) + it.view_off;
      iov[niov].iov_len = (size_t)(it.view->len - it.view_off);
      ++niov;
    }
    if (it.tail_off < it.tail.size()) {
      iov[niov].iov_base = it.tail.data() + it.tail_off;
      iov[niov].iov_len = it.tail.size() - it.tail_off;
      ++niov;
    }
    if (niov == 0) {  // zero-length payload edge: item already complete
      if (it.counted_pending && s->pending > 0) --s->pending;
      rbuf_unref(s, it.view);
      c->tx.pop_front();
      continue;
    }
    ssize_t w = writev(c->fd, iov, niov);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
        rconn_interest(s, c, true);
        return true;
      }
      return false;  // peer reset mid-send
    }
    s->st.bytes_sent += (uint64_t)w;
    size_t left = (size_t)w;
    size_t head_left = it.head.size() - it.head_off;
    size_t adv = left < head_left ? left : head_left;
    it.head_off += adv;
    left -= adv;
    size_t view_left =
        it.view != nullptr ? (size_t)(it.view->len - it.view_off) : 0;
    adv = left < view_left ? left : view_left;
    it.view_off += adv;
    left -= adv;
    it.tail_off += left;
    bool done = it.head_off == it.head.size() &&
                (it.view == nullptr || it.view_off >= it.view->len) &&
                it.tail_off >= it.tail.size();
    if (!done) {
      rconn_interest(s, c, true);
      return true;
    }
    if (it.counted_pending && s->pending > 0) --s->pending;
    rbuf_unref(s, it.view);
    c->tx.pop_front();
  }
  rconn_interest(s, c, false);
  if (c->closing) return false;
  return true;
}

// Queue one PSR1 reply (net.py _reply byte layout: retry_after_s packed
// only on retry replies, 0.0 otherwise). `tail` is the optional FRS1
// freshness trailer riding after the payload; its length lands in the
// reply's pad1 byte (0 = none, so non-requesting readers see replies
// byte-identical to the pre-freshness wire).
void rqueue_reply(ReadServer* s, RConn* c, uint8_t kind, uint64_t version,
                  uint64_t base, double retry_after,
                  const uint8_t* inline_payload, uint64_t inline_len,
                  RBuf* view, bool admitted,
                  const uint8_t* tail = nullptr, uint64_t tail_len = 0) {
  PsrRep h{};
  h.magic = kPsrMagic;
  h.kind = kind;
  h.pad1 = (uint8_t)(tail_len <= 255 ? tail_len : 0);
  h.version = version;
  h.base_version = base;
  h.retry_after_s = retry_after;
  h.payload_len = view != nullptr ? view->len : inline_len;
  TxItem it;
  const uint8_t* hp = reinterpret_cast<const uint8_t*>(&h);
  it.head.assign(hp, hp + sizeof(h));
  if (inline_payload != nullptr && inline_len > 0)
    it.head.insert(it.head.end(), inline_payload, inline_payload + inline_len);
  if (view != nullptr) ++view->inflight;
  it.view = view;
  if (tail != nullptr && h.pad1 > 0)
    it.tail.assign(tail, tail + h.pad1);
  it.counted_pending = admitted;
  if (admitted) ++s->pending;
  c->tx.push_back(std::move(it));
}

void rqueue_error(ReadServer* s, RConn* c, const char* msg) {
  ++s->st.reads_error;
  rqueue_reply(s, c, PSR_ERROR, 0, 0, 0.0,
               reinterpret_cast<const uint8_t*>(msg), strlen(msg), nullptr,
               false);
}

// Parse + answer every complete request buffered on the conn — the
// net.py _parse_one / shed-at-parse / handle_read sequence, inline.
void rconn_handle(ReadServer* s, RConn* c) {
  size_t off = 0;
  while (c->rx.size() - off >= sizeof(PsrReq)) {
    PsrReq req;
    std::memcpy(&req, c->rx.data() + off, sizeof(req));
    if (req.magic != kPsrMagic || req.op != kPsrOpRead) {
      // net.py: clear the torn buffer, answer, close after the flush
      c->rx.clear();
      off = 0;
      ++s->st.rejected_frames;
      rqueue_error(s, c, "bad request magic/op");
      c->closing = true;
      break;
    }
    size_t total = sizeof(PsrReq) + req.tenant_len;
    if (c->rx.size() - off < total) break;  // partial tenant bytes
    std::string tenant(reinterpret_cast<const char*>(c->rx.data() + off +
                                                     sizeof(PsrReq)),
                       req.tenant_len);
    if (tenant.empty()) tenant = s->default_tenant;
    off += total;
    bool want_delta = (req.flags & kPsrFlagWantDelta) != 0;
    bool want_fresh = (req.flags & kPsrFlagWantFresh) != 0;
    uint64_t have = req.have_version;
    RTenant* t = nullptr;
    auto ti = s->tenants.find(tenant);
    if (ti != s->tenants.end()) t = ti->second;
    // admission control, BEFORE tenant validation (net.py sheds at
    // parse time): past the depth the reply is an immediate retry
    // carrying the tenant's latest version + the suggested backoff
    if (s->pending >= s->admission_depth) {
      ++s->st.reads_shed;
      rqueue_reply(s, c, PSR_RETRY, t != nullptr ? t->latest : 0, 0,
                   s->retry_after_s, nullptr, 0, nullptr, false);
      continue;
    }
    if (t == nullptr) {
      std::string msg = "unknown tenant '" + tenant + "'";
      rqueue_error(s, c, msg.c_str());
      continue;
    }
    if (t->full == nullptr || t->latest == 0) {
      // nothing published yet: ask the reader to come back (retry with
      // version 0 — distinguishable from a shed, which echoes latest)
      rqueue_reply(s, c, PSR_RETRY, 0, 0, s->retry_after_s, nullptr, 0,
                   nullptr, false);
      continue;
    }
    ++s->st.reads_total;
    if (have > 0 && (t->min_have == 0 || have < t->min_have))
      t->min_have = have;
    // the trailer describes t->latest by construction (installed under
    // this same lock at publish time), so version consistency is free
    const uint8_t* ftail =
        want_fresh && !t->fresh.empty() ? t->fresh.data() : nullptr;
    uint64_t ftail_len = ftail != nullptr ? t->fresh.size() : 0;
    if (have == t->latest) {
      ++s->st.reads_not_modified;
      rqueue_reply(s, c, PSR_NOT_MODIFIED, t->latest, have, 0.0, nullptr,
                   0, nullptr, true);
      continue;
    }
    if (want_delta && have > 0) {
      auto di = t->deltas.find(have);
      if (di != t->deltas.end()) {
        RBuf* d = di->second;
        ++s->st.reads_delta;
        if (d->served > 0) ++s->st.coalesce_hits;
        ++d->served;
        if (t->full->len > d->len)
          s->st.delta_bytes_saved += t->full->len - d->len;
        if (ftail != nullptr) ++t->fresh_replies;
        rqueue_reply(s, c, PSR_DELTA, t->latest, have, 0.0, nullptr, 0, d,
                     true, ftail, ftail_len);
        continue;
      }
      // base aged out of the window / encode declined: full fallback,
      // identical to the Python ring_ageout / delta_full_fallback path
    }
    ++s->st.reads_full;
    ++t->full->served;
    if (ftail != nullptr) ++t->fresh_replies;
    rqueue_reply(s, c, PSR_FULL, t->latest, 0, 0.0, nullptr, 0, t->full,
                 true, ftail, ftail_len);
  }
  if (off > 0) c->rx.erase(c->rx.begin(), c->rx.begin() + off);
}

// Drain one reader socket; returns false when the conn should close.
bool rconn_read(ReadServer* s, RConn* c) {
  for (;;) {
    uint8_t buf[65536];
    ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
    if (r > 0) {
      c->rx.insert(c->rx.end(), buf, buf + r);
      continue;
    }
    if (r == 0) {  // EOF: a torn request still buffered is accounted
      if (!c->rx.empty()) ++s->st.eof_mid_request;
      return false;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) break;
    if (!c->rx.empty()) ++s->st.eof_mid_request;
    return false;
  }
  rconn_handle(s, c);
  return rconn_flush(s, c);
}

int raccept_all(ReadServer* s) {
  int events = 0;
  for (;;) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    set_nonblock(fd);
    set_nodelay(fd);
    RConn* c = new RConn();
    c->fd = fd;
    s->conns.push_back(c);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = c;
    epoll_ctl(s->epfd, EPOLL_CTL_ADD, fd, &ev);
    ++s->st.accepted_total;
    ++events;
  }
  return events;
}

RTenant* rtenant_get(ReadServer* s, const char* tenant) {
  std::string key(tenant != nullptr ? tenant : "");
  auto it = s->tenants.find(key);
  if (it != s->tenants.end()) return it->second;
  RTenant* t = new RTenant();
  s->tenants.emplace(std::move(key), t);
  return t;
}

}  // namespace

extern "C" {

// ---- native read tier -----------------------------------------------------

// Listen on host:port (0 = auto; read back with tps_read_port). Returns
// NULL on failure (no socket / no epoll / no eventfd — Python falls
// back to the selectors loop).
void* tps_read_create(const char* host, uint16_t port,
                      uint64_t admission_depth, double retry_after_s,
                      const char* default_tenant) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (host != nullptr && *host != '\0' &&
      inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return nullptr;
  }
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, 1024) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);
  set_nonblock(fd);
  int epfd = epoll_create1(0);
  int wake_fd = eventfd(0, EFD_NONBLOCK);
  if (epfd < 0 || wake_fd < 0) {
    close(fd);
    if (epfd >= 0) close(epfd);
    if (wake_fd >= 0) close(wake_fd);
    return nullptr;
  }
  ReadServer* s = new ReadServer();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->epfd = epfd;
  s->wake_fd = wake_fd;
  s->admission_depth = admission_depth;
  s->retry_after_s = retry_after_s;
  s->default_tenant = (default_tenant != nullptr) ? default_tenant : "";
  // the default tenant exists from construction (mirrors ServingCore's
  // _ensure_tenant): a pre-publish read is "nothing published yet"
  // (retry), not "unknown tenant"
  rtenant_get(s, s->default_tenant.c_str());
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = nullptr;  // nullptr marks the listener
  epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
  ev.data.ptr = &g_wake_sentinel;
  epoll_ctl(epfd, EPOLL_CTL_ADD, wake_fd, &ev);
  return s;
}

uint16_t tps_read_port(void* h) { return ((ReadServer*)h)->port; }

// Install the latest full snapshot view for a tenant. `data` must stay
// valid until `token` comes back through tps_read_released (Python pins
// the frozen SnapshotStore array). Retires the previous full view AND
// every delta (their base→latest window ended with this publish).
void tps_read_publish(void* h, const char* tenant, uint64_t version,
                      const uint8_t* data, uint64_t len, uint64_t token) {
  ReadServer* s = (ReadServer*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  RTenant* t = rtenant_get(s, tenant);
  rbuf_retire(s, t->full);
  for (auto& kv : t->deltas) rbuf_retire(s, kv.second);
  t->deltas.clear();
  RBuf* b = new RBuf();
  b->token = token;
  b->data = data;
  b->len = len;
  t->full = b;
  t->latest = version;
  // the old trailer describes the superseded version: never serve it
  // with the new one (tps_read_set_fresh re-installs right after)
  t->fresh.clear();
}

// Install the FRS1 freshness trailer for a tenant's current latest
// version (copied — no lifetime contract, unlike the payload views).
// len == 0 clears the trailer; publish_wall > 0 updates the export.
void tps_read_set_fresh(void* h, const char* tenant, const uint8_t* data,
                        uint64_t len, double publish_wall) {
  ReadServer* s = (ReadServer*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  RTenant* t = rtenant_get(s, tenant);
  if (data != nullptr && len > 0 && len <= 255)
    t->fresh.assign(data, data + len);
  else
    t->fresh.clear();
  if (publish_wall > 0.0) t->publish_wall = publish_wall;
}

// Per-tenant freshness export (oldest-served-version / last-publish-wall
// pair + trailer reply count). Returns 1 when the tenant exists.
int tps_read_fresh_stats(void* h, const char* tenant, ReadFreshStats* out) {
  ReadServer* s = (ReadServer*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  std::string key(tenant != nullptr ? tenant : "");
  auto it = s->tenants.find(key);
  if (it == s->tenants.end()) return 0;
  RTenant* t = it->second;
  out->latest_version = t->latest;
  out->last_publish_wall = t->publish_wall;
  out->fresh_replies = t->fresh_replies;
  out->min_have_version = t->min_have;
  return 1;
}

// Install one pre-encoded delta (base → current latest) for a tenant.
// Same lifetime contract as tps_read_publish.
void tps_read_add_delta(void* h, const char* tenant, uint64_t base,
                        const uint8_t* data, uint64_t len, uint64_t token) {
  ReadServer* s = (ReadServer*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  RTenant* t = rtenant_get(s, tenant);
  auto it = t->deltas.find(base);
  if (it != t->deltas.end()) rbuf_retire(s, it->second);
  RBuf* b = new RBuf();
  b->token = token;
  b->data = data;
  b->len = len;
  t->deltas[base] = b;
}

// One pump pass: epoll_wait (OUTSIDE the lock, up to timeout_ms — the
// ctypes call releases the GIL so Python's pump thread blocks here
// cheaply), then accept/read/parse/reply/flush under the lock. Returns
// progress events (0 = idle).
int tps_read_pump(void* h, int timeout_ms) {
  ReadServer* s = (ReadServer*)h;
  timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  epoll_event evs[128];
  int ne = epoll_wait(s->epfd, evs, 128, timeout_ms);
  int events = 0;
  if (ne > 0) {
    std::lock_guard<std::mutex> lk(s->mu);
    for (int e = 0; e < ne; ++e) {
      void* p = evs[e].data.ptr;
      if (p == nullptr) {
        events += raccept_all(s);
        continue;
      }
      if (p == &g_wake_sentinel) {
        uint64_t v;
        while (read(s->wake_fd, &v, sizeof(v)) > 0) {
        }
        ++events;
        continue;
      }
      RConn* c = (RConn*)p;
      // the conn may already have been closed by an earlier event in
      // this same batch (e.g. EPOLLIN+EPOLLOUT with a dead peer)
      bool live = false;
      for (RConn* q : s->conns)
        if (q == c) {
          live = true;
          break;
        }
      if (!live) continue;
      bool ok = true;
      if (evs[e].events & (EPOLLIN | EPOLLHUP | EPOLLERR))
        ok = rconn_read(s, c);
      if (ok && (evs[e].events & EPOLLOUT)) ok = rconn_flush(s, c);
      if (!ok) rconn_close(s, c);
      ++events;
    }
  }
  timespec t1;
  clock_gettime(CLOCK_MONOTONIC, &t1);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    ++s->st.pump_calls;
    s->st.pump_ns += (uint64_t)(t1.tv_sec - t0.tv_sec) * 1000000000ull +
                     (uint64_t)(t1.tv_nsec - t0.tv_nsec);
  }
  return events;
}

// Pop up to `cap` drained+retired tokens. Python runs the release hooks
// (snapshot ring unpin / delta buffer drop) for each.
int tps_read_released(void* h, uint64_t* out, int cap) {
  ReadServer* s = (ReadServer*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  int n = 0;
  while (n < cap && !s->released.empty()) {
    out[n++] = s->released.front();
    s->released.pop_front();
  }
  return n;
}

void tps_read_stats(void* h, ReadStats* out) {
  ReadServer* s = (ReadServer*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  s->st.conns = s->conns.size();
  s->st.pending = s->pending;
  *out = s->st;
}

// Live admission retuning (the control plane's set_admission_depth).
void tps_read_set_admission(void* h, uint64_t depth, double retry_after_s) {
  ReadServer* s = (ReadServer*)h;
  std::lock_guard<std::mutex> lk(s->mu);
  s->admission_depth = depth;
  s->retry_after_s = retry_after_s;
}

// Wake a pump blocked in epoll_wait (publish visibility / shutdown).
void tps_read_wake(void* h) {
  ReadServer* s = (ReadServer*)h;
  uint64_t one = 1;
  ssize_t r = write(s->wake_fd, &one, sizeof(one));
  (void)r;
}

// Tear down. The pump thread must have exited (Python joins it first).
// Remaining tokens are NOT surfaced — Python releases everything it
// still tracks after this call.
void tps_read_close(void* h) {
  ReadServer* s = (ReadServer*)h;
  if (s == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    while (!s->conns.empty()) rconn_close(s, s->conns.back());
    for (auto& kv : s->tenants) {
      RTenant* t = kv.second;
      if (t->full != nullptr) delete t->full;
      for (auto& dv : t->deltas) delete dv.second;
      delete t;
    }
    s->tenants.clear();
  }
  if (s->listen_fd >= 0) close(s->listen_fd);
  if (s->epfd >= 0) close(s->epfd);
  if (s->wake_fd >= 0) close(s->wake_fd);
  delete s;
}

// ---- read-tier ABI self-description ---------------------------------------
// Runtime twin of the abi-drift rule for the read plane: native_read.py
// re-reads these at bind time and refuses the library on any mismatch
// with serving/net.py's struct layouts.

uint32_t tps_abi_psr_magic(void) { return kPsrMagic; }

uint32_t tps_abi_psr_req_bytes(void) { return (uint32_t)sizeof(PsrReq); }

uint32_t tps_abi_psr_rep_bytes(void) { return (uint32_t)sizeof(PsrRep); }

uint32_t tps_abi_read_stats_bytes(void) {
  return (uint32_t)sizeof(ReadStats);
}

uint32_t tps_abi_read_fresh_stats_bytes(void) {
  return (uint32_t)sizeof(ReadFreshStats);
}

}  // extern "C"
