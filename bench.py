"""Headline benchmark: gradient aggregation + fused SGD update latency.

This is the reference's entire job — encode/serialize per-parameter
gradients, exchange across workers, sum, and step (``ps.py:103-193``) —
measured for a ResNet-18-sized gradient set (~11M params, ~60 tensors,
8 workers):

- **reference-style baseline**: the reference's host pipeline re-created
  in numpy/pickle (its wire: per-param pickle of each worker's ndarray,
  blosc level-0 = framing only so a byte-copy, ``mpi_comms.py:18-26``;
  then per-param unpickle → 8-way sum → eager momentum-SGD update loop,
  ``ps.py:161-214``). Network transfer is *excluded* — this is the purely
  local serialize/decode/sum/update cost the reference pays even on
  localhost.
- **ours**: the same aggregation semantics as one fused XLA program on
  the TPU (identity codec ``decode_sum`` + fused ``sgd_update`` — exactly
  the code path ``MPI_PS.step`` runs per chip, where multi-chip meshes
  add one ICI psum).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} with
vs_baseline = baseline_ms / ours_ms (speedup factor, >1 is better).
"""

from __future__ import annotations

import json
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs import IdentityCodec
from pytorch_ps_mpi_tpu.models import ResNet18
from pytorch_ps_mpi_tpu.optim import SGDHyper, init_sgd_state, sgd_update

WORKERS = 8
REPS = 20


def make_grads(params, workers, seed=0):
    rng = np.random.RandomState(seed)
    leaves, treedef = jax.tree.flatten(params)
    stacked = [rng.randn(workers, *np.shape(x)).astype(np.float32) for x in leaves]
    return treedef, stacked


def reference_style_step(np_params, np_bufs, worker_msgs, lr=0.01, momentum=0.9):
    """One aggregation+update step the reference's way: per-param unpickle
    of every worker's message, numpy sum, eager momentum SGD."""
    for i, msgs in enumerate(worker_msgs):
        grads = [pickle.loads(m) for m in msgs]          # ps.py:166, mpi_comms.py:174
        d_p = grads[0].copy()
        for g in grads[1:]:
            d_p += g                                     # ps.py:176 sum(grads)
        buf = np_bufs[i]
        buf *= momentum
        buf += d_p                                       # ps.py:207-208
        np_params[i] -= lr * buf                         # ps.py:214


def run_reference_baseline(treedef, stacked):
    np_params = [np.zeros(s.shape[1:], np.float32) for s in stacked]
    np_bufs = [np.zeros_like(p) for p in np_params]
    times = []
    for _ in range(max(3, REPS // 4)):
        t0 = time.perf_counter()
        # encode/serialize side (overlapped with backprop in the reference,
        # but still CPU work it must do): pickle each worker's each tensor
        worker_msgs = [
            [pickle.dumps(s[w]) for w in range(WORKERS)] for s in stacked
        ]
        reference_style_step(np_params, np_bufs, worker_msgs)
        times.append(time.perf_counter() - t0)
    return min(times)


def run_ours(treedef, stacked):
    params = jax.tree.unflatten(treedef, [jnp.zeros(s.shape[1:]) for s in stacked])
    grads_stacked = jax.tree.unflatten(treedef, [jnp.asarray(s) for s in stacked])
    state = init_sgd_state(params)
    h = SGDHyper(lr=0.01, momentum=0.9)
    code = IdentityCodec()

    @jax.jit
    def step(params, state, grads_stacked):
        summed = jax.tree.map(
            lambda g, p: code.decode_sum(g, p.shape, p.dtype), grads_stacked, params
        )
        return sgd_update(params, summed, state, h)

    params, state = step(params, state, grads_stacked)  # compile
    jax.block_until_ready(params)
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        params, state = step(params, state, grads_stacked)
        jax.block_until_ready(params)
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    model = ResNet18(num_classes=10, small_inputs=True)
    params = model.init(jax.random.key(0), jnp.ones((1, 32, 32, 3)))
    treedef, stacked = make_grads(params, WORKERS)
    n_params = sum(int(np.prod(s.shape[1:])) for s in stacked)

    ref_s = run_reference_baseline(treedef, stacked)
    ours_s = run_ours(treedef, stacked)

    print(
        json.dumps(
            {
                "metric": f"resnet18_{n_params//10**6}M_grad_aggregation_sgd_update_ms",
                "value": round(ours_s * 1e3, 4),
                "unit": "ms",
                "vs_baseline": round(ref_s / ours_s, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
