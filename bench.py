"""Headline benchmark: gradient aggregation + fused SGD update latency.

This is the reference's entire job — encode/serialize per-parameter
gradients, exchange across workers, sum, and step (``ps.py:103-193``) —
measured for a ResNet-18-sized gradient set (~11M params, ~60 tensors,
8 workers):

- **reference-style baseline**: the reference's host pipeline re-created
  in numpy/pickle (its wire: per-param pickle of each worker's ndarray,
  blosc level-0 = framing only so a byte-copy, ``mpi_comms.py:18-26``;
  then per-param unpickle → 8-way sum → eager momentum-SGD update loop,
  ``ps.py:161-214``). Network transfer is *excluded* — this is the purely
  local serialize/decode/sum/update cost the reference pays even on
  localhost.
- **ours**: the same aggregation semantics as one fused XLA program on
  the TPU (identity codec ``decode_sum`` + fused ``sgd_update`` — exactly
  the code path ``MPI_PS.step`` runs per chip, where multi-chip meshes
  add one ICI psum).

Device work is deliberately just TWO jitted programs (grad/param
materialization from on-device PRNG, then the step), with parameter
shapes discovered host-side via ``jax.eval_shape`` — no eager per-op
dispatch, no bulk host→device transfers, so the benchmark stays fast
even when the TPU sits behind a high-latency tunnel.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} with
vs_baseline = baseline_ms / ours_ms (speedup factor, >1 is better).
"""

from __future__ import annotations

import json
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.utils.backend_guard import (
    enable_compilation_cache,
    ensure_live_backend,
)

enable_compilation_cache()

from pytorch_ps_mpi_tpu.codecs import IdentityCodec
from pytorch_ps_mpi_tpu.models import ResNet18
from pytorch_ps_mpi_tpu.optim import SGDHyper, init_sgd_state, sgd_update

WORKERS = 8
REPS = 20


def param_structs():
    """Parameter ShapeDtypeStructs via tracing only — no device ops."""
    model = ResNet18(num_classes=10, small_inputs=True)
    return jax.eval_shape(
        lambda k: model.init(k, jnp.ones((1, 32, 32, 3), jnp.float32)),
        jax.random.key(0),
    )


def reference_style_step(np_params, np_bufs, worker_msgs, lr=0.01, momentum=0.9):
    """One aggregation+update step the reference's way: per-param unpickle
    of every worker's message, numpy sum, eager momentum SGD."""
    for i, msgs in enumerate(worker_msgs):
        grads = [pickle.loads(m) for m in msgs]          # ps.py:166, mpi_comms.py:174
        d_p = grads[0].copy()
        for g in grads[1:]:
            d_p += g                                     # ps.py:176 sum(grads)
        buf = np_bufs[i]
        buf *= momentum
        buf += d_p                                       # ps.py:207-208
        np_params[i] -= lr * buf                         # ps.py:214


def run_reference_baseline(shapes):
    rng = np.random.RandomState(0)
    stacked = [rng.randn(WORKERS, *s).astype(np.float32) for s in shapes]
    np_params = [np.zeros(s, np.float32) for s in shapes]
    np_bufs = [np.zeros_like(p) for p in np_params]
    times = []
    for _ in range(max(3, REPS // 4)):
        t0 = time.perf_counter()
        # encode/serialize side (overlapped with backprop in the reference,
        # but still CPU work it must do): pickle each worker's each tensor
        worker_msgs = [
            [pickle.dumps(s[w]) for w in range(WORKERS)] for s in stacked
        ]
        reference_style_step(np_params, np_bufs, worker_msgs)
        times.append(time.perf_counter() - t0)
    return min(times)


def run_ours(structs):
    code = IdentityCodec()
    h = SGDHyper(lr=0.01, momentum=0.9)
    leaves, treedef = jax.tree.flatten(structs)

    @jax.jit
    def materialize(key):
        keys = jax.random.split(key, len(leaves))
        grads_stacked = jax.tree.unflatten(
            treedef,
            [
                jax.random.normal(k, (WORKERS,) + s.shape, jnp.float32)
                for k, s in zip(keys, leaves)
            ],
        )
        params = jax.tree.unflatten(
            treedef, [jnp.zeros(s.shape, jnp.float32) for s in leaves]
        )
        return params, init_sgd_state(params), grads_stacked

    @jax.jit
    def step(params, state, grads_stacked):
        summed = jax.tree.map(
            lambda g, p: code.decode_sum(g, p.shape, p.dtype), grads_stacked, params
        )
        return sgd_update(params, summed, state, h)

    params, state, grads_stacked = materialize(jax.random.key(0))
    jax.block_until_ready(params)
    params, state = step(params, state, grads_stacked)  # compile
    jax.block_until_ready(params)
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        params, state = step(params, state, grads_stacked)
        jax.block_until_ready(params)
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    ensure_live_backend()
    structs = param_structs()
    shapes = [s.shape for s in jax.tree.leaves(structs)]
    n_params = sum(int(np.prod(s)) for s in shapes)

    ref_s = run_reference_baseline(shapes)
    ours_s = run_ours(structs)

    print(
        json.dumps(
            {
                "metric": f"resnet18_{n_params//10**6}M_grad_aggregation_sgd_update_ms",
                "value": round(ours_s * 1e3, 4),
                "unit": "ms",
                "vs_baseline": round(ref_s / ours_s, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
