"""Headline benchmarks, honestly labeled with the backend that ran them.

Emits one JSON line per metric, each carrying ``backend`` (the JAX backend
that actually executed the measurement), ``fallback`` (True when that
executing backend is the host CPU; judged from the backend itself, not
from the liveness probe, whose verdict is reported separately as
``probe_live`` — on a loaded host the probe subprocess can time out while
the in-process backend is live TPU), and ``device_kind`` — so a
CPU-fallback run can never masquerade as a TPU result (VERDICT r1 item 1).

On a CPU-fallback run the tail of the output additionally *replays* the
newest committed TPU measurements (VERDICT r3 item 1): those lines keep
``backend: "tpu"`` (the backend that EXECUTED the measurement) but every
one carries ``replayed: true``, ``provenance: "watcher <timestamp>"`` and
``age_hours`` — the live-vs-recalled distinction rides on ``replayed``,
never on backend alone. The final line is then a ``tpu_record_summary``
so a last-line parse of the round record lands on measured TPU numbers
(aggregation latency + MFU) with honest provenance.

Line 1 — gradient aggregation + fused SGD update latency, the reference's
entire job (encode/serialize per-parameter gradients, exchange across
workers, sum, step — ``ps.py:103-193``) for a ResNet-18-sized gradient set
(~11M params, ~60 tensors, 8 workers):

- **reference-style baseline**: the reference's host pipeline re-created
  in numpy/pickle (its wire: per-param pickle of each worker's ndarray,
  blosc level-0 = framing only so a byte-copy, ``mpi_comms.py:18-26``;
  then per-param unpickle → 8-way sum → eager momentum-SGD update loop,
  ``ps.py:161-214``). Network transfer is *excluded* — this is the purely
  local serialize/decode/sum/update cost the reference pays even on
  localhost. A sanity floor, not the TPU story.
- **ours**: the same aggregation semantics as one fused XLA program on
  the accelerator (identity codec ``decode_sum`` + fused ``sgd_update`` —
  exactly the code path ``MPI_PS.step`` runs per chip, where multi-chip
  meshes add one ICI psum).

Line 2 — end-to-end ResNet-18 training step (fwd+bwd+update) steps/sec
with measured-FLOPs MFU (XLA cost analysis / device time / bf16 peak for
the device kind). ``vs_baseline`` compares against the same XLA program
compiled for the host CPU backend — the BASELINE.md steps/sec anchor.

Timing methodology: the tunneled axon backend's ``block_until_ready`` is
a no-op and every value fetch costs one ~68 ms round-trip, so all device
times come from K-step fused ``lax.scan`` programs with the fetch RTT
subtracted — validated against a known-FLOPs matmul control at 97% of
the chip's published peak (see ``utils/devtime.py``). Per-call walls
including the RTT are reported alongside, honestly labeled.

When the backend is a real TPU, a Mosaic-compiled Pallas smoke test
(sign pack/unpack + int8 quant/dequant round-trips, interpret=False) runs
first and its status rides in line 1 as ``pallas_mosaic``.
"""

from __future__ import annotations

import json
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.utils.backend_guard import (
    enable_compilation_cache,
    ensure_live_backend,
)

enable_compilation_cache()

from pytorch_ps_mpi_tpu.codecs import IdentityCodec
from pytorch_ps_mpi_tpu.models import ResNet18
from pytorch_ps_mpi_tpu.optim import SGDHyper, init_sgd_state, sgd_update
from pytorch_ps_mpi_tpu.utils.devtime import (
    device_kind,
    peak_flops_for,
    rtt_floor,
    rtt_subtracted_ms,
    safe_ratio,
    timed,
)

WORKERS = 8
REPS = 20  # lowered to 5 at runtime on the CPU-fallback path
TRAIN_BATCH = 256
# steps fused into one program for RTT-amortized timing: at ~0.5 ms/step
# the 50-step signal is ~25 ms against a ~68 ms RTT floor, comfortably
# above its jitter (20 steps left the aggregation signal at ~10 ms, close
# enough to the noise that a sweep could clamp to 0)
SCAN_K = 50


def emit(metric: str, value: float, unit: str, vs_baseline: float,
         live: bool, **extra) -> None:
    backend = jax.default_backend()
    rec = {
        "metric": metric,
        "value": round(value, 4),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 2),
        "backend": backend,
        # the backend that EXECUTED the measurement is the truth; the
        # probe's verdict can disagree (a loaded host can time the probe
        # subprocess out while the in-process backend is live TPU, which
        # once produced tpu-backend lines labeled fallback=true)
        "fallback": backend == "cpu",
        "probe_live": live,
        "device_kind": device_kind(),
    }
    rec.update(extra)
    print(json.dumps(rec), flush=True)


# ---------------------------------------------------------------------------
# Pallas-under-Mosaic smoke (VERDICT r1 item 2)
# ---------------------------------------------------------------------------

def pallas_mosaic_smoke() -> str:
    """Compile + run the Pallas kernel families on the current backend.
    On TPU this is a real Mosaic lowering (interpret=False via
    ops._common.interpret); returns a status string for the JSON line."""
    if jax.default_backend() != "tpu":
        return "skipped (backend is not tpu; kernels would run interpreted)"
    try:
        from pytorch_ps_mpi_tpu.ops.quant_pallas import (
            dequantize_int8,
            quantize_int8,
        )
        from pytorch_ps_mpi_tpu.ops.sign_pallas import pack_signs, unpack_signs

        n = 1 << 20
        x = jax.random.normal(jax.random.key(0), (n,), jnp.float32)
        packed = pack_signs(x)
        signs = unpack_signs(packed)
        jax.block_until_ready(signs)
        if not bool(jnp.all((signs >= 0) == (x >= 0))):
            return "fail: sign round-trip mismatch"
        q, scale = quantize_int8(x)
        deq = dequantize_int8(q, scale)
        err = float(jnp.max(jnp.abs(deq - x)))
        if err > float(scale) * 0.51:
            return f"fail: int8 round-trip err {err}"
        # flash attention: Mosaic lowering + numerics vs the dense oracle,
        # in bf16 — the configuration the models actually run. Tolerance
        # is bf16-scale: BOTH programs' matmuls ride the MXU at its native
        # width, so they agree only to bf16 rounding (strict f32
        # equivalence is covered by the CPU interpret-mode tests). >= 2
        # heads so the flattened batch*heads dim exercises the real tile
        # rule (bh == 1 made every block spec trivially legal and let a
        # lowering regression through this very smoke once).
        from pytorch_ps_mpi_tpu.ops.attention_pallas import (
            _attention_jnp,
            flash_attention,
        )

        qa = jax.random.normal(jax.random.key(1), (1, 128, 2, 64),
                               jnp.bfloat16)
        fo = flash_attention(qa, qa, qa, causal=True)
        ro, _ = _attention_jnp(qa, qa, qa, 0, 0, True, 64 ** -0.5)
        ferr = float(jnp.max(jnp.abs(fo.astype(jnp.float32)
                                     - ro.astype(jnp.float32))))
        if ferr > 2e-2:
            return f"fail: flash-attention err {ferr}"
        return "ok (mosaic-compiled: quant, sign, flash-attention)"
    except Exception as e:  # lowering errors are exactly what we're probing
        return f"fail: {type(e).__name__}: {str(e)[:200]}"


# ---------------------------------------------------------------------------
# Line 1: aggregation + update microbench
# ---------------------------------------------------------------------------

def param_structs():
    """Parameter ShapeDtypeStructs via tracing only — no device ops."""
    model = ResNet18(num_classes=10, small_inputs=True)
    return jax.eval_shape(
        lambda k: model.init(k, jnp.ones((1, 32, 32, 3), jnp.float32)),
        jax.random.key(0),
    )


def reference_style_step(np_params, np_bufs, worker_msgs, lr=0.01, momentum=0.9):
    """One aggregation+update step the reference's way: per-param unpickle
    of every worker's message, numpy sum, eager momentum SGD."""
    for i, msgs in enumerate(worker_msgs):
        grads = [pickle.loads(m) for m in msgs]          # ps.py:166, mpi_comms.py:174
        d_p = grads[0].copy()
        for g in grads[1:]:
            d_p += g                                     # ps.py:176 sum(grads)
        buf = np_bufs[i]
        buf *= momentum
        buf += d_p                                       # ps.py:207-208
        np_params[i] -= lr * buf                         # ps.py:214


def run_reference_baseline(shapes):
    rng = np.random.RandomState(0)
    stacked = [rng.randn(WORKERS, *s).astype(np.float32) for s in shapes]
    np_params = [np.zeros(s, np.float32) for s in shapes]
    np_bufs = [np.zeros_like(p) for p in np_params]
    times = []
    for _ in range(max(3, REPS // 4)):
        t0 = time.perf_counter()
        # encode/serialize side (overlapped with backprop in the reference,
        # but still CPU work it must do): pickle each worker's each tensor
        worker_msgs = [
            [pickle.dumps(s[w]) for w in range(WORKERS)] for s in stacked
        ]
        reference_style_step(np_params, np_bufs, worker_msgs)
        times.append(time.perf_counter() - t0)
    return min(times)


def run_ours(structs):
    code = IdentityCodec()
    h = SGDHyper(lr=0.01, momentum=0.9)
    leaves, treedef = jax.tree.flatten(structs)

    @jax.jit
    def materialize(key):
        keys = jax.random.split(key, len(leaves))
        grads_stacked = jax.tree.unflatten(
            treedef,
            [
                jax.random.normal(k, (WORKERS,) + s.shape, jnp.float32)
                for k, s in zip(keys, leaves)
            ],
        )
        params = jax.tree.unflatten(
            treedef, [jnp.zeros(s.shape, jnp.float32) for s in leaves]
        )
        return params, init_sgd_state(params), grads_stacked

    @jax.jit
    def step(params, state, grads_stacked):
        summed = jax.tree.map(
            lambda g, p: code.decode_sum(g, p.shape, p.dtype), grads_stacked, params
        )
        return sgd_update(params, summed, state, h)

    params, state, grads_stacked = materialize(jax.random.key(0))

    # K dependent aggregation+update steps fused in one lax.scan program:
    # with the per-fetch tunnel RTT subtracted, wall/K is what the device
    # itself spends per step (see utils/devtime.py for the validation).
    k = SCAN_K

    @jax.jit
    def step_scanned(params, state, grads_stacked):
        def body(carry, _):
            p, s = carry
            # derive the step's gradients from the carry (numerically
            # negligible): loop-invariant grads would let XLA hoist the
            # whole 8-way aggregation out of the scan, leaving only the
            # update inside — measured 0.16 ms/step vs the honest 0.49
            g_dep = jax.tree.map(
                lambda g, pp: g + pp[None] * jnp.asarray(1e-30, pp.dtype),
                grads_stacked, p,
            )
            summed = jax.tree.map(
                lambda g, pp: code.decode_sum(g, pp.shape, pp.dtype),
                g_dep, p,
            )
            return sgd_update(p, summed, s, h), None

        (p, s), _ = jax.lax.scan(body, (params, state), None, length=k)
        return p, s

    # timed() compiles/warms both and skips the scan pass on low-RTT
    return timed(
        lambda: step(params, state, grads_stacked),
        lambda: step_scanned(params, state, grads_stacked),
        k, reps=REPS,
    )


# ---------------------------------------------------------------------------
# Line 2: end-to-end ResNet-18 train step, steps/sec + MFU
# ---------------------------------------------------------------------------

def make_train_step(dtype=jnp.float32):
    # f32 params either way; dtype is the conv/matmul compute precision
    # (bf16 is the MXU's native width — the TPU-first configuration)
    model = ResNet18(num_classes=10, small_inputs=True, dtype=dtype)
    h = SGDHyper(lr=0.01, momentum=0.9)

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    def train_step(params, state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, new_state = sgd_update(params, grads, state, h)
        return new_params, new_state, loss

    return model, train_step


def run_train_bench(dtype=jnp.float32, cpu_anchor=True):
    """Returns (wall_s_per_call, device_s_per_step, flops_per_step,
    cpu_step_seconds_or_None) — wall includes the tunnel fetch RTT,
    device is the scan-amortized RTT-subtracted time."""
    model, train_step = make_train_step(dtype)
    x = jax.random.normal(jax.random.key(1), (TRAIN_BATCH, 32, 32, 3))
    y = jax.random.randint(jax.random.key(2), (TRAIN_BATCH,), 0, 10)
    params = jax.jit(model.init)(jax.random.key(0), x[:1])
    state = init_sgd_state(params)

    fn = jax.jit(train_step)
    flops = 0.0
    try:
        cost = fn.lower(params, state, (x, y)).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
    except Exception:
        pass

    # RTT-corrected timing (utils/devtime.py): per-call wall incl. the
    # tunnel fetch, plus SCAN_K fused steps for true device time per step
    @jax.jit
    def train_scanned(params, state, batch):
        def body(carry, _):
            p, s = carry
            p2, s2, loss = train_step(p, s, batch)
            return (p2, s2), loss

        (p, s), losses = jax.lax.scan(
            body, (params, state), None, length=SCAN_K
        )
        return p, s, losses

    step_s, scan_step_s = timed(
        lambda: fn(params, state, (x, y)),
        lambda: train_scanned(params, state, (x, y)),
        SCAN_K, reps=REPS,
    )

    # CPU anchor: identical program on the host backend (skip if we're
    # already ON the host backend — then vs_baseline is 1.0 by definition)
    cpu_s = None
    if cpu_anchor and jax.default_backend() != "cpu":
        try:
            cpu = jax.devices("cpu")[0]
            xc, yc = jax.device_put((x, y), cpu)
            pc = jax.device_put(params, cpu)
            sc = jax.device_put(state, cpu)
            cfn = jax.jit(train_step)
            pc2, sc2, _ = cfn(pc, sc, (xc, yc))
            jax.block_until_ready(pc2)
            ctimes = []
            for _ in range(3):
                t0 = time.perf_counter()
                pc2, sc2, _ = cfn(pc2, sc2, (xc, yc))
                jax.block_until_ready(pc2)
                ctimes.append(time.perf_counter() - t0)
            cpu_s = min(ctimes)
        except Exception:
            cpu_s = None
    return step_s, scan_step_s, flops, cpu_s


def _telemetry_dir():
    """``BENCH_TELEMETRY_DIR=dir python bench.py`` arms the run-wide
    FlightRecorder (bench takes no CLI args by design — the env var is
    the flag): each bench phase records a span, and the run drops
    ``bench.jsonl`` + a per-phase ``report.txt`` in the directory."""
    import os

    tdir = os.environ.get("BENCH_TELEMETRY_DIR")
    if not tdir:
        return None
    os.makedirs(tdir, exist_ok=True)
    from pytorch_ps_mpi_tpu import telemetry

    telemetry.configure(worker="bench")
    return tdir


def _telemetry_flush(tdir):
    if not tdir:
        return
    import os

    from pytorch_ps_mpi_tpu import telemetry
    from tools.telemetry_report import format_table, summarize

    rec = telemetry.get_recorder()
    path = rec.dump_jsonl(os.path.join(tdir, "bench.jsonl"))
    report = format_table(summarize([path]))
    with open(os.path.join(tdir, "report.txt"), "w") as f:
        f.write(report + "\n")
    print(f"telemetry: {path} + report.txt", flush=True)


def main():
    global REPS, SCAN_K
    tdir = _telemetry_dir()
    from pytorch_ps_mpi_tpu.telemetry import span

    live = ensure_live_backend()
    replay_lines = []
    if jax.default_backend() == "cpu":
        REPS = 5   # keep the fallback path's wall time bounded
        SCAN_K = 5  # no ~68 ms RTT to amortize on the host backend
        # Replay the committed TPU truth FIRST as well as last: the CPU
        # fallback takes tens of minutes, and if the driver's window ever
        # truncates this run mid-way, the round record must already hold
        # the measured TPU numbers (the summary is re-emitted at the end
        # so a complete run's last line still parses to TPU truth).
        import os

        from pytorch_ps_mpi_tpu.utils.provenance import fallback_record_lines

        replay_lines = fallback_record_lines(
            os.path.dirname(os.path.abspath(__file__))
        )
        for rec in replay_lines:
            print(json.dumps(rec), flush=True)
    with span("bench.pallas_smoke"):
        smoke = pallas_mosaic_smoke()

    structs = param_structs()
    shapes = [s.shape for s in jax.tree.leaves(structs)]
    n_params = sum(int(np.prod(s)) for s in shapes)

    with span("bench.reference_baseline"):
        ref_s = run_reference_baseline(shapes)
    with span("bench.aggregation_update"):
        ours_wall_s, ours_dev_s = run_ours(structs)
    from pytorch_ps_mpi_tpu.utils.devtime import scan_pass_runs

    if scan_pass_runs():
        method = (
            f"value = device time per step from a fused {SCAN_K}-step scan "
            "(carry-dependent grads, so aggregation cannot be hoisted) with "
            "the tunnel fetch RTT subtracted (utils/devtime.py); "
            "wall_ms_per_call is one step incl. that RTT"
        )
    else:  # the scan pass never ran — do not claim it did
        method = (
            "value = min single-call wall time (fetch RTT < 1 ms on this "
            "backend, so call wall IS device time and the scan pass is "
            "skipped — utils/devtime.py)"
        )
    emit(
        f"resnet18_{n_params//10**6}M_grad_aggregation_sgd_update_ms",
        ours_dev_s * 1e3,
        "ms",
        safe_ratio(ref_s, ours_dev_s),
        live,
        pallas_mosaic=smoke,
        wall_ms_per_call=round(ours_wall_s * 1e3, 2),
        rtt_probe_ms=round(rtt_floor() * 1e3, 2),
        rtt_subtracted_ms=rtt_subtracted_ms(),
        baseline="reference-style numpy/pickle pipeline on this host CPU. "
        + method,
    )

    with span("bench.train_step_f32"):
        step_wall_s, step_dev_s, flops, cpu_s = run_train_bench()
    peak = peak_flops_for(device_kind())
    mfu = safe_ratio(flops, step_dev_s * peak) if peak > 0 else 0.0
    if jax.default_backend() == "cpu":
        vs, note = 1.0, "this IS the host CPU backend (ratio 1.0 by definition)"
    elif cpu_s is not None:
        vs, note = (
            safe_ratio(cpu_s, step_dev_s),
            "same XLA program on host CPU backend",
        )
    else:
        # never fabricate a measured-looking ratio from a failed anchor
        vs, note = 0.0, "cpu anchor failed; vs_baseline not measured"
    emit(
        f"resnet18_train_step_b{TRAIN_BATCH}_steps_per_sec",
        safe_ratio(1.0, step_dev_s),
        "steps/sec",
        vs,
        live,
        step_ms_device=round(step_dev_s * 1e3, 3),
        wall_ms_per_call=round(step_wall_s * 1e3, 3),
        flops_per_step=flops,
        mfu=round(mfu, 4),
        baseline=note,
    )

    # Line 3 (accelerator only): the TPU-first configuration — bf16
    # compute (f32 params), the MXU's native precision
    if jax.default_backend() != "cpu":
        with span("bench.train_step_bf16"):
            bw, bd, bflops, _ = run_train_bench(jnp.bfloat16,
                                                cpu_anchor=False)
        bmfu = safe_ratio(bflops, bd * peak) if peak > 0 else 0.0
        emit(
            f"resnet18_train_step_b{TRAIN_BATCH}_bf16_steps_per_sec",
            safe_ratio(1.0, bd),
            "steps/sec",
            safe_ratio(step_dev_s, bd),
            live,
            step_ms_device=round(bd * 1e3, 3),
            wall_ms_per_call=round(bw * 1e3, 3),
            flops_per_step=bflops,
            mfu=round(bmfu, 4),
            baseline="same model with f32 compute (line 2) on this device",
        )

        # Line 4 (accelerator only): BASELINE config #5 — BERT-base MLM
        # (132M params, Adam), bf16 compute: the large-flat-gradient
        # stress configuration, and this framework's best MFU. Skipped on
        # the CPU fallback (a 132M fwd+bwd on one host core would take
        # minutes per rep for no information). Guarded: a BERT-path
        # failure (e.g. an attention-kernel lowering regression) must
        # not cost the ResNet lines already emitted.
        try:
            with span("bench.bert_mlm"):
                bert_line(live)
        except Exception as e:
            # same naming scheme as the success record (param count
            # unknown here) so metric-joins see an errored row, not a
            # silently missing series
            emit(f"bert_base_mlm_train_step_b{BERT_BATCH}_s{BERT_SEQ}"
                 "_bf16_steps_per_sec",
                 0.0, "steps/sec", 0.0, live,
                 error=f"{type(e).__name__}: {str(e)[:300]}")
    else:
        # CPU fallback: re-emit the replay summary LAST so a complete
        # run's last-line parse lands on the measured TPU truth (the
        # full replay block already printed first — see main()'s head).
        # Re-read rather than re-print the head snapshot: the CPU run
        # takes tens of minutes, during which the watcher may have
        # appended a FRESH TPU sweep (and age_hours must reflect now).
        import os

        from pytorch_ps_mpi_tpu.utils.provenance import fallback_record_lines

        tail = fallback_record_lines(os.path.dirname(os.path.abspath(__file__)))
        if tail:
            print(json.dumps(tail[-1]), flush=True)
    _telemetry_flush(tdir)


BERT_BATCH, BERT_SEQ = 16, 128


def bert_line(live: bool, batch: int = BERT_BATCH, seq: int = BERT_SEQ,
              scan_k: int = 8) -> None:
    from pytorch_ps_mpi_tpu.models import BertConfig, BertMLM
    from pytorch_ps_mpi_tpu.models.bert import mlm_loss
    from pytorch_ps_mpi_tpu.optim import AdamHyper, adam_update, init_adam_state

    cfg = BertConfig(dtype=jnp.bfloat16, max_position=max(512, seq))
    model = BertMLM(cfg)
    h = AdamHyper(lr=1e-4)

    def loss_fn(params, b):
        tokens, targets, mask = b
        return mlm_loss(model.apply(params, tokens), targets, mask)

    def train_step(params, state, b):
        loss, grads = jax.value_and_grad(loss_fn)(params, b)
        p2, s2 = adam_update(params, grads, state, h)
        return p2, s2, loss

    key = jax.random.key(1)
    b = (
        jax.random.randint(key, (batch, seq), 0, cfg.vocab_size),
        jax.random.randint(jax.random.fold_in(key, 1), (batch, seq), 0,
                           cfg.vocab_size),
        jax.random.bernoulli(jax.random.fold_in(key, 2), 0.15, (batch, seq)),
    )
    params = jax.jit(model.init)(jax.random.key(0), b[0][:1])
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    state = init_adam_state(params)
    fn = jax.jit(train_step)
    flops = 0.0
    try:
        cost = fn.lower(params, state, b).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
    except Exception:
        pass

    @jax.jit
    def scanned(params, state, b):
        def body(c, _):
            p, s, _ = train_step(c[0], c[1], b)
            return (p, s), None

        (p, s), _ = jax.lax.scan(body, (params, state), None, length=scan_k)
        return p, s

    wall_s, dev_s = timed(
        lambda: fn(params, state, b),
        lambda: scanned(params, state, b),
        scan_k, reps=5,
    )
    peak = peak_flops_for(device_kind())
    emit(
        f"bert_base_{n_params//10**6}M_mlm_train_step_b{batch}_s{seq}"
        "_bf16_steps_per_sec",
        safe_ratio(1.0, dev_s),
        "steps/sec",
        round(safe_ratio(flops, dev_s * peak), 4) if peak else 0.0,
        live,
        step_ms_device=round(dev_s * 1e3, 3),
        wall_ms_per_call=round(wall_s * 1e3, 3),
        flops_per_step=flops,
        mfu=round(safe_ratio(flops, dev_s * peak), 4) if peak else 0.0,
        baseline="vs_baseline = MFU vs the chip's published bf16 peak "
                 "(BASELINE config #5, the large-flat-gradient stress "
                 "model; full codec wire table in benchmarks/bert_bench.py)",
    )


if __name__ == "__main__":
    main()
