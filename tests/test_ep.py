"""Expert parallelism (parallel/ep.py): GShard-style top-1 MoE with
all_to_all dispatch must equal the dense per-token oracle, drop tokens
past capacity, differentiate cleanly, and compose with data parallelism.

Tokens are sharded over the expert axis (each device contributes its own
slice — the realistic layout) and shard_maps are vma-checked so the
collective transposes are exact (see parallel/pp.py's module note on
check_vma=False inflating psum transposes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_ps_mpi_tpu.parallel.ep import (
    init_moe,
    moe_apply,
    moe_dense_oracle,
    moe_spec,
)

D_MODEL, F = 8, 16
E = 8  # global experts


@pytest.fixture(scope="module")
def exp4():
    return Mesh(np.array(jax.devices()[:4]), ("expert",))


def _build(key=0, n_tokens=32):
    params = init_moe(jax.random.key(key), D_MODEL, F, E)
    x = jax.random.normal(jax.random.key(key + 1), (n_tokens, D_MODEL))
    return params, x


def test_moe_matches_dense_oracle(exp4):
    params, x = _build()
    spec = moe_spec(params, "expert")
    fwd = jax.jit(
        jax.shard_map(
            lambda p, x: moe_apply(x, p, "expert", capacity=32),
            mesh=exp4, in_specs=(spec, P("expert")), out_specs=P("expert"),
        )
    )
    out = fwd(params, x)
    ref = moe_dense_oracle(x, params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_moe_capacity_drops_overflow_tokens(exp4):
    """With capacity 1, at most one token per expert per SOURCE DEVICE
    gets computed; the rest come back exactly zero (GShard drop
    semantics), and served tokens still match the oracle."""
    params, x = _build(key=7)
    spec = moe_spec(params, "expert")
    fwd = jax.jit(
        jax.shard_map(
            lambda p, x: moe_apply(x, p, "expert", capacity=1),
            mesh=exp4, in_specs=(spec, P("expert")), out_specs=P("expert"),
        )
    )
    out = np.asarray(fwd(params, x))
    ref = np.asarray(moe_dense_oracle(x, params))

    from pytorch_ps_mpi_tpu.parallel.ep import _route_top1

    eidx = np.asarray(_route_top1(x, params["wr"])[0])
    n_loc = len(eidx) // 4
    dropped = 0
    for dev in range(4):
        seen = set()
        for t in range(dev * n_loc, (dev + 1) * n_loc):
            if eidx[t] not in seen:
                seen.add(eidx[t])
                np.testing.assert_allclose(out[t], ref[t],
                                           rtol=1e-5, atol=1e-6)
            else:
                np.testing.assert_array_equal(out[t], np.zeros(D_MODEL))
                dropped += 1
    assert dropped > 0  # the test actually exercised drops


def test_moe_grads_match_dense_oracle(exp4):
    """d(loss)/d(expert weights) through dispatch + all_to_all + combine
    equals the dense oracle's gradients (expert grads arrive sharded,
    router grads replicated)."""
    params, x = _build(key=3)
    n = x.shape[0]
    tgt = jax.random.normal(jax.random.key(9), x.shape)
    spec = moe_spec(params, "expert")

    def loss_pp(p, x_loc, tgt_loc):
        out = moe_apply(x_loc, p, "expert", capacity=32)
        return lax.psum(jnp.sum((out - tgt_loc) ** 2), "expert") / (
            n * D_MODEL
        )

    g_pp = jax.jit(
        jax.shard_map(
            lambda p, x, t: jax.grad(loss_pp)(p, x, t),
            mesh=exp4, in_specs=(spec, P("expert"), P("expert")),
            out_specs={"wr": P(), "w1": P("expert"), "w2": P("expert")},
        )
    )(params, x, tgt)

    g_ref = jax.grad(
        lambda p: jnp.mean((moe_dense_oracle(x, p) - tgt) ** 2)
    )(params)
    for k in ("w1", "w2", "wr"):
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_ref[k]),
                                   rtol=2e-4, atol=1e-7, err_msg=k)


def test_moe_composes_with_data_parallel():
    """DP x EP on a 2x4 mesh, the GShard layout: tokens sharded over
    BOTH axes jointly (every device contributes its own 4-token slice),
    experts over 'expert'; every token's output equals the oracle."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    params, x = _build(key=5, n_tokens=32)  # 4 tokens per device
    spec = moe_spec(params, "expert")
    fwd = jax.jit(
        jax.shard_map(
            lambda p, x: moe_apply(x, p, "expert", capacity=32),
            mesh=mesh, in_specs=(spec, P(("data", "expert"))),
            out_specs=P(("data", "expert")),
        )
    )
    out = fwd(params, x)
    ref = moe_dense_oracle(x, params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_moe_top2_matches_dense_oracle(exp4):
    """GShard top-2 gating (renormalized pair of gates, each choice its
    own dispatch pass) == the dense top-2 oracle, forward AND gradients."""
    params, x = _build(key=9)
    spec = moe_spec(params, "expert")

    def spmd(p, xs):
        return moe_apply(xs, p, "expert", capacity=32, top_k=2)

    fwd = jax.jit(
        jax.shard_map(
            spmd, mesh=exp4, in_specs=(spec, P("expert")),
            out_specs=P("expert"),
        )
    )
    out = fwd(params, x)
    ref = moe_dense_oracle(x, params, top_k=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # top-2 is NOT top-1: the second expert contributes
    ref1 = moe_dense_oracle(x, params, top_k=1)
    assert float(jnp.max(jnp.abs(ref - ref1))) > 1e-4

    # gradients through the distributed top-2 path == dense
    tgt = jax.random.normal(jax.random.key(3), x.shape)

    def dist_loss(p):
        def body(p, xs, ts):
            o = moe_apply(xs, p, "expert", capacity=32, top_k=2)
            return lax.psum(jnp.sum((o - ts) ** 2), "expert")

        return jax.shard_map(
            body, mesh=exp4,
            in_specs=(spec, P("expert"), P("expert")), out_specs=P(),
        )(p, x, tgt)

    def dense_loss(p):
        return jnp.sum((moe_dense_oracle(x, p, top_k=2) - tgt) ** 2)

    g_dist = jax.grad(dist_loss)(params)
    g_dense = jax.grad(dense_loss)(params)
    for a, b in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_dense)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_moe_top2_gates_renormalized():
    """The two chosen gates sum to 1 per token (GShard convention)."""
    from pytorch_ps_mpi_tpu.parallel.ep import _route_topk

    params, x = _build(key=11)
    _, gates = _route_topk(x, params["wr"], 2)
    np.testing.assert_allclose(np.asarray(gates.sum(axis=-1)),
                               np.ones(x.shape[0]), rtol=1e-5)


def test_load_balance_loss_properties(exp4):
    """Switch aux loss: exactly 1.0 at a perfectly uniform assignment,
    > 1 when the router collapses, matches the E*sum(f*P) formula, and
    the expert_axis form psums to the GLOBAL balance."""
    from pytorch_ps_mpi_tpu.parallel.ep import load_balance_loss

    n, d = 64, D_MODEL
    x = jax.random.normal(jax.random.key(13), (n, d))

    # collapsed router: one dominant column -> loss far above 1. The
    # lower bound is DERIVED for this mesh/construction, not hard-coded
    # (the old absolute 2.0 sat above the measured 1.95 on the 8-way
    # virtual mesh): column 0 scores 5*sum(x_row) =: z, every other
    # expert 0, so tokens with z >= 3 argmax to expert 0 with
    # P_0 >= e^3/(e^3+E-1), tokens with z <= -3 tie-break to expert 1
    # with P_1 >= (1 - e^-3/(e^-3+E-1))/(E-1), and
    # loss = E*sum_e f_e*P̄_e >= E*(q_hi^2*p_hi + q_lo^2*p1_lo) with the
    # q's the (deterministic, seeded) margin-band fractions. The bound
    # must itself clear the uniform router's 1.0 by a margin, or it
    # would not detect collapse.
    wr_collapsed = jnp.zeros((d, E)).at[:, 0].set(5.0)
    l_col = float(load_balance_loss(x, wr_collapsed))
    z = 5.0 * np.asarray(x.sum(axis=1))
    q_hi = float((z >= 3.0).mean())
    q_lo = float((z <= -3.0).mean())
    p_hi = np.e**3 / (np.e**3 + (E - 1))
    p1_lo = (1.0 - np.e**-3 / (np.e**-3 + (E - 1))) / (E - 1)
    bound = E * (q_hi * q_hi * p_hi + q_lo * q_lo * p1_lo)
    assert bound > 1.2, bound  # the derived bound detects collapse
    assert l_col > bound, (l_col, bound)

    # random router: near-uniform-ish, strictly less than collapsed
    wr = 0.02 * jax.random.normal(jax.random.key(14), (d, E))
    l_rand = float(load_balance_loss(x, wr))
    assert 0.9 < l_rand < l_col

    # formula check against a hand computation (top-1)
    probs = jax.nn.softmax(x @ wr, axis=-1)
    eidx = jnp.argmax(probs, axis=-1)
    f = np.bincount(np.asarray(eidx), minlength=E) / n
    want = E * float((f * np.asarray(probs.mean(axis=0))).sum())
    np.testing.assert_allclose(l_rand, want, rtol=1e-5)

    # distributed form == computing on the concatenated global tokens
    l_global = float(load_balance_loss(x, wr))
    l_dist = float(jax.jit(
        jax.shard_map(
            lambda xs: load_balance_loss(xs, wr, expert_axis="expert")[None],
            mesh=exp4, in_specs=P("expert"), out_specs=P("expert"),
        )
    )(x)[0])
    np.testing.assert_allclose(l_dist, l_global, rtol=1e-5)


def test_switch_aux_loss_sown_and_trainable():
    """cfg.aux_loss_weight sows the weighted balance loss per MoE layer
    (one value each, differentiable w.r.t. the router), and descending
    the aux loss alone genuinely improves balance — the sign check a
    nonzero-gradient assert cannot give."""
    from pytorch_ps_mpi_tpu.models.moe import SwitchConfig, SwitchMLM

    cfg = SwitchConfig(vocab_size=211, hidden_size=32, num_layers=2,
                       num_heads=4, intermediate_size=48, max_position=32,
                       n_experts=8, capacity=256, aux_loss_weight=0.01)
    tokens = jax.random.randint(jax.random.key(0), (2, 16), 0, 211)
    model = SwitchMLM(cfg)
    # init sows too: keep only the params collection (the documented
    # usage — apply with mutable=["aux_loss"] collects fresh values)
    params = {"params": model.init(jax.random.key(1), tokens)["params"]}

    logits, aux = model.apply(params, tokens, mutable=["aux_loss"])
    sown = jax.tree.leaves(aux["aux_loss"])
    assert len(sown) == cfg.num_layers  # one per MoE layer
    total_aux = sum(jnp.sum(v) for v in sown)
    assert float(total_aux) > 0.0
    # the sown values already carry the weight: each ~ 0.01 * O(1)
    assert float(total_aux) < 1.0

    # and it is differentiable: grads w.r.t. the router are nonzero
    def loss(p):
        _, a = model.apply(p, tokens, mutable=["aux_loss"])
        return sum(jnp.sum(v) for v in jax.tree.leaves(a["aux_loss"]))

    g = jax.grad(loss)(params)
    wr_grads = [np.asarray(v) for path, v in
                jax.tree_util.tree_flatten_with_path(g)[0]
                if any(getattr(p, "key", "") == "wr" for p in path)]
    assert wr_grads and any(np.abs(w).max() > 0 for w in wr_grads)


def test_load_balance_loss_descent_improves_balance():
    """Gradient descent on the aux loss ALONE reduces it from a
    collapsed router — the sign/semantics check (a wrong-signed psum or
    negated loss would pass a nonzero-grad assert but fail this)."""
    from pytorch_ps_mpi_tpu.parallel.ep import load_balance_loss

    n, d = 64, D_MODEL
    x = jax.random.normal(jax.random.key(21), (n, d))
    wr = jnp.zeros((d, E)).at[:, 0].set(2.0)  # collapsed start

    loss = jax.jit(lambda w: load_balance_loss(x, w, top_k=2))
    grad = jax.jit(jax.grad(lambda w: load_balance_loss(x, w, top_k=2)))
    l0 = float(loss(wr))
    for _ in range(50):
        wr = wr - 0.5 * grad(wr)
    l1 = float(loss(wr))
    assert l1 < l0, (l0, l1)
    assert l1 < 1.5  # approaching the uniform optimum of 1.0
