"""Expert parallelism (parallel/ep.py): GShard-style top-1 MoE with
all_to_all dispatch must equal the dense per-token oracle, drop tokens
past capacity, differentiate cleanly, and compose with data parallelism.

Tokens are sharded over the expert axis (each device contributes its own
slice — the realistic layout) and shard_maps are vma-checked so the
collective transposes are exact (see parallel/pp.py's module note on
check_vma=False inflating psum transposes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_ps_mpi_tpu.parallel.ep import (
    init_moe,
    moe_apply,
    moe_dense_oracle,
    moe_spec,
)

D_MODEL, F = 8, 16
E = 8  # global experts


@pytest.fixture(scope="module")
def exp4():
    return Mesh(np.array(jax.devices()[:4]), ("expert",))


def _build(key=0, n_tokens=32):
    params = init_moe(jax.random.key(key), D_MODEL, F, E)
    x = jax.random.normal(jax.random.key(key + 1), (n_tokens, D_MODEL))
    return params, x


def test_moe_matches_dense_oracle(exp4):
    params, x = _build()
    spec = moe_spec(params, "expert")
    fwd = jax.jit(
        jax.shard_map(
            lambda p, x: moe_apply(x, p, "expert", capacity=32),
            mesh=exp4, in_specs=(spec, P("expert")), out_specs=P("expert"),
        )
    )
    out = fwd(params, x)
    ref = moe_dense_oracle(x, params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_moe_capacity_drops_overflow_tokens(exp4):
    """With capacity 1, at most one token per expert per SOURCE DEVICE
    gets computed; the rest come back exactly zero (GShard drop
    semantics), and served tokens still match the oracle."""
    params, x = _build(key=7)
    spec = moe_spec(params, "expert")
    fwd = jax.jit(
        jax.shard_map(
            lambda p, x: moe_apply(x, p, "expert", capacity=1),
            mesh=exp4, in_specs=(spec, P("expert")), out_specs=P("expert"),
        )
    )
    out = np.asarray(fwd(params, x))
    ref = np.asarray(moe_dense_oracle(x, params))

    from pytorch_ps_mpi_tpu.parallel.ep import _route_top1

    eidx = np.asarray(_route_top1(x, params["wr"])[0])
    n_loc = len(eidx) // 4
    dropped = 0
    for dev in range(4):
        seen = set()
        for t in range(dev * n_loc, (dev + 1) * n_loc):
            if eidx[t] not in seen:
                seen.add(eidx[t])
                np.testing.assert_allclose(out[t], ref[t],
                                           rtol=1e-5, atol=1e-6)
            else:
                np.testing.assert_array_equal(out[t], np.zeros(D_MODEL))
                dropped += 1
    assert dropped > 0  # the test actually exercised drops


def test_moe_grads_match_dense_oracle(exp4):
    """d(loss)/d(expert weights) through dispatch + all_to_all + combine
    equals the dense oracle's gradients (expert grads arrive sharded,
    router grads replicated)."""
    params, x = _build(key=3)
    n = x.shape[0]
    tgt = jax.random.normal(jax.random.key(9), x.shape)
    spec = moe_spec(params, "expert")

    def loss_pp(p, x_loc, tgt_loc):
        out = moe_apply(x_loc, p, "expert", capacity=32)
        return lax.psum(jnp.sum((out - tgt_loc) ** 2), "expert") / (
            n * D_MODEL
        )

    g_pp = jax.jit(
        jax.shard_map(
            lambda p, x, t: jax.grad(loss_pp)(p, x, t),
            mesh=exp4, in_specs=(spec, P("expert"), P("expert")),
            out_specs={"wr": P(), "w1": P("expert"), "w2": P("expert")},
        )
    )(params, x, tgt)

    g_ref = jax.grad(
        lambda p: jnp.mean((moe_dense_oracle(x, p) - tgt) ** 2)
    )(params)
    for k in ("w1", "w2", "wr"):
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_ref[k]),
                                   rtol=2e-4, atol=1e-7, err_msg=k)


def test_moe_composes_with_data_parallel():
    """DP x EP on a 2x4 mesh, the GShard layout: tokens sharded over
    BOTH axes jointly (every device contributes its own 4-token slice),
    experts over 'expert'; every token's output equals the oracle."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    params, x = _build(key=5, n_tokens=32)  # 4 tokens per device
    spec = moe_spec(params, "expert")
    fwd = jax.jit(
        jax.shard_map(
            lambda p, x: moe_apply(x, p, "expert", capacity=32),
            mesh=mesh, in_specs=(spec, P(("data", "expert"))),
            out_specs=P(("data", "expert")),
        )
    )
    out = fwd(params, x)
    ref = moe_dense_oracle(x, params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_moe_top2_matches_dense_oracle(exp4):
    """GShard top-2 gating (renormalized pair of gates, each choice its
    own dispatch pass) == the dense top-2 oracle, forward AND gradients."""
    params, x = _build(key=9)
    spec = moe_spec(params, "expert")

    def spmd(p, xs):
        return moe_apply(xs, p, "expert", capacity=32, top_k=2)

    fwd = jax.jit(
        jax.shard_map(
            spmd, mesh=exp4, in_specs=(spec, P("expert")),
            out_specs=P("expert"),
        )
    )
    out = fwd(params, x)
    ref = moe_dense_oracle(x, params, top_k=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # top-2 is NOT top-1: the second expert contributes
    ref1 = moe_dense_oracle(x, params, top_k=1)
    assert float(jnp.max(jnp.abs(ref - ref1))) > 1e-4

    # gradients through the distributed top-2 path == dense
    tgt = jax.random.normal(jax.random.key(3), x.shape)

    def dist_loss(p):
        def body(p, xs, ts):
            o = moe_apply(xs, p, "expert", capacity=32, top_k=2)
            return lax.psum(jnp.sum((o - ts) ** 2), "expert")

        return jax.shard_map(
            body, mesh=exp4,
            in_specs=(spec, P("expert"), P("expert")), out_specs=P(),
        )(p, x, tgt)

    def dense_loss(p):
        return jnp.sum((moe_dense_oracle(x, p, top_k=2) - tgt) ** 2)

    g_dist = jax.grad(dist_loss)(params)
    g_dense = jax.grad(dense_loss)(params)
    for a, b in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_dense)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_moe_top2_gates_renormalized():
    """The two chosen gates sum to 1 per token (GShard convention)."""
    from pytorch_ps_mpi_tpu.parallel.ep import _route_topk

    params, x = _build(key=11)
    _, gates = _route_topk(x, params["wr"], 2)
    np.testing.assert_allclose(np.asarray(gates.sum(axis=-1)),
                               np.ones(x.shape[0]), rtol=1e-5)
