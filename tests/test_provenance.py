"""Round-record provenance recall (VERDICT r3 item 1).

The bench-of-record must carry measured TPU numbers even when the tunnel
is down at the moment the driver runs ``bench.py``. These tests cover the
pure half (`utils/provenance.py`) against both synthetic artifact trees
and the real repo's committed artifacts.
"""

import json
import os
from datetime import datetime

from pytorch_ps_mpi_tpu.utils.provenance import (
    fallback_record_lines,
    load_tpu_records,
    newest_per_metric,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(path, lines):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")


def _mk_repo(tmp_path):
    root = str(tmp_path)
    _write(
        os.path.join(root, "benchmarks", "results", "tpu_old.jsonl"),
        [
            {
                "metric": "resnet18_11M_grad_aggregation_sgd_update_ms",
                "value": 1.5,
                "unit": "ms",
                "vs_baseline": 400.0,
                "backend": "tpu",
                "captured_by": "tpu_watch sweep 2026-07-29T10:00:00",
            },
            # CPU record must never be recalled as TPU truth
            {
                "metric": "resnet18_train_step_b256_steps_per_sec",
                "value": 0.08,
                "backend": "cpu",
                "mfu": 0.0,
            },
        ],
    )
    # Newer sweep supersedes the old aggregation number; adds an MFU line.
    _write(
        os.path.join(root, "benchmarks", "results", "tpu_new.jsonl"),
        [
            {
                "metric": "resnet18_11M_grad_aggregation_sgd_update_ms",
                "value": 0.779,
                "unit": "ms",
                "vs_baseline": 775.47,
                "backend": "tpu",
                "captured_by": "tpu_watch sweep 2026-07-30T06:02:46",
            },
            {
                "metric": "resnet18_train_step_b256_bf16_steps_per_sec",
                "value": 119.99,
                "unit": "steps/sec",
                "backend": "tpu",
                "mfu": 0.4539,
                "captured_by": "tpu_watch sweep 2026-07-30T06:02:46",
            },
        ],
    )
    # Watcher log with an uncurated, even newer bench stdout inside a
    # stage record — must be unwrapped and win on recency.
    _write(
        os.path.join(root, "BENCH_TPU_WATCH.jsonl"),
        [
            {"stage": "probe", "status": "down", "ts": "2026-07-30T14:00:00"},
            {
                "stage": "bench",
                "status": "ok",
                "ts": "2026-07-30T18:00:00",
                "stdout": json.dumps(
                    {
                        "metric": "resnet18_train_step_b256_steps_per_sec",
                        "value": 97.0,
                        "unit": "steps/sec",
                        "backend": "tpu",
                        "mfu": 0.37,
                    }
                )
                + "\n",
            },
        ],
    )
    return root


def test_load_filters_to_tpu_and_unwraps_watcher(tmp_path):
    recs = load_tpu_records(_mk_repo(tmp_path))
    assert all(r["backend"] == "tpu" for r in recs)
    metrics = {r["metric"] for r in recs}
    assert "resnet18_train_step_b256_steps_per_sec" in metrics  # from watcher
    # the watcher-wrapped record inherits the stage timestamp
    wrapped = [r for r in recs if r["metric"] == "resnet18_train_step_b256_steps_per_sec"]
    assert any("2026-07-30T18:00:00" in r.get("captured_by", "") for r in wrapped)


def test_newest_per_metric_prefers_latest_sweep(tmp_path):
    newest = newest_per_metric(load_tpu_records(_mk_repo(tmp_path)))
    agg = newest["resnet18_11M_grad_aggregation_sgd_update_ms"]
    assert agg["value"] == 0.779  # 07-30 sweep beats 07-29


def test_fallback_lines_end_with_tpu_summary(tmp_path):
    now = datetime.fromisoformat("2026-07-30T20:00:00")
    lines = fallback_record_lines(_mk_repo(tmp_path), now=now)
    assert lines, "TPU artifacts exist; fallback lines must not be empty"
    summary = lines[-1]
    assert summary["metric"] == "tpu_record_summary"
    assert summary["backend"] == "tpu"
    assert summary["aggregation_ms"] == 0.779
    assert summary["mfu"] == 0.4539
    assert summary["provenance"].startswith("watcher 2026-07-30T")
    # ages measured against the stamped capture times, oldest key line wins
    # age_hours reflects the records FEEDING the headline (agg/best-mfu,
    # both from the 06:02 capture here — ~13.9h old), never an unrelated
    # fresher record; the all-lines bound rides under its own name
    assert summary["age_hours"] >= 13.9
    assert summary["provenance"].startswith("watcher 2026-07-30T06:")
    assert summary["oldest_record_age_hours"] >= summary["age_hours"]
    for rec in lines[:-1]:
        assert rec["provenance"].startswith("watcher")
        assert "age_hours" in rec
        assert rec["record_source"].startswith("committed TPU artifact")
        assert rec["replayed"] is True  # live-vs-recalled rides on this key
    # every line must survive a json round-trip (the driver parses stdout)
    for rec in lines:
        json.loads(json.dumps(rec))


def test_implausible_mfu_records_never_recalled(tmp_path):
    """mfu >= 1 is a measurement bug (pre-RTT-correction watcher stages);
    it must not win the summary's best-MFU slot."""
    root = _mk_repo(tmp_path)
    _write(
        os.path.join(root, "benchmarks", "results", "tpu_buggy.jsonl"),
        [
            {
                "metric": "bert_base_132M_mlm_train_step_b16_s128",
                "value": 347.6,
                "backend": "tpu",
                "mfu": 2.4182,
                "captured_by": "tpu_watch sweep 2026-07-30T19:00:00",
            }
        ],
    )
    summary = fallback_record_lines(root)[-1]
    assert summary["mfu"] < 1.0
    metrics = {r.get("metric") for r in fallback_record_lines(root)}
    assert "bert_base_132M_mlm_train_step_b16_s128" not in metrics


def test_zero_value_records_never_recalled(tmp_path):
    """A 0.0 value on a rate metric is a failed capture (devtime
    zero-clamp — the committed bert bf16 0.0 row, VERDICT r4 weak #5);
    even when it is the NEWEST record for its metric it must not be
    recalled, and must not shadow an older genuine measurement."""
    root = _mk_repo(tmp_path)
    _write(
        os.path.join(root, "benchmarks", "results", "tpu_zero.jsonl"),
        [
            {
                "metric": "bert_base_mlm_train_step_b16_s128_bf16_steps_per_sec",
                "value": 0.0,
                "unit": "steps/sec",
                "backend": "tpu",
                "captured_by": "tpu_watch sweep 2026-07-30T19:30:00",
            },
            # newest-per-metric shadow case: a zero row NEWER than a real one
            {
                "metric": "resnet18_train_step_b256_bf16_steps_per_sec",
                "value": 0.0,
                "unit": "steps/sec",
                "backend": "tpu",
                "captured_by": "tpu_watch sweep 2026-07-30T19:30:00",
            },
        ],
    )
    lines = fallback_record_lines(root)
    by_metric = {r.get("metric"): r for r in lines}
    assert "bert_base_mlm_train_step_b16_s128_bf16_steps_per_sec" not in by_metric
    # the genuine 06:02 bf16 line still wins its metric
    assert by_metric["resnet18_train_step_b256_bf16_steps_per_sec"]["value"] == 119.99


def test_real_repo_zero_bf16_row_is_tagged():
    """The specific committed failed capture must carry an error tag so
    both the error filter and the value<=0 gate exclude it."""
    import pytest

    path = os.path.join(
        REPO, "benchmarks", "results", "tpu_v5e_2026-07-31_sweep.jsonl")
    if not os.path.exists(path):
        pytest.skip("sweep artifact not in this tree")
    rows = [json.loads(l) for l in open(path) if l.strip()]
    zero = [r for r in rows
            if r.get("metric") == "bert_base_mlm_train_step_b16_s128_bf16_steps_per_sec"]
    assert zero and all("error" in r for r in zero)
    metrics = {r.get("metric") for r in fallback_record_lines(REPO)}
    assert "bert_base_mlm_train_step_b16_s128_bf16_steps_per_sec" not in metrics


def test_summary_value_unit_without_aggregation_record(tmp_path):
    """No grad_aggregation survivor -> summary still honors the
    value/unit contract, drawn from the best train-step line; a string
    mfu must neither crash the max() nor win it."""
    root = str(tmp_path / "nogg")
    _write(
        os.path.join(root, "benchmarks", "results", "tpu_only_steps.jsonl"),
        [
            {
                "metric": "resnet18_train_step_b256_bf16_steps_per_sec",
                "value": 119.99,
                "unit": "steps/sec",
                "backend": "tpu",
                "mfu": 0.4539,
                "captured_by": "tpu_watch sweep 2026-07-30T06:02:46",
            },
            {
                "metric": "bert_base_132M_mlm_train_step_b16_s128",
                "value": 65.5,
                "unit": "steps/sec",
                "backend": "tpu",
                "mfu": "0.9999",  # string: must not TypeError in max()
                "captured_by": "tpu_watch sweep 2026-07-30T07:00:00",
            },
        ],
    )
    summary = fallback_record_lines(root)[-1]
    assert summary["metric"] == "tpu_record_summary"
    assert summary["unit"] == "steps/sec"
    assert summary["value"] == 65.5  # string mfu parses to 0.9999, wins
    assert summary["mfu"] == 0.9999
    json.loads(json.dumps(summary))


def test_fallback_lines_empty_when_no_tpu_truth(tmp_path):
    root = str(tmp_path / "bare")
    os.makedirs(os.path.join(root, "benchmarks", "results"), exist_ok=True)
    assert fallback_record_lines(root) == []


def test_real_repo_artifacts_yield_a_summary():
    """The actual committed artifacts must produce a TPU summary line —
    the guarantee BENCH_r04.json relies on. Data-dependent by design
    (it checks the working tree's artifacts, not synthetic ones), so it
    skips rather than fails if the artifacts are ever pruned."""
    import pytest

    lines = fallback_record_lines(REPO)
    if not lines:
        pytest.skip("no committed TPU artifacts in this tree")
    summary = lines[-1]
    assert summary["metric"] == "tpu_record_summary"
    assert summary["replayed"] is True
    assert "value" in summary and "unit" in summary
    assert summary.get("mfu", 0) > 0  # plausibility gate keeps it < 1.0
    assert summary.get("mfu", 1) < 1.0


def test_replayed_lines_never_reingested(tmp_path):
    """Echo-loop guard: a CPU-fallback bench's stdout (replayed TPU
    copies) wrapped into the watcher log must NOT come back as fresh
    records — the wrapper's new timestamp would crown a stale value
    newest."""
    root = _mk_repo(tmp_path)
    stale_copy = {
        "metric": "resnet18_11M_grad_aggregation_sgd_update_ms",
        "value": 1.5,  # the OLD 07-29 number
        "backend": "tpu",
        "replayed": True,
        "provenance": "watcher 2026-07-29T10:00:00",
    }
    _write(
        os.path.join(root, "BENCH_TPU_WATCH.jsonl"),
        [
            {"stage": "bench", "status": "ok",
             "ts": "2026-07-31T09:00:00",  # newest wrapper timestamp
             "stdout": json.dumps(stale_copy) + "\n"},
        ],
    )
    newest = newest_per_metric(load_tpu_records(root))
    agg = newest["resnet18_11M_grad_aggregation_sgd_update_ms"]
    assert agg["value"] == 0.779  # the genuine 07-30 sweep still wins
    assert not agg.get("replayed")
