"""Resilience layer: deterministic fault injection, self-verifying wire
frames, worker auto-reconnect, supervised elastic recovery, degraded
sync-barrier rounds.

The failure scenarios the async stack used to die on, each now (a)
injectable on purpose — seeded fault plans, reproducible event logs —
and (b) survivable: rejected frames are counted instead of crashing the
PS, workers back off and reconnect instead of raising, the supervisor
respawns dead workers and restarts a crashed server from its checkpoint
cadence, and a sync-barrier round completes over the surviving workers
instead of hanging forever (SURVEY §5.3: the reference's MPI default
killed the whole job on any rank failure — this is the opposite end of
that spectrum).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from pytorch_ps_mpi_tpu.parallel import dcn
from pytorch_ps_mpi_tpu.resilience import (
    CRASH_EXIT_CODE,
    FRAME_MAGIC_V1,
    FaultInjector,
    HEADER_BYTES,
    HEADER_BYTES_V1,
    ResilientWorker,
    Supervisor,
    open_frame,
    read_lineage,
    seal_frame,
    wire_fingerprint,
)

pytestmark = pytest.mark.skipif(
    dcn.get_lib() is None, reason="native toolchain unavailable"
)


def _template(n=8):
    return {"w": np.zeros((n,), np.float32)}


# ---------------------------------------------------------------------------
# frames: seal/open, rejection reasons, config fingerprint
# ---------------------------------------------------------------------------

def test_frame_roundtrip_and_rejection_reasons():
    payload = np.arange(6, dtype=np.float32)
    buf = np.empty(HEADER_BYTES + payload.nbytes, np.uint8)
    fp = 0x1234ABCD5678EF90
    frame = seal_frame(buf, payload, fp)
    assert frame.nbytes == HEADER_BYTES + payload.nbytes

    got, err = open_frame(frame, fp, payload.nbytes)
    assert err is None
    np.testing.assert_array_equal(np.frombuffer(got, np.float32), payload)

    # corruption: any flipped payload byte fails the CRC
    bad = frame.copy()
    bad[HEADER_BYTES + 3] ^= 0x01
    assert open_frame(bad, fp, payload.nbytes)[1] == "corrupt"

    # config drift: a different fingerprint is rejected BEFORE the CRC
    assert open_frame(frame, fp ^ 1, payload.nbytes)[1] == "config"

    # truncation: declared length no longer matches the buffer
    assert open_frame(frame[:-4], fp, payload.nbytes)[1] == "size"
    # size mismatch against the wire spec (misconfigured worker)
    assert open_frame(frame, fp, payload.nbytes + 8)[1] == "size"

    # garbage / unframed peer
    bad = frame.copy()
    bad[0] ^= 0xFF
    assert open_frame(bad, fp, payload.nbytes)[1] == "magic"
    assert open_frame(frame[:4], fp, None)[1] == "short"

    # the lineage trace-ID fields ride the v2 header and round-trip
    frame2 = seal_frame(buf, payload, fp, step=9, seq=123,
                        send_wall=1234.5)
    assert open_frame(frame2, fp, payload.nbytes)[1] is None
    assert read_lineage(frame2) == (9, 123, 1234.5)


def _v1_frame(payload: np.ndarray, fingerprint: int) -> np.ndarray:
    """A PR 3 v1 frame (20-byte header, no lineage fields) as an
    old-format worker would emit it."""
    import struct
    import zlib

    buf = np.empty(HEADER_BYTES_V1 + payload.nbytes, np.uint8)
    struct.pack_into("<IIIQ", buf, 0, FRAME_MAGIC_V1, payload.nbytes,
                     zlib.crc32(payload.view(np.uint8)) & 0xFFFFFFFF,
                     fingerprint)
    buf[HEADER_BYTES_V1:] = payload.view(np.uint8)
    return buf


def test_v1_frame_rejected_with_version_reason():
    """Frame-format version bump done right: a v1 frame — even one that
    was perfectly valid under the old format, correct CRC and
    fingerprint included — is rejected with the EXPLICIT reason
    ``"version"`` (not misread as garbage, size or corruption)."""
    payload = np.arange(6, dtype=np.float32)
    fp = 0x1234ABCD5678EF90
    old = _v1_frame(payload, fp)
    got, err = open_frame(old, fp, payload.nbytes)
    assert got is None and err == "version"
    # a v1 frame SHORTER than a v2 header is still identified by magic
    tiny = _v1_frame(np.zeros(2, np.float32), fp)
    assert tiny.nbytes < HEADER_BYTES
    assert open_frame(tiny, fp, None)[1] == "version"


def test_v1_frame_against_v2_server_rejected_not_fatal():
    """Wire compat on the live transport: an old-format worker pushing
    v1 frames at a v-new server becomes a counted per-worker rejection
    — the PS keeps serving its v2 workers."""
    import ctypes

    tpl = _template()
    name = f"/psq_v1_{os.getpid()}"
    server = dcn.ShmPSServer(name, num_workers=2, template=tpl, frame=True,
                             max_staleness=10**9)
    w = dcn.ShmPSWorker(name, 0, tpl, frame=True)
    try:
        server.publish({"w": np.arange(8, dtype=np.float32)})
        _, ver = w.read_params(timeout=30)

        # worker id 1 speaks the OLD frame format (correct payload size
        # and fingerprint under v1 — only the format version is stale)
        old = _v1_frame(np.ones(8, np.float32), server._fingerprint)
        rc = server._lib.psq_push_grad(
            server._h, 1, old.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)), old.nbytes, int(ver))
        assert rc == 1
        assert server.poll_grad() is None  # rejected, not raised
        assert server.frames_rejected == {1: 1}
        assert server.grads_received == 0  # never entered accounting

        # the v2 worker is unaffected
        w.push_grad({"w": np.full(8, 5.0, np.float32)}, ver,
                    lineage=(3, 4))
        item = server.poll_grad()
        assert item is not None and item[0] == 0
        assert (server.last_push_meta["step"],
                server.last_push_meta["seq"]) == (3, 4)
    finally:
        w.close()
        server.close()


def test_wire_fingerprint_detects_config_drift():
    """The same-byte-count mismatches PR 2 documented as 'undetectable'
    (codec-kw drift, bucket layout drift) produce different
    fingerprints; per-worker codec seeds do not."""
    from pytorch_ps_mpi_tpu.codecs import get_codec
    from pytorch_ps_mpi_tpu.parallel.dcn import CodecWire

    tpl = {"a": np.zeros((64,), np.float32),
           "b": np.zeros((32,), np.float32)}

    # raw wire: fingerprint depends on the template layout
    assert wire_fingerprint(None, tpl) == wire_fingerprint(None, tpl)
    tpl2 = {"a": np.zeros((32,), np.float32),
            "b": np.zeros((64,), np.float32)}  # same bytes, swapped layout
    assert wire_fingerprint(None, tpl) != wire_fingerprint(None, tpl2)

    code = get_codec("sign", use_pallas=False)
    w_server = CodecWire(code, tpl, seed=0)
    w_worker = CodecWire(code, tpl, seed=7)  # per-worker seed: same config
    assert (wire_fingerprint(w_server, tpl)
            == wire_fingerprint(w_worker, tpl))

    # codec identity drift
    w_other = CodecWire(get_codec("bf16"), tpl, seed=0)
    assert wire_fingerprint(w_server, tpl) != wire_fingerprint(w_other, tpl)


# ---------------------------------------------------------------------------
# fault plans: validation + determinism
# ---------------------------------------------------------------------------

def test_fault_plan_validation():
    with pytest.raises(ValueError, match="unknown kind"):
        FaultInjector([{"at_step": 1, "worker": 0, "kind": "explode"}])
    with pytest.raises(ValueError, match="missing worker"):
        FaultInjector([{"at_step": 1, "kind": "drop"}])
    with pytest.raises(ValueError, match="crash_server"):
        FaultInjector([{"at_step": 1, "worker": 0, "kind": "crash_server"}])


def test_fault_plan_deterministic_replay(tmp_path):
    """Same plan + seed → identical event logs AND identical corrupt
    byte positions; a different seed moves the corruption."""
    plan = [
        {"at_step": 2, "worker": 0, "kind": "corrupt"},
        {"at_step": 4, "worker": 0, "kind": "drop"},
        {"at_step": 5, "worker": 1, "kind": "delay", "delay_ms": 1},
        {"at_step": 7, "worker": "server", "kind": "crash_server"},
    ]

    def replay(seed, log_dir):
        cfg = {"fault_plan": plan, "fault_seed": seed,
               "fault_log_dir": str(log_dir)}
        bufs = []
        for role in (0, 1, "server"):
            inj = FaultInjector.from_cfg(cfg, role=role)
            for step in range(10):
                for f in inj.faults_at(step):
                    inj.fire(f)
                    if f["kind"] == "corrupt":
                        b = np.zeros(128, np.uint8)
                        inj.corrupt(f, b)
                        bufs.append(b.copy())
        events = []
        for role in (0, 1, "server"):
            from pytorch_ps_mpi_tpu.resilience import load_fault_log

            events.extend(load_fault_log(
                os.path.join(str(log_dir), f"faults-{role}.jsonl")))
        return sorted((e["id"], e["kind"], str(e["worker"]), e["at_step"])
                      for e in events), bufs

    ev1, bufs1 = replay(3, tmp_path / "r1")
    ev2, bufs2 = replay(3, tmp_path / "r2")
    assert ev1 == ev2 and len(ev1) == 4
    for a, b in zip(bufs1, bufs2):
        np.testing.assert_array_equal(a, b)
    ev3, bufs3 = replay(4, tmp_path / "r3")
    assert ev3 == ev1  # events are plan-determined, seed-free
    assert any(not np.array_equal(a, b) for a, b in zip(bufs1, bufs3))

    # fired-marking: a respawned process skips its crash fault
    cfg = {"fault_plan": plan, "fault_seed": 3, "fault_fired": [3]}
    inj = FaultInjector.from_cfg(cfg, role="server")
    assert inj.faults_at(7) == []


# ---------------------------------------------------------------------------
# frame checking on the live transports
# ---------------------------------------------------------------------------

def test_shm_corrupt_and_truncated_frames_rejected_and_counted():
    """A corrupted or short frame becomes a counted per-worker rejection
    (metrics + /metrics text), never a decode crash; valid frames keep
    flowing afterwards."""
    import ctypes

    tpl = _template()
    name = f"/psq_rej_{os.getpid()}"
    server = dcn.ShmPSServer(name, num_workers=2, template=tpl, frame=True,
                             max_staleness=10**9)
    w = dcn.ShmPSWorker(name, 0, tpl, frame=True)
    try:
        server.publish({"w": np.arange(8, dtype=np.float32)})
        _, ver = w.read_params(timeout=30)

        w._tamper = lambda buf: buf.__setitem__(HEADER_BYTES + 1,
                                                buf[HEADER_BYTES + 1] ^ 0xFF)
        w.push_grad({"w": np.ones(8, np.float32)}, ver)
        assert server.poll_grad() is None  # rejected, not raised
        assert server.frames_rejected_total == 1
        assert server.frames_rejected == {0: 1}

        # truncated/unframed push from a rogue worker id 1 (raw bytes,
        # no header): rejected and attributed to that worker
        short = np.ones(3, np.float32).view(np.uint8)
        rc = server._lib.psq_push_grad(
            server._h, 1, short.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)), short.nbytes, 1)
        assert rc == 1
        assert server.poll_grad() is None
        assert server.frames_rejected == {0: 1, 1: 1}

        # the canonical schema + prometheus text carry the counts
        assert server.metrics()["frames_rejected"] == 2.0
        text = server.prometheus_text()
        assert 'ps_frames_rejected_total{worker="0"} 1' in text
        assert 'ps_frames_rejected_total{worker="1"} 1' in text

        # a healthy push still decodes — the PS survived its bad clients
        w.push_grad({"w": np.full(8, 5.0, np.float32)}, ver)
        item = server.poll_grad()
        assert item is not None
        np.testing.assert_array_equal(np.asarray(item[2]["w"]),
                                      np.full(8, 5.0, np.float32))
        # rejected frames never entered gradient accounting
        assert server.grads_received == 1
    finally:
        w.close()
        server.close()


def test_tcp_size_mismatched_frame_rejected_not_fatal():
    """The satellite fix: a worker pushing the wrong wire size used to
    raise RuntimeError INTO the serve loop, killing the PS for everyone.
    With frames on it is a counted rejection and the server keeps
    serving the correctly-configured workers."""
    from pytorch_ps_mpi_tpu.parallel import tcp

    if tcp.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    tpl = _template(16)
    server = tcp.TcpPSServer(0, num_workers=2, template=tpl, frame=True,
                             max_staleness=10**9)
    good = None
    try:
        server.publish({"w": np.zeros(16, np.float32)})

        # rogue client: valid transport frames, wrong payload size (a
        # worker built against a different codec/template config)
        import socket
        import struct

        s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        payload = np.ones(4, np.float32).tobytes()  # 16B, spec wants 64+20
        hdr = struct.pack("<IB3xIQQ", 0x31535054, 4, 1, 1, len(payload))
        s.sendall(struct.pack("<IB3xIQQ", 0x31535054, 1, 1, 0, 0))  # HELLO
        s.sendall(hdr + payload)
        deadline = time.time() + 30
        while server.frames_rejected_total == 0 and time.time() < deadline:
            assert server.poll_grad() is None
            time.sleep(0.005)
        assert server.frames_rejected.get(1) == 1
        s.close()

        # a well-configured framed worker is unaffected
        good = tcp.TcpPSWorker("127.0.0.1", server.port, 0, tpl, frame=True)
        done = {}

        def body():
            _, ver = good.read_params(timeout=30)
            good.push_grad({"w": np.full(16, 2.0, np.float32)}, ver,
                           timeout=30)
            done["ok"] = True

        t = threading.Thread(target=body)
        t.start()
        item = None
        deadline = time.time() + 30
        while item is None and time.time() < deadline:
            item = server.poll_grad()
            time.sleep(0.002)
        t.join(timeout=30)
        assert done.get("ok") and item is not None
        assert item[0] == 0
        np.testing.assert_array_equal(np.asarray(item[2]["w"]),
                                      np.full(16, 2.0, np.float32))
    finally:
        if good is not None:
            good.close()
        server.close()


@pytest.mark.parametrize("transport", ["shm", "tcp"])
def test_nan_push_counted_and_quarantined_both_transports(transport):
    """Numerics quarantine (telemetry.numerics) on the live wires: a NaN
    gradient push survives every frame check (the bytes are valid —
    poison is a NUMERICS failure, not a wire one), is counted per worker
    through the same _reject_frame machinery as corrupt frames, and
    quarantines exactly the offending worker on both transports."""
    from pytorch_ps_mpi_tpu.telemetry.numerics import NumericsMonitor

    tpl = _template(16)
    workers = []
    if transport == "tcp":
        from pytorch_ps_mpi_tpu.parallel import tcp

        if tcp.get_lib() is None:
            pytest.skip("native toolchain unavailable")
        server = tcp.TcpPSServer(0, num_workers=2, template=tpl,
                                 frame=True, max_staleness=10**9)
        make = lambda wid: tcp.TcpPSWorker("127.0.0.1", server.port, wid,
                                           tpl, frame=True)
    else:
        name = f"/psq_nan_{os.getpid()}"
        server = dcn.ShmPSServer(name, num_workers=2, template=tpl,
                                 frame=True, max_staleness=10**9)
        make = lambda wid: dcn.ShmPSWorker(name, wid, tpl, frame=True)
    try:
        numon = NumericsMonitor(server, {"numerics_kw": {"policy": "skip"}})
        server.publish({"w": np.zeros(16, np.float32)})
        workers = [make(0), make(1)]

        def push(wid, grad, n=1):
            def body():
                _, ver = workers[wid].read_params(timeout=30)
                for _ in range(n):
                    workers[wid].push_grad({"w": grad}, ver, timeout=30)

            t = threading.Thread(target=body)
            t.start()
            items = []
            deadline = time.time() + 30
            while len(items) < n and time.time() < deadline:
                item = server.poll_grad()
                if item is not None:
                    items.append(item)
                time.sleep(0.002)
            t.join(timeout=30)
            assert len(items) == n
            return items

        # healthy push from worker 0, poisoned pushes from worker 1
        (item,) = push(0, np.ones(16, np.float32))
        assert numon.observe_push(item[0], item[2]) == "apply"
        for item in push(1, np.full(16, np.nan, np.float32), n=2):
            assert numon.observe_push(item[0], item[2]) == "skip"

        assert numon.is_quarantined(1) and not numon.is_quarantined(0)
        m = server.metrics()
        assert m["nonfinite_total"] == 2.0
        assert m["grad_norm"] == pytest.approx(4.0)  # ||ones(16)||
        assert server.frames_rejected.get(1) == 2  # counted like corrupt
        text = server.prometheus_text()
        assert 'ps_worker_nonfinite_total{worker="1"} 2' in text
        assert 'ps_worker_quarantined{worker="1"} 1' in text
        assert "ps_nonfinite_total 2" in text
    finally:
        for w in workers:
            w.close()
        server.close()


def test_tcp_never_connected_worker_reported_immediately():
    """Satellite fix for ``last_seen`` ageing: liveness clocks start at
    first CONNECT, not server start — a worker that never showed up is
    reported as missing right away instead of after ``timeout`` seconds
    from server start."""
    from pytorch_ps_mpi_tpu.parallel import tcp

    if tcp.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    tpl = _template(4)
    server = tcp.TcpPSServer(0, num_workers=2, template=tpl)
    w0 = None
    try:
        server.publish({"w": np.zeros(4, np.float32)})
        w0 = tcp.TcpPSWorker("127.0.0.1", server.port, 0, tpl)
        deadline = time.time() + 30
        while not server.connected(0) and time.time() < deadline:
            time.sleep(0.01)
        assert server.connected(0)

        # a HUGE timeout would previously hide worker 1 until that many
        # seconds after server start; now it is flagged immediately
        missing = server.stragglers(timeout=3600.0)
        assert 1 in missing and 0 not in missing
    finally:
        if w0 is not None:
            w0.close()
        server.close()


# ---------------------------------------------------------------------------
# worker-side retry/reconnect
# ---------------------------------------------------------------------------

def test_resilient_worker_survives_shm_server_restart():
    """A restarted shm server recreates the segment; the old worker's
    pushes land in an orphaned mailbox and time out. ResilientWorker
    reconnects (re-opens the name → finds the live segment) and the push
    stream resumes — previously this worker raised and died."""
    tpl = _template()
    name = f"/psq_rw_{os.getpid()}"
    server_a = dcn.ShmPSServer(name, num_workers=1, template=tpl,
                               max_staleness=10**9)
    server_a.publish({"w": np.zeros(8, np.float32)})
    w = ResilientWorker(
        lambda: dcn.ShmPSWorker(name, 0, tpl, timeout=10.0),
        worker_id=0, backoff_base=0.01, backoff_max=0.1, seed=5,
    )
    server_b = None
    try:
        _, ver = w.read_params(timeout=10)
        w.push_grad({"w": np.ones(8, np.float32)}, ver, timeout=2.0)
        assert server_a.poll_grad() is not None

        server_a.close()  # unlinks the segment ("crash")
        server_b = dcn.ShmPSServer(name, num_workers=1, template=tpl,
                                   max_staleness=10**9)
        server_b.version = 10  # restored-from-checkpoint version jump
        server_b.publish({"w": np.full(8, 3.0, np.float32)})

        # one push is lost in the orphaned mailbox; the next times out
        # and triggers the reconnect — bounded by short op timeouts
        w.push_grad({"w": np.ones(8, np.float32)}, ver, timeout=1.0)
        w.push_grad({"w": np.full(8, 2.0, np.float32)}, ver, timeout=1.0)
        deadline = time.time() + 30
        got = []
        while len(got) < 1 and time.time() < deadline:
            item = server_b.poll_grad()
            if item is None:
                time.sleep(0.005)
                continue
            got.append(item)
        assert got, "replacement server never received the re-pushed grad"
        assert w.reconnects >= 1
        # the reconnected worker reads the REPLACEMENT's snapshot
        params, ver2 = w.read_params(timeout=10)
        assert ver2 >= 11
        np.testing.assert_array_equal(params["w"],
                                      np.full(8, 3.0, np.float32))
    finally:
        w.close()
        if server_b is not None:
            server_b.close()


def test_resilient_worker_survives_tcp_server_restart():
    """TCP flavor: the worker's socket EOFs when the server dies; the
    reconnect retries until the replacement binds the SAME port, then
    pushes resume."""
    from pytorch_ps_mpi_tpu.parallel import tcp

    if tcp.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    class _Pumper:
        """Continuously pump/poll a TCP server on a thread (the serve
        loop's role) so worker-side blocking calls get answered."""

        def __init__(self, server):
            self.server = server
            self.got = 0
            self._stop = threading.Event()
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            while not self._stop.is_set():
                if self.server.poll_grad() is not None:
                    self.got += 1
                time.sleep(0.002)

        def stop(self):
            self._stop.set()
            self._t.join(timeout=10)

    tpl = _template()
    server_a = tcp.TcpPSServer(0, num_workers=1, template=tpl,
                               max_staleness=10**9)
    port = server_a.port
    server_a.publish({"w": np.zeros(8, np.float32)})
    pump_a = _Pumper(server_a)
    w = ResilientWorker(
        lambda: tcp.TcpPSWorker("127.0.0.1", port, 0, tpl, timeout=10.0),
        worker_id=0, backoff_base=0.01, backoff_max=0.2, seed=5,
    )
    server_b = None
    pump_b = None
    try:
        _, ver = w.read_params(timeout=10)
        w.push_grad({"w": np.ones(8, np.float32)}, ver, timeout=10.0)
        deadline = time.time() + 30
        while pump_a.got < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert pump_a.got == 1

        pump_a.stop()
        server_a.close()
        server_b = tcp.TcpPSServer(port, num_workers=1, template=tpl,
                                   max_staleness=10**9)
        server_b.version = 10
        server_b.publish({"w": np.full(8, 3.0, np.float32)})
        pump_b = _Pumper(server_b)

        # EOF on the dead socket → immediate reconnect → push lands
        w.push_grad({"w": np.full(8, 2.0, np.float32)}, ver, timeout=10.0)
        deadline = time.time() + 30
        while pump_b.got < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert pump_b.got == 1
        assert w.reconnects >= 1
    finally:
        if pump_b is not None:
            pump_b.stop()
        else:
            pump_a.stop()
        w.close()
        if server_b is not None:
            server_b.close()


def test_join_workers_reaps_stragglers():
    """The worker-process-leak fix: a fleet where one member never exits
    is terminated and reaped on the failure path, and exit codes come
    back in order."""
    import subprocess
    import sys

    quick = subprocess.Popen([sys.executable, "-c", "print('ok')"])
    stuck = subprocess.Popen([sys.executable, "-c",
                              "import time; time.sleep(600)"])
    from pytorch_ps_mpi_tpu.parallel.async_train import join_workers

    t0 = time.time()
    codes = join_workers([quick, stuck], timeout=3.0)
    assert time.time() - t0 < 30.0
    assert codes[0] == 0
    assert codes[1] != 0 and codes[1] is not None  # SIGTERM'd
    assert stuck.poll() is not None  # actually reaped, no zombie fleet


# ---------------------------------------------------------------------------
# degraded sync-barrier rounds (in-process fleet: threads, no jax spawns)
# ---------------------------------------------------------------------------

def test_sync_barrier_degrades_when_worker_dies_instead_of_hanging():
    """A dead worker used to wedge ``serve(sync_barrier=True)`` forever
    at the barrier. Now, once a round has waited
    ``cfg['degraded_round_after']``, transport-dead workers are excluded
    and the round completes over the survivors — counted, not hung."""
    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem, serve

    cfg = {
        "model": "mlp", "model_kw": {"features": (8, 4)}, "in_shape": (8,),
        "batch": 8, "seed": 1, "optim": "sgd", "hyper": {"lr": 0.01},
        "degraded_round_after": 0.6,
    }
    _, params0, _, _ = make_problem(cfg)
    name = f"/psq_deg_{os.getpid()}"
    server = dcn.ShmPSServer(name, num_workers=2, template=params0,
                             max_staleness=10**9)
    workers = []
    threads = []
    state = {"done": 0}
    try:
        def worker_body(wid, steps):
            w = dcn.ShmPSWorker(name, wid, params0, timeout=30.0)
            workers.append(w)
            _, ver = w.read_params(timeout=30.0)
            import jax

            g = jax.tree.map(lambda x: np.full(np.shape(x), 1e-3,
                                               np.float32), params0)
            for k in range(steps):
                _, ver = w.read_params(timeout=30.0)
                w.push_grad(g, ver, timeout=30.0)
                time.sleep(0.02)
            state["done"] += 1
            # worker 1 "dies" silently after its steps: no close, no
            # more pushes — the shm silence-window case

        threads = [threading.Thread(target=worker_body, args=(0, 8)),
                   threading.Thread(target=worker_body, args=(1, 2))]
        for t in threads:
            t.start()
        # stop on APPLIED count: without degradation the barrier can
        # never apply more than 2x the dead worker's 2 pushes, so
        # reaching 10 applied *requires* degraded rounds (or the 60 s
        # timeout fails the wall assertion below — the old behavior,
        # which hung forever)
        params, m = serve(
            server, cfg, total_grads=10, sync_barrier=True, timeout=60.0,
        )
        for t in threads:
            t.join(timeout=30)
    finally:
        for w in workers:
            w.close()
        server.close()

    # both full rounds (2 grads each) and degraded rounds (worker 0
    # alone) happened; nothing hung — the loop returned well inside its
    # timeout with every pushed gradient consumed
    assert m["degraded_rounds"] >= 1
    assert m["applied"] == 10
    assert m["wall_s"] < 45.0
    assert m["grads_received"] == 10


# ---------------------------------------------------------------------------
# supervised chaos E2E (multi-process; the acceptance scenario)
# ---------------------------------------------------------------------------

def _chaos_cfg(tmp_path, tag):
    return {
        "model": "mlp", "model_kw": {"features": (16, 4)}, "in_shape": (8,),
        "batch": 32, "seed": 11, "optim": "sgd", "hyper": {"lr": 0.05},
        "steps": 16,
        "open_timeout": 60.0, "push_timeout": 3.0,
        "frame_check": True, "resilient": True,
        "resilience_kw": {"backoff_base": 0.02, "backoff_max": 0.5,
                          "max_retries": 20},
        "degraded_round_after": 2.0,
        # non-crash faults all target worker 0 (which is never respawned)
        # so each fires exactly once — a respawned worker replays its
        # step counter and would deterministically re-fire its own
        # non-crash faults, which is correct replay behavior but would
        # complicate the exact-event-list assertion below
        "fault_plan": [
            {"at_step": 2, "worker": 0, "kind": "corrupt"},
            {"at_step": 3, "worker": 0, "kind": "delay", "delay_ms": 20},
            {"at_step": 4, "worker": 1, "kind": "crash_worker"},
            {"at_step": 5, "worker": 0, "kind": "drop"},
            {"at_step": 6, "worker": 0, "kind": "duplicate"},
            {"at_step": 12, "worker": "server", "kind": "crash_server"},
        ],
        "fault_seed": 7,
        "fault_log_dir": str(tmp_path / f"faults_{tag}"),
    }


def _run_supervised(tmp_path, tag):
    cfg = _chaos_cfg(tmp_path, tag)
    sup = Supervisor(
        cfg, 2, shm_name=f"/psq_chaos_{os.getpid()}_{tag}",
        checkpoint_dir=str(tmp_path / f"ckpt_{tag}"), checkpoint_every=4,
        timeout=240.0,
    )
    params, m = sup.run()
    events = []
    for role in (0, 1, "server"):
        from pytorch_ps_mpi_tpu.resilience import load_fault_log

        events.extend(load_fault_log(os.path.join(
            cfg["fault_log_dir"], f"faults-{role}.jsonl")))
    return sup, m, sorted((e["id"], e["kind"], str(e["worker"]),
                           e["at_step"]) for e in events)


def test_supervised_chaos_run_recovers_everything(tmp_path):
    """The acceptance scenario: under a fault plan injecting a worker
    crash, a server crash, and a corrupted frame (plus drop/delay/
    duplicate), a 2-worker async run completes with the loss improved,
    zero hung rounds, and every recovery counter nonzero — including in
    the Prometheus ``/metrics`` text."""
    sup, m, events = _run_supervised(tmp_path, "a")

    # training survived the chaos and still learned — judged against the
    # RUN's initial loss (phase 1's metrics die with the crashed server)
    assert m["loss_final"] < m["run_loss_initial"], m
    # every worker finished cleanly (respawns included)
    assert m["worker_exit_codes"] == [0, 0]
    assert m["workers_abandoned"] == 0.0
    # each recovery mechanism actually fired
    assert m["worker_respawns"] >= 1.0
    assert m["server_restarts"] >= 1.0
    assert m["worker_reconnects"] >= 1.0
    assert m["frames_rejected"] >= 1.0
    # the publish version never went backwards across the restart
    assert m["versions_monotonic"] is True
    assert m["supervised_phases"] >= 2.0
    # recovery counters are scrapable where an operator would look
    text = sup.final_prometheus_text
    assert "ps_worker_respawns_total 1" in text
    assert "ps_server_restarts_total 1" in text
    # per-worker labeled series carry run totals ACROSS the server
    # restart (worker 0's rejection happened on the phase-1 server)
    assert 'ps_frames_rejected_total{worker="0"} 1' in text
    assert "ps_worker_reconnects_total" in text
    # all six fault kinds fired exactly once, crash faults not re-fired
    # by the respawned generation
    assert [e[1] for e in events] == [
        "corrupt", "delay", "crash_worker", "drop", "duplicate",
        "crash_server",
    ]


@pytest.mark.slow
def test_supervised_chaos_is_deterministic(tmp_path):
    """Two supervised runs with the same fault plan + seed produce
    identical injected-event logs (the reproducible-chaos contract; the
    fast path of this check runs in ``make chaos-smoke``)."""
    _, m1, ev1 = _run_supervised(tmp_path, "d1")
    _, m2, ev2 = _run_supervised(tmp_path, "d2")
    assert ev1 == ev2
    assert m1["worker_exit_codes"] == m2["worker_exit_codes"] == [0, 0]
